"""Rabit-compatible rendezvous tracker.

Capability parity with tracker/dmlc_tracker/tracker.py — same wire protocol,
so reference rabit workers could rendezvous here and our workers could
rendezvous with the reference tracker:

- framed int/str TCP protocol with magic 0x_ff99 handshake (ExSocket /
  SlaveEntry, tracker.py:24-78)
- worker handshake carries (rank, world_size, jobid, cmd) with
  cmd ∈ {start, recover, shutdown, print} (tracker.py:66-69)
- rank assignment: batch assignment sorted by host once all expected workers
  are pending; jobid→rank map makes ranks stable across restarts; 'recover'
  re-enters with the old rank (tracker.py:254-320)
- link maps: binary-heap tree (get_neighbor/get_tree, tracker.py:165-191), a
  tree-sharing ring for long-message/recovery paths (find_share_ring /
  get_ring, tracker.py:193-225), relabeled so ring order is contiguous
  (get_link_map, tracker.py:227-252)
- peer-link brokering: the goodset/badset reconciliation loop that tells each
  worker which already-listening peers to dial (assign_rank,
  tracker.py:80-135)
- PSTracker scheduler bootstrap exporting DMLC_PS_ROOT_URI/PORT
  (tracker.py:336-386)
- world size may be decided by the first worker (tracker.py:281-287)

TPU-new on top of the reference protocol: a lightweight ``heartbeat``
command (send_heartbeat) lets running workers report liveness plus an
epoch/metrics summary line; the tracker records last_seen per rank and
logs workers whose gap exceeds ``DMLC_TPU_HEARTBEAT_GAP`` as stragglers.
A straggler that reports again is logged as recovered
(``dmlc_tracker_straggler_recoveries_total``) and re-armed, so a rank
that flaps is warned about every time, not once forever.
Reference trackers ignore unknown jobids, and our tracker treats the
command as fire-and-forget, so the extension stays wire-compatible.

Elastic membership (also TPU-new; docs/robustness.md "Elastic
membership"): the tracker owns a monotonically increasing
``world_version`` — the generation of the currently assigned world. Two
more commands extend the handshake: ``join`` registers a warm spare
(world_size −1) or a scale-up request (world_size 0) and parks the
connection until a transition activates it; ``elastic`` re-enters the
job into the *next* generation — the tracker acks the target version
(−1 = refused, e.g. an evicted worker), batches entrants over a
quiescence window (``DMLC_TPU_ELASTIC_WINDOW_S``), backfills missing
ranks from parked spares, then rebuilds tree/ring for the new world and
assigns fresh ranks. Running workers learn a transition is pending from
the heartbeat ack (it carries the target version; pre-elastic workers
ignored the ack value, so the wire stays compatible) and re-enter at
their next checkpoint boundary. ``DMLC_TPU_EVICT_AFTER_S`` adds an
eviction policy on top of straggler detection: a rank silent for that
long is refused re-entry and the survivors drain into a smaller world
instead of failing the job.

The job observability plane (obs/plane.py) rides the same command: when
``DMLC_TPU_STATUS_PORT`` is set the tracker starts an HTTP status server
(/healthz, /workers, /metrics, /trace), advertises
``DMLC_TPU_OBS_PUBLISH``/``DMLC_TPU_STATUS_URI`` to workers, and parses
the optional ``\\nOBS1 <json>`` suffix workers then append to their
heartbeat payloads (metric snapshot + span batch + clock probe). With
the knob unset none of this exists: no socket, no thread, and heartbeat
ingestion goes to the shared no-op plane.

On TPU this socket machinery is only the *control* plane (CPU-parity runs and
process bootstrap); the data plane is XLA collectives over ICI — see
dmlc_tpu.collective.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from dmlc_tpu import obs
from dmlc_tpu.obs import flight
from dmlc_tpu.obs import plane as obs_plane
from dmlc_tpu.params.knobs import (
    elastic_window_s,
    evict_after_s,
    heartbeat_gap,
    status_port,
)
from dmlc_tpu.utils.logging import DMLCError

MAGIC = 0xFF99


class SpareUnused(DMLCError):
    """The job finished without this warm spare being activated — the
    clean 'never needed' outcome, not a failure."""

logger = logging.getLogger("dmlc_tpu.tracker")


class FramedSocket:
    """int/str framing over a TCP socket (ExSocket, tracker.py:24-47).

    Ints are native-endian i32 ('@i') to stay wire-compatible.
    """

    def __init__(self, sock: socket.socket):
        self.sock = sock
        # Collectives exchange many small frames (ints, headers, short
        # tree payloads); without TCP_NODELAY, Nagle coalescing + the
        # peer's delayed ACK serialize them into ~40 ms stalls — measured
        # on the loopback crossover sweep as tree allreduce at 4-16 KB
        # running at 0.04-0.18 MB/s (100 ms/op) before this, 2-3 orders
        # of magnitude off. Latency-bound frames must go out immediately.
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # non-TCP transports (tests may pass socketpairs)

    def recv_all(self, nbytes: int) -> bytes:
        parts = []
        nread = 0
        while nread < nbytes:
            chunk = self.sock.recv(min(nbytes - nread, 65536))
            if not chunk:
                raise ConnectionError("peer closed during recv")
            parts.append(chunk)
            nread += len(chunk)
        return b"".join(parts)

    def recv_int(self) -> int:
        return struct.unpack("@i", self.recv_all(4))[0]

    def send_int(self, value: int) -> None:
        self.sock.sendall(struct.pack("@i", value))

    def send_str(self, value: str) -> None:
        data = value.encode()
        self.send_int(len(data))
        self.sock.sendall(data)

    def recv_str(self) -> str:
        return self.recv_all(self.recv_int()).decode()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _resolve_ip(host: str) -> str:
    return socket.getaddrinfo(host, None)[0][4][0]


def get_host_ip(host_ip: Optional[str] = None) -> str:
    """Best-effort routable IP (tracker.py:389-407)."""
    if host_ip in (None, "auto", "ip"):
        try:
            ip = socket.gethostbyname(socket.getfqdn())
        except socket.gaierror:
            ip = socket.gethostbyname(socket.gethostname())
        if ip.startswith("127."):
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                probe.connect(("10.255.255.255", 1))
                ip = probe.getsockname()[0]
            except OSError:
                ip = "127.0.0.1"
            finally:
                probe.close()
        return ip
    if host_ip == "dns":
        return socket.getfqdn()
    return host_ip


# ---------------------------------------------------------------------------
# Topology: tree + ring link maps
# ---------------------------------------------------------------------------


def tree_neighbors(rank: int, world: int) -> List[int]:
    """Binary-heap neighbors of ``rank`` (tracker.py:165-175)."""
    r1 = rank + 1
    out = []
    if r1 > 1:
        out.append(r1 // 2 - 1)
    if r1 * 2 - 1 < world:
        out.append(r1 * 2 - 1)
    if r1 * 2 < world:
        out.append(r1 * 2)
    return out


def build_tree(world: int) -> Tuple[Dict[int, List[int]], Dict[int, int]]:
    tree = {r: tree_neighbors(r, world) for r in range(world)}
    parent = {r: (r + 1) // 2 - 1 for r in range(world)}
    return tree, parent


def _dfs_share_ring(
    tree: Dict[int, List[int]], parent: Dict[int, int], root: int
) -> List[int]:
    """DFS order that shares edges with the tree (tracker.py:193-210)."""
    children = [v for v in tree[root] if v != parent[root]]
    order = [root]
    for i, child in enumerate(children):
        sub = _dfs_share_ring(tree, parent, child)
        if i == len(children) - 1:
            sub.reverse()
        order.extend(sub)
    return order


def build_ring(
    tree: Dict[int, List[int]], parent: Dict[int, int]
) -> Dict[int, Tuple[int, int]]:
    order = _dfs_share_ring(tree, parent, 0)
    world = len(tree)
    ring: Dict[int, Tuple[int, int]] = {}
    for pos in range(world):
        ring[order[pos]] = (order[(pos - 1) % world], order[(pos + 1) % world])
    return ring


def build_link_maps(world: int):
    """Tree+ring, relabeled so ring order is 0,1,2,... (tracker.py:227-252)."""
    tree, parent = build_tree(world)
    ring = build_ring(tree, parent)
    relabel = {0: 0}
    cur = 0
    for i in range(world - 1):
        cur = ring[cur][1]
        relabel[cur] = i + 1
    tree2 = {relabel[k]: [relabel[x] for x in v] for k, v in tree.items()}
    parent2 = {
        relabel[k]: (relabel[v] if k != 0 else -1) for k, v in parent.items()
    }
    ring2 = {relabel[k]: (relabel[a], relabel[b]) for k, (a, b) in ring.items()}
    return tree2, parent2, ring2


# ---------------------------------------------------------------------------
# Tracker
# ---------------------------------------------------------------------------


class _Worker:
    """Tracker-side view of one connected worker (SlaveEntry)."""

    def __init__(self, sock: socket.socket, addr):
        self.conn = FramedSocket(sock)
        self.host = _resolve_ip(addr[0])
        magic = self.conn.recv_int()
        if magic != MAGIC:
            raise ConnectionError(f"invalid magic {magic:#x} from {self.host}")
        self.conn.send_int(MAGIC)
        self.rank = self.conn.recv_int()
        self.world_size = self.conn.recv_int()
        self.jobid = self.conn.recv_str()
        self.cmd = self.conn.recv_str()
        self.wait_accept = 0
        self.port: Optional[int] = None

    def decide_rank(self, job_map: Dict[str, int]) -> int:
        if self.rank >= 0:
            return self.rank
        if self.jobid != "NULL" and self.jobid in job_map:
            return job_map[self.jobid]
        return -1

    def assign_rank(
        self,
        rank: int,
        wait_conn: Dict[int, "_Worker"],
        tree: Dict[int, List[int]],
        parent: Dict[int, int],
        ring: Dict[int, Tuple[int, int]],
    ) -> List[int]:
        """Send topology + broker peer connections (tracker.py:80-135)."""
        self.rank = rank
        neighbors: Set[int] = set(tree[rank])
        rprev, rnext = ring[rank]
        conn = self.conn
        conn.send_int(rank)
        conn.send_int(parent[rank])
        conn.send_int(len(tree))
        conn.send_int(len(neighbors))
        for r in neighbors:
            conn.send_int(r)
        if rprev != -1 and rprev != rank:
            neighbors.add(rprev)
            conn.send_int(rprev)
        else:
            conn.send_int(-1)
        if rnext != -1 and rnext != rank:
            neighbors.add(rnext)
            conn.send_int(rnext)
        else:
            conn.send_int(-1)
        while True:
            ngood = conn.recv_int()
            goodset = {conn.recv_int() for _ in range(ngood)}
            assert goodset.issubset(neighbors), (goodset, neighbors)
            badset = neighbors - goodset
            to_connect = [r for r in badset if r in wait_conn]
            conn.send_int(len(to_connect))
            conn.send_int(len(badset) - len(to_connect))
            for r in to_connect:
                conn.send_str(wait_conn[r].host)
                conn.send_int(wait_conn[r].port)
                conn.send_int(r)
            nerr = conn.recv_int()
            if nerr != 0:
                continue
            self.port = conn.recv_int()
            done = []
            for r in to_connect:
                wait_conn[r].wait_accept -= 1
                if wait_conn[r].wait_accept == 0:
                    done.append(r)
            for r in done:
                wait_conn.pop(r, None)
            self.wait_accept = len(badset) - len(to_connect)
            return done


class RabitTracker:
    """The rendezvous tracker (tracker.py:137-334)."""

    def __init__(
        self,
        host_ip: str,
        num_workers: int,
        port: int = 9091,
        port_end: int = 9999,
    ):
        family = socket.getaddrinfo(host_ip, None)[0][0]
        sock = socket.socket(family, socket.SOCK_STREAM)
        bound = False
        for p in range(port, port_end):
            try:
                sock.bind((host_ip, p))
                self.port = p
                bound = True
                break
            except OSError as err:
                if err.errno in (48, 98):  # EADDRINUSE
                    continue
                raise
        if not bound:
            raise OSError(f"no free tracker port in [{port},{port_end})")
        sock.listen(256)
        self.sock = sock
        self.host_ip = host_ip
        self.num_workers = num_workers
        self.thread: Optional[threading.Thread] = None
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        # heartbeat satellite state: rank → last_seen / last payload line
        self.heartbeat_gap = heartbeat_gap()
        self._hb_lock = threading.Lock()
        self._last_seen: Dict[int, float] = {}
        self._hb_info: Dict[int, str] = {}
        self._hb_flagged: Set[int] = set()
        # elastic membership state: world_version is the generation of the
        # currently assigned world (1 after the first rendezvous); the
        # target version is what heartbeat acks advertise — it runs one
        # ahead while a membership transition is pending. Evicted ranks
        # (and their jobids, which survive the rank reshuffle at commit)
        # are refused elastic re-entry.
        self.world_version = 0
        self._target_version = 0
        self.elastic_window = elastic_window_s()
        self.evict_after = evict_after_s()
        self._evicted_ranks: Set[int] = set()
        self._evicted_jobids: Set[str] = set()
        self._rank_jobids: Dict[int, str] = {}
        self._m_heartbeats = obs.registry().counter(
            "dmlc_tracker_heartbeats_total", "worker heartbeats received")
        self._m_straggler_recoveries = obs.registry().counter(
            "dmlc_tracker_straggler_recoveries_total",
            "flagged stragglers that resumed heartbeating")
        # job observability plane: live only when DMLC_TPU_STATUS_PORT is
        # set; otherwise the shared no-op plane and no HTTP server at all
        sp = status_port()
        if sp is None:
            self.plane = obs_plane.NOOP_PLANE
            self.status: Optional[obs_plane.StatusServer] = None
        else:
            self.plane = obs_plane.StatusPlane(
                num_workers=num_workers, heartbeat_gap=self.heartbeat_gap)
            self.status = obs_plane.StatusServer(self.plane, port=sp)
            self.status.start()
            logger.info("status server on http://%s:%d (/healthz /workers "
                        "/metrics /trace /data)", host_ip, self.status.port)
        logger.info("tracker listening on %s:%d", host_ip, self.port)

    def worker_envs(self) -> Dict[str, object]:
        """Env contract handed to workers (tracker.py:177-183). When the
        status plane is armed, workers are additionally told to publish
        obs payloads and where the status server lives."""
        envs: Dict[str, object] = {
            "DMLC_TRACKER_URI": self.host_ip,
            "DMLC_TRACKER_PORT": self.port,
        }
        if self.status is not None:
            envs["DMLC_TPU_OBS_PUBLISH"] = 1
            envs["DMLC_TPU_STATUS_URI"] = "%s:%d" % (
                self.host_ip, self.status.port)
        return envs

    def attach_data_dispatcher(self, dispatcher) -> None:
        """Wire a :class:`~dmlc_tpu.data.dispatcher.DataDispatcher` into
        this tracker's status plane so ``/data`` serves its live
        worker/lease/requeue snapshot (a no-op when the plane is the
        shared no-op plane — no status server, nothing to serve)."""
        self.plane.set_data_provider(dispatcher.snapshot)

    # ---- heartbeat satellite -------------------------------------------
    def _note_heartbeat(self, rank: int, payload: str) -> None:
        """Record a worker's liveness report and flag stragglers: any
        other rank whose last report is older than ``heartbeat_gap``
        seconds gets warned about once per lapse. A flagged rank that
        reports again is logged as recovered, counted, and re-armed —
        a flapping worker is warned about every time it goes quiet.

        The payload may carry an ``OBS1`` JSON suffix (obs/plane.py);
        it is split off here and fed to the status plane."""
        recv_unix_ns = time.time_ns()
        now = recv_unix_ns / 1e9
        obs_obj = None
        if obs_plane.PAYLOAD_MARK in payload:
            payload, _sep, blob = payload.partition(obs_plane.PAYLOAD_MARK)
            try:
                obs_obj = json.loads(blob)
            except ValueError:
                logger.warning("undecodable obs payload from rank %d", rank)
        with self._hb_lock:
            recovered = rank in self._hb_flagged
            self._last_seen[rank] = now
            self._hb_info[rank] = payload
            self._hb_flagged.discard(rank)
            stale = [
                (r, now - seen) for r, seen in self._last_seen.items()
                if r != rank and now - seen > self.heartbeat_gap
                and r not in self._hb_flagged
            ]
            self._hb_flagged.update(r for r, _ in stale)
        self._m_heartbeats.inc()
        if recovered:
            self._m_straggler_recoveries.inc()
            logger.info("straggler recovered: rank %d is heartbeating "
                        "again", rank)
        logger.debug("heartbeat from rank %d: %s", rank, payload)
        for r, gap in stale:
            logger.warning(
                "straggler: rank %d last heartbeat %.1fs ago (threshold "
                "%.1fs); last report: %s",
                r, gap, self.heartbeat_gap, self._hb_info.get(r, ""),
            )
        self.plane.note_live(rank, now, payload)
        if obs_obj is not None:
            self.plane.note_payload(rank, obs_obj, recv_unix_ns)

    def heartbeats(self) -> Dict[int, Tuple[float, str]]:
        """Snapshot of rank → (last_seen unix time, last payload line)."""
        with self._hb_lock:
            return {
                r: (seen, self._hb_info.get(r, ""))
                for r, seen in self._last_seen.items()
            }

    # ---- elastic membership --------------------------------------------
    def _evict_scan(self, now: float) -> List[int]:
        """Eviction policy (``DMLC_TPU_EVICT_AFTER_S``): a rank whose
        last heartbeat is older than the threshold is marked evicted —
        its jobid is banned from elastic re-entry and the bumped
        heartbeat ack tells survivors to drain into a new generation at
        their next checkpoint boundary (run_with_recovery's elastic
        path). Returns the ranks newly evicted by this scan. A fired
        ``tracker.evict`` faultpoint defers that rank's eviction to the
        next scan, so eviction storms are chaos-testable."""
        if self.evict_after <= 0:
            return []
        from dmlc_tpu.resilience import InjectedFault, faultpoint

        with self._hb_lock:
            stale = [
                r for r, seen in self._last_seen.items()
                if now - seen > self.evict_after
                and r not in self._evicted_ranks
            ]
        evicted = []
        for rank in sorted(stale):
            try:
                faultpoint("tracker.evict")
            except InjectedFault as err:
                logger.warning("eviction of rank %d deferred by injected "
                               "fault: %s", rank, err)
                continue
            self._evicted_ranks.add(rank)
            jobid = self._rank_jobids.get(rank)
            if jobid and jobid != "NULL":
                self._evicted_jobids.add(jobid)
            evicted.append(rank)
            logger.warning("evicting rank %d: no heartbeat for more than "
                           "%.1fs", rank, self.evict_after)
            self.plane.note_membership("evict", rank=rank)
            flight.record_event("member.evict", rank=rank,
                                after_s=self.evict_after)
        if evicted and self._target_version == self.world_version:
            self._target_version = self.world_version + 1
        return evicted

    @staticmethod
    def _release_joiners(joiners: List[Tuple["_Worker", bool]]) -> None:
        """Close parked joiner conns: a closed activation socket is the
        'job finished without needing you' signal (request_join raises
        SpareUnused and the spare process exits cleanly)."""
        for w, _is_spare in joiners:
            w.conn.close()
        joiners.clear()

    def _accept_loop(self, num_workers: int) -> None:
        shutdown: Dict[int, _Worker] = {}
        wait_conn: Dict[int, _Worker] = {}
        job_map: Dict[str, int] = {}
        pending: List[_Worker] = []
        todo: List[int] = []
        tree = parent = ring = None
        # elastic membership state for the open transition: parked joiner
        # conns (warm spares / grow requests) awaiting activation,
        # entrants mid-rendezvous into the next generation, the
        # quiescence deadline, how many joiners were woken into this
        # transition, and whether the spare-backfill pass already ran.
        joiners: List[Tuple[_Worker, bool]] = []  # (conn, is_spare)
        entrants: List[_Worker] = []
        deadline: Optional[float] = None
        activated = 0
        backfilled = False

        def activate(w: _Worker) -> bool:
            """Wake a parked joiner into the pending generation."""
            try:
                w.conn.send_int(self._target_version)
                return True
            except OSError:
                w.conn.close()
                return False

        def call_up(want_spares: int) -> int:
            """Activate every parked grow joiner plus up to
            ``want_spares`` warm spares; dead conns are dropped."""
            nonlocal joiners
            woken = 0
            keep: List[Tuple[_Worker, bool]] = []
            for w, is_spare in joiners:
                if is_spare and want_spares <= 0:
                    keep.append((w, is_spare))
                    continue
                if activate(w):
                    woken += 1
                    if is_spare:
                        want_spares -= 1
            joiners = keep
            return woken

        def commit_generation() -> None:
            """Rebuild the world from the collected entrants: new link
            maps, fresh batch rank assignment, bumped world_version."""
            nonlocal tree, parent, ring, todo, wait_conn, job_map
            nonlocal entrants, deadline, activated, backfilled, num_workers
            new_world = len(entrants)
            self.world_version += 1
            self._target_version = self.world_version
            num_workers = self.num_workers = new_world
            tree, parent, ring = build_link_maps(new_world)
            todo = list(range(new_world))
            wait_conn = {}
            job_map = {}
            batch = sorted(entrants, key=lambda w: w.host)
            entrants = []
            deadline = None
            activated = 0
            backfilled = False
            self._rank_jobids = {}
            for w in batch:
                r = todo.pop(0)
                if w.jobid != "NULL":
                    job_map[w.jobid] = r
                self._rank_jobids[r] = w.jobid
                w.assign_rank(r, wait_conn, tree, parent, ring)
                if w.wait_accept > 0:
                    wait_conn[r] = w
            with self._hb_lock:
                # the rank space was reassigned: stale last-seen entries
                # would flag phantom stragglers in the new generation
                self._last_seen.clear()
                self._hb_info.clear()
                self._hb_flagged.clear()
            self._evicted_ranks.clear()
            self.plane.note_membership(
                "rebuild", world_version=self.world_version, world=new_world)
            flight.record_event("member.rebuild",
                                world_version=self.world_version,
                                world=new_world)
            logger.info("@tracker generation %d committed: world=%d",
                        self.world_version, new_world)

        # the accept timeout is the tracker's clock: transition deadlines
        # and eviction scans must run even when no connection arrives
        self.sock.settimeout(0.25)
        while len(shutdown) < num_workers:
            try:
                fd, addr = self.sock.accept()
            except socket.timeout:
                fd = None
            except OSError:
                # close() pulled the listening socket out from under us:
                # a deliberate stop, not a protocol failure
                self._release_joiners(joiners)
                return
            worker = None
            if fd is not None:
                fd.settimeout(None)  # protocol recvs must block as before
                try:
                    worker = _Worker(fd, addr)
                except ConnectionError as err:
                    logger.warning("rejected connection: %s", err)
                    fd.close()
                    worker = None
            now = time.time()
            if worker is not None and worker.cmd == "print":
                logger.info(worker.conn.recv_str().strip())
                worker = None
            elif worker is not None and worker.cmd == "heartbeat":
                try:
                    payload = worker.conn.recv_str()
                    # ack before processing: the worker measures this
                    # round-trip as the RTT in its clock-skew probe, so
                    # tracker-side parsing time must not inflate it. The
                    # ack value is the target world_version — a worker on
                    # an older generation knows to re-enter at its next
                    # checkpoint boundary (pre-elastic workers ignored
                    # the ack value, so the wire stays compatible).
                    worker.conn.send_int(self._target_version)
                    # second ack frame: the encoded /profile request (0 =
                    # none). Workers that don't opt in never read it —
                    # the bytes die with the one-shot connection, so the
                    # wire stays compatible both directions.
                    worker.conn.send_int(self.plane.profile_word())
                    self._note_heartbeat(worker.rank, payload)
                except (ConnectionError, OSError) as err:
                    logger.warning("heartbeat from %s failed: %s",
                                   worker.host, err)
                finally:
                    worker.conn.close()
                worker = None
            elif worker is not None and worker.cmd == "shutdown":
                assert worker.rank >= 0 and worker.rank not in shutdown
                shutdown[worker.rank] = worker
                logger.debug("shutdown from rank %d", worker.rank)
                worker = None
            elif worker is not None and worker.cmd == "join":
                # warm spare (world_size −1) or scale-up request: ack
                # with the current generation and park the conn until a
                # transition activates it
                is_spare = worker.world_size < 0
                try:
                    worker.conn.send_int(self.world_version)
                except OSError:
                    worker.conn.close()
                else:
                    joiners.append((worker, is_spare))
                    if (not is_spare and tree is not None
                            and self._target_version == self.world_version):
                        # a grow request opens a pending transition;
                        # running workers learn from the heartbeat ack
                        self._target_version = self.world_version + 1
                    self.plane.note_membership(
                        "join", jobid=worker.jobid, spare=is_spare)
                    flight.record_event("member.join", jobid=worker.jobid,
                                        spare=is_spare)
                    logger.info("parked %s joiner %s",
                                "spare" if is_spare else "grow",
                                worker.jobid)
                worker = None
            elif worker is not None and worker.cmd == "elastic":
                refused = (
                    tree is None  # no world to re-enter yet
                    or (worker.jobid != "NULL"
                        and worker.jobid in self._evicted_jobids)
                    or (worker.rank >= 0
                        and worker.rank in self._evicted_ranks)
                )
                if refused:
                    logger.info("refused elastic re-entry from %s (rank %d)",
                                worker.jobid, worker.rank)
                    try:
                        worker.conn.send_int(-1)
                    except OSError:
                        pass
                    worker.conn.close()
                    worker = None
                else:
                    if self._target_version == self.world_version:
                        self._target_version = self.world_version + 1
                    if deadline is None:
                        backfilled = False
                        activated = call_up(0)  # grow joiners ride along
                    try:
                        worker.conn.send_int(self._target_version)
                    except OSError:
                        worker.conn.close()
                    else:
                        entrants.append(worker)
                        deadline = now + self.elastic_window
                    worker = None
            if worker is not None:
                assert worker.cmd in ("start", "recover"), worker.cmd
                if tree is None:
                    assert worker.cmd == "start"
                    if worker.world_size > 0:
                        num_workers = worker.world_size
                        self.num_workers = num_workers
                    tree, parent, ring = build_link_maps(num_workers)
                    todo = list(range(num_workers))
                else:
                    assert worker.world_size in (-1, num_workers)
                if worker.cmd == "recover":
                    assert worker.rank >= 0
                rank = worker.decide_rank(job_map)
                if rank == -1:
                    assert todo, "no unassigned ranks left"
                    pending.append(worker)
                    if len(pending) == len(todo):
                        pending.sort(key=lambda w: w.host)
                        for w in pending:
                            r = todo.pop(0)
                            if w.jobid != "NULL":
                                job_map[w.jobid] = r
                            self._rank_jobids[r] = w.jobid
                            w.assign_rank(r, wait_conn, tree, parent, ring)
                            if w.wait_accept > 0:
                                wait_conn[r] = w
                            logger.debug("assigned rank %d to %s", r, w.host)
                        pending = []
                    if not todo:
                        logger.info("@tracker all %d workers started",
                                    num_workers)
                        if self.start_time is None:
                            self.start_time = time.time()
                        self.world_version += 1  # generation 1
                        self._target_version = self.world_version
                        if any(not s for _, s in joiners):
                            # a grow request parked before the first world
                            # formed: open a transition right away
                            self._target_version = self.world_version + 1
                        self.plane.note_membership(
                            "rebuild", world_version=self.world_version,
                            world=num_workers)
                else:
                    worker.assign_rank(rank, wait_conn, tree, parent, ring)
                    self._rank_jobids[rank] = worker.jobid
                    if worker.wait_accept > 0:
                        wait_conn[rank] = worker
                    logger.debug("%s from rank %d", worker.cmd, rank)
            # ---- elastic bookkeeping: runs on every pass (conn or tick)
            if tree is not None:
                self._evict_scan(now)
            if deadline is not None and entrants:
                expected = num_workers - len(self._evicted_ranks) + activated
                if len(entrants) >= expected:
                    commit_generation()
                elif now >= deadline:
                    if not backfilled:
                        backfilled = True
                        woken = call_up(expected - len(entrants))
                        activated += woken
                        if woken:
                            # give the backfill one window to arrive
                            deadline = now + self.elastic_window
                        else:
                            commit_generation()
                    else:
                        commit_generation()
        self._release_joiners(joiners)
        self.end_time = time.time()
        if self.start_time is not None:
            logger.info(
                "@tracker %.3f secs between node start and job finish",
                self.end_time - self.start_time,
            )

    def start(self, num_workers: Optional[int] = None) -> None:
        n = num_workers if num_workers is not None else self.num_workers
        self.thread = threading.Thread(
            target=self._accept_loop, args=(n,), daemon=True, name="rabit-tracker"
        )
        self.thread.start()

    def join(self, tasks_alive: Optional[Callable[[], bool]] = None,
             grace_s: float = 5.0) -> None:
        """Wait for the job to finish.

        ``tasks_alive`` (from the launcher) reports whether any worker
        process is still running. The reference tracker blocks forever if
        workers die before rendezvous (tracker.py:329-331 joins
        unconditionally); here, once every launcher task has exited while
        the accept loop is still waiting, the job can never complete — fail
        fast with a diagnostic instead of hanging.
        """
        deadline = None
        warned = False
        while self.thread is not None and self.thread.is_alive():
            self.thread.join(0.1)
            if tasks_alive is None or tasks_alive():
                deadline = None
                continue
            now = time.time()
            if deadline is None:
                deadline = now + grace_s  # let in-flight shutdowns drain
            elif now > deadline:
                if self.start_time is None:
                    # Rendezvous never completed: the job cannot make
                    # progress, abort.
                    raise DMLCError(
                        "all worker processes exited but the tracker is "
                        "still waiting for rendezvous — workers likely "
                        "crashed before connecting (check their logs)"
                    )
                # The job DID start; the launched commands may have
                # detached (wrapper scripts, nohup) with real workers
                # still connected — warn once and keep waiting, matching
                # the reference's unconditional join (tracker.py:329-331).
                if not warned:
                    logger.warning(
                        "launcher tasks exited but the job started and has "
                        "not sent all shutdowns; assuming detached workers "
                        "and waiting"
                    )
                    warned = True

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def close(self) -> None:
        self.sock.close()
        if self.status is not None:
            self.status.close()


def send_heartbeat(
    tracker_uri: str,
    tracker_port: int,
    rank: int,
    epoch: int = -1,
    metrics: str = "",
    timeout: float = 10.0,
    obs_json: Optional[str] = None,
    want_profile: bool = False,
):
    """Worker-side heartbeat: one short-lived connection carrying the
    standard handshake with cmd="heartbeat" plus a free-form payload line
    (``epoch=N <metrics>`` — e.g. ``obs.summary_line()``). Waits for the
    tracker's ack so a heartbeat observed by the caller is recorded.

    Returns the ack value: the tracker's *target* ``world_version``. An
    elastic worker compares it to its engine generation — a larger value
    means a membership transition is pending and it should re-enter at
    its next checkpoint boundary (``collective.elastic_sync``).
    Pre-elastic trackers acked a literal 0; treat ``<= generation`` as
    'no change'.

    ``obs_json`` (built by ``obs.plane.build_payload``) rides the same
    string frame behind the ``OBS1`` marker — still one line of opaque
    text to a tracker that does not know the extension.

    ``want_profile=True`` (the obs publisher) additionally reads the
    tracker's second ack frame — the encoded ``/profile`` request word —
    and returns ``(ack, profile_word)``. A tracker predating the frame
    just closes the connection and the word reads as 0, so opting in is
    safe against any tracker. The default leaves the frame unread
    (compatible with the original single-int contract)."""
    sock = socket.create_connection((tracker_uri, tracker_port),
                                    timeout=timeout)
    conn = FramedSocket(sock)
    try:
        conn.send_int(MAGIC)
        magic = conn.recv_int()
        if magic != MAGIC:
            raise ConnectionError(f"invalid tracker magic {magic:#x}")
        conn.send_int(rank)
        conn.send_int(-1)
        conn.send_str("NULL")
        conn.send_str("heartbeat")
        payload = f"epoch={epoch}"
        if metrics:
            payload += " " + metrics
        if obs_json:
            from dmlc_tpu.obs.plane import PAYLOAD_MARK

            payload += PAYLOAD_MARK + obs_json
        conn.send_str(payload)
        ack = conn.recv_int()  # ack: the tracker's target world_version
        if not want_profile:
            return ack
        try:
            profile_word = conn.recv_int()
        except (ConnectionError, OSError, struct.error):
            profile_word = 0  # pre-profile tracker: no second frame
        return ack, profile_word
    finally:
        conn.close()


def request_join(
    tracker_uri: str,
    tracker_port: int,
    jobid: str = "NULL",
    spare: bool = True,
    timeout: Optional[float] = None,
) -> int:
    """Worker-side ``join`` handshake: register as a warm spare (or, with
    ``spare=False``, a scale-up request) and block until the tracker
    activates us into a membership transition.

    Returns the generation to enter — the caller then re-dials with
    ``cmd='elastic'`` (``SocketEngine(cmd="elastic")``) to rendezvous
    into that world. Raises :class:`SpareUnused` when the tracker closes
    the parked connection without activating us: the job finished and
    the spare was never needed, a clean exit rather than a failure.
    ``timeout`` bounds the activation wait (None = as long as the job
    runs). The dial carries a ``tracker.join`` faultpoint so membership
    transitions are chaos-testable end to end."""
    from dmlc_tpu.resilience import RetryPolicy, faultpoint

    def dial() -> FramedSocket:
        faultpoint("tracker.join")
        sock = socket.create_connection((tracker_uri, tracker_port),
                                        timeout=30)
        conn = FramedSocket(sock)
        try:
            conn.send_int(MAGIC)
            got = conn.recv_int()
            if got != MAGIC:
                raise DMLCError(f"invalid tracker magic {got:#x}")
            conn.send_int(-1)
            conn.send_int(-1 if spare else 0)
            conn.send_str(jobid)
            conn.send_str("join")
            conn.recv_int()  # registration ack: the current generation
        except BaseException:
            conn.close()
            raise
        return conn

    # same narrowed classifier as the collective dial: a bad-magic
    # DMLCError means the wrong service, retrying cannot fix it
    conn = RetryPolicy(
        max_attempts=5, base_s=0.2, cap_s=2.0,
        classify=lambda err: isinstance(err, (ConnectionError, OSError)),
    ).call(dial, "tracker.join",
           display=f"tracker {tracker_uri}:{tracker_port}")
    try:
        conn.sock.settimeout(timeout)
        try:
            generation = conn.recv_int()
        except ConnectionError as err:
            raise SpareUnused(
                "tracker closed before activation — the job finished "
                "without needing this joiner") from err
    finally:
        conn.close()
    if generation < 0:
        raise DMLCError("tracker refused the join request")
    return generation


class PSTracker:
    """Parameter-server scheduler bootstrap (tracker.py:336-386): spawns the
    user command as DMLC_ROLE=scheduler and advertises DMLC_PS_ROOT_URI/PORT."""

    def __init__(
        self,
        host_ip: str,
        cmd: Optional[str],
        port: int = 9091,
        port_end: int = 9999,
        envs: Optional[Dict[str, object]] = None,
    ):
        self.cmd = cmd
        self.host_ip = host_ip
        if cmd is None:
            return
        probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.port = None
        for p in range(port, port_end):
            try:
                probe.bind(("", p))
                self.port = p
                probe.close()
                break
            except OSError:
                continue
        assert self.port is not None, "no free scheduler port"
        env = os.environ.copy()
        env["DMLC_ROLE"] = "scheduler"
        env["DMLC_PS_ROOT_URI"] = str(host_ip)
        env["DMLC_PS_ROOT_PORT"] = str(self.port)
        for k, v in (envs or {}).items():
            env[k] = str(v)
        self.thread = threading.Thread(
            target=lambda: subprocess.check_call(self.cmd, env=env, shell=True),
            daemon=True,
            name="ps-scheduler",
        )
        self.thread.start()

    def worker_envs(self) -> Dict[str, object]:
        if self.cmd is None:
            return {}
        return {"DMLC_PS_ROOT_URI": self.host_ip, "DMLC_PS_ROOT_PORT": self.port}

    def alive(self) -> bool:
        return self.cmd is not None and self.thread.is_alive()

    def join(self, tasks_alive: Optional[Callable[[], bool]] = None,
             grace_s: float = 5.0) -> None:
        """Wait for the scheduler to finish.

        Mirrors :meth:`RabitTracker.join`'s liveness contract: a
        scheduler whose worker processes have all died can never finish,
        so once ``tasks_alive`` reports no live tasks for ``grace_s``
        seconds, fail fast with a diagnostic instead of hanging on the
        scheduler thread forever (the old behavior joined
        unconditionally, so one dead PS worker wedged the submit)."""
        if self.cmd is None:
            return
        deadline = None
        while self.thread.is_alive():
            self.thread.join(0.1)
            if tasks_alive is None or tasks_alive():
                deadline = None
                continue
            now = time.time()
            if deadline is None:
                deadline = now + grace_s  # let in-flight exits drain
            elif now > deadline:
                raise DMLCError(
                    "all PS worker processes exited but the scheduler is "
                    "still running — workers likely died before "
                    "registering (check their logs)"
                )


def submit_with_tracker(
    nworker: int,
    nserver: int,
    fun_submit: Callable[[int, int, Dict[str, object]], None],
    host_ip: str = "auto",
    pscmd: Optional[str] = None,
    tasks_alive: Optional[Callable[[], bool]] = None,
) -> None:
    """Start a tracker, hand env vars to the launcher callback, join
    (tracker.py:410-433). ``tasks_alive`` lets process-owning launchers
    (local) report worker liveness so a pre-rendezvous crash aborts the
    job instead of hanging the tracker forever."""
    envs: Dict[str, object] = {
        "DMLC_NUM_WORKER": nworker,
        "DMLC_NUM_SERVER": nserver,
    }
    ip = get_host_ip(host_ip)
    if nserver == 0:
        tracker = RabitTracker(host_ip=ip, num_workers=nworker)
        envs.update(tracker.worker_envs())
        tracker.start(nworker)
        if tracker.alive():
            fun_submit(nworker, nserver, envs)
        tracker.join(tasks_alive=tasks_alive)
    else:
        ps = PSTracker(host_ip=ip, cmd=pscmd, envs=envs)
        envs.update(ps.worker_envs())
        if ps.alive() or pscmd is None:
            fun_submit(nworker, nserver, envs)
        ps.join(tasks_alive=tasks_alive)
