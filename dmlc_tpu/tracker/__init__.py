"""Distributed launch & rendezvous control plane.

Capability parity with tracker/dmlc_tracker/ in the reference: the
``dmlc-submit``-style CLI (opts/submit), the rabit rendezvous tracker
(rank assignment, tree+ring link maps, peer-link brokering, recover), the
parameter-server scheduler bootstrap, and per-cluster launchers — plus the
TPU-new ``--cluster=tpu`` mode that maps rendezvous onto
``jax.distributed.initialize`` and one worker process per TPU host.
"""

from dmlc_tpu.tracker.rendezvous import (
    MAGIC,
    FramedSocket,
    RabitTracker,
    PSTracker,
    submit_with_tracker,
)

__all__ = [
    "MAGIC",
    "FramedSocket",
    "RabitTracker",
    "PSTracker",
    "submit_with_tracker",
]
