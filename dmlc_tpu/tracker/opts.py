"""CLI option surface for ``dmlc-submit``.

Capability parity with tracker/dmlc_tracker/opts.py: the same option names,
defaults, cluster list (plus the new ``tpu`` cluster), memory-string parsing
(opts.py:39-57), automatic file caching that rewrites local paths in the
command to shipped ``./basename`` paths (get_cache_file_set, opts.py:6-36),
and the ``DMLC_SUBMIT_CLUSTER`` env default (opts.py:168-174).
"""

from __future__ import annotations

import argparse
import os
from typing import List, Set, Tuple

CLUSTERS = (
    "local",
    "ssh",
    "mpi",
    "sge",
    "slurm",
    "yarn",
    "mesos",
    "kubernetes",
    "tpu",
)


def get_memory_mb(mem_str: str) -> int:
    """Parse '1g'/'512m'/'2048' into MB (opts.py:39-57)."""
    s = str(mem_str).strip().lower()
    if s.endswith("g"):
        return int(float(s[:-1]) * 1024)
    if s.endswith("m"):
        return int(float(s[:-1]))
    return int(s)


def get_cache_file_set(args) -> Tuple[Set[str], List[str]]:
    """Scan the command for local files to auto-ship (opts.py:6-36).

    Returns (fileset, rewritten_command): each command token naming an
    existing local file is added to the cache set and rewritten to its
    basename (the launcher ships it into the task working directory).
    """
    fset: Set[str] = set()
    for fname in args.files:
        fset.add(fname)
    command: List[str] = []
    for i, tok in enumerate(args.command):
        if args.auto_file_cache and os.path.exists(tok) and os.path.isfile(tok):
            fset.add(tok)
            if i == 0 and tok.endswith(".py"):
                command.append(f"python {os.path.basename(tok)}")
            else:
                command.append(f"./{os.path.basename(tok)}")
        else:
            command.append(tok)
    return fset, command


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dmlc-submit",
        description="Submit a distributed dmlc_tpu job to a cluster.",
    )
    cluster_default = os.environ.get("DMLC_SUBMIT_CLUSTER")
    parser.add_argument(
        "--cluster",
        type=str,
        choices=list(CLUSTERS),
        default=cluster_default,
        required=cluster_default is None,
        help="Cluster backend to submit the job to "
        "(default from DMLC_SUBMIT_CLUSTER).",
    )
    parser.add_argument(
        "-n", "--num-workers", required=True, type=int,
        help="Number of worker processes to launch.",
    )
    parser.add_argument(
        "--worker-cores", default=1, type=int,
        help="CPU cores requested per worker.",
    )
    parser.add_argument(
        "--worker-memory", default="1g", type=str,
        help="Memory per worker, e.g. 1g / 512m.",
    )
    parser.add_argument(
        "-s", "--num-servers", default=0, type=int,
        help="Number of parameter-server processes.",
    )
    parser.add_argument(
        "--server-cores", default=1, type=int,
        help="CPU cores requested per server.",
    )
    parser.add_argument(
        "--server-memory", default="1g", type=str,
        help="Memory per server, e.g. 1g / 512m.",
    )
    parser.add_argument("--jobname", default=None, type=str, help="Job name.")
    parser.add_argument(
        "--queue", default="default", type=str, help="Cluster queue to submit to."
    )
    parser.add_argument(
        "--log-level", default="INFO", type=str,
        choices=["INFO", "DEBUG"], help="Logging level.",
    )
    parser.add_argument("--log-file", default=None, type=str,
                        help="Also append tracker logs to this file.")
    parser.add_argument(
        "--host-ip", default=None, type=str,
        help="Tracker IP the workers connect back to.",
    )
    parser.add_argument(
        "-H", "--host-file", default=None, type=str,
        help="Hostfile (one 'ip[:port]' per line) for ssh/mpi/tpu clusters.",
    )
    parser.add_argument(
        "--sge-log-dir", default=None, type=str,
        help="Directory for SGE stdout/stderr logs.",
    )
    parser.add_argument(
        "--auto-file-cache", default=True, type=lambda s: s not in ("0", "false"),
        help="Auto-ship local files named in the command.",
    )
    parser.add_argument(
        "--files", default=[], action="append",
        help="Extra files to ship to the task directory.",
    )
    parser.add_argument(
        "--archives", default=[], action="append",
        help="Archives to ship and unpack in the task directory.",
    )
    parser.add_argument(
        "--env", action="append", default=[],
        help="Extra NAME=VALUE env vars to forward to tasks.",
    )
    parser.add_argument(
        "--hdfs-tempdir", default="/tmp", type=str,
        help="Temp directory on the shared FS for shipped files.",
    )
    parser.add_argument(
        "--ship-libcxx", default=None, type=str,
        help="Path to a libstdc++ directory to ship with the job.",
    )
    parser.add_argument(
        "--sync-dst-dir", default=None, type=str,
        help="rsync the current directory to this path on every host first.",
    )
    parser.add_argument(
        "--slurm-worker-nodes", default=None, type=int,
        help="Node count for the worker srun allocation.",
    )
    parser.add_argument(
        "--slurm-server-nodes", default=None, type=int,
        help="Node count for the server srun allocation.",
    )
    parser.add_argument(
        "--mesos-master", default=None, type=str, help="Mesos master URI."
    )
    parser.add_argument(
        "--kube-namespace", default="default", type=str,
        help="Kubernetes namespace.",
    )
    parser.add_argument(
        "--kube-worker-image", default="python:3.11", type=str,
        help="Container image for workers.",
    )
    parser.add_argument(
        "--kube-server-image", default="python:3.11", type=str,
        help="Container image for servers.",
    )
    parser.add_argument(
        "--yarn-app-classpath", default=None, type=str,
        help="Override YARN application classpath.",
    )
    # --- tpu cluster options (new; no reference analog) ---
    parser.add_argument(
        "--tpu-coordinator-port", default=8476, type=int,
        help="Port for jax.distributed coordination on host 0.",
    )
    parser.add_argument(
        "--tpu-hosts", default=None, type=str,
        help="Comma-separated TPU host list; default from --host-file, "
        "TPU_WORKER_HOSTNAMES, else localhost.",
    )
    parser.add_argument(
        "--max-attempts", default=None, type=int,
        help="Per-task restart attempts (DMLC_NUM_ATTEMPT / DMLC_MAX_ATTEMPT).",
    )
    parser.add_argument(
        "--status-port", default=None, type=int,
        help="Start the tracker HTTP status server on this port "
        "(0 = ephemeral; sets DMLC_TPU_STATUS_PORT). Serves /healthz, "
        "/workers, /metrics, /trace.",
    )
    parser.add_argument(
        "--elastic", action="store_true", default=False,
        help="Enable elastic membership (sets DMLC_TPU_ELASTIC): workers "
        "may join, be evicted, and be replaced mid-run at rendezvous "
        "boundaries instead of failing the job.",
    )
    parser.add_argument(
        "--spares", default=0, type=int,
        help="Warm-spare worker tasks to launch beyond --num-workers; they "
        "park on the tracker's join handshake until a membership "
        "transition calls them up (local cluster only).",
    )
    parser.add_argument(
        "command", nargs=argparse.REMAINDER,
        help="Command to launch on every task.",
    )
    return parser


def get_opts(argv=None) -> argparse.Namespace:
    """Parse argv into a namespace; normalizes env list and memory fields."""
    args, unknown = build_parser().parse_known_args(argv)
    # argparse.REMAINDER can swallow a leading '--' separator; drop only
    # that one — inner '--' tokens belong to the user's command
    rem = list(args.command or [])
    if rem and rem[0] == "--":
        rem = rem[1:]
    args.command = rem + list(unknown)
    if not args.command:
        raise ValueError("no command to launch — pass it after the options")
    args.worker_memory_mb = get_memory_mb(args.worker_memory)
    args.server_memory_mb = get_memory_mb(args.server_memory)
    env_pairs = {}
    for item in args.env:
        if "=" not in item:
            raise ValueError(f"--env expects NAME=VALUE, got {item!r}")
        k, v = item.split("=", 1)
        env_pairs[k] = v
    args.env_map = env_pairs
    return args
