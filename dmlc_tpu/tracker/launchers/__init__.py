"""Per-cluster launchers for dmlc-submit.

Each module exposes ``submit(args)``, mirroring the per-cluster submit
functions of tracker/dmlc_tracker/{local,ssh,mpi,sge,slurm,yarn,mesos,
kubernetes}.py — plus the new ``tpu`` launcher (SURVEY §2.8 "TPU mapping"),
which discovers TPU pod topology and boots one worker per TPU host with
jax.distributed coordination env.

For testability every launcher that shells out builds its commands through
pure ``plan_*`` functions that tests can assert on without a cluster.
"""

from __future__ import annotations

from importlib import import_module

_LAUNCHERS = {
    "local": "dmlc_tpu.tracker.launchers.local",
    "ssh": "dmlc_tpu.tracker.launchers.ssh",
    "mpi": "dmlc_tpu.tracker.launchers.mpi",
    "sge": "dmlc_tpu.tracker.launchers.sge",
    "slurm": "dmlc_tpu.tracker.launchers.slurm",
    "yarn": "dmlc_tpu.tracker.launchers.yarn",
    "mesos": "dmlc_tpu.tracker.launchers.mesos",
    "kubernetes": "dmlc_tpu.tracker.launchers.kubernetes",
    "tpu": "dmlc_tpu.tracker.launchers.tpu",
}


def get_launcher(cluster: str):
    """Return the launcher module for a cluster name (submit.py:43-56)."""
    if cluster not in _LAUNCHERS:
        raise ValueError(
            f"unknown cluster {cluster!r}; choose from {sorted(_LAUNCHERS)}"
        )
    return import_module(_LAUNCHERS[cluster])
