"""Shared helpers for launchers: env assembly and shell quoting.

Mirrors the env-forwarding conventions of the reference launchers: every
task receives the tracker envs plus DMLC_TASK_ID / DMLC_ROLE /
DMLC_JOB_CLUSTER (local.py:12-44, ssh.py:55-79) and a pass-through set of
performance/cloud env vars (ssh.py:26-31).
"""

from __future__ import annotations

import os
import shlex
from typing import Dict, Optional

# env vars forwarded from the submitter's environment when set (ssh.py:26-31)
PASS_ENV_KEYS = (
    "OMP_NUM_THREADS",
    "KMP_AFFINITY",
    "LD_LIBRARY_PATH",
    "PYTHONPATH",
    "AWS_ACCESS_KEY_ID",
    "AWS_SECRET_ACCESS_KEY",
    "GOOGLE_APPLICATION_CREDENTIALS",
    "JAX_PLATFORMS",
    "XLA_FLAGS",
    "DMLC_INTERFACE",
)


def task_env(
    base_envs: Dict[str, object],
    task_id: int,
    role: str,
    cluster: str,
    extra: Optional[Dict[str, str]] = None,
    attempt: int = 0,
) -> Dict[str, str]:
    """Full env map for one task (local.py:12-30)."""
    env = {k: str(v) for k, v in base_envs.items()}
    env["DMLC_TASK_ID"] = str(task_id)
    env["DMLC_ROLE"] = role
    env["DMLC_JOB_CLUSTER"] = cluster
    env["DMLC_NUM_ATTEMPT"] = str(attempt)
    for key in PASS_ENV_KEYS:
        if key in os.environ and key not in env:
            env[key] = os.environ[key]
    if extra:
        env.update(extra)
    return env


def export_prefix(env: Dict[str, str]) -> str:
    """`export k=v; …` shell prefix for remote execution (ssh.py:72-79)."""
    parts = [f"export {k}={shlex.quote(str(v))};" for k, v in sorted(env.items())]
    return " ".join(parts)
