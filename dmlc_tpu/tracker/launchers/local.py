"""Local launcher: N processes on this host (tracker/dmlc_tracker/local.py).

Spawns num_workers + num_servers subprocesses, each with the DMLC_* env
contract (DMLC_TASK_ID, DMLC_ROLE, DMLC_JOB_CLUSTER=local — local.py:12-23)
and a per-task retry loop honoring ``--max-attempts`` / ``DMLC_NUM_ATTEMPT``
(local.py:25-44).
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Dict, List

from dmlc_tpu.resilience.preempt import EXIT_PREEMPTED
from dmlc_tpu.tracker.launchers.common import task_env
from dmlc_tpu.tracker.rendezvous import submit_with_tracker

#: relaunch-after-preemption ceiling: exit-75 restarts do not consume
#: --max-attempts (a preempted task did nothing wrong), but an unbounded
#: loop would hide a task that exits 75 pathologically
MAX_PREEMPT_RELAUNCHES = 32


def submit(args) -> None:
    nrepeat = args.max_attempts or int(os.environ.get("DMLC_NUM_ATTEMPT", 1))
    cmd = " ".join(args.command)
    threads: List[threading.Thread] = []

    def run_task(task_id: int, role: str, envs: Dict[str, object],
                 spare: bool = False) -> None:
        extra = dict(args.env_map)
        if spare:
            # DMLC_TPU_SPARE makes collective.init() park on the tracker's
            # join handshake instead of rendezvousing immediately
            extra["DMLC_TPU_SPARE"] = "1"
        env = task_env(envs, task_id, role, "local", extra=extra)
        attempts = max(1, nrepeat)
        preempt_relaunches = 0
        while attempts > 0:
            full = os.environ.copy()
            full.update(env)
            full["DMLC_NUM_ATTEMPT"] = str(max(1, nrepeat) - attempts)
            code = subprocess.Popen(cmd, env=full, shell=True).wait()
            if code == 0:
                return
            if (code == EXIT_PREEMPTED
                    and preempt_relaunches < MAX_PREEMPT_RELAUNCHES):
                # the preemption handler committed a job snapshot and
                # exited with the relaunch code: restart WITHOUT burning
                # a retry attempt — the relaunched task resumes from the
                # committed manifest (docs/robustness.md)
                preempt_relaunches += 1
                print(f"{role} {task_id} preempted (exit {code}); "
                      f"relaunching to resume from its job snapshot "
                      f"(relaunch {preempt_relaunches})")
                continue
            flight_dir = full.get("DMLC_TPU_FLIGHTREC")
            if flight_dir:
                print(f"{role} {task_id} exited {code}; flight-recorder "
                      f"dump (if any): "
                      f"{flight_dir}/flightrec-rank{task_id}.json")
            attempts -= 1
            if attempts > 0:
                print(f"{role} {task_id} exited {code}; retrying "
                      f"({attempts} attempts left)")

    def fun_submit(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        for i in range(nworker + nserver):
            role = "worker" if i < nworker else "server"
            tid = i if i < nworker else i - nworker
            t = threading.Thread(
                target=run_task, args=(tid, role, envs), daemon=True
            )
            t.start()
            threads.append(t)
        # warm spares: worker-role tasks beyond the base world, with task
        # ids (= rabit jobids) that can never collide with real workers
        for j in range(max(0, getattr(args, "spares", 0) or 0)):
            t = threading.Thread(
                target=run_task, args=(nworker + j, "worker", envs),
                kwargs={"spare": True}, daemon=True,
            )
            t.start()
            threads.append(t)

    submit_with_tracker(
        args.num_workers,
        args.num_servers,
        fun_submit,
        host_ip=args.host_ip or "auto",
        # threads own the worker processes: once they are all done while the
        # tracker still waits, the job can never finish — fail fast.
        tasks_alive=lambda: any(t.is_alive() for t in threads),
    )
    for t in threads:
        t.join()
