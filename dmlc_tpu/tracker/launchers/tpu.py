"""TPU launcher: ``dmlc-submit --cluster=tpu`` (SURVEY §2.8 "TPU mapping").

New in this framework (no reference analog — the reference predates TPUs):
discovers the TPU pod topology, spawns ONE worker process per TPU host, and
exports both the classic ``DMLC_*`` contract (so rabit-style control-plane
code keeps working) and the jax.distributed coordination contract:

- ``DMLC_TPU_COORDINATOR``  host0:port for jax.distributed.initialize
- ``DMLC_TPU_NUM_PROC``     number of TPU hosts (processes)
- ``DMLC_TPU_PROC_ID``      this host's process index (== DMLC_TASK_ID)

Workers call :func:`dmlc_tpu.parallel.initialize_from_env` which turns these
into ``jax.distributed.initialize(...)``; after that ``jax.devices()`` spans
the pod and collectives ride ICI (the socket tree/ring of the reference
tracker is replaced by XLA AllReduce — SURVEY §5.8).

The tracker's ``recover`` path (tracker.py:279-291) maps to the per-task
restart loop below + elastic jax.distributed re-init + checkpoint restore:
the JAX runtime is fail-stop (a dead peer terminates the survivors), so
every terminated worker exits nonzero — including exit 41 from
``reinit_recover``'s hung-re-init watchdog — and ``run_task`` relaunches it
with ``DMLC_NUM_ATTEMPT`` bumped; the relaunched processes rendezvous in
``initialize_from_env`` on the same coordinator and resume from the shared
checkpoint URI (``dmlc_tpu.collective.run_with_recovery`` round contract;
proven end to end in tests/test_device_recovery.py).

Host discovery order: --tpu-hosts, --host-file, ``TPU_WORKER_HOSTNAMES``
(set by Cloud TPU runtimes), else single-host localhost.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import threading
from typing import Dict, List, Tuple

from dmlc_tpu.tracker.launchers.common import export_prefix, task_env
from dmlc_tpu.tracker.launchers.ssh import parse_hostfile
from dmlc_tpu.tracker.rendezvous import submit_with_tracker

LOCAL_HOSTS = ("localhost", "127.0.0.1", "::1")


def discover_hosts(args) -> List[Tuple[str, int]]:
    """[(host, ssh_port)] for every TPU host in the pod."""
    if getattr(args, "tpu_hosts", None):
        return [(h.strip(), 22) for h in args.tpu_hosts.split(",") if h.strip()]
    if getattr(args, "host_file", None):
        return parse_hostfile(args.host_file)
    env_hosts = os.environ.get("TPU_WORKER_HOSTNAMES")
    if env_hosts:
        return [(h.strip(), 22) for h in env_hosts.split(",") if h.strip()]
    return [("localhost", 22)]


def coordination_env(
    hosts: List[Tuple[str, int]], proc_id: int, port: int
) -> Dict[str, str]:
    """The jax.distributed bootstrap triple for one host."""
    coord_host = hosts[0][0]
    if coord_host in LOCAL_HOSTS:
        coord_host = "127.0.0.1"
    return {
        "DMLC_TPU_COORDINATOR": f"{coord_host}:{port}",
        "DMLC_TPU_NUM_PROC": str(len(hosts)),
        "DMLC_TPU_PROC_ID": str(proc_id),
    }


def plan(args, nworker: int, nserver: int, envs: Dict[str, object]):
    """[(host, ssh_port, task_id, env, argv_or_none)] — argv None ⇒ local."""
    if nserver != 0:
        raise ValueError(
            "--cluster=tpu does not run parameter servers: sharded state "
            "lives in pjit-partitioned arrays on the chips (use "
            "--num-servers=0; see SURVEY §2.9 PS mapping)"
        )
    hosts = discover_hosts(args)
    if nworker != len(hosts):
        # one process per TPU host is the contract; mismatch is an error the
        # user should see early, not a silent reshard
        raise ValueError(
            f"--cluster=tpu launches one worker per TPU host: "
            f"--num-workers={nworker} but {len(hosts)} hosts discovered "
            f"({[h for h, _ in hosts]})"
        )
    out = []
    for i, (host, port) in enumerate(hosts):
        env = task_env(envs, i, "worker", "tpu", extra=args.env_map)
        env.update(coordination_env(hosts, i, args.tpu_coordinator_port))
        if host in LOCAL_HOSTS:
            out.append((host, port, i, env, None))
        else:
            argv = ssh_argv(host, port, env, " ".join(args.command))
            out.append((host, port, i, env, argv))
    return out


def ssh_argv(host: str, port: int, env: Dict[str, str], cmd: str) -> List[str]:
    remote = f"{export_prefix(env)} cd {shlex.quote(os.getcwd())}; {cmd}"
    return ["ssh", "-o", "StrictHostKeyChecking=no", "-p", str(port), host,
            remote]


def submit(args) -> None:
    attempts_per_task = max(1, args.max_attempts or 1)
    cmd = " ".join(args.command)
    threads: List[threading.Thread] = []

    def run_task(host: str, port: int, env: Dict[str, str], local: bool) -> None:
        remaining = attempts_per_task
        while remaining > 0:
            env = dict(env)
            env["DMLC_NUM_ATTEMPT"] = str(attempts_per_task - remaining)
            if local:
                full = os.environ.copy()
                full.update(env)
                code = subprocess.Popen(cmd, env=full, shell=True).wait()
            else:
                # rebuild per attempt so the remote sees the attempt counter
                code = subprocess.Popen(ssh_argv(host, port, env, cmd)).wait()
            if code == 0:
                return
            remaining -= 1
            if remaining > 0:
                print(f"tpu host task exited {code}; restarting "
                      f"({remaining} attempts left)")

    def fun_submit(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        for host, port, tid, env, argv in plan(args, nworker, nserver, envs):
            t = threading.Thread(
                target=run_task, args=(host, port, env, argv is None),
                daemon=True,
            )
            t.start()
            threads.append(t)

    submit_with_tracker(
        args.num_workers, args.num_servers, fun_submit,
        host_ip=args.host_ip or "auto",
    )
    for t in threads:
        t.join()
