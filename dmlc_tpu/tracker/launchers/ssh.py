"""SSH launcher (tracker/dmlc_tracker/ssh.py).

Round-robins tasks over a hostfile of ``ip[:port]`` lines (ssh.py:38-53),
optionally rsyncs the working directory to every host first (sync_dir,
ssh.py:13-21), and launches each task as
``ssh -p port host 'export ENV…; cd dir; cmd'`` (ssh.py:72-79).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import threading
from typing import Dict, List, Tuple

from dmlc_tpu.tracker.launchers.common import export_prefix, task_env
from dmlc_tpu.tracker.rendezvous import submit_with_tracker


def parse_hostfile(path: str) -> List[Tuple[str, int]]:
    """Hostfile lines 'ip[:port]' → [(host, ssh_port)] (ssh.py:38-53)."""
    hosts: List[Tuple[str, int]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if ":" in line:
                host, port = line.rsplit(":", 1)
                hosts.append((host, int(port)))
            else:
                hosts.append((line, 22))
    if not hosts:
        raise ValueError(f"hostfile {path} has no hosts")
    return hosts


def sync_dir(local_dir: str, host: str, port: int, dst_dir: str) -> List[str]:
    """rsync command shipping local_dir to host:dst_dir (ssh.py:13-21)."""
    return [
        "rsync", "-az", "--rsh", f"ssh -o StrictHostKeyChecking=no -p {port}",
        local_dir + "/", f"{host}:{dst_dir}",
    ]


def plan_ssh_command(
    host: str,
    port: int,
    env: Dict[str, str],
    command: str,
    workdir: str,
) -> List[str]:
    """The ssh invocation for one task (ssh.py:72-79)."""
    remote = f"{export_prefix(env)} cd {shlex.quote(workdir)}; {command}"
    return [
        "ssh", "-o", "StrictHostKeyChecking=no", "-p", str(port), host, remote,
    ]


def plan(args, nworker: int, nserver: int, envs: Dict[str, object]):
    """Pure command plan: [(role, task_id, argv)] for tests and execution."""
    hosts = parse_hostfile(args.host_file)
    workdir = (
        args.sync_dst_dir if args.sync_dst_dir else os.getcwd()
    )
    cmd = " ".join(args.command)
    out = []
    for i in range(nworker + nserver):
        role = "worker" if i < nworker else "server"
        tid = i if i < nworker else i - nworker
        host, port = hosts[i % len(hosts)]
        env = task_env(envs, tid, role, "ssh", extra=args.env_map)
        out.append((role, tid, plan_ssh_command(host, port, env, cmd, workdir)))
    return out


def submit(args) -> None:
    if not args.host_file:
        raise ValueError("ssh cluster needs --host-file")
    if args.sync_dst_dir:
        for host, port in parse_hostfile(args.host_file):
            subprocess.check_call(sync_dir(os.getcwd(), host, port,
                                           args.sync_dst_dir))
    threads: List[threading.Thread] = []

    def fun_submit(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        for role, tid, argv in plan(args, nworker, nserver, envs):
            t = threading.Thread(
                target=lambda a=argv: subprocess.Popen(a).wait(), daemon=True
            )
            t.start()
            threads.append(t)

    submit_with_tracker(
        args.num_workers, args.num_servers, fun_submit,
        host_ip=args.host_ip or "auto",
        # every ssh session exiting while rendezvous is incomplete means the
        # job can never start — abort instead of hanging (rendezvous.join)
        tasks_alive=lambda: any(t.is_alive() for t in threads),
    )
    for t in threads:
        t.join()
