"""YARN retry/blacklist controller — the Java ApplicationMaster's failure
policy, in-repo (ApplicationMaster.java:76 maxNumAttempt, :212-213
DMLC_MAX_ATTEMPT, :332-354 onContainersCompleted: attempt counter + node
blacklist + re-queue + abort past the budget).

Two layers, both usable without the AM jar:

- ``RetryController``: the pure policy. Task records carry attempt counts;
  a failure blacklists the node it ran on and re-queues the task; a task
  exceeding ``max_attempt`` aborts the job. Cluster-agnostic — the local
  and tpu launchers could drive it too.
- ``drive_app``: an application-level driver that polls the YARN
  ResourceManager REST API (``/ws/v1/cluster/apps/{id}``) the way the AM
  polls the RM callbacks: submit → watch state → on failure, blacklist
  the failing attempt's nodes and resubmit (fresh attempt), up to the
  budget. This is how the behavior exists here even when the cluster only
  accepts plain app submissions.
"""

from __future__ import annotations

import json
import os
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from dmlc_tpu.utils.logging import DMLCError, log_info


def default_max_attempt() -> int:
    """DMLC_MAX_ATTEMPT, default 3 (ApplicationMaster.java:76,212-213)."""
    return int(os.environ.get("DMLC_MAX_ATTEMPT", 3))


@dataclass
class TaskRecord:
    task_id: int
    role: str = "worker"
    attempts: int = 0
    node: Optional[str] = None
    done: bool = False


@dataclass
class RetryController:
    """Pure AM failure policy: blacklist + bounded per-task retries."""

    num_tasks: int
    max_attempt: int = field(default_factory=default_max_attempt)
    blacklist: Set[str] = field(default_factory=set)
    aborted: Optional[str] = None  # abort reason, None while healthy

    def __post_init__(self):
        self.records: Dict[int, TaskRecord] = {
            i: TaskRecord(i) for i in range(self.num_tasks)
        }
        self._pending: List[int] = list(range(self.num_tasks))

    # ---- scheduling side ----------------------------------------------

    def pending(self) -> List[int]:
        """Task ids awaiting (re)launch, in order."""
        return list(self._pending)

    def allowed_node(self, node: str) -> bool:
        return node not in self.blacklist

    def assigned(self, task_id: int, node: str) -> None:
        """A task was placed on ``node`` (container allocated)."""
        if task_id in self._pending:
            self._pending.remove(task_id)
        self.records[task_id].node = node

    # ---- completion side ----------------------------------------------

    def completed(self, task_id: int, exit_code: int) -> None:
        """Container finished. Success retires the task; failure counts the
        attempt, blacklists the node, re-queues — or aborts past budget
        (onContainersCompleted, ApplicationMaster.java:332-354)."""
        rec = self.records[task_id]
        if exit_code == 0:
            rec.done = True
            return
        rec.attempts += 1
        if rec.node is not None:
            self.blacklist.add(rec.node)
            log_info(
                "yarn-controller: task %d failed on %s (attempt %d); "
                "node blacklisted", task_id, rec.node, rec.attempts,
            )
        rec.node = None
        if rec.attempts >= self.max_attempt:
            self.aborted = (
                f"task {task_id} failed {rec.attempts} times "
                f"(max_attempt={self.max_attempt})"
            )
            return
        self._pending.append(rec.task_id)

    @property
    def finished(self) -> bool:
        return all(r.done for r in self.records.values())

    def check_healthy(self) -> None:
        if self.aborted:
            raise DMLCError(f"[DMLC] job aborted: {self.aborted}")


# ---------------------------------------------------------------------------
# Application-level REST driver
# ---------------------------------------------------------------------------


def _rest_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def app_state(rm_url: str, app_id: str) -> dict:
    """{state, finalStatus, diagnostics} for one application."""
    doc = _rest_json(f"{rm_url.rstrip('/')}/ws/v1/cluster/apps/{app_id}")
    return doc.get("app", {})


def app_attempt_nodes(rm_url: str, app_id: str) -> List[str]:
    """Hosts of the application's attempts (the nodes to blacklist when the
    app failed there)."""
    doc = _rest_json(
        f"{rm_url.rstrip('/')}/ws/v1/cluster/apps/{app_id}/appattempts"
    )
    attempts = (doc.get("appAttempts") or {}).get("appAttempt") or []
    return [a["nodeHttpAddress"] for a in attempts if a.get("nodeHttpAddress")]


def drive_app(
    rm_url: str,
    submit_fn: Callable[[Set[str]], str],
    max_attempt: Optional[int] = None,
    poll_interval_s: float = 5.0,
    timeout_s: float = 24 * 3600,
) -> str:
    """Submit and babysit a YARN application with AM-style retries.

    ``submit_fn(blacklist) -> app_id`` performs one submission, honoring
    the blacklisted hosts (e.g. via the node-label/placement args of the
    submission command). The driver polls the RM REST API until the app
    finishes; a FAILED/KILLED attempt adds its nodes to the blacklist and
    resubmits, up to ``max_attempt`` (DMLC_MAX_ATTEMPT). Returns the
    succeeding app id, or raises DMLCError with the final diagnostics.
    """
    budget = max_attempt if max_attempt is not None else default_max_attempt()
    blacklist: Set[str] = set()
    deadline = time.monotonic() + timeout_s
    last_diag = ""
    for attempt in range(budget):
        app_id = submit_fn(set(blacklist))
        log_info("yarn-controller: submitted %s (attempt %d/%d)",
                 app_id, attempt + 1, budget)
        while True:
            if time.monotonic() > deadline:
                raise DMLCError(
                    f"[DMLC] yarn app {app_id} timed out after {timeout_s}s"
                )
            info = app_state(rm_url, app_id)
            state = info.get("state")
            if state in ("FINISHED", "FAILED", "KILLED"):
                break
            time.sleep(poll_interval_s)
        final = info.get("finalStatus")
        if state == "FINISHED" and final == "SUCCEEDED":
            return app_id
        last_diag = info.get("diagnostics", "")
        try:
            failed_nodes = app_attempt_nodes(rm_url, app_id)
        except Exception:  # attempts endpoint is best-effort
            failed_nodes = []
        for node in failed_nodes:
            blacklist.add(node)
        log_info(
            "yarn-controller: app %s %s/%s; blacklisting %s",
            app_id, state, final, failed_nodes,
        )
    raise DMLCError(
        f"[DMLC] yarn job failed {budget} times "
        f"(max_attempt={budget}); last diagnostics: {last_diag}"
    )
