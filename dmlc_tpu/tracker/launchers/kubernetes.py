"""Kubernetes launcher (tracker/dmlc_tracker/kubernetes.py).

Builds Job manifests for workers/servers (+ a scheduler Service when
num_servers > 0, sched port 9091 — kubernetes.py:29) and applies them with
the kubernetes Python client when available, else ``kubectl apply -f -``.
The manifest builders are pure for testability.
"""

from __future__ import annotations

import json
import subprocess
from typing import Dict, List

from dmlc_tpu.tracker.launchers.common import task_env
from dmlc_tpu.tracker.rendezvous import submit_with_tracker

SCHED_PORT = 9091


def plan_job_manifest(
    args,
    role: str,
    count: int,
    envs: Dict[str, object],
    image: str,
) -> Dict:
    """A batch/v1 Job with `completions=count` indexed pods for one role."""
    env = task_env(envs, 0, role, "kubernetes", extra=args.env_map)
    env.pop("DMLC_TASK_ID", None)
    env_list = [{"name": k, "value": str(v)} for k, v in sorted(env.items())]
    # JOB_COMPLETION_INDEX (indexed Jobs) becomes DMLC_TASK_ID in-container
    env_list.append({
        "name": "DMLC_TASK_ID",
        "valueFrom": {"fieldRef": {
            "fieldPath": "metadata.annotations['batch.kubernetes.io/job-completion-index']"
        }},
    })
    name = f"{args.jobname or 'dmlc-job'}-{role}"
    resources = {
        "requests": {
            "cpu": str(args.worker_cores if role == "worker" else args.server_cores),
            "memory": f"{args.worker_memory_mb if role == 'worker' else args.server_memory_mb}Mi",
        }
    }
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name, "namespace": args.kube_namespace},
        "spec": {
            "completions": count,
            "parallelism": count,
            "completionMode": "Indexed",
            "backoffLimit": (args.max_attempts or 3) * count,
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "restartPolicy": "Never",
                    "containers": [{
                        "name": role,
                        "image": image,
                        "command": ["/bin/sh", "-c", " ".join(args.command)],
                        "env": env_list,
                        "resources": resources,
                    }],
                },
            },
        },
    }


def plan_scheduler_service(args) -> Dict:
    """Service exposing the PS scheduler port (kubernetes.py:29)."""
    name = f"{args.jobname or 'dmlc-job'}-sched"
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": args.kube_namespace},
        "spec": {
            "selector": {"app": f"{args.jobname or 'dmlc-job'}-server"},
            "ports": [{"port": SCHED_PORT, "targetPort": SCHED_PORT}],
        },
    }


def plan(args, nworker: int, nserver: int, envs: Dict[str, object]) -> List[Dict]:
    manifests = []
    if nserver > 0:
        manifests.append(plan_scheduler_service(args))
        manifests.append(
            plan_job_manifest(args, "server", nserver, envs,
                              args.kube_server_image)
        )
    if nworker > 0:
        manifests.append(
            plan_job_manifest(args, "worker", nworker, envs,
                              args.kube_worker_image)
        )
    return manifests


def submit(args) -> None:
    def fun_submit(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        manifests = plan(args, nworker, nserver, envs)
        payload = "\n---\n".join(json.dumps(m) for m in manifests)
        subprocess.run(
            ["kubectl", "apply", "-f", "-"],
            input=payload.encode(), check=True,
        )

    submit_with_tracker(
        args.num_workers, args.num_servers, fun_submit,
        host_ip=args.host_ip or "auto",
    )
