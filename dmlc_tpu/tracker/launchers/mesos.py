"""Mesos launcher (tracker/dmlc_tracker/mesos.py).

The reference drives pymesos (or plain subprocess fallback) to launch one
task per worker/server with cpus/mem resources. pymesos is not available in
this image, so this launcher provides the task-plan surface (pure, tested)
and executes it through pymesos only when importable; otherwise it raises
with a clear message.
"""

from __future__ import annotations

from typing import Dict, List

from dmlc_tpu.tracker.launchers.common import task_env
from dmlc_tpu.tracker.rendezvous import submit_with_tracker


def plan(args, nworker: int, nserver: int, envs: Dict[str, object]) -> List[Dict]:
    """[{name, role, task_id, cpus, mem_mb, env, command}] per task."""
    tasks = []
    for i in range(nworker + nserver):
        role = "worker" if i < nworker else "server"
        tid = i if i < nworker else i - nworker
        env = task_env(envs, tid, role, "mesos", extra=args.env_map)
        tasks.append({
            "name": f"{args.jobname or 'dmlc-job'}-{role}-{tid}",
            "role": role,
            "task_id": tid,
            "cpus": args.worker_cores if role == "worker" else args.server_cores,
            "mem_mb": (args.worker_memory_mb if role == "worker"
                       else args.server_memory_mb),
            "env": env,
            "command": " ".join(args.command),
        })
    return tasks


def submit(args) -> None:
    if not args.mesos_master:
        raise ValueError("mesos cluster needs --mesos-master")
    try:
        import pymesos  # noqa: F401
    except ImportError as err:
        raise RuntimeError(
            "mesos launcher requires the pymesos package, which is not "
            "installed in this environment"
        ) from err

    def fun_submit(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        raise NotImplementedError(
            "pymesos scheduler drive-loop not wired in this build"
        )

    submit_with_tracker(
        args.num_workers, args.num_servers, fun_submit,
        host_ip=args.host_ip or "auto",
    )
