"""Mesos launcher (tracker/dmlc_tracker/mesos.py).

The reference drives one task per worker/server with cpus/mem resources
through pymesos.subprocess, falling back to the ``mesos-execute`` CLI when
pymesos is absent (mesos.py:17-57), under a started tracker
(mesos.py:66-104). Same structure here: ``plan()`` is the pure task list
(surface-tested without a cluster), ``submit()`` starts the tracker and
drives every task on a daemon thread through the best available runner.
The runner is injectable (``runner=`` / ``_pick_runner``) so the drive
loop itself is unit-testable with a fake scheduler.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import threading
import uuid
from typing import Callable, Dict, List, Optional

from dmlc_tpu.tracker.launchers.common import task_env
from dmlc_tpu.tracker.rendezvous import submit_with_tracker

# env passed through to tasks beyond the DMLC_* contract (mesos.py:60-63)
_PASSTHROUGH = ("OMP_NUM_THREADS", "KMP_AFFINITY", "LD_LIBRARY_PATH")


def plan(args, nworker: int, nserver: int, envs: Dict[str, object]) -> List[Dict]:
    """[{name, role, task_id, cpus, mem_mb, env, command}] per task."""
    tasks = []
    for i in range(nworker + nserver):
        role = "worker" if i < nworker else "server"
        tid = i if i < nworker else i - nworker
        env = task_env(envs, tid, role, "mesos", extra=args.env_map)
        for key in _PASSTHROUGH:
            if key in os.environ:
                env.setdefault(key, os.environ[key])
        tasks.append({
            "name": f"{args.jobname or 'dmlc-job'}-{role}-{tid}",
            "role": role,
            "task_id": tid,
            "cpus": args.worker_cores if role == "worker" else args.server_cores,
            "mem_mb": (args.worker_memory_mb if role == "worker"
                       else args.server_memory_mb),
            "env": env,
            "command": " ".join(args.command),
        })
    return tasks


def _run_pymesos(task: Dict) -> None:
    import pymesos.subprocess  # noqa: PLC0415 — optional dependency

    env = {str(k): str(v) for k, v in task["env"].items()}
    pymesos.subprocess.check_call(
        task["command"], shell=True, env=env, cwd=os.getcwd(),
        cpus=task["cpus"], mem=task["mem_mb"],
    )


def _run_mesos_execute(task: Dict) -> None:
    """CLI fallback: the reference's mesos-execute shape (mesos.py:32-56)."""
    master = os.environ["MESOS_MASTER"]
    if ":" not in master:
        master += ":5050"
    env = {str(k): str(v) for k, v in task["env"].items()}
    prog = f"cd {os.getcwd()} && {task['command']}"
    resources = f"cpus:{task['cpus']};mem:{task['mem_mb']}"
    cmd = [
        "mesos-execute",
        f"--master={master}",
        f"--name={task['name']}-{uuid.uuid4()}",
        f"--command={prog}",
        f"--env={json.dumps(env)}",
        f"--resources={resources}",
    ]
    subprocess.check_call(
        cmd, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT
    )


def _pick_runner() -> Callable[[Dict], None]:
    try:
        import pymesos.subprocess  # noqa: F401

        return _run_pymesos
    except ImportError:
        pass
    if shutil.which("mesos-execute"):
        return _run_mesos_execute
    raise RuntimeError(
        "mesos launcher needs either the pymesos package or the "
        "mesos-execute CLI on PATH"
    )


def submit(args, runner: Optional[Callable[[Dict], None]] = None) -> None:
    if not (args.mesos_master or os.environ.get("MESOS_MASTER")):
        raise ValueError("mesos cluster needs --mesos-master")
    if args.mesos_master:
        os.environ["MESOS_MASTER"] = args.mesos_master
    run_task = runner if runner is not None else _pick_runner()
    threads: List[threading.Thread] = []
    errors: List[tuple] = []

    def run_wrapped(task: Dict) -> None:
        # a swallowed launch failure would leave the tracker waiting for
        # a worker that never comes — record it so submit() can raise
        try:
            run_task(task)
        except BaseException as err:  # noqa: BLE001 — crosses the thread
            errors.append((task["name"], err))

    def fun_submit(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        for task in plan(args, nworker, nserver, envs):
            t = threading.Thread(target=run_wrapped, args=(task,), daemon=True)
            t.start()
            threads.append(t)

    from dmlc_tpu.tracker.rendezvous import RabitTracker, get_host_ip

    if args.num_servers:
        # PS jobs keep the reference's tracker composition
        submit_with_tracker(
            args.num_workers, args.num_servers, fun_submit,
            host_ip=args.host_ip or "auto",
            tasks_alive=lambda: any(t.is_alive() for t in threads),
        )
        for t in threads:
            t.join()
    else:
        # rabit jobs: join the TASK threads before the tracker so a failed
        # launch raises instead of hanging the tracker's rendezvous wait
        ip = get_host_ip(args.host_ip or "auto")
        tracker = RabitTracker(host_ip=ip, num_workers=args.num_workers)
        envs: Dict[str, object] = {
            "DMLC_NUM_WORKER": args.num_workers,
            "DMLC_NUM_SERVER": 0,
        }
        envs.update(tracker.worker_envs())
        tracker.start(args.num_workers)
        fun_submit(args.num_workers, 0, envs)
        for t in threads:
            t.join()
        if not errors:
            # all task threads are done here; if rendezvous never completed
            # the join aborts instead of hanging (RabitTracker.join)
            tracker.join(tasks_alive=lambda: any(t.is_alive() for t in threads))
    if errors:
        name, err = errors[0]
        raise RuntimeError(
            f"mesos task {name} failed ({len(errors)} task(s) total): {err}"
        ) from err
