"""Sun Grid Engine launcher (tracker/dmlc_tracker/sge.py).

Writes a generated ``rundmlc.sh`` that computes the task's role and id from
the SGE array-task id (the reference derives role from task id in
launcher.py:41-47) and submits it as ``qsub -t 1-N`` array job.
"""

from __future__ import annotations

import os
import stat
import subprocess
from typing import Dict, List

from dmlc_tpu.tracker.launchers.common import export_prefix, task_env
from dmlc_tpu.tracker.rendezvous import submit_with_tracker

RUN_SCRIPT = "rundmlc.sh"


def plan_run_script(
    env: Dict[str, str], command: str, nworker: int, nserver: int
) -> str:
    """The array-task bootstrap script: role/task-id from SGE_TASK_ID."""
    lines = [
        "#!/bin/bash",
        export_prefix(env),
        # SGE_TASK_ID is 1-based; tasks [1, nworker] are workers
        f"TID=$((SGE_TASK_ID - 1))",
        f"if [ $TID -lt {nworker} ]; then",
        "  export DMLC_ROLE=worker",
        "  export DMLC_TASK_ID=$TID",
        "else",
        "  export DMLC_ROLE=server",
        f"  export DMLC_TASK_ID=$((TID - {nworker}))",
        "fi",
        command,
    ]
    return "\n".join(lines) + "\n"


def plan_qsub(
    script: str, ntasks: int, queue: str, cores: int, log_dir: str, jobname: str
) -> List[str]:
    argv = ["qsub", "-cwd", "-t", f"1-{ntasks}", "-S", "/bin/bash",
            "-q", queue, "-pe", "smp", str(cores), "-N", jobname]
    if log_dir:
        argv += ["-o", log_dir, "-e", log_dir]
    argv.append(script)
    return argv


def submit(args) -> None:
    def fun_submit(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        env = task_env(envs, 0, "worker", "sge", extra=args.env_map)
        # role/task-id are decided inside the script, drop the placeholders
        for k in ("DMLC_TASK_ID", "DMLC_ROLE"):
            env.pop(k, None)
        text = plan_run_script(env, " ".join(args.command), nworker, nserver)
        with open(RUN_SCRIPT, "w") as fh:
            fh.write(text)
        os.chmod(RUN_SCRIPT, os.stat(RUN_SCRIPT).st_mode | stat.S_IEXEC)
        argv = plan_qsub(
            RUN_SCRIPT, nworker + nserver, args.queue, args.worker_cores,
            args.sge_log_dir, args.jobname or "dmlc-job",
        )
        subprocess.check_call(argv)

    submit_with_tracker(
        args.num_workers, args.num_servers, fun_submit,
        host_ip=args.host_ip or "auto",
    )
