"""YARN launcher (tracker/dmlc_tracker/yarn.py).

The reference builds a Java ApplicationMaster jar and submits via
``hadoop jar`` with the job description in env vars (yarn.py:36-129). This
launcher reproduces the submission surface — the ``hadoop jar`` command
line, file/archive localization, per-role cores+memory env — against any
dmlc-compatible YARN AM jar (``DMLC_YARN_JAR`` env or --yarn-app-classpath);
it does not vendor the Java AM itself. The AM's retry/blacklist policy
(ApplicationMaster.java:76,212-213,332-354) exists in-repo too:
``yarn_controller.RetryController`` is the pure policy, ``drive_app``
polls the RM REST API for application-level retries, and this submit
retries the blocking submission itself up to DMLC_MAX_ATTEMPT.
"""

from __future__ import annotations

import os
import subprocess
from typing import Dict, List

from dmlc_tpu.tracker.launchers.common import task_env
from dmlc_tpu.tracker.opts import get_cache_file_set
from dmlc_tpu.tracker.rendezvous import submit_with_tracker


def plan_hadoop_jar(
    args, nworker: int, nserver: int, envs: Dict[str, object], jar: str
) -> List[str]:
    env = task_env(envs, 0, "worker", "yarn", extra=args.env_map)
    for k in ("DMLC_TASK_ID", "DMLC_ROLE"):
        env.pop(k, None)
    env.update({
        "DMLC_NUM_WORKER": str(nworker),
        "DMLC_NUM_SERVER": str(nserver),
        "DMLC_WORKER_CORES": str(args.worker_cores),
        "DMLC_WORKER_MEMORY_MB": str(args.worker_memory_mb),
        "DMLC_SERVER_CORES": str(args.server_cores),
        "DMLC_SERVER_MEMORY_MB": str(args.server_memory_mb),
        "DMLC_MAX_ATTEMPT": str(args.max_attempts or 3),
        "DMLC_JOB_CLUSTER": "yarn",
    })
    fset, command = get_cache_file_set(args)
    if args.archives:
        env["DMLC_JOB_ARCHIVES"] = ",".join(args.archives)
    argv = ["hadoop", "jar", jar, "org.apache.hadoop.yarn.dmlc.Client"]
    if args.queue:
        argv += ["-queue", args.queue]
    if args.jobname:
        argv += ["-jobname", args.jobname]
    for f in sorted(fset):
        argv += ["-file", f]
    argv += ["-env", ",".join(f"{k}={v}" for k, v in sorted(env.items()))]
    argv += command
    return argv


def submit(args) -> None:
    jar = os.environ.get("DMLC_YARN_JAR") or args.yarn_app_classpath
    if not jar:
        raise RuntimeError(
            "yarn cluster needs a dmlc YARN ApplicationMaster jar: set "
            "DMLC_YARN_JAR or --yarn-app-classpath to its path"
        )

    from dmlc_tpu.tracker.launchers.yarn_controller import default_max_attempt
    from dmlc_tpu.utils.logging import log_info

    budget = args.max_attempts or default_max_attempt()

    def fun_submit(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        argv = plan_hadoop_jar(args, nworker, nserver, envs, jar)
        for attempt in range(budget):
            try:
                subprocess.check_call(argv)
                return
            except subprocess.CalledProcessError as err:
                if attempt + 1 >= budget:
                    raise
                log_info(
                    "yarn submission failed (rc=%d), attempt %d/%d",
                    err.returncode, attempt + 1, budget,
                )

    submit_with_tracker(
        args.num_workers, args.num_servers, fun_submit,
        host_ip=args.host_ip or "auto",
    )
