"""Slurm launcher (tracker/dmlc_tracker/slurm.py).

Workers and servers each get an ``srun`` allocation with the DMLC env
exported through ``--export`` (the reference uses an env-prefix; --export is
the srun-native equivalent). Node counts come from --slurm-worker-nodes /
--slurm-server-nodes when given.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, List, Optional

from dmlc_tpu.tracker.launchers.common import task_env
from dmlc_tpu.tracker.rendezvous import submit_with_tracker


def plan_srun(
    n: int,
    env: Dict[str, str],
    command: List[str],
    nodes: Optional[int] = None,
    cores: int = 1,
    memory_mb: int = 1024,
) -> List[str]:
    # env-prefix form (the reference's style): srun propagates the caller's
    # environment by default, and unlike --export=k=v,... it is safe for
    # values containing commas (XLA_FLAGS, LD_LIBRARY_PATH)
    argv = ["env"] + [f"{k}={v}" for k, v in sorted(env.items())]
    argv += ["srun", f"--ntasks={n}", f"--cpus-per-task={cores}",
             f"--mem-per-cpu={memory_mb}M"]
    if nodes:
        argv.append(f"--nodes={nodes}")
    return argv + list(command)


def plan(args, nworker: int, nserver: int, envs: Dict[str, object]):
    out = []
    if nworker > 0:
        env = task_env(envs, 0, "worker", "slurm", extra=args.env_map)
        del env["DMLC_TASK_ID"]  # derived from SLURM_PROCID downstream
        out.append(plan_srun(nworker, env, args.command,
                             args.slurm_worker_nodes, args.worker_cores,
                             args.worker_memory_mb))
    if nserver > 0:
        env = task_env(envs, 0, "server", "slurm", extra=args.env_map)
        del env["DMLC_TASK_ID"]
        out.append(plan_srun(nserver, env, args.command,
                             args.slurm_server_nodes, args.server_cores,
                             args.server_memory_mb))
    return out


def submit(args) -> None:
    threads: List[threading.Thread] = []

    def fun_submit(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        for argv in plan(args, nworker, nserver, envs):
            t = threading.Thread(
                target=lambda a=argv: subprocess.Popen(a).wait(), daemon=True
            )
            t.start()
            threads.append(t)

    submit_with_tracker(
        args.num_workers, args.num_servers, fun_submit,
        host_ip=args.host_ip or "auto",
    )
    for t in threads:
        t.join()
