"""MPI launcher (tracker/dmlc_tracker/mpi.py).

Builds ``mpirun -n N`` with env forwarding in the flavor of the detected
MPI: OpenMPI uses repeated ``-x NAME=VALUE`` and MPICH uses ``-env NAME
VALUE`` (mpi.py:12-36). Workers and servers are two mpirun invocations with
different DMLC_ROLE.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, List, Optional

from dmlc_tpu.tracker.launchers.common import task_env
from dmlc_tpu.tracker.rendezvous import submit_with_tracker


def detect_mpi_flavor() -> str:
    """'openmpi' | 'mpich' from `mpirun --version` (mpi.py:14-24)."""
    try:
        out = subprocess.run(
            ["mpirun", "--version"], capture_output=True, text=True, timeout=10
        ).stdout.lower()
    except (OSError, subprocess.TimeoutExpired):
        return "openmpi"
    return "mpich" if ("mpich" in out or "hydra" in out) else "openmpi"


def plan_mpirun(
    n: int,
    role: str,
    env: Dict[str, str],
    command: List[str],
    flavor: str = "openmpi",
    hostfile: Optional[str] = None,
) -> List[str]:
    """One mpirun invocation for n tasks of a role (mpi.py:26-36)."""
    argv: List[str] = ["mpirun", "-n", str(n)]
    if hostfile:
        argv += ["--hostfile", hostfile]
    if flavor == "openmpi":
        for k, v in sorted(env.items()):
            argv += ["-x", f"{k}={v}"]
    else:  # mpich
        for k, v in sorted(env.items()):
            argv += ["-env", k, str(v)]
    return argv + list(command)


def plan(args, nworker: int, nserver: int, envs: Dict[str, object],
         flavor: Optional[str] = None):
    flavor = flavor or detect_mpi_flavor()
    out = []
    if nworker > 0:
        # DMLC_TASK_ID comes from the MPI rank downstream; pass 0 as base
        env = task_env(envs, 0, "worker", "mpi", extra=args.env_map)
        del env["DMLC_TASK_ID"]
        out.append(plan_mpirun(nworker, "worker", env, args.command,
                               flavor, args.host_file))
    if nserver > 0:
        env = task_env(envs, 0, "server", "mpi", extra=args.env_map)
        del env["DMLC_TASK_ID"]
        out.append(plan_mpirun(nserver, "server", env, args.command,
                               flavor, args.host_file))
    return out


def submit(args) -> None:
    threads: List[threading.Thread] = []

    def fun_submit(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        for argv in plan(args, nworker, nserver, envs):
            t = threading.Thread(
                target=lambda a=argv: subprocess.Popen(a).wait(), daemon=True
            )
            t.start()
            threads.append(t)

    submit_with_tracker(
        args.num_workers, args.num_servers, fun_submit,
        host_ip=args.host_ip or "auto",
    )
    for t in threads:
        t.join()
