"""In-container bootstrap shim (tracker/dmlc_tracker/launcher.py).

Runs *inside* a scheduled container/array task before the user command:
unpacks shipped archives (DMLC_JOB_ARCHIVES, launcher.py:60-70), derives the
task's role/id from the scheduler's task index when the launcher could not
set them directly (SGE role calc, launcher.py:41-47), extends
LD_LIBRARY_PATH/CLASSPATH for HDFS when present (launcher.py:20-39), then
execs the user command (launcher.py:76).

Usage: ``python -m dmlc_tpu.tracker.shim user-cmd args…``
"""

from __future__ import annotations

import os
import subprocess
import sys
import zipfile


def unpack_archives() -> None:
    archives = os.environ.get("DMLC_JOB_ARCHIVES", "")
    for item in archives.split(","):
        item = item.strip()
        if not item:
            continue
        base = os.path.basename(item)
        name = base.rsplit(".", 1)[0]
        if os.path.exists(base) and not os.path.exists(name):
            with zipfile.ZipFile(base) as zf:
                zf.extractall(name)


def derive_role_from_scheduler_env() -> None:
    """SGE/Slurm array tasks: role+task-id from the array index when the
    launcher couldn't export them per-task (launcher.py:41-47)."""
    if "DMLC_ROLE" in os.environ and "DMLC_TASK_ID" in os.environ:
        return
    raw = os.environ.get("SGE_TASK_ID") or os.environ.get("SLURM_PROCID")
    if raw is None:
        return
    tid = int(raw)
    if os.environ.get("SGE_TASK_ID"):
        tid -= 1  # SGE is 1-based
    nworker = int(os.environ.get("DMLC_NUM_WORKER", 1))
    if tid < nworker:
        os.environ["DMLC_ROLE"] = "worker"
        os.environ["DMLC_TASK_ID"] = str(tid)
    else:
        os.environ["DMLC_ROLE"] = "server"
        os.environ["DMLC_TASK_ID"] = str(tid - nworker)


def extend_hadoop_env() -> None:
    hadoop_home = os.environ.get("HADOOP_HDFS_HOME") or os.environ.get(
        "HADOOP_HOME"
    )
    if not hadoop_home:
        return
    lib = os.path.join(hadoop_home, "lib", "native")
    if os.path.isdir(lib):
        prev = os.environ.get("LD_LIBRARY_PATH", "")
        os.environ["LD_LIBRARY_PATH"] = f"{lib}:{prev}" if prev else lib


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: python -m dmlc_tpu.tracker.shim CMD [ARGS…]",
              file=sys.stderr)
        return 2
    unpack_archives()
    derive_role_from_scheduler_env()
    extend_hadoop_env()
    # single token ⇒ a pre-built shell command line (how launchers invoke the
    # shim); multiple tokens ⇒ a faithful argv, re-quoted per token
    import shlex

    cmd = argv[0] if len(argv) == 1 else " ".join(shlex.quote(t) for t in argv)
    return subprocess.call(cmd, shell=True)


if __name__ == "__main__":
    raise SystemExit(main())
