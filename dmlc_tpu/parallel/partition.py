"""Regex-driven parameter partitioning: rules → PartitionSpec/NamedSharding.

The reference has no notion of parameter placement — rabit callers keep
the model on the host and allreduce it over sockets. The SPMD training
path inverts that: parameters LIVE sharded (or replicated) on the device
mesh and the placement is declared once, as data, instead of hard-coded
per step builder. A partition-rule table is a sequence of

    (regex, PartitionSpec)

pairs; a parameter pytree is flattened to ``/``-joined leaf names
(``"w"``, ``"layers/0/kernel"``), each non-scalar leaf takes the spec of
the FIRST rule whose regex ``re.search``-matches its name, and scalars
are always replicated (``P()``) without consulting the table. An
unmatched leaf is a hard error: silent replication of a tensor the
author meant to shard is exactly the placement bug this layer exists to
remove, and ``scripts/check_partition_rules.py`` lints the in-tree rule
tables for both misses and ambiguous (multi-rule) matches.

Built on the shape of the fmengine/EasyLM ``match_partition_rules``
utilities (SNIPPETS.md [2]/[3]), grafted onto this package's mesh
helpers (``parallel/mesh.py``).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_tpu.utils.logging import DMLCError

__all__ = [
    "REPLICATED_RULES",
    "leaf_names",
    "named_tree_map",
    "match_partition_rules",
    "lint_partition_rules",
    "sharding_tree",
    "shard_params",
]

#: Catch-all table: every leaf replicated. The right default for small
#: data-parallel models (linear/FM dp steps) where only the BATCH is
#: sharded and the psum output must land identically on every device.
REPLICATED_RULES: Tuple[Tuple[str, P], ...] = ((r".*", P()),)


def _key_str(key: Any) -> str:
    """One path entry → its name segment (dict key, attr name, index)."""
    for attr in ("key", "name", "idx"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


def _path_name(path, sep: str = "/") -> str:
    return sep.join(_key_str(k) for k in path)


def named_tree_map(fn: Callable[[str, Any], Any], tree, sep: str = "/"):
    """``tree_map`` where ``fn`` receives ``(leaf_name, leaf)`` — leaf
    names are the ``sep``-joined pytree path (dict keys / attr names /
    sequence indices)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: fn(_path_name(path, sep), leaf), tree
    )


def leaf_names(tree, sep: str = "/") -> List[str]:
    """The ``sep``-joined path name of every leaf, in flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [_path_name(path, sep) for path, _ in flat]


def _is_scalar(leaf) -> bool:
    shape = tuple(getattr(leaf, "shape", ()))
    return len(shape) == 0 or int(np.prod(shape)) == 1


def match_partition_rules(rules: Sequence[Tuple[str, P]], params,
                          sep: str = "/"):
    """PartitionSpec pytree for ``params`` from a ``(regex, spec)`` table.

    Scalar leaves (rank 0 or one element) are replicated without
    consulting the rules; every other leaf takes the first rule whose
    regex matches its ``sep``-joined name, and a leaf no rule matches
    raises ``DMLCError`` (run ``lint_partition_rules`` — or the
    ``scripts/check_partition_rules.py`` gate — to find ambiguous
    tables before they ship).
    """

    def get_spec(name: str, leaf):
        if _is_scalar(leaf):
            return P()
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise DMLCError(
            f"no partition rule matches param {name!r} "
            f"(rules: {[r for r, _ in rules]!r})"
        )

    return named_tree_map(get_spec, params, sep=sep)


def lint_partition_rules(rules: Sequence[Tuple[str, P]], params,
                         sep: str = "/") -> List[str]:
    """Problems list for ``scripts/check_partition_rules.py``: every
    non-scalar leaf must match EXACTLY one rule. Zero matches is the
    silent-replication bug; two or more means the table's first-match
    order is load-bearing, which a later edit will break silently.
    Scalars are exempt (the runtime replicates them before the table is
    consulted). Returns [] for a clean table."""
    problems: List[str] = []
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        name = _path_name(path, sep)
        if _is_scalar(leaf):
            continue
        hits = [rule for rule, _ in rules if re.search(rule, name)]
        if not hits:
            problems.append(f"{name}: matched by no rule")
        elif len(hits) > 1:
            problems.append(
                f"{name}: matched by {len(hits)} rules {hits!r} "
                "(first-match order is load-bearing)"
            )
    return problems


def sharding_tree(mesh: Mesh, specs):
    """PartitionSpec pytree → NamedSharding pytree over ``mesh``."""
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params, mesh: Mesh,
                 rules: Sequence[Tuple[str, P]] = REPLICATED_RULES,
                 specs=None, sep: str = "/"):
    """Place every leaf of ``params`` on ``mesh`` with its rule-derived
    ``NamedSharding`` (or a precomputed ``specs`` tree). The returned
    tree is committed — jit/shard_map steps consume it without a fresh
    placement per call, and re-calling with a NEW mesh is the elastic
    re-entry path (``collective.on_membership_change``): leaves are
    re-placed onto the rebuilt mesh whatever device set it now spans."""
    if specs is None:
        specs = match_partition_rules(rules, params, sep=sep)
    shardings = sharding_tree(mesh, specs)
    return jax.tree_util.tree_map(
        lambda leaf, sh: jax.device_put(leaf, sh), params, shardings
    )
