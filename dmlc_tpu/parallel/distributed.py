"""Worker-side multi-host bootstrap: the jax.distributed half of the
``--cluster=tpu`` contract (dmlc_tpu.tracker.launchers.tpu).

The launcher exports DMLC_TPU_COORDINATOR / DMLC_TPU_NUM_PROC /
DMLC_TPU_PROC_ID; :func:`initialize_from_env` turns those into
``jax.distributed.initialize(...)`` so ``jax.devices()`` spans the pod.
This replaces the reference worker's connect-back handshake to the socket
tracker (tracker.py:58-135) for the *data* plane; the socket engine remains
available as the control plane (dmlc_tpu.collective.socket_engine).
"""

from __future__ import annotations

import os
from typing import Optional

_initialized = False


def env_process_info() -> Optional[dict]:
    """{coordinator, num_processes, process_id} from DMLC_TPU_* env, or None
    when not launched by the tpu launcher."""
    coord = os.environ.get("DMLC_TPU_COORDINATOR")
    if not coord:
        return None
    return {
        "coordinator": coord,
        "num_processes": int(os.environ.get("DMLC_TPU_NUM_PROC", 1)),
        "process_id": int(
            os.environ.get("DMLC_TPU_PROC_ID",
                           os.environ.get("DMLC_TASK_ID", 0))
        ),
    }


def multiprocess_env() -> bool:
    """True when the DMLC_TPU_* launcher contract names a multi-process
    job — the single recoverability predicate shared by run_with_recovery
    and reinit_recover."""
    info = env_process_info()
    return info is not None and info["num_processes"] > 1


def elastic_capable() -> bool:
    """True when this process's collective bootstrap can change world size
    mid-run. The jax.distributed runtime pins ``num_processes`` at
    initialize time, so a multi-process device job cannot rebuild into a
    different-sized world without a full relaunch; only the socket-engine
    path re-rendezvouses elastically (tracker cmd='elastic')."""
    return not multiprocess_env()


def initialize_from_env(force: bool = False) -> bool:
    """Call jax.distributed.initialize from the DMLC_TPU_* env contract.

    Returns True when multi-host init ran (or already ran), False when the
    env says single-process (no-op). Safe to call more than once; ``force``
    shuts down and re-initializes (elastic recovery — the tracker 'recover'
    analog, SURVEY §5.3).
    """
    global _initialized
    info = env_process_info()
    if info is None or info["num_processes"] <= 1:
        return False
    import jax

    if _initialized and not force:
        return True
    if _initialized and force:
        jax.distributed.shutdown()
        _initialized = False
    jax.distributed.initialize(
        coordinator_address=info["coordinator"],
        num_processes=info["num_processes"],
        process_id=info["process_id"],
    )
    _initialized = True
    return True


def shutdown() -> None:
    global _initialized
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    info = env_process_info()
    if info is not None:
        return info["process_id"]
    return int(os.environ.get("DMLC_TASK_ID", 0))


def process_count() -> int:
    info = env_process_info()
    if info is not None:
        return info["num_processes"]
    return int(os.environ.get("DMLC_NUM_WORKER", 1))
