"""Device-mesh construction and sharding helpers.

The reference's tracker computes a binary tree + ring over worker sockets
(tracker.py:185-252) and brokers the links; on TPU the ICI torus plus XLA's
collective scheduler replace all of it. These helpers build the standard
meshes ("dp" over all chips; optional "dcn" outer axis for multi-slice) and
the shardings the rest of the framework uses.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axis_sizes: Dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh over ``devices`` (default: all) with the given axis
    sizes, e.g. {"dp": 8} or {"dp": 4, "mp": 2}. Axis sizes must multiply to
    the device count; -1 once means "fill"."""
    devs = list(devices if devices is not None else jax.devices())
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devs) // known
    total = int(np.prod(sizes))
    if total != len(devs):
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} need {total} devices, "
            f"have {len(devs)}"
        )
    arr = np.asarray(devs).reshape(sizes)
    return Mesh(arr, tuple(names))


def _multislice_order(devs, num_slices: Optional[int]):
    """Order devices for the multi-slice reshape → (devices, num_slices).

    Grouping policy: when the runtime reports slice_index AND it matches
    ``num_slices`` (>1), devices sort along the hardware's own slice
    boundaries, verified equal-sized — an uneven split would silently put
    devices from two slices in one dcn row, i.e. DCN hops inside an "ICI"
    axis. A single reported slice (or no slice info) cuts ``num_slices``
    contiguous virtual groups in device order — single-slice hardware and
    CPU test meshes rehearsing the multi-slice path. Asking for FEWER
    groups than the hardware's slice count is rejected for the same
    row-mixing reason."""
    slice_ids = {getattr(d, "slice_index", None) for d in devs}
    reported = len(slice_ids) if None not in slice_ids else None
    if num_slices is None:
        if reported is None:
            raise ValueError(
                "num_slices is required when devices do not report "
                "slice_index"
            )
        num_slices = reported
    if num_slices <= 0 or len(devs) % num_slices:
        raise ValueError(
            f"{len(devs)} devices do not split into {num_slices} slices"
        )
    per_slice = len(devs) // num_slices
    if reported is not None and reported > 1:
        # Hardware reports real slices: sort along slice boundaries and
        # require num_slices to be a multiple of the hardware count, so
        # each contiguous dcn row subdivides ONE slice (subdividing is
        # conservative — some "dcn" hops are really ICI — but a row
        # spanning two slices would silently put DCN hops inside an ICI
        # axis, which the row check below rejects in every case).
        if num_slices % reported:
            raise ValueError(
                f"num_slices={num_slices} does not tile the {reported} "
                "hardware slices (must be a multiple, so no dcn row "
                "spans two slices)"
            )
        devs = sorted(devs, key=lambda d: (d.slice_index, d.id))
        for row in range(num_slices):
            row_devs = devs[row * per_slice:(row + 1) * per_slice]
            if len({d.slice_index for d in row_devs}) != 1:
                raise ValueError(
                    "devices do not split into equal-sized slices: "
                    f"dcn row {row} spans slices "
                    f"{sorted({d.slice_index for d in row_devs})}"
                )
    return devs, num_slices


def make_multislice_mesh(
    ici_axes: Dict[str, int],
    num_slices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    dcn_axis: str = "dcn",
) -> Mesh:
    """Multi-slice mesh: an outer ``dcn`` axis over slices × inner ICI axes
    within each slice.

    On multi-slice TPU, chips within a slice talk over ICI (fast torus) and
    slices talk over DCN (the data-center network, ~an order of magnitude
    less bandwidth). The slice axis is therefore placed OUTERMOST —
    slowest-varying — so any collective over the inner axes stays entirely
    on ICI, and only the (small) cross-slice hop of a hybrid reduction
    rides DCN (SURVEY §5.8; the scaling-book hybrid-dp recipe: psum over
    ("dcn", "dp") lowers to per-slice reduce over ICI + one cross-slice
    exchange).

    Devices are grouped by their ``slice_index`` attribute when the
    runtime reports multiple slices matching ``num_slices`` (real
    multi-slice jobs, equal-sized groups verified); on single-slice
    hardware or CPU/virtual meshes, ``num_slices`` contiguous virtual
    groups are cut in device order (rehearsing the multi-slice path —
    see :func:`_multislice_order` for the full policy). ``ici_axes``
    follows :func:`make_mesh` semantics within one slice (-1 once means
    fill).

    Use with the hybrid train step::

        mesh = make_multislice_mesh({"dp": -1}, num_slices=2)
        step = make_linear_train_step(mesh, axis=("dcn", "dp"))
    """
    devs = list(devices if devices is not None else jax.devices())
    devs, num_slices = _multislice_order(devs, num_slices)
    per_slice = len(devs) // num_slices
    names = list(ici_axes.keys())
    sizes = list(ici_axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = per_slice // known
    if int(np.prod(sizes)) != per_slice:
        raise ValueError(
            f"ici axes {dict(zip(names, sizes))} need "
            f"{int(np.prod(sizes))} devices/slice, have {per_slice}"
        )
    arr = np.asarray(devs).reshape([num_slices] + sizes)
    return Mesh(arr, tuple([dcn_axis] + names))


def data_parallel_mesh(
    devices: Optional[Sequence[jax.Device]] = None, axis: str = "dp"
) -> Mesh:
    """One-axis mesh over every chip — the allreduce-DP topology that
    replaces the tracker's tree+ring."""
    devs = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devs), (axis,))


def local_mesh(axis: str = "dp") -> Mesh:
    """Mesh over this process's addressable devices only."""
    return Mesh(np.asarray(jax.local_devices()), (axis,))


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (parameters / scalars)."""
    return NamedSharding(mesh, P())


def local_axis_shards(mesh: Mesh, axes) -> int:
    """How many shard sections THIS process's data divides over along
    ``axes`` (a name or list of names) when the leading dimension is
    sharded ``P(axes)``.

    Single-process: the full axis extent (product over ``axes``).
    Multi-process: only the axis positions this process's devices occupy —
    each host packs its local rows into its LOCAL shards and
    ``make_array_from_process_local_data`` concatenates hosts into the
    global array (packing by the GLOBAL extent instead would interleave
    half of one host's shard with half of another's on every device).
    Shared by DeviceFeed and the GBDT learner — one copy of the
    mesh-geometry subtlety.
    """
    from dmlc_tpu.utils.logging import check

    axes = [axes] if isinstance(axes, str) else list(axes)
    if jax.process_count() <= 1:
        return int(np.prod([mesh.shape[a] for a in axes]))
    arr = mesh.devices
    local_ids = {d.id for d in jax.local_devices()}
    mask = np.frompyfunc(lambda d: d.id in local_ids, 1, 1)(
        arr).astype(bool)
    axis_idxs = [mesh.axis_names.index(a) for a in axes]
    other = tuple(i for i in range(arr.ndim) if i not in axis_idxs)
    shards = int(mask.any(axis=other).sum()) if other else int(mask.sum())
    check(
        shards > 0,
        "mesh holds none of process %d's devices — this process cannot "
        "contribute shards",
        jax.process_index(),
    )
    return shards


def mesh_rank_info() -> Dict[str, int]:
    """The DMLC_* style rank/world bookkeeping, sourced from JAX.

    Mirrors what the reference tracker hands each worker via env
    (tracker.py:182-183): rank = process_index, world = process_count.
    """
    return {
        "rank": jax.process_index(),
        "world_size": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
