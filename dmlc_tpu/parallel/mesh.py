"""Device-mesh construction and sharding helpers.

The reference's tracker computes a binary tree + ring over worker sockets
(tracker.py:185-252) and brokers the links; on TPU the ICI torus plus XLA's
collective scheduler replace all of it. These helpers build the standard
meshes ("dp" over all chips; optional "dcn" outer axis for multi-slice) and
the shardings the rest of the framework uses.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axis_sizes: Dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh over ``devices`` (default: all) with the given axis
    sizes, e.g. {"dp": 8} or {"dp": 4, "mp": 2}. Axis sizes must multiply to
    the device count; -1 once means "fill"."""
    devs = list(devices if devices is not None else jax.devices())
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devs) // known
    total = int(np.prod(sizes))
    if total != len(devs):
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} need {total} devices, "
            f"have {len(devs)}"
        )
    arr = np.asarray(devs).reshape(sizes)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(
    devices: Optional[Sequence[jax.Device]] = None, axis: str = "dp"
) -> Mesh:
    """One-axis mesh over every chip — the allreduce-DP topology that
    replaces the tracker's tree+ring."""
    devs = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devs), (axis,))


def local_mesh(axis: str = "dp") -> Mesh:
    """Mesh over this process's addressable devices only."""
    return Mesh(np.asarray(jax.local_devices()), (axis,))


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (parameters / scalars)."""
    return NamedSharding(mesh, P())


def mesh_rank_info() -> Dict[str, int]:
    """The DMLC_* style rank/world bookkeeping, sourced from JAX.

    Mirrors what the reference tracker hands each worker via env
    (tracker.py:182-183): rank = process_index, world = process_count.
    """
    return {
        "rank": jax.process_index(),
        "world_size": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
