"""Mesh / sharding helpers: the TPU replacement for the reference's socket
tree+ring topologies (tracker.py:185-252).

On TPU the interconnect is a torus and XLA chooses the collective algorithm;
what remains of the reference's topology bookkeeping is (a) building the
device mesh, (b) choosing which axes ride ICI vs DCN, and (c) the
rank/world-size bookkeeping the tracker used to own.
"""

from dmlc_tpu.parallel.mesh import (
    make_mesh,
    make_multislice_mesh,
    data_parallel_mesh,
    local_mesh,
    batch_sharding,
    replicated_sharding,
    mesh_rank_info,
    local_axis_shards,
)
from dmlc_tpu.parallel.partition import (
    REPLICATED_RULES,
    leaf_names,
    lint_partition_rules,
    match_partition_rules,
    named_tree_map,
    shard_params,
    sharding_tree,
)

__all__ = [
    "make_mesh",
    "make_multislice_mesh",
    "data_parallel_mesh",
    "local_mesh",
    "batch_sharding",
    "replicated_sharding",
    "mesh_rank_info",
    "local_axis_shards",
    "REPLICATED_RULES",
    "leaf_names",
    "lint_partition_rules",
    "match_partition_rules",
    "named_tree_map",
    "shard_params",
    "sharding_tree",
]
