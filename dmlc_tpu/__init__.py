"""dmlc_tpu: a TPU-native rebuild of the dmlc-core capability surface.

This package provides, idiomatically for TPU (JAX/XLA) + native C++ where the
reference is native:

- ``dmlc_tpu.utils``   — logging/CHECK/Error, timer, common helpers
  (reference: include/dmlc/logging.h, timer.h, common.h)
- ``dmlc_tpu.params``  — Parameter/Registry/Config/env spine
  (reference: include/dmlc/parameter.h, registry.h, config.h)
- ``dmlc_tpu.io``      — Stream/SeekStream, FileSystem plugins, URI dispatch,
  RecordIO format, InputSplit sharding machinery
  (reference: include/dmlc/io.h, recordio.h, src/io/)
- ``dmlc_tpu.data``    — RowBlock CSR batches, libsvm/libfm/csv parsers,
  row iterators, threaded prefetch pipelines
  (reference: include/dmlc/data.h, src/data/)
- ``dmlc_tpu.device``  — the TPU-new part: CSR batches bucketed/padded into
  static-shape XLA device buffers, async H2D overlap, per-host sharding
- ``dmlc_tpu.collective`` — rabit-style Allreduce/Broadcast/CheckPoint over a
  jax.sharding.Mesh (ICI/DCN collectives) plus a CPU socket path
  (reference: the tracker side of rabit bootstrap, tracker/dmlc_tracker/)
- ``dmlc_tpu.tracker`` — dmlc-submit-compatible launcher with ``--cluster=tpu``
  (reference: tracker/dmlc_tracker/)
- ``dmlc_tpu.models`` / ``dmlc_tpu.ops`` / ``dmlc_tpu.parallel`` — demo
  allreduce-SGD learners, sparse ops (SpMV), mesh/sharding helpers

The native C++ core (streams, RecordIO, InputSplit, parsers, prefetcher) lives
in ``cpp/`` and is loaded through ``dmlc_tpu.native`` (ctypes); every native
component has a pure-Python twin so the package works before the .so is built.
"""

__version__ = "0.1.0"

from dmlc_tpu.utils.logging import DMLCError, check, log_info, log_warning, log_error

__all__ = [
    "DMLCError",
    "check",
    "log_info",
    "log_warning",
    "log_error",
    "__version__",
]
