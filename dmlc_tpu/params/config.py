"""``key = value`` config-file parser.

Capability parity with ``dmlc::Config`` (reference include/dmlc/config.h +
src/config.cc): ``#`` comments, quoted string values with escape sequences,
optional multi-value mode (a key may appear multiple times), iteration in
insertion order, and proto-text export (``ToProtoString``, config.h:102).
"""

from __future__ import annotations

import io
from typing import Dict, Iterator, List, Tuple

from dmlc_tpu.utils.logging import DMLCError

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\"}
_REV_ESCAPES = {"\n": "\\n", "\t": "\\t", "\r": "\\r", '"': '\\"', "\\": "\\\\"}


def _tokenize(text: str) -> List[str]:
    """Tokenize into keys, '=', and (possibly quoted) values.

    Mirrors the tokenizer state machine of src/config.cc:30-100: whitespace
    separates tokens, ``#`` starts a line comment outside quotes, double quotes
    group a token and process escapes.
    """
    tokens: List[str] = []
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch in " \t\r\n":
            i += 1
            continue
        if ch == "#":
            while i < n and text[i] != "\n":
                i += 1
            continue
        if ch == "=":
            tokens.append("=")
            i += 1
            continue
        if ch == '"':
            i += 1
            out = []
            closed = False
            while i < n:
                c = text[i]
                if c == "\\":
                    if i + 1 >= n:
                        raise DMLCError("Config: dangling escape in quoted string")
                    esc = text[i + 1]
                    out.append(_ESCAPES.get(esc, esc))
                    i += 2
                    continue
                if c == '"':
                    closed = True
                    i += 1
                    break
                out.append(c)
                i += 1
            if not closed:
                raise DMLCError("Config: unterminated quoted string")
            tokens.append('"' + "".join(out))  # marker prefix: was quoted
            continue
        start = i
        while i < n and text[i] not in ' \t\r\n=#"':
            i += 1
        tokens.append(text[start:i])
    return tokens


class Config:
    """Ordered key/value config, optionally multi-valued.

    ``multi_value=True`` keeps every occurrence of a repeated key (reference
    Config ctor flag, config.h:46-56); otherwise later wins.
    """

    def __init__(self, source: str | io.TextIOBase | None = None, multi_value: bool = False):
        self.multi_value = multi_value
        self._items: List[Tuple[str, str]] = []
        self._index: Dict[str, int] = {}
        if source is not None:
            if hasattr(source, "read"):
                self.load_string(source.read())  # type: ignore[union-attr]
            else:
                self.load_string(source)  # type: ignore[arg-type]

    # ---- parsing -------------------------------------------------------
    def load_string(self, text: str) -> None:
        tokens = _tokenize(text)
        i = 0
        while i < len(tokens):
            if i + 2 >= len(tokens) + 1 and tokens[i] == "=":
                raise DMLCError("Config: stray '='")
            if i + 2 > len(tokens) or tokens[i + 1] != "=":
                raise DMLCError(
                    f"Config: expected 'key = value' near {tokens[i]!r}"
                )
            key = tokens[i]
            if key.startswith('"'):
                key = key[1:]
            value = tokens[i + 2] if i + 2 < len(tokens) else None
            if value is None:
                raise DMLCError(f"Config: missing value for key {key!r}")
            if value == "=":
                raise DMLCError(f"Config: missing value for key {key!r}")
            if value.startswith('"'):
                value = value[1:]
            self.set_param(key, value)
            i += 3

    def load_file(self, path: str) -> None:
        with open(path, "r", encoding="utf-8") as fp:
            self.load_string(fp.read())

    # ---- mutation ------------------------------------------------------
    def set_param(self, key: str, value) -> None:
        value = str(value)
        if not self.multi_value and key in self._index:
            self._items[self._index[key]] = (key, value)
        else:
            self._index[key] = len(self._items)
            self._items.append((key, value))

    # ---- access --------------------------------------------------------
    def get_param(self, key: str) -> str:
        if key not in self._index:
            raise KeyError(key)
        if self.multi_value:
            # Last occurrence wins for scalar access.
            for k, v in reversed(self._items):
                if k == key:
                    return v
        return self._items[self._index[key]][1]

    def get_all(self, key: str) -> List[str]:
        return [v for k, v in self._items if k == key]

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def __iter__(self) -> Iterator[Tuple[str, str]]:
        """Iterate (key, value) in insertion order (reference ConfigIterator)."""
        return iter(self._items)

    def items(self) -> List[Tuple[str, str]]:
        return list(self._items)

    # ---- export --------------------------------------------------------
    def to_proto_string(self) -> str:
        """proto-text export: ``key : "value"`` lines (config.h:102)."""
        lines = []
        for key, value in self._items:
            escaped = "".join(_REV_ESCAPES.get(c, c) for c in value)
            lines.append(f'{key} : "{escaped}"')
        return "\n".join(lines) + ("\n" if lines else "")
