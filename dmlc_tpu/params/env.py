"""Typed environment-variable access (reference GetEnv/SetEnv,
parameter.h:45-56, 1035-1063)."""

from __future__ import annotations

import os
from typing import Any, Optional, Type, TypeVar

from dmlc_tpu.params.parameter import FieldInfo

T = TypeVar("T")


def get_env(key: str, default: T, ftype: Optional[Type[T]] = None) -> T:
    """Read env var ``key`` parsed as the type of ``default``.

    Uses the same string→typed parsing as Parameter fields (bool accepts
    true/false/1/0, etc.). Missing variable returns ``default``.
    """
    raw = os.environ.get(key)
    if raw is None:
        return default
    info = FieldInfo(ftype or type(default))
    info.name = key
    return info.parse(raw)  # type: ignore[return-value]


def set_env(key: str, value: Any) -> None:
    """Set env var ``key`` from a typed value, using Parameter stringification."""
    info = FieldInfo(type(value))
    info.name = key
    os.environ[key] = info.to_string(value)
