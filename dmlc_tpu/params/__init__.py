"""Configuration spine: Parameter structs, factory Registry, Config files, env.

Reference capabilities mirrored: include/dmlc/parameter.h (declarative typed
parameter structs with validation + docgen), include/dmlc/registry.h (global
factory singletons), include/dmlc/config.h + src/config.cc (key=value config
files), parameter.h:1035-1063 (typed GetEnv/SetEnv).
"""

from dmlc_tpu.params.parameter import Parameter, ParamError, field
from dmlc_tpu.params.registry import Registry, RegistryEntry
from dmlc_tpu.params.config import Config
from dmlc_tpu.params.env import get_env, set_env
from dmlc_tpu.params.knobs import (
    default_host_prefetch,
    default_nthread,
    default_prefetch,
)

__all__ = [
    "Parameter",
    "ParamError",
    "field",
    "Registry",
    "RegistryEntry",
    "Config",
    "get_env",
    "set_env",
    "default_nthread",
    "default_prefetch",
    "default_host_prefetch",
]
