"""Declarative typed parameter structs.

Capability parity with ``dmlc::Parameter`` (reference include/dmlc/parameter.h):

- declare fields with type, default, range/enum constraints, aliases, docs
  (``DMLC_DECLARE_FIELD`` + ``FieldEntry`` specializations, parameter.h:260-292,
  653-1029)
- ``init(kwargs)`` with unknown-key policies kAllowUnknown / kAllMatch /
  kAllowHidden (parameter.h:87-101, 135-160)
- required fields without defaults raise "Required parameter ... not presented"
  (parameter.h:424-429, 595-600)
- string→typed parsing: bool accepts true/false/1/0 (parameter.h:944-977);
  int enums via add_enum (parameter.h:713-925); floats reject INF/NAN-producing
  and subnormal inputs (parameter.h:982-1029 — the reference's stof throws
  out_of_range for subnormals, covered by unittest_param.cc:13-21)
- ``__DICT__``/``update_dict`` (parameter.h:168-180), JSON ``save``/``load``
  (parameter.h:185-197), ``__DOC__`` docgen (parameter.h:202-213)
- rich ``ParamError`` messages embedding the full generated docstring
  (parameter.h:403-421)

Idiomatic-Python shape: fields are class attributes built by ``field(...)``
(a descriptor-light dataclass pattern) instead of CRTP + offset-of; validation
runs on ``init`` and on attribute assignment of parsed values.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Mapping, Optional, Sequence

from dmlc_tpu.utils.logging import DMLCError

# Smallest positive normal float32/float64; the reference parses float fields
# with std::stof which raises out_of_range on subnormal literals
# (unittest_param.cc:13-21 pins this behavior).
_FLT_MIN = 1.17549435e-38
_DBL_MIN = 2.2250738585072014e-308


def _dmlc_json():
    """The io/json.py streaming layer, imported lazily: params is a base
    layer and must not pull the whole io package at import time."""
    from dmlc_tpu.io import json as dmlc_json

    return dmlc_json


class ParamError(DMLCError):
    """Raised on unknown keys, parse failures, constraint violations, or
    missing required fields (reference dmlc::ParamError, parameter.h:62)."""


class _Unset:
    def __repr__(self) -> str:  # pragma: no cover
        return "<unset>"


UNSET = _Unset()


class FieldInfo:
    """Metadata for one declared field (reference FieldEntry hierarchy)."""

    def __init__(
        self,
        ftype: type,
        default: Any = UNSET,
        *,
        description: str = "",
        lower_bound: Any = None,
        upper_bound: Any = None,
        enum: Optional[Mapping[str, Any]] = None,
        aliases: Sequence[str] = (),
        optional_none: bool = False,
    ):
        self.ftype = ftype
        self.default = default
        self.description = description
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.enum = dict(enum) if enum else None
        self.aliases = tuple(aliases)
        # optional_none: dmlc::optional<T> semantics — the string "None" parses
        # to None (parameter.h:819-925).
        self.optional_none = optional_none
        self.name = "?"  # filled by the metaclass

    # ---- parsing -------------------------------------------------------
    def parse(self, value: Any) -> Any:
        if self.optional_none and (
            value is None or (isinstance(value, str) and value == "None")
        ):
            return None
        if self.enum is not None:
            return self._parse_enum(value)
        if self.ftype is bool:
            return self._parse_bool(value)
        if self.ftype is int:
            return self._parse_int(value)
        if self.ftype is float:
            return self._parse_float(value)
        if self.ftype is str:
            return str(value)
        # Fallback: try the constructor directly.
        try:
            return self.ftype(value)
        except Exception as err:  # noqa: BLE001
            raise ParamError(
                f"Invalid value {value!r} for parameter {self.name}: {err}"
            ) from err

    def _parse_enum(self, value: Any) -> Any:
        assert self.enum is not None
        if isinstance(value, str) and value in self.enum:
            return self.enum[value]
        if value in self.enum.values():
            return value
        expected = ", ".join(f"{k!r}" for k in self.enum)
        raise ParamError(
            f"Invalid value {value!r} for parameter {self.name}; "
            f"expected one of {{{expected}}}"
        )

    def _parse_bool(self, value: Any) -> bool:
        if isinstance(value, bool):
            return value
        s = str(value).strip().lower()
        if s in ("true", "1"):
            return True
        if s in ("false", "0"):
            return False
        raise ParamError(
            f"Invalid value {value!r} for boolean parameter {self.name}; "
            f"expected true/false/1/0"
        )

    def _parse_int(self, value: Any) -> int:
        if isinstance(value, bool):
            raise ParamError(f"Invalid bool for int parameter {self.name}")
        if isinstance(value, int):
            return value
        try:
            return int(str(value).strip(), 0)
        except ValueError as err:
            raise ParamError(
                f"Invalid value {value!r} for int parameter {self.name}"
            ) from err

    def _parse_float(self, value: Any) -> float:
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            out = float(value)
        else:
            s = str(value).strip()
            try:
                out = float(s)
            except ValueError as err:
                raise ParamError(
                    f"Invalid value {value!r} for float parameter {self.name}"
                ) from err
            low = s.lower()
            if "inf" in low or "nan" in low or "x" in low:
                # reference strtonum/stof path rejects INF/NAN/hex literals
                raise ParamError(
                    f"Invalid value {value!r} for float parameter {self.name}"
                )
        if math.isinf(out) or math.isnan(out):
            raise ParamError(
                f"Value {value!r} out of range for float parameter {self.name}"
            )
        if out != 0.0 and abs(out) < _FLT_MIN:
            # std::stof out_of_range on subnormals (unittest_param.cc:13-21)
            raise ParamError(
                f"Value {value!r} is subnormal for float parameter {self.name}"
            )
        return out

    # ---- validation ----------------------------------------------------
    def check(self, value: Any) -> None:
        if value is None and self.optional_none:
            return
        if self.lower_bound is not None and value < self.lower_bound:
            raise ParamError(
                f"Value {value!r} for parameter {self.name} should be "
                f">= {self.lower_bound}"
            )
        if self.upper_bound is not None and value > self.upper_bound:
            raise ParamError(
                f"Value {value!r} for parameter {self.name} should be "
                f"<= {self.upper_bound}"
            )

    # ---- docs / stringification ---------------------------------------
    def type_string(self) -> str:
        base = {int: "int", float: "float", bool: "boolean", str: "string"}.get(
            self.ftype, self.ftype.__name__
        )
        if self.enum is not None:
            base = "{" + ", ".join(repr(k) for k in self.enum) + "}"
        if self.optional_none:
            base = f"optional[{base}]"
        rng = []
        if self.lower_bound is not None:
            rng.append(f">= {self.lower_bound}")
        if self.upper_bound is not None:
            rng.append(f"<= {self.upper_bound}")
        if rng:
            base += ", " + " and ".join(rng)
        if self.default is UNSET:
            base += ", required"
        else:
            base += f", default={self.to_string(self.default)}"
        return base

    def to_string(self, value: Any) -> str:
        if value is None:
            return "None"
        if self.enum is not None:
            for key, val in self.enum.items():
                if val == value:
                    return key
        if isinstance(value, bool):
            return "true" if value else "false"
        return str(value)


def field(
    ftype: type,
    default: Any = UNSET,
    *,
    description: str = "",
    lower_bound: Any = None,
    upper_bound: Any = None,
    enum: Optional[Mapping[str, Any]] = None,
    aliases: Sequence[str] = (),
    optional_none: bool = False,
) -> FieldInfo:
    """Declare a parameter field (reference DMLC_DECLARE_FIELD + modifiers
    set_range/set_lower_bound/add_enum/set_default/describe/DMLC_DECLARE_ALIAS,
    parameter.h:260-292)."""
    return FieldInfo(
        ftype,
        default,
        description=description,
        lower_bound=lower_bound,
        upper_bound=upper_bound,
        enum=enum,
        aliases=aliases,
        optional_none=optional_none,
    )


class _ParameterMeta(type):
    def __new__(mcls, name, bases, ns):
        fields: Dict[str, FieldInfo] = {}
        for base in bases:
            fields.update(getattr(base, "__param_fields__", {}))
        for key, val in list(ns.items()):
            if isinstance(val, FieldInfo):
                val.name = key
                fields[key] = val
                del ns[key]
        ns["__param_fields__"] = fields
        alias_map: Dict[str, str] = {}
        for key, info in fields.items():
            for alias in info.aliases:
                alias_map[alias] = key
        ns["__param_aliases__"] = alias_map
        return super().__new__(mcls, name, bases, ns)


class Parameter(metaclass=_ParameterMeta):
    """Base class for declarative parameter structs.

    Usage::

        class MyParam(Parameter):
            num_hidden = field(int, 64, lower_bound=1, description="...")
            act = field(str, "relu", enum={"relu": "relu", "tanh": "tanh"})

        p = MyParam(num_hidden=128)         # or MyParam().init(kwargs)
    """

    __param_fields__: Dict[str, FieldInfo] = {}
    __param_aliases__: Dict[str, str] = {}

    def __init__(self, **kwargs: Any):
        for key, info in self.__param_fields__.items():
            object.__setattr__(
                self, key, info.default if info.default is not UNSET else UNSET
            )
        if kwargs:
            self.init(kwargs)

    # ---- core init -----------------------------------------------------
    def init(
        self,
        kwargs: Mapping[str, Any],
        *,
        allow_unknown: bool = False,
        allow_hidden: bool = False,
    ) -> Dict[str, Any]:
        """Initialize from string (or typed) kwargs.

        Returns the dict of unknown kwargs when ``allow_unknown`` (reference
        kAllowUnknown, InitAllowUnknown parameter.h:144-152); otherwise raises
        ``ParamError`` listing candidates (parameter.h:403-421). Keys starting
        with ``__`` and ending ``__`` are skipped when ``allow_hidden``
        (kAllowHidden, parameter.h:97-101).
        """
        unknown: Dict[str, Any] = {}
        fields = self.__param_fields__
        aliases = self.__param_aliases__
        for key, value in kwargs.items():
            target = aliases.get(key, key)
            if target in fields:
                info = fields[target]
                parsed = info.parse(value)
                info.check(parsed)
                object.__setattr__(self, target, parsed)
            elif allow_hidden and key.startswith("__") and key.endswith("__"):
                continue
            elif allow_unknown:
                unknown[key] = value
            else:
                raise ParamError(
                    f"Cannot find parameter {key!r} in {type(self).__name__}.\n"
                    f"{self.__doc_string__()}"
                )
        missing = [
            name
            for name, info in fields.items()
            if getattr(self, name) is UNSET and info.default is UNSET
        ]
        if missing:
            raise ParamError(
                f"Required parameter(s) {', '.join(missing)} of "
                f"{type(self).__name__} not presented.\n{self.__doc_string__()}"
            )
        return unknown

    def __setattr__(self, key: str, value: Any) -> None:
        info = self.__param_fields__.get(key)
        if info is not None:
            value = info.parse(value)
            info.check(value)
        object.__setattr__(self, key, value)

    # ---- dict / json / doc surface ------------------------------------
    def to_dict(self) -> Dict[str, str]:
        """All fields as strings (reference __DICT__, parameter.h:168-173)."""
        return {
            name: info.to_string(getattr(self, name))
            for name, info in self.__param_fields__.items()
        }

    def update_dict(self, target: Dict[str, str]) -> None:
        """Merge this parameter's fields into ``target`` (UpdateDict,
        parameter.h:176-180)."""
        target.update(self.to_dict())

    def save(self, fp) -> None:
        """Save as a JSON object of string values (parameter.h:185-190),
        through the in-repo streaming writer (io/json.py — json.h:188)."""
        _dmlc_json().dump(self.to_dict(), fp)

    def load(self, fp) -> None:
        """Load from JSON written by ``save`` (parameter.h:193-197),
        through the in-repo streaming reader (io/json.py — json.h:43)."""
        self.init(_dmlc_json().load(fp))

    def saves(self) -> str:
        return _dmlc_json().dumps(self.to_dict())

    def loads(self, text: str) -> None:
        self.init(_dmlc_json().loads(text))

    @classmethod
    def fields(cls) -> Dict[str, FieldInfo]:
        """Field metadata (reference __FIELDS__, parameter.h:202-205)."""
        return dict(cls.__param_fields__)

    @classmethod
    def __doc_string__(cls) -> str:
        """Generated docstring (reference __DOC__, parameter.h:208-213)."""
        lines = [f"Parameters of {cls.__name__}:"]
        for name, info in cls.__param_fields__.items():
            lines.append(f"  {name} : {info.type_string()}")
            if info.description:
                lines.append(f"      {info.description}")
        return "\n".join(lines)

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v}" for k, v in self.to_dict().items())
        return f"{type(self).__name__}({inner})"

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Parameter):
            return NotImplemented
        return type(self) is type(other) and self.to_dict() == other.to_dict()
