"""Global factory registries.

Capability parity with ``dmlc::Registry`` (reference include/dmlc/registry.h):
named singleton registries of factory entries with Find/List/ListAllNames,
aliases (registry.h:27-122), and entries carrying name/description/arguments/
return-type metadata (FunctionRegEntryBase, registry.h:146-222).

Idiomatic-Python shape: a generic ``Registry`` class with a decorator-based
``register``; the DMLC_REGISTRY_ENABLE/REGISTER macro dance and static-link
FILE_TAG tricks are unnecessary in Python (import side effects do the job).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

from dmlc_tpu.params.parameter import ParamError

T = TypeVar("T")


class RegistryEntry(Generic[T]):
    """One registered factory (reference FunctionRegEntryBase)."""

    def __init__(self, name: str, body: Callable[..., T]):
        self.name = name
        self.body = body
        self.description = ""
        self.arguments: List[Dict[str, str]] = []
        self.return_type = ""

    def describe(self, description: str) -> "RegistryEntry[T]":
        self.description = description
        return self

    def add_argument(
        self, name: str, type_str: str, description: str = ""
    ) -> "RegistryEntry[T]":
        self.arguments.append(
            {"name": name, "type": type_str, "description": description}
        )
        return self

    def set_return_type(self, rtype: str) -> "RegistryEntry[T]":
        self.return_type = rtype
        return self

    def __call__(self, *args: Any, **kwargs: Any) -> T:
        return self.body(*args, **kwargs)


class Registry(Generic[T]):
    """A named registry of factory entries.

    Class-level registries are obtained with ``Registry.get(name)`` — the
    Python analog of ``Registry<EntryType>::Get()`` singletons.
    """

    _registries: Dict[str, "Registry[Any]"] = {}
    _lock = threading.Lock()

    def __init__(self, name: str):
        self.name = name
        self._entries: Dict[str, RegistryEntry[T]] = {}
        self._entry_list: List[RegistryEntry[T]] = []

    @classmethod
    def get(cls, name: str) -> "Registry[Any]":
        with cls._lock:
            reg = cls._registries.get(name)
            if reg is None:
                reg = Registry(name)
                cls._registries[name] = reg
            return reg

    # ---- registration --------------------------------------------------
    def register(
        self, name: str, body: Optional[Callable[..., T]] = None
    ) -> Any:
        """Register a factory; usable directly or as a decorator.

        Mirrors ``__REGISTER__`` (registry.h:88-105): duplicate names raise.
        """

        def do_register(fn: Callable[..., T]) -> RegistryEntry[T]:
            with self._lock:
                if name in self._entries:
                    raise ParamError(
                        f"{name!r} already registered in registry {self.name!r}"
                    )
                entry: RegistryEntry[T] = RegistryEntry(name, fn)
                self._entries[name] = entry
                self._entry_list.append(entry)
                return entry

        if body is not None:
            return do_register(body)
        return do_register

    def add_alias(self, key_name: str, alias: str) -> None:
        """Register ``alias`` pointing at ``key_name``'s entry
        (registry.h:108-122)."""
        with self._lock:
            entry = self._entries.get(key_name)
            if entry is None:
                raise ParamError(
                    f"Cannot alias {key_name!r}: not found in {self.name!r}"
                )
            if alias in self._entries and self._entries[alias] is not entry:
                raise ParamError(f"Alias {alias!r} already taken in {self.name!r}")
            self._entries[alias] = entry

    # ---- lookup --------------------------------------------------------
    def find(self, name: str) -> Optional[RegistryEntry[T]]:
        return self._entries.get(name)

    def lookup(self, name: str) -> RegistryEntry[T]:
        entry = self.find(name)
        if entry is None:
            known = ", ".join(sorted(self._entries))
            raise ParamError(
                f"Unknown entry {name!r} in registry {self.name!r}; "
                f"known entries: [{known}]"
            )
        return entry

    def list_entries(self) -> List[RegistryEntry[T]]:
        return list(self._entry_list)

    def list_all_names(self) -> List[str]:
        return list(self._entries.keys())
