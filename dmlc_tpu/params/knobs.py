"""Ingest-pipeline tuning knobs, resolved through the typed env layer.

The ingest→HBM pipeline (data/pipeline.py + device/feed.py) has three
load-bearing degrees of freedom, each exposed the reference way
(parameter.h:1035-1063 typed GetEnv) so deployments tune them without
code changes:

- ``DMLC_TPU_NTHREAD``   — parse workers per parser (chunk fan-out width)
- ``DMLC_TPU_PREFETCH``  — device transfers kept in flight ahead of the
  consumer (``BatchSpec.prefetch``; 1 = classic double-buffer)
- ``DMLC_TPU_HOST_PREFETCH`` — parsed-but-undispatched host batches the
  feed's producer thread may buffer (-1 = auto: 0 on a 1-core host,
  else 2 — ``DeviceFeed.host_prefetch``'s own default)

Every call site that previously hard-coded a width resolves through
these helpers, so one env var retunes the whole stack (create_parser,
DeviceFeed, the learners' fit loops, bench.py).

The observability layer (dmlc_tpu/obs) adds three more:

- ``DMLC_TPU_METRICS`` — 0 disables the metrics registry (registrations
  hand out a shared no-op child; default 1, whose hot path is one
  lock-and-add)
- ``DMLC_TPU_TRACE`` — path for the Chrome trace-event JSON written by
  ``obs.span`` (empty = tracing off, the default)
- ``DMLC_TPU_METRICS_EXPORT`` — path the registry is exported to at
  epoch boundaries: ``*.prom`` → Prometheus textfile, else JSONL
  (empty = no file export, the default)
- ``DMLC_TPU_HEARTBEAT_GAP`` — seconds without a worker heartbeat
  before the tracker logs it as a straggler (default 60)
"""

from __future__ import annotations

from typing import Optional

from dmlc_tpu.params.env import get_env


def default_nthread(explicit: Optional[int] = None) -> int:
    """Parse-worker count: the explicit argument when given, else the
    ``DMLC_TPU_NTHREAD`` env knob, else 2 (the reference's default)."""
    if explicit is not None:
        return max(1, int(explicit))
    return max(1, get_env("DMLC_TPU_NTHREAD", 2))


def default_prefetch(explicit: Optional[int] = None) -> int:
    """Device-transfer window: explicit argument, else ``DMLC_TPU_PREFETCH``,
    else 1 (double-buffer)."""
    if explicit is not None:
        return max(1, int(explicit))
    return max(1, get_env("DMLC_TPU_PREFETCH", 1))


def default_host_prefetch(explicit: Optional[int] = None) -> Optional[int]:
    """Host-batch queue depth: explicit argument, else
    ``DMLC_TPU_HOST_PREFETCH`` (-1 → None → DeviceFeed's cpu-count auto),
    else None."""
    if explicit is not None:
        return explicit
    val = get_env("DMLC_TPU_HOST_PREFETCH", -1)
    return None if val < 0 else val


def metrics_enabled() -> bool:
    """Whether the obs metrics registry hands out live children
    (``DMLC_TPU_METRICS``, default on). Read at metric *registration*
    time, never on the per-increment path."""
    return get_env("DMLC_TPU_METRICS", True)


def trace_path() -> str:
    """Chrome-trace output path for ``obs.span`` (``DMLC_TPU_TRACE``;
    empty = tracing off)."""
    return get_env("DMLC_TPU_TRACE", "")


def metrics_export_path() -> str:
    """Epoch-boundary registry export target (``DMLC_TPU_METRICS_EXPORT``;
    ``*.prom`` → Prometheus textfile, anything else → JSONL appends,
    empty = no file export)."""
    return get_env("DMLC_TPU_METRICS_EXPORT", "")


def heartbeat_gap() -> float:
    """Straggler threshold in seconds for tracker heartbeats
    (``DMLC_TPU_HEARTBEAT_GAP``, default 60)."""
    return float(get_env("DMLC_TPU_HEARTBEAT_GAP", 60.0))
