"""Ingest-pipeline tuning knobs, resolved through the typed env layer.

The ingest→HBM pipeline (data/pipeline.py + device/feed.py) has three
load-bearing degrees of freedom, each exposed the reference way
(parameter.h:1035-1063 typed GetEnv) so deployments tune them without
code changes:

- ``DMLC_TPU_NTHREAD``   — parse workers per parser (chunk fan-out width)
- ``DMLC_TPU_PREFETCH``  — device transfers kept in flight ahead of the
  consumer (``BatchSpec.prefetch``; 1 = classic double-buffer)
- ``DMLC_TPU_HOST_PREFETCH`` — parsed-but-undispatched host batches the
  feed's producer thread may buffer (-1 = auto: 0 on a 1-core host,
  else 2 — ``DeviceFeed.host_prefetch``'s own default)
- ``DMLC_TPU_DEVICE_RESIDENT`` — the device-resident fast path: parsed
  RowBlocks emit straight into pooled staging (pad-in-place, one copy),
  one ``device_put`` per batch, donated landing buffers (default off)

Every call site that previously hard-coded a width resolves through
these helpers, so one env var retunes the whole stack (create_parser,
DeviceFeed, the learners' fit loops, bench.py).

The observability layer (dmlc_tpu/obs) adds three more:

- ``DMLC_TPU_METRICS`` — 0 disables the metrics registry (registrations
  hand out a shared no-op child; default 1, whose hot path is one
  lock-and-add)
- ``DMLC_TPU_TRACE`` — path for the Chrome trace-event JSON written by
  ``obs.span`` (empty = tracing off, the default)
- ``DMLC_TPU_METRICS_EXPORT`` — path the registry is exported to at
  epoch boundaries: ``*.prom`` → Prometheus textfile, else JSONL
  (empty = no file export, the default)
- ``DMLC_TPU_HEARTBEAT_GAP`` — seconds without a worker heartbeat
  before the tracker logs it as a straggler (default 60)

The job observability plane (obs/plane.py + obs/flight.py) adds:

- ``DMLC_TPU_STATUS_PORT`` — port for the tracker's HTTP status server
  (0 = ephemeral; unset = no server, no thread, no socket — the default)
- ``DMLC_TPU_STATUS_URI`` — ``host:port`` of the running status server,
  exported *by* the tracker to workers (informational; never set it
  yourself)
- ``DMLC_TPU_OBS_PUBLISH`` — workers piggyback obs payloads on tracker
  heartbeats when 1; exported by the tracker when its status plane is
  armed (default off — a worker never surprises a reference tracker)
- ``DMLC_TPU_OBS_PAYLOAD_MAX`` — byte cap for one heartbeat obs payload
  (default 65536; oldest spans shed first, counted in
  ``dmlc_obs_spans_dropped_total``)
- ``DMLC_TPU_FLIGHTREC`` — directory the crash flight recorder dumps
  ``flightrec-rank<k>.json`` into (empty = recorder off, the default)
- ``DMLC_TPU_FLIGHTREC_CAP`` — flight-recorder ring capacity in records
  (default 256)

The resilience layer (dmlc_tpu/resilience) adds five more:

- ``DMLC_TPU_RETRY_BUDGET`` — process-wide retry token bucket capacity
  (0 = unlimited, the default; see resilience/retry.py)
- ``DMLC_TPU_RETRY_DEADLINE_S`` — default wall-clock deadline per
  retried logical call, seconds (0 = none, the default)
- ``DMLC_TPU_FAULTS`` — deterministic fault-injection spec, e.g.
  ``io.read:p=0.02:seed=7;collective.send:nth=3`` (empty = every
  faultpoint is a shared no-op, the default)
- ``DMLC_TPU_HEDGE_S`` — latency threshold in seconds after which the
  readahead fetch path issues one hedged backup request (0 = hedging
  off, the default)
- ``DMLC_TPU_CKPT_FALLBACK_URI`` — secondary checkpoint directory that
  ``CheckpointManager`` commits to when the primary URI exhausts its
  retry budget (empty = no fallback, the default)

Preemption-proof snapshots (collective/snapshot.py +
resilience/preempt.py, see docs/robustness.md "Preemption & resume")
add two more:

- ``DMLC_TPU_SNAP_EVERY_S`` — wall-clock job-snapshot cadence in
  seconds: with a ``Snapshotter`` armed, an epoch boundary also commits
  when this much time passed since the last committed snapshot, on top
  of the epoch cadence (0 = epoch cadence only, the default)
- ``DMLC_TPU_PREEMPT_DEADLINE_S`` — seconds the preemption handler
  budgets between a preemption notice (SIGTERM or injected
  ``preempt.notice``) and process exit; the just-in-time snapshot
  commit must land inside it (default 30)

Elastic membership (tracker/rendezvous.py + collective, see
docs/robustness.md "Elastic membership") adds four more:

- ``DMLC_TPU_ELASTIC`` — workers opt into generation re-rendezvous: a
  collective failure (or a bumped heartbeat ack) re-enters the tracker
  with ``cmd='elastic'`` into a rebuilt world instead of ``recover``
  into the old one (default off — fixed-world rabit semantics)
- ``DMLC_TPU_ELASTIC_WINDOW_S`` — tracker-side quiescence window for a
  membership transition: the generation commits this many seconds after
  the last entrant arrived (default 3)
- ``DMLC_TPU_EVICT_AFTER_S`` — tracker-side eviction policy: a rank
  whose last heartbeat is older than this is marked evicted and the
  survivors drain into a smaller world via ``run_with_recovery``
  (0 = eviction off, the default; requires workers that heartbeat)
- ``DMLC_TPU_SPARE`` — set by the launcher (``--spares N``) on warm
  spare tasks: ``collective.init`` registers via the tracker ``join``
  handshake and blocks until a transition activates the spare (or
  exits 0 if the job finishes without needing it)

The fault-tolerant data service (data/dispatcher.py + data/service.py,
see docs/distributed.md "Disaggregated ingest") adds five more:

- ``DMLC_TPU_DATA_CHUNKS`` — chunks the dispatcher splits one dataset
  into (the lease/requeue granularity; default 16)
- ``DMLC_TPU_DATA_LEASE_S`` — seconds a leased/delivered chunk may stay
  unacked before the dispatcher requeues it (default 30)
- ``DMLC_TPU_DATA_DEAD_S`` — seconds of heartbeat silence before a data
  worker is declared dead and its leases requeued (default 10)
- ``DMLC_TPU_DATA_PENDING_CAP`` — cap on one service's undelivered-block
  requeue stash; a full stash backpressures then drops (default 64;
  0 or negative = unbounded, the pre-cap behavior)
- ``DMLC_TPU_DATA_HEDGE_S`` — seconds of fetch silence before a
  dispatcher-mode client hedges the fetch against a second live worker
  (0 = hedging off, the default)

The multi-tenant fleet layer (data/dispatcher.py jobs + the shared
source cache + the autoscaler, see docs/distributed.md "Multi-tenant
fleet") adds four more:

- ``DMLC_TPU_DATA_MAX_JOBS`` — tenant jobs one dispatcher admits before
  refusing registration with typed backpressure (DataBusyError;
  default 8)
- ``DMLC_TPU_DATA_JOB_INFLIGHT`` — default per-job cap on
  leased+delivered chunks in flight; the fair-share scheduler answers
  ``busy`` above it (0 = uncapped, the default)
- ``DMLC_TPU_DATA_CACHE_MB`` — byte budget (in MiB) of the per-worker
  job-shared source cache: N jobs reading one dataset parse it once
  (default 256; 0 disables the tier, every parse goes direct)
- ``DMLC_TPU_DATA_SCALE_INTERVAL_S`` — seconds between worker-autoscaler
  control-loop ticks (default 1.0)

Device telemetry (obs/device_telemetry.py, see docs/observability.md
"Device telemetry") adds two more:

- ``DMLC_TPU_DEVICE_TELEMETRY`` — the recompile sentinel, H2D meter, and
  HBM gauges (default on; 0 makes ``instrumented_jit`` return the plain
  ``jax.jit`` callable and ``h2d_meter`` return None — the disabled hot
  path is byte-for-byte the uninstrumented one)
- ``DMLC_TPU_HBM_POLL_S`` — period in seconds for the background HBM
  sampler thread (0 = no thread, the default; sampling still happens at
  payload-publish and bench boundaries)

The collective engine layer (collective/__init__.py, see
docs/distributed.md "Device collectives") adds:

- ``DMLC_TPU_COLLECTIVE`` — engine selection for ``collective.init``:
  ``auto`` (default), ``device`` (in-mesh XLA collectives — the SPMD
  training path), ``socket`` (reference rabit tree/ring, the
  CPU/cross-host fallback), ``local``. An explicit ``engine=`` argument
  to ``init`` always beats the env.

The vectorized text-parse path (data/vparse.py + cpp/parse_simd.cc, see
docs/pipeline.md "Vectorized parse") adds three more:

- ``DMLC_TPU_PARSE_BACKEND`` — chunk-parse implementation selector:
  ``auto`` (default: native core when loadable, else the vectorized
  numpy path), ``native`` (native-or-vector, never scalar), ``vector``
  (numpy columnar path even when the native core is available — the
  parity suite's workhorse), ``scalar`` (pure-Python reference oracle)
- ``DMLC_TPU_PARSE_PROCS`` — when > 0, PipelinedParser routes chunk
  parses through a pool of that many worker *processes* instead of
  parsing on its worker threads (GIL-free scaling for the Python parse
  backends; ordering, backpressure and error poisoning are unchanged
  because the OrderedWindow threads block on the process futures)
- ``DMLC_TPU_SIMD`` — native engine dispatch: unset/empty = adaptive
  (a first-line probe routes long-feature-id corpora to the AVX2 tile
  engine, short-id corpora to the scalar SWAR core), ``1`` = always use
  the engine when the CPU supports it (parity tests force this),
  anything else = engine off

The goodput ledger and runtime watchdog (obs/goodput.py +
obs/watchdog.py, see docs/observability.md "Goodput & attribution")
add five more:

- ``DMLC_TPU_WATCHDOG_STALL_S`` — cumulative seconds of zero ledger
  progress before the watchdog fires a ``stall`` alert (default 60;
  0 disables stall detection)
- ``DMLC_TPU_WATCHDOG_PROFILE`` — when 1, a firing watchdog triggers
  the on-demand device profiler capture for the regression window
  (default off)
- ``DMLC_TPU_PARSE_PEAK_MBPS`` — roofline ceiling for the parse stage
  in MB/s (default 1000 — the vectorized parse_only tier)
- ``DMLC_TPU_STEP_PEAK_MBPS`` — roofline ceiling for the device step's
  byte rate in MB/s (default 0 = unknown; set from the model's measured
  FLOP rate)
- ``DMLC_TPU_ICI_PEAK_GBPS`` — per-direction per-link ICI peak in GB/s
  (default 45; the same figure bench_collective.py scores utilization
  against)

The compiled-step cost attribution layer (obs/xla_cost.py, see
docs/observability.md "Compiled-step cost attribution") adds three
more:

- ``DMLC_TPU_STEP_SAMPLE_N`` — device-step latency sampling stride:
  every N-th step gets a ``block_until_ready`` and a
  ``dmlc_step_device_ms`` observation (default 64; 0 = never)
- ``DMLC_TPU_PEAK_FLOPS`` — model-based roofline peak in FLOP/s for the
  MFU verdict (default 0 = use the measured matmul probe)
- ``DMLC_TPU_PEAK_HBM_GBPS`` — model-based memory-bandwidth peak in
  GB/s for the achieved-HBM-fraction verdict (default 0 = use the
  measured streaming probe)

Baked columnar shards (io/shard.py + tools/bake.py, see
docs/pipeline.md "Baked shards & global shuffle") add three more:

- ``DMLC_TPU_SHUFFLE`` — windowed global-shuffle seed for shard reads
  (≥ 0 arms a seeded permutation of the global window table, a pure
  function of (seed, epoch); -1 — the default — reads windows in baked
  order). The ``shuffle_chunks`` URI arg beats the env per dataset.
- ``DMLC_TPU_SHUFFLE_WINDOW`` — shuffle unit in consecutive baked
  windows (default 1, floor 1): larger units trade shuffle quality for
  longer sequential runs on disk
- ``DMLC_TPU_SHARD_MMAP`` — zero-copy shard reads: windows decode as
  ``np.frombuffer`` views over one file mapping (default on; 0 falls
  back to seek+read per window — NFS or map-exhausted hosts)

The determinism audit plane (obs/audit.py, see docs/observability.md
"Audit plane") adds two more:

- ``DMLC_TPU_AUDIT`` — streaming stage-digest ledger: ``1``/``full``
  digests every chunk/batch/step, ``sample`` digests every
  ``DMLC_TPU_AUDIT_SAMPLE_N``-th chunk, anything else (the default)
  hands every call site the shared no-op auditor — the hot path stays
  allocation-free
- ``DMLC_TPU_AUDIT_SAMPLE_N`` — sampling stride for ``sample`` mode
  (default 16, floor 1)

``KNOWN_KNOBS`` below is the authoritative list of every
``DMLC_TPU_*`` variable the tree reads; ``scripts/check_faultpoints.py``
fails CI when a knob is referenced anywhere without being registered
here.
"""

from __future__ import annotations

from typing import Optional

from dmlc_tpu.params.env import get_env


def default_nthread(explicit: Optional[int] = None) -> int:
    """Parse-worker count: the explicit argument when given, else the
    ``DMLC_TPU_NTHREAD`` env knob, else 2 (the reference's default)."""
    if explicit is not None:
        return max(1, int(explicit))
    return max(1, get_env("DMLC_TPU_NTHREAD", 2))


def default_prefetch(explicit: Optional[int] = None) -> int:
    """Device-transfer window: explicit argument, else ``DMLC_TPU_PREFETCH``,
    else 1 (double-buffer)."""
    if explicit is not None:
        return max(1, int(explicit))
    return max(1, get_env("DMLC_TPU_PREFETCH", 1))


def default_host_prefetch(explicit: Optional[int] = None) -> Optional[int]:
    """Host-batch queue depth: explicit argument, else
    ``DMLC_TPU_HOST_PREFETCH`` (-1 → None → DeviceFeed's cpu-count auto),
    else None."""
    if explicit is not None:
        return explicit
    val = get_env("DMLC_TPU_HOST_PREFETCH", -1)
    return None if val < 0 else val


def metrics_enabled() -> bool:
    """Whether the obs metrics registry hands out live children
    (``DMLC_TPU_METRICS``, default on). Read at metric *registration*
    time, never on the per-increment path."""
    return get_env("DMLC_TPU_METRICS", True)


def trace_path() -> str:
    """Chrome-trace output path for ``obs.span`` (``DMLC_TPU_TRACE``;
    empty = tracing off)."""
    return get_env("DMLC_TPU_TRACE", "")


def metrics_export_path() -> str:
    """Epoch-boundary registry export target (``DMLC_TPU_METRICS_EXPORT``;
    ``*.prom`` → Prometheus textfile, anything else → JSONL appends,
    empty = no file export)."""
    return get_env("DMLC_TPU_METRICS_EXPORT", "")


def heartbeat_gap() -> float:
    """Straggler threshold in seconds for tracker heartbeats
    (``DMLC_TPU_HEARTBEAT_GAP``, default 60)."""
    return float(get_env("DMLC_TPU_HEARTBEAT_GAP", 60.0))


def status_port() -> Optional[int]:
    """Tracker status-server port (``DMLC_TPU_STATUS_PORT``; 0 =
    ephemeral). None — the default — means no server at all: the tracker
    keeps the shared no-op plane, binds nothing, starts no thread."""
    val = get_env("DMLC_TPU_STATUS_PORT", -1)
    return None if val < 0 else val


def obs_publish_enabled() -> bool:
    """Whether this worker piggybacks obs payloads onto tracker
    heartbeats (``DMLC_TPU_OBS_PUBLISH``; exported by the tracker when
    its status plane is armed, default off)."""
    return get_env("DMLC_TPU_OBS_PUBLISH", False)


def obs_payload_max() -> int:
    """Byte cap for one heartbeat obs payload
    (``DMLC_TPU_OBS_PAYLOAD_MAX``, default 64 KiB, floor 1 KiB so the
    liveness + clock-probe core always fits)."""
    return max(1024, get_env("DMLC_TPU_OBS_PAYLOAD_MAX", 65536))


def flightrec_dir() -> str:
    """Crash flight-recorder dump directory (``DMLC_TPU_FLIGHTREC``;
    empty = recorder off, the default)."""
    return get_env("DMLC_TPU_FLIGHTREC", "")


def flightrec_capacity() -> int:
    """Flight-recorder ring capacity in records
    (``DMLC_TPU_FLIGHTREC_CAP``, default 256, floor 16)."""
    return max(16, get_env("DMLC_TPU_FLIGHTREC_CAP", 256))


def retry_budget_tokens() -> int:
    """Process-wide retry token-bucket capacity
    (``DMLC_TPU_RETRY_BUDGET``; 0 = unlimited, the default)."""
    return max(0, get_env("DMLC_TPU_RETRY_BUDGET", 0))


def retry_deadline_s() -> float:
    """Default wall-clock deadline per retried logical call in seconds
    (``DMLC_TPU_RETRY_DEADLINE_S``; 0 = no deadline, the default)."""
    return max(0.0, float(get_env("DMLC_TPU_RETRY_DEADLINE_S", 0.0)))


def faults_spec() -> str:
    """The deterministic fault-injection spec (``DMLC_TPU_FAULTS``;
    empty = faultpoints are a shared no-op, the default). Grammar in
    resilience/faults.py and docs/robustness.md."""
    return get_env("DMLC_TPU_FAULTS", "")


def hedge_threshold_s() -> float:
    """Latency threshold after which the readahead fetch path issues a
    single hedged backup request (``DMLC_TPU_HEDGE_S``; 0 = hedging
    off, the default)."""
    return max(0.0, float(get_env("DMLC_TPU_HEDGE_S", 0.0)))


def ckpt_fallback_uri() -> str:
    """Secondary checkpoint directory used when commits to the primary
    URI exhaust their retry budget (``DMLC_TPU_CKPT_FALLBACK_URI``;
    empty = no fallback, the default)."""
    return get_env("DMLC_TPU_CKPT_FALLBACK_URI", "")


def snap_every_s() -> float:
    """Wall-clock job-snapshot cadence (``DMLC_TPU_SNAP_EVERY_S``,
    default 0 = epoch cadence only): with a ``Snapshotter`` armed, an
    epoch boundary also commits when this many seconds passed since the
    last committed snapshot, whatever the epoch cadence says."""
    return max(0.0, float(get_env("DMLC_TPU_SNAP_EVERY_S", 0.0)))


def preempt_deadline_s() -> float:
    """Seconds budgeted between a preemption notice (SIGTERM or an
    injected ``preempt.notice`` fault) and process exit
    (``DMLC_TPU_PREEMPT_DEADLINE_S``, default 30): the just-in-time
    coordinated snapshot commit must land inside this window."""
    return max(0.0, float(get_env("DMLC_TPU_PREEMPT_DEADLINE_S", 30.0)))


def elastic_enabled() -> bool:
    """Whether this worker participates in elastic membership
    (``DMLC_TPU_ELASTIC``, default off): collective failures and bumped
    heartbeat acks re-rendezvous into the tracker's next generation
    (``cmd='elastic'``) instead of recovering into the fixed world."""
    return get_env("DMLC_TPU_ELASTIC", False)


def elastic_window_s() -> float:
    """Tracker-side quiescence window in seconds for one membership
    transition (``DMLC_TPU_ELASTIC_WINDOW_S``, default 3): the new
    generation commits once no new entrant has arrived for this long,
    floor 0.1 so the accept loop always gets a tick to batch entrants."""
    return max(0.1, float(get_env("DMLC_TPU_ELASTIC_WINDOW_S", 3.0)))


def evict_after_s() -> float:
    """Tracker-side straggler eviction threshold in seconds
    (``DMLC_TPU_EVICT_AFTER_S``; 0 = eviction off, the default). A rank
    whose last heartbeat is older than this is marked evicted: its next
    elastic re-entry is refused and the survivors rebuild without it."""
    return max(0.0, float(get_env("DMLC_TPU_EVICT_AFTER_S", 0.0)))


def data_chunks(explicit: Optional[int] = None) -> int:
    """Chunk count for lease-based dispatch: the explicit argument when
    given, else ``DMLC_TPU_DATA_CHUNKS``, else 16. More chunks = finer
    reassignment granularity (less lost work per worker death) at more
    lease RPCs per epoch; floor 1."""
    if explicit is not None:
        return max(1, int(explicit))
    return max(1, get_env("DMLC_TPU_DATA_CHUNKS", 16))


def data_lease_s(explicit: Optional[float] = None) -> float:
    """Chunk lease duration in seconds: explicit argument, else
    ``DMLC_TPU_DATA_LEASE_S``, else 30. Size it well above one chunk's
    parse+serve+consume time — a too-short lease requeues chunks that
    merely ran slow (their late deliveries are then rejected: correct,
    but wasted work). Floor 0.1."""
    if explicit is not None:
        return max(0.1, float(explicit))
    return max(0.1, float(get_env("DMLC_TPU_DATA_LEASE_S", 30.0)))


def data_dead_after_s(explicit: Optional[float] = None) -> float:
    """Data-worker death threshold in seconds of heartbeat silence:
    explicit argument, else ``DMLC_TPU_DATA_DEAD_S``, else 10. Workers
    heartbeat at a third of this, so one lost beat never reads as a
    crash. Floor 0.1."""
    if explicit is not None:
        return max(0.1, float(explicit))
    return max(0.1, float(get_env("DMLC_TPU_DATA_DEAD_S", 10.0)))


def data_pending_cap() -> int:
    """Cap on a block service's undelivered-block requeue stash
    (``DMLC_TPU_DATA_PENDING_CAP``, default 64; 0 or negative =
    unbounded). A full stash backpressures the stashing thread briefly,
    then drops the block — metered as a drop, never silently."""
    return get_env("DMLC_TPU_DATA_PENDING_CAP", 64)


def data_max_jobs(explicit: Optional[int] = None) -> int:
    """Tenant jobs one dispatcher admits: explicit argument, else
    ``DMLC_TPU_DATA_MAX_JOBS``, else 8. Registration past the cap is
    refused with ``DataBusyError`` — typed backpressure the client's
    RetryPolicy already classifies transient. Floor 1."""
    if explicit is not None:
        return max(1, int(explicit))
    return max(1, get_env("DMLC_TPU_DATA_MAX_JOBS", 8))


def data_job_inflight() -> int:
    """Default per-job in-flight chunk cap (leased + delivered) for the
    fair-share lease scheduler (``DMLC_TPU_DATA_JOB_INFLIGHT``; 0 =
    uncapped, the default). ``add_job(max_inflight=...)`` overrides it
    per job."""
    return max(0, get_env("DMLC_TPU_DATA_JOB_INFLIGHT", 0))


def data_cache_mb() -> int:
    """Byte budget in MiB for the job-shared source cache
    (``DMLC_TPU_DATA_CACHE_MB``, default 256; 0 disables the tier —
    every chunk parse goes direct). Read once, at first cache use."""
    return max(0, get_env("DMLC_TPU_DATA_CACHE_MB", 256))


def data_scale_interval_s(explicit: Optional[float] = None) -> float:
    """Worker-autoscaler control-loop period in seconds: explicit
    argument, else ``DMLC_TPU_DATA_SCALE_INTERVAL_S``, else 1.0. Floor
    0.05 — the loop samples a snapshot per tick and must not busy-spin
    the dispatcher lock."""
    if explicit is not None:
        return max(0.05, float(explicit))
    return max(0.05, float(get_env("DMLC_TPU_DATA_SCALE_INTERVAL_S", 1.0)))


def data_hedge_s() -> float:
    """Fetch-hedging threshold for dispatcher-mode clients in seconds
    (``DMLC_TPU_DATA_HEDGE_S``; 0 = hedging off, the default). Distinct
    from ``DMLC_TPU_HEDGE_S`` (the readahead I/O hedge): this one races
    a whole chunk fetch against a second data worker."""
    return max(0.0, float(get_env("DMLC_TPU_DATA_HEDGE_S", 0.0)))


def device_resident() -> bool:
    """Whether ``DeviceFeed`` uses the device-resident fast path
    (``DMLC_TPU_DEVICE_RESIDENT``, default off): parsed columnar
    RowBlocks are emitted straight into pooled staging (one copy,
    pad-in-place), the whole batch crosses H2D as one ``device_put``,
    and the jitted steps donate the landing buffers back to XLA. The
    legacy materialize+pad path stays the default and the fallback
    (non-CSR layouts, exotic parsers). Read once per feed, at
    construction."""
    return get_env("DMLC_TPU_DEVICE_RESIDENT", False)


def device_telemetry_enabled() -> bool:
    """Whether the device telemetry layer is live
    (``DMLC_TPU_DEVICE_TELEMETRY``, default on). Read once where each
    surface is built (jit wrap time, feed construction), never on the
    per-dispatch path."""
    return get_env("DMLC_TPU_DEVICE_TELEMETRY", True)


def hbm_poll_s() -> float:
    """Background HBM sampler period in seconds (``DMLC_TPU_HBM_POLL_S``;
    0 = no poller thread, the default)."""
    return max(0.0, float(get_env("DMLC_TPU_HBM_POLL_S", 0.0)))


def watchdog_stall_s() -> float:
    """Cumulative seconds without goodput-ledger progress before the
    runtime watchdog fires a ``stall`` alert
    (``DMLC_TPU_WATCHDOG_STALL_S``, default 60; 0 = stall detection
    off)."""
    return max(0.0, float(get_env("DMLC_TPU_WATCHDOG_STALL_S", 60.0)))


def watchdog_profile() -> bool:
    """Whether a firing watchdog auto-triggers the on-demand device
    profiler capture for the regression window
    (``DMLC_TPU_WATCHDOG_PROFILE``, default off)."""
    return get_env("DMLC_TPU_WATCHDOG_PROFILE", False)


def parse_peak_mbps() -> float:
    """Roofline ceiling for the parse stage in MB/s
    (``DMLC_TPU_PARSE_PEAK_MBPS``, default 1000 — the vectorized
    parse_only bench tier; 0 = unknown)."""
    return max(0.0, float(get_env("DMLC_TPU_PARSE_PEAK_MBPS", 1000.0)))


def step_peak_mbps() -> float:
    """Roofline ceiling for the device step's consumed byte rate in
    MB/s (``DMLC_TPU_STEP_PEAK_MBPS``, default 0 = unknown — set it
    from the model's measured FLOP rate to score step utilization)."""
    return max(0.0, float(get_env("DMLC_TPU_STEP_PEAK_MBPS", 0.0)))


def ici_peak_gbps() -> float:
    """Per-direction per-link ICI peak bandwidth in GB/s
    (``DMLC_TPU_ICI_PEAK_GBPS``, default 45 — the figure
    bench_collective.py scores utilization against)."""
    return max(0.0, float(get_env("DMLC_TPU_ICI_PEAK_GBPS", 45.0)))


def step_sample_n() -> int:
    """Device-step latency sampling stride (``DMLC_TPU_STEP_SAMPLE_N``,
    default 64, floor 0 = never sample): every N-th step the fit loop
    adds one ``block_until_ready`` around the step output and records
    ``dmlc_step_device_ms`` — the other N−1 steps dispatch async with no
    added sync. Read once per fit, at FitLoopObs construction."""
    return max(0, int(get_env("DMLC_TPU_STEP_SAMPLE_N", 64)))


def peak_flops() -> float:
    """Model-based roofline peak in FLOP/s (``DMLC_TPU_PEAK_FLOPS``,
    default 0 = auto: the measured matmul probe
    ``obs.xla_cost.probed_peak_flops`` stands in). The MFU verdict is
    window FLOPs (steps × per-step XLA flops) over this ceiling."""
    return max(0.0, float(get_env("DMLC_TPU_PEAK_FLOPS", 0.0)))


def peak_hbm_gbps() -> float:
    """Model-based device-memory-bandwidth peak in GB/s
    (``DMLC_TPU_PEAK_HBM_GBPS``, default 0 = auto: the measured
    streaming probe ``obs.xla_cost.probed_hbm_gbps`` stands in). The
    achieved-HBM-fraction verdict is window bytes accessed over this
    ceiling."""
    return max(0.0, float(get_env("DMLC_TPU_PEAK_HBM_GBPS", 0.0)))


def audit_mode() -> str:
    """Determinism-audit ledger mode (``DMLC_TPU_AUDIT``): ``full``
    (aliases ``1``/``on``) digests every chunk, parsed block, emitted
    batch, and model step; ``sample`` digests every
    :func:`audit_sample_n`-th sequence number for bounded overhead;
    ``off`` — the default — makes :func:`dmlc_tpu.obs.audit.auditor`
    return the shared no-op child (zero-alloc hot path)."""
    val = str(get_env("DMLC_TPU_AUDIT", "")).strip().lower()
    if val in ("1", "on", "full", "true"):
        return "full"
    if val == "sample":
        return "sample"
    return "off"


def audit_sample_n() -> int:
    """Digest stride for ``DMLC_TPU_AUDIT=sample``
    (``DMLC_TPU_AUDIT_SAMPLE_N``, default 16, floor 1): only sequence
    numbers divisible by N are digested, trading localization
    granularity for overhead."""
    return max(1, get_env("DMLC_TPU_AUDIT_SAMPLE_N", 16))


def parse_backend() -> str:
    """Chunk-parse implementation (``DMLC_TPU_PARSE_BACKEND``): one of
    ``auto`` (native when loadable, else vector — the default),
    ``native``, ``vector``, ``scalar``. Unknown values read as auto."""
    val = str(get_env("DMLC_TPU_PARSE_BACKEND", "auto")).strip().lower()
    return val if val in ("auto", "native", "vector", "scalar") else "auto"


def parse_procs() -> int:
    """Process-pool parse workers (``DMLC_TPU_PARSE_PROCS``, default 0 =
    parse on the PipelinedParser's own threads). When > 0 each worker
    thread submits its chunk to a shared pool of this many processes and
    blocks on the future, so window ordering, backpressure and error
    poisoning behave exactly as in the threaded path."""
    return max(0, get_env("DMLC_TPU_PARSE_PROCS", 0))


def shuffle_seed() -> int:
    """Windowed global-shuffle seed for baked shard reads
    (``DMLC_TPU_SHUFFLE``, default -1 = shuffle off). A seed ≥ 0 arms a
    seeded permutation of the shard window table — a pure function of
    (seed, epoch), independent of the world size, so re-sharding and
    resume replay the same global order (io/shard.py). A
    ``shuffle_chunks=`` URI arg overrides the env per dataset."""
    return int(get_env("DMLC_TPU_SHUFFLE", -1))


def shuffle_window() -> int:
    """Shuffle unit in consecutive baked windows
    (``DMLC_TPU_SHUFFLE_WINDOW``, default 1, floor 1): the permutation
    moves runs of this many windows together, trading shuffle quality
    for longer sequential reads."""
    return max(1, get_env("DMLC_TPU_SHUFFLE_WINDOW", 1))


def shard_mmap() -> bool:
    """Zero-copy shard reads (``DMLC_TPU_SHARD_MMAP``, default on):
    window decodes are ``np.frombuffer`` views over one shared file
    mapping. 0 falls back to seek+read per window."""
    return bool(get_env("DMLC_TPU_SHARD_MMAP", True))


def collective_engine() -> str:
    """Collective engine selection (``DMLC_TPU_COLLECTIVE``): one of
    ``auto`` (the default — device when a multi-process mesh is up,
    socket when a tracker URI is set, else local), ``device`` (in-mesh
    XLA collectives — the SPMD training path), ``socket`` (the
    reference rabit tree/ring over TCP — CPU/cross-host fallback),
    ``local`` (single-process no-op world). Unknown values read as
    auto. Consulted by ``collective.init(engine="auto")`` only — an
    explicit ``engine=`` argument always wins over the env."""
    val = str(get_env("DMLC_TPU_COLLECTIVE", "auto")).strip().lower()
    return val if val in ("auto", "device", "socket", "local") else "auto"


def is_spare() -> bool:
    """Whether this process was launched as a warm spare
    (``DMLC_TPU_SPARE``, set by the launcher's ``--spares`` tasks).
    ``collective.init`` then registers through the tracker ``join``
    handshake and blocks until a membership transition activates it."""
    return get_env("DMLC_TPU_SPARE", False)


# Every DMLC_TPU_* env var the tree reads, in one place. The faultpoint
# lint (scripts/check_faultpoints.py) greps the source for DMLC_TPU_*
# literals and fails when one is missing from this registry, so a new
# knob cannot ship undocumented.
KNOWN_KNOBS = (
    # ingest pipeline
    "DMLC_TPU_NTHREAD",
    "DMLC_TPU_PREFETCH",
    "DMLC_TPU_HOST_PREFETCH",
    "DMLC_TPU_READAHEAD_MB",
    "DMLC_TPU_READAHEAD_CONNS",
    "DMLC_TPU_FEED_PUT",
    "DMLC_TPU_DEVICE_RESIDENT",
    # vectorized parse path
    "DMLC_TPU_PARSE_BACKEND",
    "DMLC_TPU_PARSE_PROCS",
    "DMLC_TPU_SIMD",
    # native bridge
    "DMLC_TPU_NATIVE",
    "DMLC_TPU_NATIVE_LIB",
    "DMLC_TPU_ABI_VERSION",
    "DMLC_TPU_PALLAS",
    # observability
    "DMLC_TPU_METRICS",
    "DMLC_TPU_TRACE",
    "DMLC_TPU_TRACE_JAX",
    "DMLC_TPU_METRICS_EXPORT",
    "DMLC_TPU_HEARTBEAT_GAP",
    # job observability plane
    "DMLC_TPU_STATUS_PORT",
    "DMLC_TPU_STATUS_URI",
    "DMLC_TPU_OBS_PUBLISH",
    "DMLC_TPU_OBS_PAYLOAD_MAX",
    "DMLC_TPU_FLIGHTREC",
    "DMLC_TPU_FLIGHTREC_CAP",
    # fault-tolerant data service
    "DMLC_TPU_DATA_CHUNKS",
    "DMLC_TPU_DATA_LEASE_S",
    "DMLC_TPU_DATA_DEAD_S",
    "DMLC_TPU_DATA_PENDING_CAP",
    "DMLC_TPU_DATA_HEDGE_S",
    # multi-tenant fleet: jobs, shared source cache, autoscaler
    "DMLC_TPU_DATA_MAX_JOBS",
    "DMLC_TPU_DATA_JOB_INFLIGHT",
    "DMLC_TPU_DATA_CACHE_MB",
    "DMLC_TPU_DATA_SCALE_INTERVAL_S",
    # device telemetry
    "DMLC_TPU_DEVICE_TELEMETRY",
    "DMLC_TPU_HBM_POLL_S",
    # goodput ledger + runtime watchdog
    "DMLC_TPU_WATCHDOG_STALL_S",
    "DMLC_TPU_WATCHDOG_PROFILE",
    # baked columnar shards
    "DMLC_TPU_SHUFFLE",
    "DMLC_TPU_SHUFFLE_WINDOW",
    "DMLC_TPU_SHARD_MMAP",
    # determinism audit plane
    "DMLC_TPU_AUDIT",
    "DMLC_TPU_AUDIT_SAMPLE_N",
    "DMLC_TPU_PARSE_PEAK_MBPS",
    "DMLC_TPU_STEP_PEAK_MBPS",
    "DMLC_TPU_ICI_PEAK_GBPS",
    "DMLC_TPU_STEP_SAMPLE_N",
    "DMLC_TPU_PEAK_FLOPS",
    "DMLC_TPU_PEAK_HBM_GBPS",
    # collective / distributed bootstrap
    "DMLC_TPU_COLLECTIVE",
    "DMLC_TPU_RECOVER_TIMEOUT",
    "DMLC_TPU_RING_THRESHOLD_BYTES",
    "DMLC_TPU_COORDINATOR",
    "DMLC_TPU_NUM_PROC",
    "DMLC_TPU_PROC_ID",
    # resilience
    "DMLC_TPU_RETRY_BUDGET",
    "DMLC_TPU_RETRY_DEADLINE_S",
    "DMLC_TPU_FAULTS",
    "DMLC_TPU_HEDGE_S",
    "DMLC_TPU_CKPT_FALLBACK_URI",
    # preemption-proof snapshots
    "DMLC_TPU_SNAP_EVERY_S",
    "DMLC_TPU_PREEMPT_DEADLINE_S",
    # elastic membership
    "DMLC_TPU_ELASTIC",
    "DMLC_TPU_ELASTIC_WINDOW_S",
    "DMLC_TPU_EVICT_AFTER_S",
    "DMLC_TPU_SPARE",
    # bench harness
    "DMLC_TPU_BENCH_DETAIL",
    "DMLC_TPU_BENCH_DIR",
    "DMLC_TPU_BENCH_PROBE_ATTEMPTS",
    "DMLC_TPU_BENCH_PROBE_TIMEOUT",
    "DMLC_TPU_BENCH_SOCKET_WORLD",
    "DMLC_TPU_HARVEST_DIR",
)
