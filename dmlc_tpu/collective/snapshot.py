"""Async job snapshotting: capture on the step path, commit off it.

:class:`~dmlc_tpu.collective.checkpoint.JobSnapshot` gives the durable
two-phase-commit format; this module keeps it off the training step
path. The split:

- :meth:`Snapshotter.capture` runs at the epoch boundary on the
  training thread: it materializes a host copy of the state tree
  (timed into ``dmlc_snap_capture_ns`` — this is the *donation-safe*
  copy: the next epoch's donating steps are free to invalidate the
  device buffers once capture returned) and hands it to the writer.
- A background writer thread serializes and two-phase-commits the
  snapshot (``dmlc_snap_write_ns``), completely off the step path. The
  goodput ledger's ``checkpoint`` stage reads ``dmlc_snap_capture_ns``,
  so the overhead the training loop actually pays is first-class in the
  stall attribution.

The writer holds a single *newest-wins* slot, not a queue: if epoch N's
snapshot is still writing when N+1's capture lands, N+1 replaces any
not-yet-started N (``dmlc_snap_skipped_total``) — a slow filesystem
can only ever delay durability, never build an unbounded backlog.
Because a skip can hit one rank and not another, version numbers are
*epoch-derived* (not a local commit counter): a skipped epoch leaves a
gap in that rank's version sequence, the same epoch maps to the same
version on every rank, and rank 0's part barrier detects a peer that
moved past the awaited version (its ``snap.rank{R}.frontier`` marker)
and abandons the superseded commit instead of stalling on a part that
will never be written.

Cadence: every ``every_epochs`` epoch boundary commits, and the
``DMLC_TPU_SNAP_EVERY_S`` wall-clock trigger promotes a boundary to a
commit when enough time passed since the last one. On a preemption
notice, :meth:`Snapshotter.finalize` enqueues the freshest captured
state and drains the writer within the grace window
(``DMLC_TPU_PREEMPT_DEADLINE_S``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple, Union

from dmlc_tpu import obs
from dmlc_tpu.collective.checkpoint import JobSnapshot, _to_host
from dmlc_tpu.utils.logging import log_info, log_warning


class Snapshotter:
    """Background two-phase-commit writer over a :class:`JobSnapshot`.

    ``capture`` is the only method the training loop calls per epoch;
    ``finalize`` is the preemption path; ``close`` drains and stops the
    writer. All public methods are safe to call from the training
    thread only (the writer thread is internal).
    """

    def __init__(
        self,
        snap: JobSnapshot,
        every_epochs: int = 1,
        every_s: Optional[float] = None,
        install_sigterm: bool = True,
    ):
        from dmlc_tpu.params import knobs
        from dmlc_tpu.resilience import preempt

        self._snap = snap
        # epoch->version mapping base: versions are derived from the
        # captured epoch (version_base + epoch - epoch_base) so every
        # rank names the same epoch's part with the same version number,
        # even when newest-wins coalescing skips an epoch on one rank
        # but not another — a skip leaves a gap in that rank's version
        # sequence instead of silently pairing different epochs under
        # one manifest (and wedging rank 0's part barrier on a version
        # the peer never writes). Re-based by mark_restored after a
        # resume.
        self._version_base = snap.version_number
        self._epoch_base = -1
        self._every_epochs = max(0, int(every_epochs))
        self._every_s = knobs.snap_every_s() if every_s is None else max(
            0.0, float(every_s))
        reg = obs.registry()
        self._h_capture = reg.histogram(
            "dmlc_snap_capture_ns",
            "per-snapshot device->host state capture on the training "
            "thread (the goodput ledger's checkpoint stage)")
        self._h_write = reg.histogram(
            "dmlc_snap_write_ns",
            "per-snapshot serialize + two-phase commit on the writer "
            "thread (off the step path)")
        self._m_commits = reg.counter(
            "dmlc_snap_commits_total", "job snapshots committed")
        self._m_skipped = reg.counter(
            "dmlc_snap_skipped_total",
            "captured snapshots superseded before the writer started "
            "them (newest-wins slot)")
        self._m_bytes = reg.counter(
            "dmlc_snap_bytes_total",
            "serialized snapshot part bytes written by this rank")
        self._cond = threading.Condition()
        self._slot: Optional[Tuple[int, Any, Optional[Dict]]] = None
        self._pending: Optional[Tuple[int, Any, Optional[Dict]]] = None
        self._writing = False
        self._stop = False
        self._committed_epoch = -1
        self._last_commit_t = time.monotonic()
        self.last_error: Optional[BaseException] = None
        if install_sigterm:
            preempt.install()
        self._thread = threading.Thread(
            target=self._run, name="dmlc-snap-writer", daemon=True)
        self._thread.start()

    # ---- training-thread surface ---------------------------------------
    def capture(
        self,
        epoch: int,
        state: Union[Any, Callable[[], Any]],
        meta: Optional[Dict[str, Any]] = None,
        force: bool = False,
    ) -> bool:
        """Host-copy ``state`` at an epoch boundary; maybe enqueue a commit.

        ``state`` may be the tree itself or a zero-arg callable building
        it (evaluated here, on the training thread, so the builder may
        read live device buffers). The host copy always becomes the
        freshest *pending* snapshot — a later preemption finalize can
        commit it even when the cadence said "not this epoch". Returns
        True when a commit was enqueued.
        """
        t0 = time.monotonic_ns()
        host = _to_host(state() if callable(state) else state)
        self._h_capture.observe(time.monotonic_ns() - t0)
        with self._cond:
            self._pending = (epoch, host, meta)
            if force or self._due_locked(epoch):
                self._enqueue_locked()
                return True
        return False

    def finalize(self, deadline_s: Optional[float] = None) -> bool:
        """Just-in-time commit for a preemption notice.

        Enqueues the freshest captured state (unless that epoch already
        committed) and waits for the writer to drain, at most
        ``deadline_s`` seconds (default: the remaining preemption grace
        window). Returns True when everything captured is durably
        committed.
        """
        from dmlc_tpu.resilience import preempt

        if deadline_s is None:
            deadline_s = preempt.deadline_remaining()
        with self._cond:
            if (self._pending is not None
                    and self._pending[0] > self._committed_epoch
                    and (self._slot is None
                         or self._slot[0] < self._pending[0])):
                self._enqueue_locked()
            drained = self._cond.wait_for(
                lambda: self._slot is None and not self._writing,
                timeout=max(0.0, deadline_s))
        if not drained:
            log_warning(
                "snapshot finalize missed the %.1fs preemption deadline; "
                "resume will use the last committed version", deadline_s)
        return drained and self.last_error is None

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until the writer is idle (tests, clean shutdown)."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self._slot is None and not self._writing,
                timeout=timeout)

    def close(self, timeout: float = 30.0) -> None:
        """Drain and stop the writer (the fit loop's ``finally`` path).

        Under a pending preemption the drain budget is the *remaining*
        grace window: :meth:`finalize` already spent its share waiting,
        and re-waiting the full timeout here would delay the exit-75
        relaunch past the deadline. The writer thread is a daemon — an
        in-flight commit it never finishes is a torn (ignored) version.
        """
        from dmlc_tpu.resilience import preempt

        if preempt.requested():
            timeout = min(timeout, preempt.deadline_remaining())
        self.drain(timeout)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)

    def mark_restored(self, epoch: int) -> None:
        """After a resume: seed the cadence and the epoch->version base.

        Every rank restores the same committed manifest, so anchoring
        the mapping at (restored version, restored epoch) keeps version
        numbers rank-consistent across relaunches.
        """
        with self._cond:
            self._committed_epoch = epoch
            self._last_commit_t = time.monotonic()
            self._version_base = self._snap.version_number
            self._epoch_base = epoch

    @property
    def committed_epoch(self) -> int:
        with self._cond:
            return self._committed_epoch

    @property
    def version_number(self) -> int:
        return self._snap.version_number

    # ---- internals -----------------------------------------------------
    def _due_locked(self, epoch: int) -> bool:
        if self._every_epochs > 0 and epoch % self._every_epochs == 0:
            return True
        return (self._every_s > 0
                and time.monotonic() - self._last_commit_t >= self._every_s)

    def _enqueue_locked(self) -> None:
        if self._slot is not None:
            self._m_skipped.inc()
        self._slot = self._pending
        self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                self._cond.wait_for(
                    lambda: self._slot is not None or self._stop)
                if self._slot is None:
                    return
                epoch, state, meta = self._slot
                self._slot = None
                self._writing = True
                version = self._version_base + (epoch - self._epoch_base)
            try:
                t0 = time.monotonic_ns()
                info = dict(meta or {})
                info["epoch"] = epoch
                self._snap.commit(state, meta=info, version=version)
                self._h_write.observe(time.monotonic_ns() - t0)
                self._m_commits.inc()
                self._m_bytes.inc(self._snap.last_part_bytes)
                with self._cond:
                    self._committed_epoch = max(self._committed_epoch, epoch)
                    self._last_commit_t = time.monotonic()
                    self.last_error = None
            except BaseException as err:  # writer thread must not die
                self.last_error = err
                log_warning("job snapshot commit failed (epoch %d): %s",
                            epoch, err)
            finally:
                with self._cond:
                    self._writing = False
                    self._cond.notify_all()


def load_snapshot(snap: JobSnapshot):
    """Restore the newest committed snapshot; re-arm the audit plane.

    Returns ``(version, state, meta)`` — ``(0, None, {})`` when the
    directory holds no committed snapshot yet. When the state tree
    carries an ``audit`` section (exported digest-chain heads), it is
    re-injected into the process auditor so the resumed run's chains
    extend the interrupted run's — the cross-rank audit plane then
    verifies the resumed run matches an uninterrupted one.
    """
    t0 = time.monotonic_ns()
    version, state, meta = snap.restore()
    obs.registry().histogram(
        "dmlc_snap_restore_ns",
        "manifest + part read and state restore on resume",
    ).observe(time.monotonic_ns() - t0)
    if not version or state is None:
        return version, state, meta
    audit_restored = False
    audit_state = state.get("audit") if isinstance(state, dict) else None
    if audit_state:
        from dmlc_tpu.obs.audit import auditor

        audit_restored = auditor().restore_state(audit_state)
    from dmlc_tpu.obs import flight

    flight.record_event("resume.verified", version=version,
                        epoch=(meta or {}).get("epoch", -1),
                        audit=audit_restored)
    log_info("resumed from job snapshot v%d (epoch %s, audit %s)",
             version, (meta or {}).get("epoch", "?"),
             "re-armed" if audit_restored else "fresh")
    return version, state, meta
