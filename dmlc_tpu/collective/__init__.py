"""Rabit-style collective API.

The user-facing surface mirrors rabit's (the library the reference's tracker
bootstraps): ``init / finalize / rank / world_size / allreduce / broadcast /
allgather / barrier / checkpoint / load_checkpoint / version_number /
tracker_print``. Engines:

- "socket": the tree/ring TCP engine speaking the reference tracker protocol
  (dmlc_tpu.collective.socket_engine) — CPU-parity runs, or anywhere the
  DMLC_TRACKER_URI env contract is in effect
- "device": XLA collectives over the TPU mesh (dmlc_tpu.collective.device),
  bootstrapped by jax.distributed (the --cluster=tpu path)
- "local": world-size-1 no-op engine

``init()`` picks automatically: DMLC_TRACKER_URI set → socket; multi-process
JAX runtime → device; else local.
"""

from __future__ import annotations

import os
import threading
from typing import Any, List, Optional

import numpy as np

from dmlc_tpu.collective import device as device_collectives
from dmlc_tpu.collective.device import (
    DeviceEngine,
    all_gather,
    make_allreduce_step,
    pmax,
    pmean,
    pmin,
    psum,
    ppermute_next,
)
from dmlc_tpu.collective.checkpoint import CheckpointManager
from dmlc_tpu.collective.socket_engine import SocketEngine
from dmlc_tpu.io.serializer import load_obj, save_obj
from dmlc_tpu.io.stream import MemoryStream
from dmlc_tpu.io.filesystem import create_stream
from dmlc_tpu.utils.logging import DMLCError, check, log_info

_engine = None
_engine_lock = threading.Lock()
_version = 0
_checkpoint_blob: Optional[bytes] = None


class _LocalEngine:
    """world=1 no-op engine (rabit semantics when not launched distributed)."""

    rank = 0
    world_size = 1

    def allreduce(self, array, op="sum"):
        return np.asarray(array)

    def broadcast(self, array, root=0):
        assert array is not None
        return np.asarray(array)

    def allgather(self, array):
        return [np.asarray(array)]

    def barrier(self):
        pass

    def tracker_print(self, msg):
        log_info("%s", msg)

    def shutdown(self):
        pass


def init(engine: str = "auto", **kwargs) -> None:
    """Initialize the collective engine (rabit.init equivalent).

    Also arms the crash flight recorder (obs/flight.py) when
    ``DMLC_TPU_FLIGHTREC`` is set — worker entry runs through here, so
    it is the natural per-process install point."""
    from dmlc_tpu.obs import flight

    flight.install_if_armed()
    global _engine
    with _engine_lock:
        if _engine is not None:
            return
        if engine == "auto":
            if os.environ.get("DMLC_TRACKER_URI"):
                engine = "socket"
            else:
                import jax

                engine = "device" if jax.process_count() > 1 else "local"
        if engine == "socket":
            _engine = SocketEngine(**kwargs)
        elif engine == "device":
            _engine = DeviceEngine(**kwargs)
        elif engine == "local":
            _engine = _LocalEngine()
        else:
            raise DMLCError(f"unknown collective engine {engine!r}")


def _get():
    if _engine is None:
        init()
    return _engine


def finalize() -> None:
    """rabit.finalize: release links / notify tracker."""
    global _engine, _version, _checkpoint_blob
    with _engine_lock:
        if _engine is not None:
            shutdown = getattr(_engine, "shutdown", None)
            if shutdown:
                shutdown()
            _engine = None
        _version = 0
        _checkpoint_blob = None


def rank() -> int:
    return _get().rank


def world_size() -> int:
    return _get().world_size


def allreduce(array, op: str = "sum") -> np.ndarray:
    """Allreduce a host array across workers (rabit.allreduce)."""
    return _get().allreduce(np.asarray(array), op=op)


def broadcast(array, root: int = 0) -> np.ndarray:
    """Broadcast from ``root`` (rabit.broadcast)."""
    return _get().broadcast(None if array is None else np.asarray(array), root=root)


def allgather(array) -> List[np.ndarray]:
    engine = _get()
    if hasattr(engine, "allgather"):
        return engine.allgather(np.asarray(array))
    return [engine.broadcast(np.asarray(array) if r == engine.rank else None, root=r)
            for r in range(engine.world_size)]


def barrier() -> None:
    _get().barrier()


def tracker_print(msg: str) -> None:
    """Print through the tracker (rank 0 style logging; rabit.tracker_print)."""
    engine = _get()
    if hasattr(engine, "tracker_print"):
        engine.tracker_print(msg)
    else:
        if engine.rank == 0:
            log_info("%s", msg)


# ---- checkpointing (rabit CheckPoint/LoadCheckPoint semantics) ------------


def checkpoint(state: Any, uri: Optional[str] = None) -> None:
    """Save a recoverable model snapshot and bump the version.

    Rabit keeps checkpoints in memory (replicated for ring recovery); here the
    blob is kept in-process and optionally persisted to any Stream URI
    (file://, gs://, mem://...) — the building blocks the reference exposes as
    Serializable + Stream::Create (io.h:112-126, SURVEY §5.4).
    """
    from dmlc_tpu.resilience import faultpoint

    global _version, _checkpoint_blob
    # before the version bump: an injected commit fault must leave the
    # in-process snapshot exactly as it was (no half-committed version)
    faultpoint("ckpt.commit")
    _version += 1
    stream = MemoryStream()
    # the version travels inside the blob so a restarted process (or a
    # recovering worker reloading the shared URI) resynchronizes
    # version_number() with the snapshot it resumes from
    save_obj(stream, ("dmlc_ckpt_v1", _version, state))
    _checkpoint_blob = stream.getvalue()
    if uri:
        with create_stream(uri, "w") as out:
            out.write(_checkpoint_blob)


def load_checkpoint(uri: Optional[str] = None) -> Optional[Any]:
    """Return (latest checkpoint state) or None if none exists.

    Also restores ``version_number()`` to the loaded snapshot's version, so
    version-gated loops agree across restarted and surviving workers.
    """
    from dmlc_tpu.resilience import faultpoint

    global _version, _checkpoint_blob
    blob = _checkpoint_blob
    if blob is None and uri:
        faultpoint("ckpt.read")
        stream = create_stream(uri, "r", allow_null=True)
        if stream is not None:
            data = []
            while True:
                piece = stream.read(1 << 20)
                if not piece:
                    break
                data.append(piece)
            blob = b"".join(data)
            _checkpoint_blob = blob
    if blob is None:
        return None
    payload = load_obj(MemoryStream(blob))
    if (
        isinstance(payload, tuple)
        and len(payload) == 3
        and payload[0] == "dmlc_ckpt_v1"
    ):
        _version = int(payload[1])
        return payload[2]
    return payload  # pre-versioned blob: state as written


def version_number() -> int:
    """Number of checkpoints taken (rabit.version_number)."""
    return _version


def reinit_recover() -> None:
    """Re-enter the job after a collective failure (tracker cmd='recover').

    Socket engine: drops every peer link without notifying the tracker,
    reconnects keeping the same rank AND the original engine's tracker
    address/jobid, and clears the in-memory checkpoint blob so the next
    ``load_checkpoint(uri)`` reads the *shared* URI — the one state every
    worker (including a freshly restarted process) can agree on. The
    reference tracker's recover re-entry (tracker.py:279-291) is the other
    half of this handshake.

    Device engine (SURVEY §5.3 TPU mapping — 'recover ⇒ jax.distributed
    re-init + checkpoint restore'): aborts the engine, then re-runs
    ``jax.distributed.initialize`` from the launcher's DMLC_TPU_* env
    contract and rebuilds the engine over the fresh runtime. The JAX
    distributed runtime is *fail-stop* — its coordination client usually
    hard-terminates surviving processes when a peer dies — so the primary
    recovery path is the tpu launcher's per-task restart loop
    (launchers/tpu.py run_task), which relaunches every terminated worker;
    the restarted processes rendezvous in ``initialize`` and resume from
    the shared checkpoint URI. The in-process path here covers the cases
    where the process outlives the failure; a watchdog turns a hung re-init
    into a clean process exit (code 41) so the launcher's restart loop
    takes over rather than leaving a zombie.

    If the rendezvous itself fails (tracker transiently unreachable), the
    aborted engine stays in place: its collectives fail fast with DMLCError,
    so a surrounding ``run_with_recovery`` loop can try again.
    """
    global _engine, _checkpoint_blob
    with _engine_lock:
        if isinstance(_engine, DeviceEngine):
            _reinit_device_engine()
            return
        check(
            isinstance(_engine, SocketEngine),
            "reinit_recover requires an active socket or device engine",
        )
        old = _engine
        old.abort()
        _checkpoint_blob = None
        _engine = SocketEngine(
            tracker_uri=old.tracker_uri,
            tracker_port=old.tracker_port,
            rank=old.rank,
            world_size=old.world_size,
            jobid=old.jobid,
            cmd="recover",
            connect_retry=old.connect_retry,
        )


def _reinit_device_engine() -> None:
    """Device-engine half of reinit_recover (engine lock held)."""
    global _engine, _checkpoint_blob
    from dmlc_tpu.parallel import distributed as _dist

    # validate before destroying anything: a reinit_recover() on an
    # unrecoverable engine must leave the engine and checkpoint intact
    check(
        _dist.multiprocess_env(),
        "device-engine recover needs the DMLC_TPU_* launcher env "
        "(multi-process); single-process jobs have nothing to recover",
    )
    old = _engine
    old.abort()
    _checkpoint_blob = None
    # jax.distributed.shutdown inside the re-init can block indefinitely
    # when the coordinator is gone; fail-stop is then the correct outcome —
    # exit so the launcher's per-task retry restarts this worker cleanly.
    timeout_s = float(os.environ.get("DMLC_TPU_RECOVER_TIMEOUT", 60))
    reinit_done = threading.Event()

    def _fail_stop():
        if not reinit_done.is_set():  # cancel() can lose the race; this
            os._exit(41)              # flag cannot

    watchdog = threading.Timer(timeout_s, _fail_stop)
    watchdog.daemon = True
    watchdog.start()
    try:
        try:
            _dist.initialize_from_env(force=True)
            # engine rebuild can also raise RuntimeError on a transiently
            # unhealthy backend — same translation so the run_with_recovery
            # retry loop (which catches DMLCError/OSError around this call)
            # keeps its try-again contract
            new_engine = DeviceEngine(axis=old.axis)
        except DMLCError:
            raise
        except Exception as err:  # gRPC/barrier errors are RuntimeError-shaped
            raise DMLCError(
                f"device re-rendezvous failed: {err}"
            ) from err
        _engine = new_engine
    finally:
        reinit_done.set()
        watchdog.cancel()


# configuration mistakes that must surface immediately, never trigger a
# world-wide recovery cascade (they are OSError subclasses, but a bad
# checkpoint URI is not a peer failure)
_NON_PEER_ERRORS = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
    FileExistsError,
)


_DEFAULT_RECOVER_ON = (DMLCError, OSError)


def run_with_recovery(round_fn, max_attempts: int = 3,
                      recover_on=_DEFAULT_RECOVER_ON):
    """rabit's checkpoint-replay pattern around one unit of collective work.

    Runs ``round_fn()``; if a collective fails (a peer died — surfaced as a
    socket/DMLC error), re-rendezvouses with ``reinit_recover`` and calls
    ``round_fn`` again. The contract for ``round_fn``: it must START from
    checkpoint state (``load_checkpoint(uri)``) so a replay resumes from the
    last agreed snapshot; its collectives must be deterministic — a worker
    that already finished the round replays it bit-identically while the
    restarted worker catches up; and every worker must run the same
    ``round_fn`` granularity (SPMD), so the abort cascade finds all peers
    inside a collective or about to enter one. An exception matching
    ``recover_on`` is treated as a peer failure and triggers a world-wide
    re-rendezvous. The default covers DMLCError (the device engine
    translates transport failures into it) and OSError (raw socket
    failures — EHOSTUNREACH etc. are not ConnectionError subclasses),
    EXCEPT filesystem-shaped subclasses (FileNotFoundError,
    PermissionError, ...): a misconfigured checkpoint URI surfaces
    immediately instead of triggering max_attempts recovery cascades.

    Failure cascades by construction: ``abort()`` closes all of this
    worker's links, so every neighbor's in-flight collective errors too and
    the whole world re-enters rendezvous together (world-size changes are
    not supported; the restarted process must come back with the same
    jobid/rank).
    """
    from dmlc_tpu.obs import flight
    from dmlc_tpu.resilience import backoff_sleep

    attempt = 0
    while True:
        try:
            return round_fn()
        except recover_on as err:
            if recover_on is _DEFAULT_RECOVER_ON and isinstance(
                err, _NON_PEER_ERRORS
            ):
                # configuration error, not a peer failure; a caller who
                # explicitly listed these types in recover_on keeps them
                raise
            attempt += 1
            with _engine_lock:
                if isinstance(_engine, SocketEngine):
                    recoverable = True
                elif isinstance(_engine, DeviceEngine):
                    from dmlc_tpu.parallel.distributed import multiprocess_env

                    recoverable = multiprocess_env()
                else:
                    recoverable = False
            if not recoverable or attempt >= max_attempts:
                flight.record_event("collective.recover", attempt=attempt,
                                    outcome="giveup", error=str(err))
                flight.dump_if_injected(err)
                raise
            flight.record_event("collective.recover", attempt=attempt,
                                outcome="retry", error=str(err))
            log_info(
                "collective failure (%s); recovering, attempt %d/%d",
                err, attempt, max_attempts,
            )
            try:
                reinit_recover()
            except (DMLCError, OSError) as rerr:
                # rendezvous failed (e.g. tracker unreachable): the aborted
                # engine fails fast on the next round_fn, which brings us
                # back here to try again until attempts run out
                log_info("recover rendezvous failed (%s); will retry", rerr)
                # jittered so a whole world of workers does not hammer a
                # restarting tracker in lockstep
                backoff_sleep(attempt, "collective.recover", base_s=0.5)


__all__ = [
    "init",
    "finalize",
    "rank",
    "world_size",
    "allreduce",
    "broadcast",
    "allgather",
    "barrier",
    "tracker_print",
    "checkpoint",
    "load_checkpoint",
    "version_number",
    "reinit_recover",
    "run_with_recovery",
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "all_gather",
    "ppermute_next",
    "make_allreduce_step",
    "CheckpointManager",
    "DeviceEngine",
    "SocketEngine",
    "device_collectives",
]
