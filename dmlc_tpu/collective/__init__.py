"""Rabit-style collective API.

The user-facing surface mirrors rabit's (the library the reference's tracker
bootstraps): ``init / finalize / rank / world_size / allreduce / broadcast /
allgather / barrier / checkpoint / load_checkpoint / version_number /
tracker_print``. Engines:

- "socket": the tree/ring TCP engine speaking the reference tracker protocol
  (dmlc_tpu.collective.socket_engine) — CPU-parity runs, or anywhere the
  DMLC_TRACKER_URI env contract is in effect
- "device": XLA collectives over the TPU mesh (dmlc_tpu.collective.device),
  bootstrapped by jax.distributed (the --cluster=tpu path)
- "local": world-size-1 no-op engine

``init()`` picks automatically: the ``DMLC_TPU_COLLECTIVE`` knob when set
(auto/device/socket/local — an explicit ``engine=`` argument still wins);
else DMLC_TRACKER_URI set → socket; multi-process JAX runtime → device;
else local.

The host-array ``allreduce``/``broadcast`` façade is the COMPATIBILITY
surface — training hot loops should keep gradients on device and reduce
in-graph instead (``bucketed_psum`` inside a jitted/shard_map step; see
models/linear.py and docs/distributed.md "Device collectives").
``on_membership_change`` lets holders of mesh-placed state reshard when
recovery or elastic re-entry rebuilds the world.

Elastic membership (socket engine only; docs/robustness.md "Elastic
membership"): with ``DMLC_TPU_ELASTIC`` set, a collective failure
re-enters the tracker's *next* generation (``reenter_elastic``) instead
of recovering into the fixed world, ``elastic_sync()`` polls for pending
transitions at checkpoint boundaries, and ``broadcast_state()`` ships
the model from rank 0 to freshly admitted ranks. A process launched with
``DMLC_TPU_SPARE`` (the launcher's ``--spares`` tasks) parks in
``init()`` on the tracker's ``join`` handshake until a transition
activates it — or exits 0 when the job finishes without needing it.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, List, Optional

import numpy as np

from dmlc_tpu.collective import device as device_collectives
from dmlc_tpu.collective.device import (
    DeviceEngine,
    all_gather,
    bucketed_psum,
    make_allreduce_step,
    pbitor,
    pmax,
    pmean,
    pmin,
    psum,
    ppermute_next,
)
from dmlc_tpu.collective.checkpoint import CheckpointManager, JobSnapshot
from dmlc_tpu.collective.snapshot import Snapshotter, load_snapshot
from dmlc_tpu.collective.socket_engine import SocketEngine
from dmlc_tpu.io.serializer import load_obj, save_obj
from dmlc_tpu.io.stream import MemoryStream
from dmlc_tpu.io.filesystem import create_stream
from dmlc_tpu.params.knobs import collective_engine, elastic_enabled, is_spare
from dmlc_tpu.utils.logging import DMLCError, check, log_info

_engine = None
_engine_lock = threading.Lock()
_version = 0
_checkpoint_blob: Optional[bytes] = None
_membership_listeners: List = []


def on_membership_change(fn) -> "Callable[[], None]":
    """Register ``fn()`` to run after every membership rebuild — elastic
    re-entry (``reenter_elastic``) and fixed-world recovery
    (``reinit_recover``, both engine halves). This is the SPMD resharding
    hook: a learner holding mesh-placed params registers a callback that
    re-places them (``shard_params``) on a mesh rebuilt over the new
    device set and drops its traced step. Returns an unregister callable.
    Listeners run OUTSIDE the engine lock, after the new engine is live,
    in registration order; a listener exception propagates to the
    recovery caller (a half-resharded learner must not train on)."""
    _membership_listeners.append(fn)

    def _unregister():
        try:
            _membership_listeners.remove(fn)
        except ValueError:
            pass

    return _unregister


def _notify_membership() -> None:
    for fn in list(_membership_listeners):
        fn()


class _LocalEngine:
    """world=1 no-op engine (rabit semantics when not launched distributed)."""

    rank = 0
    world_size = 1

    def allreduce(self, array, op="sum"):
        return np.asarray(array)

    def broadcast(self, array, root=0):
        assert array is not None
        return np.asarray(array)

    def allgather(self, array):
        return [np.asarray(array)]

    def barrier(self):
        pass

    def tracker_print(self, msg):
        log_info("%s", msg)

    def shutdown(self):
        pass


def _spare_wait(kwargs) -> dict:
    """Warm-spare bootstrap (DMLC_TPU_SPARE, set by the launcher's
    ``--spares`` tasks): park on the tracker's ``join`` handshake until a
    membership transition activates this process, then return the kwargs
    overrides for a cmd='elastic' rendezvous into the new generation. If
    the job finishes without ever needing the spare, the tracker closes
    the parked connection and this process exits 0 — never being needed
    is the clean outcome, not a failure."""
    from dmlc_tpu.tracker.rendezvous import SpareUnused, request_join

    uri = kwargs.get("tracker_uri") or os.environ.get("DMLC_TRACKER_URI")
    port = int(kwargs.get("tracker_port")
               or os.environ.get("DMLC_TRACKER_PORT", 0))
    jobid = kwargs.get("jobid") or os.environ.get("DMLC_TASK_ID", "NULL")
    try:
        generation = request_join(uri, port, jobid=jobid, spare=True)
    except SpareUnused:
        log_info("warm spare %s never activated; exiting clean", jobid)
        raise SystemExit(0)
    log_info("warm spare %s called up into generation %d", jobid, generation)
    return {"cmd": "elastic", "rank": -1, "world_size": -1}


def init(engine: str = "auto", **kwargs) -> None:
    """Initialize the collective engine (rabit.init equivalent).

    Also arms the crash flight recorder (obs/flight.py) when
    ``DMLC_TPU_FLIGHTREC`` is set — worker entry runs through here, so
    it is the natural per-process install point."""
    from dmlc_tpu.obs import flight

    flight.install_if_armed()
    global _engine
    with _engine_lock:
        if _engine is not None:
            return
        if engine == "auto":
            # the DMLC_TPU_COLLECTIVE knob beats auto-detection but never
            # an explicit engine= argument (call sites that hard-pin an
            # engine know something the deployment env does not)
            engine = collective_engine()
        if engine == "auto":
            if os.environ.get("DMLC_TRACKER_URI"):
                engine = "socket"
            else:
                import jax

                engine = "device" if jax.process_count() > 1 else "local"
        if engine == "socket":
            if is_spare():
                kwargs = dict(kwargs, **_spare_wait(kwargs))
            _engine = SocketEngine(**kwargs)
        elif engine == "device":
            _engine = DeviceEngine(**kwargs)
        elif engine == "local":
            _engine = _LocalEngine()
        else:
            raise DMLCError(f"unknown collective engine {engine!r}")


def _get():
    if _engine is None:
        init()
    return _engine


def engine_kind() -> str:
    """The active engine's kind — "socket", "device", or "local" —
    initializing through the auto path on first use. Callers branch on
    this to pick a sync flavor (e.g. LinearLearner: host-allreduce loop
    across socket processes vs the in-graph SPMD step on a mesh)."""
    eng = _get()
    if isinstance(eng, SocketEngine):
        return "socket"
    if isinstance(eng, DeviceEngine):
        return "device"
    return "local"


def finalize() -> None:
    """rabit.finalize: release links / notify tracker."""
    global _engine, _version, _checkpoint_blob
    with _engine_lock:
        if _engine is not None:
            shutdown = getattr(_engine, "shutdown", None)
            if shutdown:
                shutdown()
            _engine = None
        _version = 0
        _checkpoint_blob = None


def rank() -> int:
    return _get().rank


def world_size() -> int:
    return _get().world_size


def allreduce(array, op: str = "sum") -> np.ndarray:
    """Allreduce a host array across workers (rabit.allreduce)."""
    return _get().allreduce(np.asarray(array), op=op)


def broadcast(array, root: int = 0) -> np.ndarray:
    """Broadcast from ``root`` (rabit.broadcast)."""
    return _get().broadcast(None if array is None else np.asarray(array), root=root)


def allgather(array) -> List[np.ndarray]:
    engine = _get()
    if hasattr(engine, "allgather"):
        return engine.allgather(np.asarray(array))
    return [engine.broadcast(np.asarray(array) if r == engine.rank else None, root=r)
            for r in range(engine.world_size)]


def barrier() -> None:
    _get().barrier()


def tracker_print(msg: str) -> None:
    """Print through the tracker (rank 0 style logging; rabit.tracker_print)."""
    engine = _get()
    if hasattr(engine, "tracker_print"):
        engine.tracker_print(msg)
    else:
        if engine.rank == 0:
            log_info("%s", msg)


# ---- checkpointing (rabit CheckPoint/LoadCheckPoint semantics) ------------


def checkpoint(state: Any, uri: Optional[str] = None) -> None:
    """Save a recoverable model snapshot and bump the version.

    Rabit keeps checkpoints in memory (replicated for ring recovery); here the
    blob is kept in-process and optionally persisted to any Stream URI
    (file://, gs://, mem://...) — the building blocks the reference exposes as
    Serializable + Stream::Create (io.h:112-126, SURVEY §5.4).
    """
    from dmlc_tpu.resilience import faultpoint

    global _version, _checkpoint_blob
    # before the version bump: an injected commit fault must leave the
    # in-process snapshot exactly as it was (no half-committed version)
    faultpoint("ckpt.commit")
    _version += 1
    stream = MemoryStream()
    # the version travels inside the blob so a restarted process (or a
    # recovering worker reloading the shared URI) resynchronizes
    # version_number() with the snapshot it resumes from
    save_obj(stream, ("dmlc_ckpt_v1", _version, state))
    _checkpoint_blob = stream.getvalue()
    if uri:
        with create_stream(uri, "w") as out:
            out.write(_checkpoint_blob)


def load_checkpoint(uri: Optional[str] = None) -> Optional[Any]:
    """Return (latest checkpoint state) or None if none exists.

    Also restores ``version_number()`` to the loaded snapshot's version, so
    version-gated loops agree across restarted and surviving workers.
    """
    from dmlc_tpu.resilience import faultpoint

    global _version, _checkpoint_blob
    blob = _checkpoint_blob
    if blob is None and uri:
        faultpoint("ckpt.read")
        stream = create_stream(uri, "r", allow_null=True)
        if stream is not None:
            data = []
            while True:
                piece = stream.read(1 << 20)
                if not piece:
                    break
                data.append(piece)
            blob = b"".join(data)
            _checkpoint_blob = blob
    if blob is None:
        return None
    payload = load_obj(MemoryStream(blob))
    if (
        isinstance(payload, tuple)
        and len(payload) == 3
        and payload[0] == "dmlc_ckpt_v1"
    ):
        _version = int(payload[1])
        return payload[2]
    return payload  # pre-versioned blob: state as written


def version_number() -> int:
    """Number of checkpoints taken (rabit.version_number)."""
    return _version


def reinit_recover() -> None:
    """Re-enter the job after a collective failure (tracker cmd='recover').

    Socket engine: drops every peer link without notifying the tracker,
    reconnects keeping the same rank AND the original engine's tracker
    address/jobid, and clears the in-memory checkpoint blob so the next
    ``load_checkpoint(uri)`` reads the *shared* URI — the one state every
    worker (including a freshly restarted process) can agree on. The
    reference tracker's recover re-entry (tracker.py:279-291) is the other
    half of this handshake.

    Device engine (SURVEY §5.3 TPU mapping — 'recover ⇒ jax.distributed
    re-init + checkpoint restore'): aborts the engine, then re-runs
    ``jax.distributed.initialize`` from the launcher's DMLC_TPU_* env
    contract and rebuilds the engine over the fresh runtime. The JAX
    distributed runtime is *fail-stop* — its coordination client usually
    hard-terminates surviving processes when a peer dies — so the primary
    recovery path is the tpu launcher's per-task restart loop
    (launchers/tpu.py run_task), which relaunches every terminated worker;
    the restarted processes rendezvous in ``initialize`` and resume from
    the shared checkpoint URI. The in-process path here covers the cases
    where the process outlives the failure; a watchdog turns a hung re-init
    into a clean process exit (code 41) so the launcher's restart loop
    takes over rather than leaving a zombie.

    If the rendezvous itself fails (tracker transiently unreachable), the
    aborted engine stays in place: its collectives fail fast with DMLCError,
    so a surrounding ``run_with_recovery`` loop can try again.
    """
    global _engine, _checkpoint_blob
    with _engine_lock:
        if isinstance(_engine, DeviceEngine):
            _reinit_device_engine()
        else:
            check(
                isinstance(_engine, SocketEngine),
                "reinit_recover requires an active socket or device engine",
            )
            old = _engine
            old.abort()
            _checkpoint_blob = None
            _engine = SocketEngine(
                tracker_uri=old.tracker_uri,
                tracker_port=old.tracker_port,
                rank=old.rank,
                world_size=old.world_size,
                jobid=old.jobid,
                cmd="recover",
                connect_retry=old.connect_retry,
            )
    # the world was rebuilt (same ranks, but a restarted peer means fresh
    # device runtime state on the device path): let SPMD holders re-place
    _notify_membership()


def _reinit_device_engine() -> None:
    """Device-engine half of reinit_recover (engine lock held)."""
    global _engine, _checkpoint_blob
    from dmlc_tpu.parallel import distributed as _dist

    # validate before destroying anything: a reinit_recover() on an
    # unrecoverable engine must leave the engine and checkpoint intact
    check(
        _dist.multiprocess_env(),
        "device-engine recover needs the DMLC_TPU_* launcher env "
        "(multi-process); single-process jobs have nothing to recover",
    )
    old = _engine
    old.abort()
    _checkpoint_blob = None
    # jax.distributed.shutdown inside the re-init can block indefinitely
    # when the coordinator is gone; fail-stop is then the correct outcome —
    # exit so the launcher's per-task retry restarts this worker cleanly.
    timeout_s = float(os.environ.get("DMLC_TPU_RECOVER_TIMEOUT", 60))
    reinit_done = threading.Event()

    def _fail_stop():
        if not reinit_done.is_set():  # cancel() can lose the race; this
            os._exit(41)              # flag cannot

    watchdog = threading.Timer(timeout_s, _fail_stop)
    watchdog.daemon = True
    watchdog.start()
    try:
        try:
            _dist.initialize_from_env(force=True)
            # engine rebuild can also raise RuntimeError on a transiently
            # unhealthy backend — same translation so the run_with_recovery
            # retry loop (which catches DMLCError/OSError around this call)
            # keeps its try-again contract
            new_engine = DeviceEngine(axis=old.axis)
        except DMLCError:
            raise
        except Exception as err:  # gRPC/barrier errors are RuntimeError-shaped
            raise DMLCError(
                f"device re-rendezvous failed: {err}"
            ) from err
        _engine = new_engine
    finally:
        reinit_done.set()
        watchdog.cancel()


# ---- elastic membership (socket engine; docs/robustness.md) ---------------


_ELASTIC_TAG = "dmlc_elastic_state_v1"


def _encode_state(state: Any, version: int) -> np.ndarray:
    """Serialize (tag, version, state) into a uint8 array so model state
    can travel over ``broadcast`` — the same Serializable building blocks
    the checkpoint path uses."""
    stream = MemoryStream()
    save_obj(stream, (_ELASTIC_TAG, int(version), state))
    return np.frombuffer(stream.getvalue(), dtype=np.uint8)


def _decode_state(blob: np.ndarray):
    """Inverse of ``_encode_state``: returns ``(version, state)``."""
    payload = load_obj(MemoryStream(np.asarray(blob, dtype=np.uint8).tobytes()))
    check(
        isinstance(payload, tuple) and len(payload) == 3
        and payload[0] == _ELASTIC_TAG,
        "broadcast_state payload is not an elastic state frame",
    )
    return int(payload[1]), payload[2]


def broadcast_state(state: Any = None, root: int = 0) -> Any:
    """Ship model state (plus the checkpoint version) from ``root`` to
    every rank — the scale-up bootstrap: a freshly admitted rank or warm
    spare receives the current model from rank 0 instead of reading a
    checkpoint it never took. Non-root ranks also adopt the root's
    ``version_number()``, so version-gated loops agree across old and new
    members. Returns the state on every rank; the root's own copy
    round-trips through serialization, so all ranks hold bit-identical
    state."""
    global _version
    eng = _get()
    if eng.world_size == 1:
        check(state is not None, "broadcast_state root must supply state")
        return state
    if eng.rank == root:
        check(state is not None, "broadcast_state root must supply state")
        blob = _encode_state(state, _version)
    else:
        blob = None
    out = eng.broadcast(blob, root=root)
    version, new_state = _decode_state(out)
    _version = version
    return new_state


def reenter_elastic() -> int:
    """Abort the engine and rendezvous into the tracker's *next*
    membership generation (tracker cmd='elastic').

    Unlike ``reinit_recover`` — which reclaims the same rank in the same
    fixed world — the tracker reassigns rank and world size from whoever
    shows up: survivors of a dead rank, grow joiners, and called-up warm
    spares all meet in one transition and get a freshly built tree/ring.
    The in-memory checkpoint blob is cleared because rank 0 of the new
    generation may be a brand-new process: the shared checkpoint URI (or
    a ``broadcast_state`` from a surviving rank) is the state every
    member can agree on. Returns the committed generation number.
    """
    global _engine, _checkpoint_blob
    from dmlc_tpu.obs import flight

    with _engine_lock:
        check(
            isinstance(_engine, SocketEngine),
            "elastic re-entry needs the socket engine (the jax.distributed "
            "runtime pins its process count at initialize time)",
        )
        old = _engine
        old.abort()
        _checkpoint_blob = None
        _engine = SocketEngine(
            tracker_uri=old.tracker_uri,
            tracker_port=old.tracker_port,
            rank=-1,
            world_size=-1,
            jobid=old.jobid,
            cmd="elastic",
            connect_retry=old.connect_retry,
        )
        eng = _engine
    # new generation, possibly new world size: SPMD param holders rebuild
    # their mesh sharding before anyone runs another step
    _notify_membership()
    flight.record_event("collective.elastic", generation=eng.generation,
                        rank=eng.rank, world=eng.world_size)
    log_info("elastic re-entry: generation %d, rank %d of %d",
             eng.generation, eng.rank, eng.world_size)
    return eng.generation


def elastic_sync(timeout: float = 10.0) -> bool:
    """Checkpoint-boundary membership poll. No-op (returns False) unless
    DMLC_TPU_ELASTIC is set and the socket engine is active; otherwise
    sends one heartbeat and, if the tracker's acked target world_version
    is ahead of this engine's generation, re-rendezvouses into the new
    world via ``reenter_elastic`` and returns True. Call it where a
    checkpoint boundary is — the one place rank/world may legally change.
    Pre-elastic trackers ack 0, which never triggers re-entry."""
    from dmlc_tpu.parallel import distributed as _dist
    from dmlc_tpu.tracker.rendezvous import send_heartbeat

    eng = _engine
    if (not elastic_enabled() or not isinstance(eng, SocketEngine)
            or not _dist.elastic_capable()):
        return False
    try:
        acked = send_heartbeat(eng.tracker_uri, eng.tracker_port, eng.rank,
                               epoch=_version, timeout=timeout)
    except (OSError, ValueError):
        return False  # liveness probe stays best-effort
    if acked <= eng.generation:
        return False
    log_info("membership transition pending (generation %d -> %d)",
             eng.generation, acked)
    reenter_elastic()
    return True


# configuration mistakes that must surface immediately, never trigger a
# world-wide recovery cascade (they are OSError subclasses, but a bad
# checkpoint URI is not a peer failure)
_NON_PEER_ERRORS = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
    FileExistsError,
)


_DEFAULT_RECOVER_ON = (DMLCError, OSError)


def run_with_recovery(round_fn, max_attempts: int = 3,
                      recover_on=_DEFAULT_RECOVER_ON):
    """rabit's checkpoint-replay pattern around one unit of collective work.

    Runs ``round_fn()``; if a collective fails (a peer died — surfaced as a
    socket/DMLC error), re-rendezvouses with ``reinit_recover`` and calls
    ``round_fn`` again. The contract for ``round_fn``: it must START from
    checkpoint state (``load_checkpoint(uri)``) so a replay resumes from the
    last agreed snapshot; its collectives must be deterministic — a worker
    that already finished the round replays it bit-identically while the
    restarted worker catches up; and every worker must run the same
    ``round_fn`` granularity (SPMD), so the abort cascade finds all peers
    inside a collective or about to enter one. An exception matching
    ``recover_on`` is treated as a peer failure and triggers a world-wide
    re-rendezvous. The default covers DMLCError (the device engine
    translates transport failures into it) and OSError (raw socket
    failures — EHOSTUNREACH etc. are not ConnectionError subclasses),
    EXCEPT filesystem-shaped subclasses (FileNotFoundError,
    PermissionError, ...): a misconfigured checkpoint URI surfaces
    immediately instead of triggering max_attempts recovery cascades.

    Failure cascades by construction: ``abort()`` closes all of this
    worker's links, so every neighbor's in-flight collective errors too and
    the whole world re-enters rendezvous together. By default the world is
    fixed — the restarted process must come back with the same jobid/rank.
    With DMLC_TPU_ELASTIC set (socket engine), the re-entry goes through
    ``reenter_elastic`` instead: a dead rank is drained rather than waited
    for, warm spares are called up to backfill, and survivors get fresh
    ranks in a rebuilt, possibly different-sized world.
    """
    from dmlc_tpu.obs import flight
    from dmlc_tpu.resilience import backoff_sleep

    attempt = 0
    while True:
        try:
            return round_fn()
        except recover_on as err:
            if recover_on is _DEFAULT_RECOVER_ON and isinstance(
                err, _NON_PEER_ERRORS
            ):
                # configuration error, not a peer failure; a caller who
                # explicitly listed these types in recover_on keeps them
                raise
            attempt += 1
            with _engine_lock:
                elastic = False
                if isinstance(_engine, SocketEngine):
                    recoverable = True
                    elastic = elastic_enabled()
                elif isinstance(_engine, DeviceEngine):
                    from dmlc_tpu.parallel.distributed import multiprocess_env

                    recoverable = multiprocess_env()
                else:
                    recoverable = False
            if not recoverable or attempt >= max_attempts:
                flight.record_event("collective.recover", attempt=attempt,
                                    outcome="giveup", error=str(err))
                flight.dump_if_injected(err)
                raise
            flight.record_event("collective.recover", attempt=attempt,
                                outcome="retry", error=str(err))
            log_info(
                "collective failure (%s); recovering, attempt %d/%d",
                err, attempt, max_attempts,
            )
            try:
                if elastic:
                    reenter_elastic()
                else:
                    reinit_recover()
            except (DMLCError, OSError) as rerr:
                # rendezvous failed (e.g. tracker unreachable): the aborted
                # engine fails fast on the next round_fn, which brings us
                # back here to try again until attempts run out
                log_info("recover rendezvous failed (%s); will retry", rerr)
                # jittered so a whole world of workers does not hammer a
                # restarting tracker in lockstep
                backoff_sleep(attempt, "collective.recover", base_s=0.5)


__all__ = [
    "init",
    "finalize",
    "engine_kind",
    "rank",
    "world_size",
    "allreduce",
    "broadcast",
    "allgather",
    "barrier",
    "tracker_print",
    "checkpoint",
    "load_checkpoint",
    "version_number",
    "reinit_recover",
    "on_membership_change",
    "run_with_recovery",
    "broadcast_state",
    "reenter_elastic",
    "elastic_sync",
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "pbitor",
    "all_gather",
    "ppermute_next",
    "bucketed_psum",
    "make_allreduce_step",
    "CheckpointManager",
    "DeviceEngine",
    "SocketEngine",
    "device_collectives",
]
