"""Device-plane collectives: XLA over ICI/DCN.

This is the TPU replacement for rabit's socket tree/ring (SURVEY §5.8 "TPU
native equivalent"): inside jit, collectives are axis-name primitives
(psum/pmean/all_gather/ppermute) that XLA lowers to ICI AllReduce etc.; at
the host level, cross-process reductions ride a jitted psum over the global
mesh via jax.experimental.multihost_utils.

Byte accounting: in-graph psums are invisible to the host-side
``dmlc_collective_*`` counters (those meter the socket/D2H fallback ops),
but every jit site here goes through ``instrumented_jit``, so the
compile-time analytics hook (obs/xla_cost.py) reads each compiled
program's collective traffic out of its optimized HLO —
``dmlc_xla_collective_bytes{fn="collective.allreduce_step"}`` (and the
SPMD model steps' own labels) is where the in-graph allreduce bytes
surface.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from dmlc_tpu import obs
from dmlc_tpu.obs.device_telemetry import h2d_meter, instrumented_jit
from dmlc_tpu.utils.jax_compat import axis_size, shard_map

from dmlc_tpu.utils.logging import DMLCError


def _bitor_reduce(x, axis=0):
    # rabit's bitwise-OR reduce (engine.h AllReduce<op::BitOR>);
    # integer-only, screened in DeviceEngine.allreduce()
    return jax.lax.reduce(
        x, jnp.zeros((), x.dtype), jax.lax.bitwise_or, (axis,)
    )


# The rabit op surface (engine.h op::Sum/Max/Min/BitOR + prod). Single
# source of truth: allreduce() validates against these keys and
# _reduce_fn() compiles from the same table — the two cannot drift.
_REDUCE_OPS = {
    "sum": jnp.sum,
    "max": jnp.max,
    "min": jnp.min,
    "prod": jnp.prod,
    "bitor": _bitor_reduce,
}


# ---- in-jit collectives (use inside shard_map/pjit-ed functions) ----------

def psum(x, axis: str = "dp"):
    """Cross-replica sum over a mesh axis (ICI AllReduce)."""
    return jax.lax.psum(x, axis_name=axis)


def pmean(x, axis: str = "dp"):
    return jax.lax.pmean(x, axis_name=axis)

def pmax(x, axis: str = "dp"):
    return jax.lax.pmax(x, axis_name=axis)


def pmin(x, axis: str = "dp"):
    return jax.lax.pmin(x, axis_name=axis)


def all_gather(x, axis: str = "dp", tiled: bool = False):
    return jax.lax.all_gather(x, axis_name=axis, tiled=tiled)


def ppermute_next(x, axis: str = "dp"):
    """Rotate shards one step around the mesh axis ring — the ICI analog of
    the tracker's ring links (tracker.py:212-225)."""
    size = axis_size(axis)
    perm = [(i, (i + 1) % size) for i in range(size)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def pbitor(x, axis: str = "dp"):
    """Cross-replica bitwise OR (rabit op::BitOR, in-graph). XLA has no
    OR all-reduce primitive, so shards are gathered and folded over the
    gathered dim — order-insensitive, so the result is bit-identical to
    the socket tree's fold regardless of topology."""
    return _bitor_reduce(jax.lax.all_gather(x, axis_name=axis), axis=0)


def bucketed_psum(tree, axis="dp", bucket: bool = True):
    """In-graph fused gradient allreduce: psum a pytree over ``axis`` with
    ONE collective per dtype. Call inside a jit/shard_map-traced step —
    this is the hot-path reduction the SPMD train steps use, so gradients
    never round-trip through host numpy or ``collective.allreduce``.

    ``bucket=True`` flattens the leaves and concatenates them into
    contiguous per-dtype buffers (dtype-preserving — bf16 grads are never
    silently upcast by a mixed concat), reduces each bucket with a single
    ``lax.psum``, and splits back to the original shapes. Large fused
    buckets are what push ICI utilization toward peak (SURVEY §7 hard
    parts). ``bucket=False`` issues one psum per leaf and leans on XLA's
    all-reduce combiner — kept for A/B measurement
    (bench_collective.grad_bucket_metrics).
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not bucket or len(leaves) <= 1:
        out = [jax.lax.psum(g, axis) for g in leaves]
        return jax.tree.unflatten(treedef, out)
    by_dtype: dict = {}
    for i, g in enumerate(leaves):
        by_dtype.setdefault(jnp.asarray(g).dtype, []).append(i)
    out = [None] * len(leaves)
    for idxs in by_dtype.values():
        flat = jnp.concatenate(
            [jnp.reshape(leaves[i], (-1,)) for i in idxs]
        )
        reduced = jax.lax.psum(flat, axis)
        offset = 0
        for i in idxs:
            size = leaves[i].size
            out[i] = jnp.reshape(
                reduced[offset:offset + size], jnp.shape(leaves[i])
            )
            offset += size
    return jax.tree.unflatten(treedef, out)


# ---- host-level collectives over the global device mesh -------------------


class DeviceEngine:
    """Host-callable allreduce/broadcast executing as XLA collectives.

    Single-process: reductions over the local mesh axis. Multi-process (one
    process per TPU host, bootstrapped by jax.distributed.initialize):
    reductions span all hosts over ICI/DCN via a jitted psum on a
    globally-sharded array.
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: str = "dp"):
        if mesh is None:
            devs = np.asarray(jax.devices())
            mesh = Mesh(devs, (axis,))
        self.mesh = mesh
        self.axis = axis
        self.rank = jax.process_index()
        self.world_size = jax.process_count()
        self._aborted = False
        self._proc_mesh: Optional[Mesh] = None
        self._reduce_fns: dict = {}
        # host-round-trip copy accounting (PR 8 H2D counters): every byte
        # this legacy path stages H2D and copies back D2H is a byte the
        # in-graph SPMD psum path does NOT move — obs-report reads these
        # to attribute exactly what retiring the host path eliminates.
        # None when device telemetry is off (no timing, no byte walk).
        self._h2d = h2d_meter(feed="collective")
        self._m_d2h = (
            obs.registry().counter(
                "dmlc_collective_d2h_bytes_total",
                "device->host result bytes copied back by host-path "
                "collectives (the copy the in-graph SPMD path eliminates)",
                op="allreduce",
            )
            if self._h2d is not None
            else None
        )

    def _process_mesh(self) -> Mesh:
        """(nproc, local) mesh with processes contiguous on the first axis
        — the layout for arrays whose leading dim is one shard per
        process."""
        if self._proc_mesh is None:
            devs = sorted(
                jax.devices(), key=lambda d: (d.process_index, d.id)
            )
            arr = np.asarray(devs).reshape(self.world_size, -1)
            self._proc_mesh = Mesh(arr, ("proc", "_local"))
        return self._proc_mesh

    def _reduce_fn(self, op: str):
        """Jitted [world, ...]-sharded → replicated reduction over dim 0.
        XLA lowers it to a real AllReduce over ICI/DCN: O(N) bytes per
        link, never a [world, N] materialization per host."""
        fn = self._reduce_fns.get(op)
        if fn is None:
            from jax.sharding import NamedSharding

            reduce_fn = _REDUCE_OPS[op]
            out_sharding = NamedSharding(self._process_mesh(), P())
            fn = instrumented_jit(
                lambda x: reduce_fn(x, axis=0),
                "collective.reduce",
                out_shardings=out_sharding,
            )
            self._reduce_fns[op] = fn
        return fn

    @staticmethod
    def _record(what: str, nbytes: int, t0: int) -> None:
        """Count a completed host collective in the obs registry.

        Registered per call — collectives are per-step, not per-row, and
        the registry hands back the same child for a repeated
        (name, labels) pair."""
        reg = obs.registry()
        reg.counter(
            "dmlc_collective_ops_total", "host collectives completed",
            op=what).inc()
        reg.counter(
            "dmlc_collective_moved_bytes_total",
            "payload bytes through host collectives", op=what).inc(nbytes)
        reg.histogram(
            "dmlc_collective_op_ns", "per-op host collective latency",
            op=what).observe(time.monotonic_ns() - t0)

    def _check_live(self) -> None:
        if self._aborted:
            raise DMLCError(
                "device engine aborted (pending recover); reinit before "
                "collectives"
            )

    def _translate(self, err: Exception, what: str) -> DMLCError:
        """Backend failures (Gloo/ICI transport errors, coordination-service
        loss) surface as assorted RuntimeError/ValueError types; collapse
        them into DMLCError so run_with_recovery's default recover_on
        catches device-plane peer failures exactly like socket ones.
        Deterministic user errors are screened out by _validate before the
        collective runs, so what reaches the wrap is transport-shaped."""
        self._aborted = True
        return DMLCError(f"device collective {what} failed: {err}")

    @staticmethod
    def _validate(array) -> np.ndarray:
        """Raise locally (unwrapped) on inputs every rank would reject —
        these must surface as user errors, not trigger recovery."""
        arr = np.asarray(array)
        if arr.dtype.kind not in "fiub":
            raise TypeError(
                f"device collectives need numeric arrays, got dtype "
                f"{arr.dtype}"
            )
        return arr

    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        """Allreduce a host array across all processes' devices.

        Each process contributes one shard of a [world, ...] device array
        (its leading dim sharded over the process axis) and a jitted
        replicated-output reduction runs as a true XLA AllReduce: O(N)
        traffic and memory per host. This is the data-plane path — large
        gradient arrays ride it, not just control-plane scalars.
        """
        self._check_live()
        arr = self._validate(array)
        if op not in _REDUCE_OPS:
            raise ValueError(f"unknown op {op!r}")
        if op == "bitor" and arr.dtype.kind not in "iub":
            raise TypeError(f"bitor needs an integer dtype, got {arr.dtype}")
        t0 = time.monotonic_ns()
        if self.world_size == 1:
            # Single process owns every device: nothing to reduce across
            # processes; return as-is (matches rabit world=1 semantics).
            self._record("allreduce", int(arr.nbytes), t0)
            return arr
        try:
            from jax.sharding import NamedSharding

            sharding = NamedSharding(self._process_mesh(), P("proc"))
            t_h2d = time.monotonic_ns()
            garr = jax.make_array_from_process_local_data(
                sharding, arr[None], (self.world_size,) + arr.shape
            )
            if self._h2d is not None:
                # the host round-trip's up-leg: this process's shard staged
                # onto device before the reduction can run
                self._h2d.note(int(arr.nbytes), time.monotonic_ns() - t_h2d)
            with obs.span("allreduce", op=op, nbytes=int(arr.nbytes)):
                # mark the in-flight chunk (set by DeviceFeed around the
                # consume yield) so the op slice joins its arrow chain
                obs.flow_step(obs.current_flow(), "chunk")
                out = self._reduce_fn(op)(garr)
            res = np.asarray(out)
            if self._m_d2h is not None:
                # ...and the down-leg: the replicated result copied back to
                # host numpy
                self._m_d2h.inc(int(res.nbytes))
            self._record("allreduce", int(arr.nbytes), t0)
            return res
        except Exception as err:  # noqa: BLE001 — backend error translation
            # deterministic user errors were screened by _validate/op-check
            # above; what reaches here is transport-shaped (ValueError
            # included — see _translate's contract), so mark the engine
            # aborted and let run_with_recovery catch it
            raise self._translate(err, "allreduce") from err

    # fixed-size broadcast header: [ndim, dims[0..7], dtype_num]
    _HDR_SLOTS = 10
    # np.dtype(num) is not a constructor; invert .num over the numeric
    # dtypes the engine supports (kind in "fiub")
    _DTYPE_BY_NUM = {
        np.dtype(t).num: np.dtype(t)
        for t in (
            np.bool_, np.int8, np.int16, np.int32, np.int64,
            np.uint8, np.uint16, np.uint32, np.uint64,
            np.float16, np.float32, np.float64,
        )
    }

    def broadcast(self, array: Optional[np.ndarray], root: int = 0) -> np.ndarray:
        """Broadcast from ``root``; non-root ranks may pass None (rabit
        semantics). broadcast_one_to_all requires every process to supply
        the same array structure, so a fixed-size header round carries
        shape+dtype first and non-roots then contribute matching zeros.

        A root-side validation error travels THROUGH the header (ndim slot
        -1) instead of raising before it: every rank stays in lockstep and
        raises the same TypeError, rather than non-roots hanging in the
        collective while the root errored out locally."""
        from jax.experimental import multihost_utils

        self._check_live()
        is_root = self.rank == root
        t0 = time.monotonic_ns()
        if self.world_size == 1:
            assert array is not None
            arr = self._validate(array)
            self._record("broadcast", int(arr.nbytes), t0)
            return arr
        header = np.zeros(self._HDR_SLOTS, dtype=np.int64)
        arr = header  # placeholder payload when the root's input is invalid
        root_err: Optional[Exception] = None
        if is_root:
            try:
                arr = self._validate(array)
                if arr.ndim > self._HDR_SLOTS - 2:
                    raise ValueError(
                        f"broadcast supports <= {self._HDR_SLOTS - 2} dims, "
                        f"got {arr.ndim}"
                    )
                if arr.dtype.num not in self._DTYPE_BY_NUM:
                    raise TypeError(
                        f"broadcast cannot encode dtype {arr.dtype}; "
                        f"supported: "
                        f"{sorted(str(d) for d in self._DTYPE_BY_NUM.values())}"
                    )
                header[0] = arr.ndim
                header[1 : 1 + arr.ndim] = arr.shape
                header[-1] = arr.dtype.num
            except (TypeError, ValueError) as err:
                root_err = err
                header[0] = -1
        try:
            header = np.asarray(
                multihost_utils.broadcast_one_to_all(header, is_source=is_root)
            )
            if int(header[0]) < 0:
                # root's input was invalid: same user error on every rank,
                # no recovery cascade, engine stays live
                if root_err is not None:
                    raise root_err
                raise TypeError(
                    "broadcast root input was invalid (see root rank log)"
                )
            if not is_root:
                ndim = int(header[0])
                shape = tuple(int(d) for d in header[1 : 1 + ndim])
                arr = np.zeros(shape, dtype=self._DTYPE_BY_NUM[int(header[-1])])
            with obs.span("broadcast", root=root, nbytes=int(arr.nbytes)):
                obs.flow_step(obs.current_flow(), "chunk")
                out = np.asarray(
                    multihost_utils.broadcast_one_to_all(arr, is_source=is_root)
                )
            self._record("broadcast", int(arr.nbytes), t0)
            return out
        except (TypeError, ValueError) as err:
            if err is root_err or int(header[0]) < 0:
                raise  # validated user error, already lockstep
            raise self._translate(err, "broadcast") from err
        except Exception as err:  # noqa: BLE001 — backend error translation
            raise self._translate(err, "broadcast") from err

    def barrier(self) -> None:
        from jax.experimental import multihost_utils

        self._check_live()
        t0 = time.monotonic_ns()
        if self.world_size > 1:
            try:
                with obs.span("barrier"):
                    obs.flow_step(obs.current_flow(), "chunk")
                    multihost_utils.sync_global_devices("dmlc_tpu_barrier")
            except Exception as err:  # noqa: BLE001 — backend translation
                raise self._translate(err, "barrier") from err
        self._record("barrier", 0, t0)

    def abort(self) -> None:
        """Mark the engine dead: collectives fail fast with DMLCError until
        a new engine is built over a re-initialized runtime (the socket
        engine's abort() contract, for the device plane)."""
        self._aborted = True

    def shutdown(self) -> None:
        self._aborted = True


# ---- gradient-sync building block (the BASELINE north-star op) ------------


def make_allreduce_step(mesh: Mesh, axis: str = "dp", bucket: bool = True):
    """Return a jitted f(sharded_grads_pytree) -> summed pytree over the
    mesh axis. Large fused buckets + donation are what push ICI
    utilization ≥90% (SURVEY §7 hard parts).

    ``bucket=True`` (default) GUARANTEES one collective per dtype: leaves
    are flattened, concatenated into a contiguous buffer (grouped by dtype
    — no silent upcasts), reduced with a single psum, and split back.
    ``bucket=False`` issues one psum per leaf and leans on XLA's
    all-reduce combiner heuristics — kept for A/B measurement
    (bench_collective.grad_bucket_metrics) and for models whose step
    already fuses everything into one psum call. The reduction body is
    :func:`bucketed_psum` — the same in-graph primitive the SPMD train
    steps (models/linear.py, models/fm.py) trace directly."""

    def _sum(grads):
        return bucketed_psum(grads, axis=axis, bucket=bucket)

    spec = P(axis)
    return instrumented_jit(
        shard_map(
            _sum,
            mesh=mesh,
            in_specs=spec,
            out_specs=P(),
        ),
        "collective.allreduce_step",
        donate_argnums=(0,),
    )
