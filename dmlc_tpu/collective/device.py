"""Device-plane collectives: XLA over ICI/DCN.

This is the TPU replacement for rabit's socket tree/ring (SURVEY §5.8 "TPU
native equivalent"): inside jit, collectives are axis-name primitives
(psum/pmean/all_gather/ppermute) that XLA lowers to ICI AllReduce etc.; at
the host level, cross-process reductions ride a jitted psum over the global
mesh via jax.experimental.multihost_utils.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


# ---- in-jit collectives (use inside shard_map/pjit-ed functions) ----------

def psum(x, axis: str = "dp"):
    """Cross-replica sum over a mesh axis (ICI AllReduce)."""
    return jax.lax.psum(x, axis_name=axis)


def pmean(x, axis: str = "dp"):
    return jax.lax.pmean(x, axis_name=axis)

def pmax(x, axis: str = "dp"):
    return jax.lax.pmax(x, axis_name=axis)


def pmin(x, axis: str = "dp"):
    return jax.lax.pmin(x, axis_name=axis)


def all_gather(x, axis: str = "dp", tiled: bool = False):
    return jax.lax.all_gather(x, axis_name=axis, tiled=tiled)


def ppermute_next(x, axis: str = "dp"):
    """Rotate shards one step around the mesh axis ring — the ICI analog of
    the tracker's ring links (tracker.py:212-225)."""
    size = jax.lax.axis_size(axis)
    perm = [(i, (i + 1) % size) for i in range(size)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


# ---- host-level collectives over the global device mesh -------------------


class DeviceEngine:
    """Host-callable allreduce/broadcast executing as XLA collectives.

    Single-process: reductions over the local mesh axis. Multi-process (one
    process per TPU host, bootstrapped by jax.distributed.initialize):
    reductions span all hosts over ICI/DCN via a jitted psum on a
    globally-sharded array.
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: str = "dp"):
        if mesh is None:
            devs = np.asarray(jax.devices())
            mesh = Mesh(devs, (axis,))
        self.mesh = mesh
        self.axis = axis
        self.rank = jax.process_index()
        self.world_size = jax.process_count()

    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        """Allreduce a host array across all processes' devices."""
        from jax.experimental import multihost_utils

        arr = np.asarray(array)
        if self.world_size == 1:
            # Single process owns every device: nothing to reduce across
            # processes; return as-is (matches rabit world=1 semantics).
            return arr
        ops = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min, "prod": jnp.prod}
        if op not in ops:
            raise ValueError(f"unknown op {op!r}")
        # stack contributions along a new leading axis sharded over processes,
        # then reduce it with a jitted global reduction (XLA AllReduce).
        stacked = multihost_utils.process_allgather(arr)
        reduce_fn = ops[op]
        return np.asarray(reduce_fn(stacked, axis=0))

    def broadcast(self, array: Optional[np.ndarray], root: int = 0) -> np.ndarray:
        from jax.experimental import multihost_utils

        if self.world_size == 1:
            assert array is not None
            return np.asarray(array)
        return np.asarray(
            multihost_utils.broadcast_one_to_all(
                array, is_source=self.rank == root
            )
        )

    def barrier(self) -> None:
        from jax.experimental import multihost_utils

        if self.world_size > 1:
            multihost_utils.sync_global_devices("dmlc_tpu_barrier")


# ---- gradient-sync building block (the BASELINE north-star op) ------------


def make_allreduce_step(mesh: Mesh, axis: str = "dp"):
    """Return a jitted f(sharded_grads_pytree) -> summed pytree using one
    fused AllReduce over the mesh axis. Large fused buckets + donation are
    what push ICI utilization ≥90% (SURVEY §7 hard parts)."""
    shard_map = jax.shard_map

    def _sum(grads):
        return jax.tree.map(lambda g: jax.lax.psum(g, axis), grads)

    spec = P(axis)
    return jax.jit(
        shard_map(
            _sum,
            mesh=mesh,
            in_specs=spec,
            out_specs=P(),
        ),
        donate_argnums=(0,),
    )
