"""Socket collective engine: the worker side of the rabit protocol.

The reference repo ships only the *tracker* half of rabit's bootstrap
(SURVEY §5.8); the worker half (rendezvous client + tree collectives) lived
downstream. This module provides that worker half, wire-compatible with our
tracker (dmlc_tpu.tracker.rendezvous) and the reference's tracker.py:

- handshake: connect to DMLC_TRACKER_URI:PORT, send magic/rank/world/jobid/
  cmd; receive rank, parent, world, tree neighbors, ring prev/next
  (mirror of tracker.py:58-104)
- peer-link brokering: listen on an ephemeral port, run the goodset/badset
  loop, dial the peers the tracker names, accept the rest
  (mirror of tracker.py:105-135)
- collectives over the tree links: Allreduce (reduce-up + broadcast-down,
  deterministic child order → bit-reproducible sums) and Broadcast;
  Allgather via per-rank broadcast rounds
- cmd='recover' re-entry with the old rank, and 'print'/'shutdown' control
  messages
- cmd='elastic' re-entry into the tracker's *next* generation: the tracker
  prefixes the standard assignment frame with the generation being joined
  (−1 = refused, e.g. this worker was evicted), rank and world size are
  assigned fresh, and the engine records the generation in
  :attr:`SocketEngine.generation` so heartbeat acks can be compared
  against it (see docs/robustness.md "Elastic membership")

On TPU this engine is the CPU-parity/control path; the data plane for
gradients is XLA collectives (dmlc_tpu.collective.device). The public
rabit-style API in dmlc_tpu.collective dispatches between them.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from dmlc_tpu.tracker.rendezvous import MAGIC, FramedSocket
from dmlc_tpu.utils.logging import DMLCError, check

# peer handshake tag (worker-to-worker links are our protocol)
_PEER_MAGIC = 0xDC99

# broadcast metadata frame size in int64 slots: 1 (ndim) + up to 23 dims + 8
# (dtype code). A protocol constant — every rank sizes the frame identically.
_META_SLOTS = 32


_REDUCERS: Dict[str, Callable[[np.ndarray, np.ndarray], np.ndarray]] = {
    "sum": lambda a, b: a + b,
    "max": np.maximum,
    "min": np.minimum,
    "prod": lambda a, b: a * b,
    "bitor": np.bitwise_or,
}


class SocketEngine:
    """One worker's connection set + tree collectives."""

    def __init__(
        self,
        tracker_uri: Optional[str] = None,
        tracker_port: Optional[int] = None,
        rank: int = -1,
        world_size: int = -1,
        jobid: Optional[str] = None,
        cmd: str = "start",
        connect_retry: int = 5,
    ):
        self.tracker_uri = tracker_uri or os.environ.get("DMLC_TRACKER_URI")
        self.tracker_port = int(
            tracker_port or os.environ.get("DMLC_TRACKER_PORT", 0)
        )
        check(self.tracker_uri, "no tracker address (DMLC_TRACKER_URI unset)")
        self.jobid = jobid or os.environ.get("DMLC_TASK_ID", "NULL")
        self.connect_retry = connect_retry
        self.rank = rank
        self.world_size = world_size
        self._aborted = False
        # membership generation this engine rendezvoused into; a static
        # (non-elastic) world is generation 1, cmd='elastic' overwrites
        # it with the tracker's committed world_version
        self.generation = 1
        env_thresh = os.environ.get("DMLC_TPU_RING_THRESHOLD_BYTES")
        if env_thresh is not None:
            try:
                self.ring_threshold_bytes = int(env_thresh)
            except ValueError:
                pass  # keep the measured default
        self.parent_rank = -1
        self.ring_prev = -1
        self.ring_next = -1
        self.tree_links: List[int] = []
        self.links: Dict[int, FramedSocket] = {}
        self._listener: Optional[socket.socket] = None
        self._connect(cmd, connect_retry)

    # ---- rendezvous ----------------------------------------------------
    def _dial_tracker(self, cmd: str) -> FramedSocket:
        sock = socket.create_connection(
            (self.tracker_uri, self.tracker_port), timeout=60
        )
        conn = FramedSocket(sock)
        conn.send_int(MAGIC)
        got = conn.recv_int()
        if got != MAGIC:
            raise DMLCError(f"tracker handshake failed: magic {got:#x}")
        conn.send_int(self.rank)
        conn.send_int(self.world_size)
        conn.send_str(self.jobid)
        conn.send_str(cmd)
        return conn

    def _connect(self, cmd: str, retries: int) -> None:
        from dmlc_tpu.resilience import RetryPolicy, faultpoint

        def dial():
            faultpoint("collective.connect")
            return self._dial_tracker(cmd)

        # classifier narrowed to connection errors on purpose: a DMLCError
        # here is a bad-magic handshake (wrong service, version skew) and
        # redialing the same port cannot fix it
        conn = RetryPolicy(
            max_attempts=max(1, retries), base_s=0.2, cap_s=2.0,
            classify=lambda err: isinstance(err, (ConnectionError, OSError)),
        ).call(dial, "collective.connect",
               display=f"tracker {self.tracker_uri}:{self.tracker_port}")

        if cmd == "elastic":
            # the elastic admission ack precedes the standard frame: the
            # generation this entrant will join, or −1 for a refusal
            # (evicted rank / banned jobid) — which redialing cannot fix
            generation = conn.recv_int()
            if generation < 0:
                conn.close()
                raise DMLCError(
                    "tracker refused elastic re-entry (evicted or banned)")
            self.generation = generation
        self.rank = conn.recv_int()
        self.parent_rank = conn.recv_int()
        self.world_size = conn.recv_int()
        num_neighbors = conn.recv_int()
        self.tree_links = [conn.recv_int() for _ in range(num_neighbors)]
        self.ring_prev = conn.recv_int()
        self.ring_next = conn.recv_int()
        expected = set(self.tree_links)
        if self.ring_prev not in (-1, self.rank):
            expected.add(self.ring_prev)
        if self.ring_next not in (-1, self.rank):
            expected.add(self.ring_next)

        # listen for peers that will dial us
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.bind(("", 0))
        self._listener.listen(16)
        my_port = self._listener.getsockname()[1]

        # goodset/badset loop (the worker half of tracker.py:105-135)
        conn.send_int(len(self.links))
        for r in self.links:
            conn.send_int(r)
        num_conn = conn.recv_int()
        num_accept = conn.recv_int()
        errors = 0
        for _ in range(num_conn):
            peer_host = conn.recv_str()
            peer_port = conn.recv_int()
            peer_rank = conn.recv_int()
            try:
                self._dial_peer(peer_host, peer_port, peer_rank)
            except OSError:
                errors += 1
        conn.send_int(errors)
        if errors:
            raise DMLCError("peer connect failed")  # tracker would loop; keep strict
        conn.send_int(my_port)
        # accept the remaining peers
        for _ in range(num_accept):
            fd, _addr = self._listener.accept()
            peer = FramedSocket(fd)
            got = peer.recv_int()
            check(got == _PEER_MAGIC, "bad peer magic")
            peer_rank = peer.recv_int()
            peer.send_int(_PEER_MAGIC)
            peer.send_int(self.rank)
            self.links[peer_rank] = peer
        conn.close()
        missing = expected - set(self.links)
        check(not missing, "missing peer links: %s", missing)

    def _dial_peer(self, host: str, port: int, peer_rank: int) -> None:
        sock = socket.create_connection((host, port), timeout=60)
        peer = FramedSocket(sock)
        peer.send_int(_PEER_MAGIC)
        peer.send_int(self.rank)
        got = peer.recv_int()
        check(got == _PEER_MAGIC, "bad peer magic")
        got_rank = peer.recv_int()
        check(got_rank == peer_rank, "peer rank mismatch")
        self.links[peer_rank] = peer

    # ---- framed array transport ---------------------------------------
    @staticmethod
    def _send_array(conn: FramedSocket, arr: np.ndarray) -> None:
        from dmlc_tpu.resilience import faultpoint

        faultpoint("collective.send")
        payload = arr.tobytes()
        header = f"{arr.dtype.str}|{','.join(map(str, arr.shape))}"
        conn.send_str(header)
        conn.send_int(len(payload))
        conn.sock.sendall(payload)

    @staticmethod
    def _recv_array(conn: FramedSocket) -> np.ndarray:
        from dmlc_tpu.resilience import faultpoint

        faultpoint("collective.recv")
        header = conn.recv_str()
        # dtype.str may itself start with '|' (e.g. "|u1"), so split from the
        # right where the shape field is.
        dtype_str, shape_str = header.rsplit("|", 1)
        shape = tuple(int(x) for x in shape_str.split(",") if x)
        nbytes = conn.recv_int()
        data = conn.recv_all(nbytes)
        return np.frombuffer(data, dtype=np.dtype(dtype_str)).reshape(shape).copy()

    # ---- collectives ----------------------------------------------------
    def _tree_children(self) -> List[int]:
        return sorted(r for r in self.tree_links if r != self.parent_rank)

    # Messages at or above this size take the ring; short messages stay
    # on the tree. This is the split rabit makes — the tracker builds BOTH
    # topologies for exactly this reason (tracker.py:193-225).
    #
    # Cost model + measurement behind the 2 MB default (round 4,
    # loopback world=4 sweep, post-TCP_NODELAY — see BASELINE.md):
    #   tree  ≈ 2·depth·α            + serial full-N folds at the root
    #   ring  ≈ 2(W-1)·α (more hops) + folds spread in N/W chunks
    # Small N: the latency term dominates and the tree's 2·log2(W) hops
    # beat the ring's 2(W-1) — measured 0.08-0.22x ring/tree at 4 KB to
    # 256 KB. Large N: the root's serial recv+fold of full-N child
    # payloads dominates and the ring's chunked schedule wins — measured
    # crossover between 1 MB (ring/tree 0.65) and 3 MB (1.0-1.24), ≈2 MB
    # at both reps; ring holds 1.1-1.4x through 16 MB. Loopback shares
    # one memory bus, so absolute GB/s are contention floors, but the
    # crossover compares the two schedules under identical contention.
    # Real networks shift α and the fold rate — override via
    # DMLC_TPU_RING_THRESHOLD_BYTES (read at engine construction) for a
    # measured deployment.
    # Derivation scope: world=4 loopback on a 1-core host (bench_collective
    # forced-topology cases, BENCH_r04). The tree's root-serialization term
    # grows with W while the ring's per-hop chunk shrinks, so at world 8+
    # the crossover should move DOWN; re-run the forced-topology sweep on a
    # multi-core host (DMLC_TPU_BENCH_SOCKET_WORLD=8) before trusting the
    # 2 MB figure there.
    ring_threshold_bytes: int = 2 << 20

    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        """Allreduce with rabit's topology split: tree (reduce-up in sorted
        child order → deterministic, bit-reproducible) for short messages,
        ring reduce-scatter + allgather for long ones. Both produce a result
        that is bit-identical across ranks and across repeated calls."""
        check(op in _REDUCERS, "unknown reduce op %s", op)
        check(not self._aborted,
              "engine aborted (pending recover); reinit before collectives")
        arr = np.asarray(array)
        if (
            arr.nbytes >= self.ring_threshold_bytes
            and self.world_size > 1
            and self.ring_prev not in (-1, self.rank)
            and self.ring_next not in (-1, self.rank)
        ):
            return self._ring_allreduce(arr, op)
        return self._tree_allreduce(arr, op)

    def _tree_allreduce(self, array: np.ndarray, op: str) -> np.ndarray:
        reduce_fn = _REDUCERS[op]
        acc = np.asarray(array).copy()
        for child in self._tree_children():
            acc = reduce_fn(acc, self._recv_array(self.links[child]))
        if self.parent_rank != -1:
            self._send_array(self.links[self.parent_rank], acc)
            acc = self._recv_array(self.links[self.parent_rank])
        for child in self._tree_children():
            self._send_array(self.links[child], acc)
        return acc

    def _ring_step(self, send_id: int, send_chunk: np.ndarray):
        """One ring exchange: send (id, chunk) to ring_next while receiving
        (id, chunk) from ring_prev. The send runs on a helper thread so two
        neighbors pushing large chunks at each other cannot deadlock on
        full TCP buffers (with world == 2, prev and next are even the same
        socket — concurrent one-send/one-recv is safe)."""
        nxt = self.links[self.ring_next]
        prv = self.links[self.ring_prev]
        send_err: List[BaseException] = []

        def _send():
            try:
                nxt.send_int(send_id)
                self._send_array(nxt, send_chunk)
            except BaseException as err:  # re-raised on the caller thread
                send_err.append(err)

        sender = threading.Thread(target=_send)
        sender.start()
        try:
            recv_id = prv.recv_int()
            recv_chunk = self._recv_array(prv)
        finally:
            sender.join()
            if send_err:
                raise DMLCError(
                    f"ring send to rank {self.ring_next} failed: {send_err[0]}"
                ) from send_err[0]
        return recv_id, recv_chunk

    def _ring_allreduce(self, array: np.ndarray, op: str) -> np.ndarray:
        """Reduce-scatter + allgather around the tracker's ring.

        Chunk ids travel with the data, so no rank needs to know its global
        ring position — chunk r originates at rank r, accumulates along the
        ring for n-1 hops (deterministic order: the fixed ring), then the
        fully-reduced chunks circulate for n-1 more hops. Every rank moves
        ~2·size·(n-1)/n bytes regardless of n."""
        reduce_fn = _REDUCERS[op]
        n = self.world_size
        flat = array.reshape(-1)
        chunks = {i: c.copy() for i, c in enumerate(np.array_split(flat, n))}

        # reduce-scatter: forward the chunk just reduced, fold the incoming
        send_id = self.rank
        for _ in range(n - 1):
            recv_id, recv_chunk = self._ring_step(send_id, chunks[send_id])
            chunks[recv_id] = reduce_fn(chunks[recv_id], recv_chunk)
            send_id = recv_id
        # send_id now names this rank's fully-reduced chunk

        # allgather: circulate the completed chunks
        for _ in range(n - 1):
            recv_id, recv_chunk = self._ring_step(send_id, chunks[send_id])
            chunks[recv_id] = recv_chunk
            send_id = recv_id

        out = np.concatenate([chunks[i] for i in range(n)])
        return out.reshape(array.shape).astype(array.dtype, copy=False)

    def broadcast(self, array: Optional[np.ndarray], root: int = 0) -> np.ndarray:
        """Tree broadcast from any root.

        Implemented as an or-style allreduce of (mine-if-root else zeros):
        first the payload shape/dtype spreads via a small allreduce, then the
        payload itself — avoiding root-path routing over the relabeled tree.
        """
        if self.world_size == 1:
            assert array is not None
            return np.asarray(array)
        is_root = self.rank == root
        # fixed-size metadata frame: [ndim, shape..., 8-byte dtype code];
        # both sides must agree on the slot count, so the dimension cap is a
        # protocol constant with an explicit check rather than a crash.
        max_ndim = _META_SLOTS - 9
        if is_root:
            check(array is not None, "broadcast root must supply data")
            arr = np.asarray(array)
            check(
                arr.ndim <= max_ndim,
                "broadcast supports at most %d dims, got %d",
                max_ndim,
                arr.ndim,
            )
            dtype_code = np.frombuffer(
                arr.dtype.str.ljust(8, " ").encode(), dtype=np.uint8
            ).astype(np.int64)
            meta = np.concatenate(
                [
                    np.asarray([arr.ndim], dtype=np.int64),
                    np.asarray(arr.shape, dtype=np.int64),
                    dtype_code,
                ]
            )
            meta_padded = np.zeros(_META_SLOTS, dtype=np.int64)
            meta_padded[: len(meta)] = meta
        else:
            meta_padded = np.zeros(_META_SLOTS, dtype=np.int64)
        meta_out = self.allreduce(meta_padded, op="sum")
        ndim = int(meta_out[0])
        shape = tuple(int(x) for x in meta_out[1 : 1 + ndim])
        dtype = np.dtype(
            bytes(meta_out[1 + ndim : 1 + ndim + 8].astype(np.uint8)).decode().strip()
        )
        if is_root:
            payload = np.asarray(array).astype(dtype).reshape(shape)
        else:
            payload = np.zeros(shape, dtype=dtype)
        view = payload.reshape(-1).view(np.uint8)
        out = self.allreduce(view, op="bitor")
        return out.view(dtype).reshape(shape)

    def allgather(self, array: np.ndarray) -> List[np.ndarray]:
        """Gather every rank's array (rabit Allgather semantics): one
        broadcast round per rank."""
        out = []
        for r in range(self.world_size):
            out.append(self.broadcast(array if r == self.rank else None, root=r))
        return out

    def barrier(self) -> None:
        self.allreduce(np.zeros(1, dtype=np.int32), op="sum")

    # ---- control messages ----------------------------------------------
    def tracker_print(self, msg: str) -> None:
        """Relay a message through the tracker log (tracker.py:269-272)."""
        conn = self._dial_tracker("print")
        conn.send_str(msg)
        conn.close()

    def abort(self) -> None:
        """Drop every peer link and the listener WITHOUT telling the tracker
        — the worker is coming back with cmd='recover'. Closing all links
        (not just the failed one) is load-bearing: peers blocked in a
        collective on this worker get a socket error, abort too, and the
        failure cascades through the tree so the whole world re-enters
        rendezvous together (rabit's abort-and-recover semantics)."""
        self._aborted = True
        for peer in self.links.values():
            peer.close()
        self.links.clear()
        if self._listener is not None:
            self._listener.close()
            self._listener = None

    def shutdown(self) -> None:
        self.abort()
        try:
            conn = self._dial_tracker("shutdown")
            conn.close()
        except (DMLCError, OSError):
            pass
