"""Rabit-style checkpoint / resume over the Stream-to-URI surface.

The reference provides the *building blocks* for checkpointing —
``Serializable`` Load/Save over any ``Stream::Create`` URI (io.h:112-126),
STL serialization (serializer.h), ``Parameter::Save/Load`` — while the
checkpoint *policy* (rabit's CheckPoint/LoadCheckPoint/version_number used
for fault recovery with the tracker's ``recover`` re-entry,
tracker.py:279-291) lives downstream. The TPU build owes that policy: this
module implements it against any filesystem backend (file://, gs://, s3://,
mem://), so a restarted worker re-joins with ``cmd='recover'`` and restores
the last committed global state.

Layout under the checkpoint URI directory::

    ckpt_v{N}.bin          global state, written by rank 0 (or all ranks
                           when ``per_rank=True``: ckpt_v{N}.rank{R}.bin)
    LATEST                 text pointer "N" — committed last, so a torn
                           write of the state file is never visible

jax arrays in the state tree are converted to host numpy on save (the
device-buffer (de)serialization path SURVEY §5.4 calls for).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from dmlc_tpu.io.filesystem import URI, create_stream, get_filesystem
from dmlc_tpu.io.serializer import load_obj, save_obj
from dmlc_tpu.utils.logging import DMLCError, check, log_warning


def _to_host(tree: Any) -> Any:
    """Device arrays → numpy, recursively, without requiring jax."""
    if isinstance(tree, dict):
        return {k: _to_host(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        mapped = [_to_host(v) for v in tree]
        if isinstance(tree, tuple):
            # NamedTuples (e.g. optax optimizer states) take *fields
            if hasattr(type(tree), "_fields"):
                return type(tree)(*mapped)
            return tuple(mapped)
        return mapped
    if hasattr(tree, "__array__") and not isinstance(tree, np.ndarray):
        return np.asarray(tree)
    return tree


class CheckpointManager:
    """CheckPoint / LoadCheckPoint / version_number (rabit API surface).

    ``rank`` selects the writer: by default only rank 0 commits the global
    state (every rank calls ``checkpoint`` — non-writers just bump their
    version, mirroring rabit where the global model is logically one).
    ``per_rank=True`` writes one state file per rank (rabit's local model)
    and loads this rank's own file.

    ``fallback_uri`` (default: the ``DMLC_TPU_CKPT_FALLBACK_URI`` knob) is
    the graceful-degradation path: when a commit to the primary URI fails
    even after the io layer's retries, the same version is committed to
    the fallback directory instead of losing the snapshot, and
    ``load_checkpoint`` considers both locations (newest committed version
    wins). Meant for a second failure domain — e.g. primary on an object
    store, fallback on local disk.
    """

    def __init__(
        self,
        uri: str,
        rank: int = 0,
        world_size: int = 1,
        per_rank: bool = False,
        keep: int = 2,
        fallback_uri: Optional[str] = None,
    ):
        check(keep >= 1, "keep must be >= 1")
        self.uri = uri.rstrip("/")
        self.rank = rank
        self.world_size = world_size
        self.per_rank = per_rank
        self.keep = keep
        if fallback_uri is None:  # "" explicitly disables the env knob
            from dmlc_tpu.params.knobs import ckpt_fallback_uri

            fallback_uri = ckpt_fallback_uri()
        fallback_uri = (fallback_uri or "").rstrip("/") or None
        if fallback_uri is not None:
            check(fallback_uri != self.uri,
                  "fallback checkpoint URI must differ from the primary")
        self._fallback_uri = fallback_uri
        self._fallback: Optional["CheckpointManager"] = None
        parsed = URI.parse(self.uri)
        if parsed.protocol in ("file://", ""):
            import os

            os.makedirs(parsed.name, exist_ok=True)
        self._version = 0
        latest = self._read_latest()
        if latest is not None:
            self._version = latest

    # ---- rabit surface -------------------------------------------------
    @property
    def version_number(self) -> int:
        """Number of committed checkpoints (rabit VersionNumber)."""
        return self._version

    def checkpoint(self, state: Any) -> int:
        """Commit ``state`` as version ``version_number + 1``; returns it.

        A commit that still fails after the io layer's retries degrades to
        the fallback URI (when configured) instead of dropping the
        snapshot; config-shaped errors (``FileNotFoundError`` etc. on a
        local path) are not degradation candidates and surface directly.
        """
        version = self._version + 1
        try:
            self._commit(version, state)
        except (DMLCError, OSError) as err:
            fb = self._fallback_manager()
            if fb is None or isinstance(
                err, (FileNotFoundError, PermissionError, IsADirectoryError,
                      NotADirectoryError)
            ):
                raise
            log_warning(
                "checkpoint v%d commit to %s failed (%s); degrading to "
                "fallback %s", version, self.uri, err, fb.uri,
            )
            from dmlc_tpu.obs import flight

            flight.record_event("ckpt.fallback", version=version,
                                uri=self.uri, error=str(err))
            fb._version = version - 1  # keep version numbering aligned
            fb._commit(version, state)
        self._version = version
        if self.rank == 0:
            self._prune(version)
        return version

    def _commit(self, version: int, state: Any) -> None:
        from dmlc_tpu.resilience import faultpoint

        faultpoint("ckpt.commit")
        if self.per_rank or self.rank == 0:
            stream = create_stream(self._state_uri(version, self.rank), "w")
            try:
                save_obj(stream, _to_host(state))
            finally:
                stream.close()
        if self.rank == 0:
            self._write_latest(version)

    def _fallback_manager(self) -> Optional["CheckpointManager"]:
        if self._fallback is None and self._fallback_uri is not None:
            self._fallback = CheckpointManager(
                self._fallback_uri, rank=self.rank,
                world_size=self.world_size, per_rank=self.per_rank,
                keep=self.keep, fallback_uri="",  # no fallback chains
            )
        return self._fallback

    def load_checkpoint(self) -> Tuple[int, Optional[Any]]:
        """(version, state) of the newest committed checkpoint, or (0, None).

        After a worker restart this re-reads LATEST, so a manager built
        fresh in the recovered process resumes from the last commit (the
        tracker keeps the rank stable across ``recover``,
        tracker.py:279-291). In ``per_rank`` mode the commit point (rank
        0's LATEST) cannot guarantee every rank's file landed, so a missing
        state file falls back version by version through the retained
        window before failing. With a fallback URI configured, whichever
        location holds the newest committed version is loaded — a restart
        after a degraded commit resumes from the fallback copy.
        """
        fb = self._fallback_manager()
        if fb is not None:
            primary_latest = self._read_latest() or 0
            if (fb._read_latest() or 0) > primary_latest:
                version, state = fb.load_checkpoint()
                self._version = max(self._version, version)
                return version, state
        return self._load_from_self()

    def _load_from_self(self) -> Tuple[int, Optional[Any]]:
        from dmlc_tpu.resilience import faultpoint

        faultpoint("ckpt.read")
        latest = self._read_latest()
        if not latest:
            return 0, None
        rank = self.rank if self.per_rank else 0
        floor = max(1, latest - self.keep + 1) if self.per_rank else latest
        for version in range(latest, floor - 1, -1):
            stream = create_stream(
                self._state_uri(version, rank), "r", allow_null=True
            )
            if stream is None:
                continue
            try:
                state = load_obj(stream)
            finally:
                stream.close()
            self._version = version
            return version, state
        raise DMLCError(
            f"checkpoint LATEST points at v{latest} but no readable state "
            f"file exists in {self.uri} (rank {rank})"
        )

    # ---- internals -----------------------------------------------------
    def _state_uri(self, version: int, rank: int) -> str:
        if self.per_rank:
            return f"{self.uri}/ckpt_v{version}.rank{rank}.bin"
        return f"{self.uri}/ckpt_v{version}.bin"

    def _write_latest(self, version: int) -> None:
        """Commit the LATEST pointer atomically.

        Local files go through write-temp-then-rename (a crash mid-write
        must never leave a truncated LATEST); object stores materialize the
        object only when the upload completes, which is already atomic
        (mem:// is a single-process test backend where this cannot race).
        """
        uri = f"{self.uri}/LATEST"
        parsed = URI.parse(uri)
        payload = str(version).encode()
        if parsed.protocol in ("file://", ""):
            import os

            tmp = parsed.name + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(payload)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, parsed.name)
            return
        stream = create_stream(uri, "w")
        try:
            stream.write(payload)
        finally:
            stream.close()

    def _read_latest(self) -> Optional[int]:
        stream = create_stream(f"{self.uri}/LATEST", "r", allow_null=True)
        if stream is None:
            return None
        try:
            parts = []
            while True:
                piece = stream.read(4096)
                if not piece:
                    break
                parts.append(piece)
            text = b"".join(parts).decode().strip()
        finally:
            stream.close()
        return int(text) if text else None

    def _prune(self, newest: int) -> None:
        """Best-effort removal of checkpoints older than the ``keep`` window."""
        fs = get_filesystem(URI.parse(self.uri))
        delete = getattr(fs, "delete", None)
        if delete is None:
            return
        ranks = range(self.world_size) if self.per_rank else (0,)
        for version in range(max(1, newest - self.keep * 4), newest - self.keep + 1):
            for rank in ranks:
                try:
                    delete(URI.parse(self._state_uri(version, rank)))
                except Exception:
                    pass
