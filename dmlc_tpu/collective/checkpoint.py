"""Rabit-style checkpoint / resume over the Stream-to-URI surface.

The reference provides the *building blocks* for checkpointing —
``Serializable`` Load/Save over any ``Stream::Create`` URI (io.h:112-126),
STL serialization (serializer.h), ``Parameter::Save/Load`` — while the
checkpoint *policy* (rabit's CheckPoint/LoadCheckPoint/version_number used
for fault recovery with the tracker's ``recover`` re-entry,
tracker.py:279-291) lives downstream. The TPU build owes that policy: this
module implements it against any filesystem backend (file://, gs://, s3://,
mem://), so a restarted worker re-joins with ``cmd='recover'`` and restores
the last committed global state.

Layout under the checkpoint URI directory::

    ckpt_v{N}.bin          global state, written by rank 0 (or all ranks
                           when ``per_rank=True``: ckpt_v{N}.rank{R}.bin)
    LATEST                 text pointer "N" — committed last, so a torn
                           write of the state file is never visible

jax arrays in the state tree are converted to host numpy on save (the
device-buffer (de)serialization path SURVEY §5.4 calls for).

:class:`JobSnapshot` layers a coordinated *job*-level snapshot on the
same surface: every rank writes its own ``snap_v{N}.rank{R}`` part
(model + data-plane frontier + RNG + audit heads), rank 0 waits for all
parts of the version to land, then commits a crc-guarded manifest
naming every part — a two-phase commit where a torn or partial write is
never visible to :meth:`JobSnapshot.restore`. See
docs/robustness.md "Preemption & resume".
"""

from __future__ import annotations

import json
import struct
import time
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from dmlc_tpu.io.filesystem import URI, create_stream, get_filesystem
from dmlc_tpu.io.serializer import load_obj, save_obj
from dmlc_tpu.io.stream import MemoryStream
from dmlc_tpu.utils.logging import DMLCError, check, log_warning


def _to_host(tree: Any) -> Any:
    """Device arrays → numpy, recursively, without requiring jax.

    Always a REAL copy, never a view: on the cpu backend
    ``np.asarray(jax_array)`` can alias the device buffer zero-copy, and
    the async snapshot writer serializes these trees while the next
    epoch's donating train steps are already reusing the donated
    buffers — an aliased "copy" would mutate under the writer (or
    outlive a freed buffer). ``np.array(..., copy=True)`` is the
    donation-safe boundary."""
    if isinstance(tree, dict):
        return {k: _to_host(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        mapped = [_to_host(v) for v in tree]
        if isinstance(tree, tuple):
            # NamedTuples (e.g. optax optimizer states) take *fields
            if hasattr(type(tree), "_fields"):
                return type(tree)(*mapped)
            return tuple(mapped)
        return mapped
    if hasattr(tree, "__array__") and not isinstance(tree, np.ndarray):
        return np.array(tree, copy=True)
    return tree


class CheckpointManager:
    """CheckPoint / LoadCheckPoint / version_number (rabit API surface).

    ``rank`` selects the writer: by default only rank 0 commits the global
    state (every rank calls ``checkpoint`` — non-writers just bump their
    version, mirroring rabit where the global model is logically one).
    ``per_rank=True`` writes one state file per rank (rabit's local model)
    and loads this rank's own file.

    ``fallback_uri`` (default: the ``DMLC_TPU_CKPT_FALLBACK_URI`` knob) is
    the graceful-degradation path: when a commit to the primary URI fails
    even after the io layer's retries, the same version is committed to
    the fallback directory instead of losing the snapshot, and
    ``load_checkpoint`` considers both locations (newest committed version
    wins). Meant for a second failure domain — e.g. primary on an object
    store, fallback on local disk.
    """

    def __init__(
        self,
        uri: str,
        rank: int = 0,
        world_size: int = 1,
        per_rank: bool = False,
        keep: int = 2,
        fallback_uri: Optional[str] = None,
    ):
        check(keep >= 1, "keep must be >= 1")
        self.uri = uri.rstrip("/")
        self.rank = rank
        self.world_size = world_size
        self.per_rank = per_rank
        self.keep = keep
        if fallback_uri is None:  # "" explicitly disables the env knob
            from dmlc_tpu.params.knobs import ckpt_fallback_uri

            fallback_uri = ckpt_fallback_uri()
        fallback_uri = (fallback_uri or "").rstrip("/") or None
        if fallback_uri is not None:
            check(fallback_uri != self.uri,
                  "fallback checkpoint URI must differ from the primary")
        self._fallback_uri = fallback_uri
        self._fallback: Optional["CheckpointManager"] = None
        parsed = URI.parse(self.uri)
        if parsed.protocol in ("file://", ""):
            import os

            os.makedirs(parsed.name, exist_ok=True)
        self._version = 0
        latest = self._read_latest()
        if latest is not None:
            self._version = latest

    # ---- rabit surface -------------------------------------------------
    @property
    def version_number(self) -> int:
        """Number of committed checkpoints (rabit VersionNumber)."""
        return self._version

    def checkpoint(self, state: Any) -> int:
        """Commit ``state`` as version ``version_number + 1``; returns it.

        A commit that still fails after the io layer's retries degrades to
        the fallback URI (when configured) instead of dropping the
        snapshot; config-shaped errors (``FileNotFoundError`` etc. on a
        local path) are not degradation candidates and surface directly.
        """
        version = self._version + 1
        try:
            self._commit(version, state)
        except (DMLCError, OSError) as err:
            fb = self._fallback_manager()
            if fb is None or isinstance(
                err, (FileNotFoundError, PermissionError, IsADirectoryError,
                      NotADirectoryError)
            ):
                raise
            log_warning(
                "checkpoint v%d commit to %s failed (%s); degrading to "
                "fallback %s", version, self.uri, err, fb.uri,
            )
            from dmlc_tpu.obs import flight

            flight.record_event("ckpt.fallback", version=version,
                                uri=self.uri, error=str(err))
            fb._version = version - 1  # keep version numbering aligned
            fb._commit(version, state)
        self._version = version
        if self.rank == 0:
            self._prune(version)
        return version

    def _commit(self, version: int, state: Any) -> None:
        from dmlc_tpu.resilience import faultpoint

        faultpoint("ckpt.commit")
        if self.per_rank or self.rank == 0:
            stream = create_stream(self._state_uri(version, self.rank), "w")
            try:
                save_obj(stream, _to_host(state))
            finally:
                stream.close()
        if self.rank == 0:
            self._write_latest(version)

    def _fallback_manager(self) -> Optional["CheckpointManager"]:
        if self._fallback is None and self._fallback_uri is not None:
            self._fallback = CheckpointManager(
                self._fallback_uri, rank=self.rank,
                world_size=self.world_size, per_rank=self.per_rank,
                keep=self.keep, fallback_uri="",  # no fallback chains
            )
        return self._fallback

    def load_checkpoint(self) -> Tuple[int, Optional[Any]]:
        """(version, state) of the newest committed checkpoint, or (0, None).

        After a worker restart this re-reads LATEST, so a manager built
        fresh in the recovered process resumes from the last commit (the
        tracker keeps the rank stable across ``recover``,
        tracker.py:279-291). In ``per_rank`` mode the commit point (rank
        0's LATEST) cannot guarantee every rank's file landed, so a missing
        state file falls back version by version through the retained
        window before failing. With a fallback URI configured, whichever
        location holds the newest committed version is loaded — a restart
        after a degraded commit resumes from the fallback copy.
        """
        fb = self._fallback_manager()
        if fb is not None:
            primary_latest = self._read_latest() or 0
            if (fb._read_latest() or 0) > primary_latest:
                version, state = fb.load_checkpoint()
                self._version = max(self._version, version)
                return version, state
        return self._load_from_self()

    def _load_from_self(self) -> Tuple[int, Optional[Any]]:
        from dmlc_tpu.resilience import faultpoint

        faultpoint("ckpt.read")
        latest = self._read_latest()
        if not latest:
            return 0, None
        rank = self.rank if self.per_rank else 0
        floor = max(1, latest - self.keep + 1) if self.per_rank else latest
        for version in range(latest, floor - 1, -1):
            stream = create_stream(
                self._state_uri(version, rank), "r", allow_null=True
            )
            if stream is None:
                continue
            try:
                state = load_obj(stream)
            finally:
                stream.close()
            self._version = version
            return version, state
        raise DMLCError(
            f"checkpoint LATEST points at v{latest} but no readable state "
            f"file exists in {self.uri} (rank {rank})"
        )

    # ---- internals -----------------------------------------------------
    def _state_uri(self, version: int, rank: int) -> str:
        if self.per_rank:
            return f"{self.uri}/ckpt_v{version}.rank{rank}.bin"
        return f"{self.uri}/ckpt_v{version}.bin"

    def _write_latest(self, version: int) -> None:
        """Commit the LATEST pointer atomically.

        Local files go through write-temp-then-rename (a crash mid-write
        must never leave a truncated LATEST); object stores materialize the
        object only when the upload completes, which is already atomic
        (mem:// is a single-process test backend where this cannot race).
        """
        uri = f"{self.uri}/LATEST"
        parsed = URI.parse(uri)
        payload = str(version).encode()
        if parsed.protocol in ("file://", ""):
            import os

            tmp = parsed.name + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(payload)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, parsed.name)
            return
        stream = create_stream(uri, "w")
        try:
            stream.write(payload)
        finally:
            stream.close()

    def _read_latest(self) -> Optional[int]:
        stream = create_stream(f"{self.uri}/LATEST", "r", allow_null=True)
        if stream is None:
            return None
        try:
            parts = []
            while True:
                piece = stream.read(4096)
                if not piece:
                    break
                parts.append(piece)
            text = b"".join(parts).decode().strip()
        finally:
            stream.close()
        return int(text) if text else None

    def _prune(self, newest: int) -> None:
        """Best-effort removal of checkpoints older than the ``keep`` window."""
        fs = get_filesystem(URI.parse(self.uri))
        delete = getattr(fs, "delete", None)
        if delete is None:
            return
        ranks = range(self.world_size) if self.per_rank else (0,)
        for version in range(max(1, newest - self.keep * 4), newest - self.keep + 1):
            for rank in ranks:
                try:
                    delete(URI.parse(self._state_uri(version, rank)))
                except Exception:
                    pass


# ---- coordinated job snapshots ----------------------------------------


class SnapshotSuperseded(DMLCError):
    """A rank moved past the awaited version without writing its part.

    Raised by the rank-0 part barrier when a peer's frontier marker shows
    it already wrote a part for a *newer* version: the peer's capture for
    the awaited version was superseded (newest-wins coalescing in the
    async writer) and its part will never land. The commit for the
    superseded version is abandoned — the newer version carries the
    durable state — instead of burning the full barrier timeout.
    """


#: Trailer magic for snapshot part files ("SNAP" little-endian).
PART_MAGIC = 0x534E4150
_PART_TRAILER = struct.Struct("<III")  # magic, crc32(payload), len(payload)


def _atomic_write(uri: str, payload: bytes) -> None:
    """Write ``payload`` so a crash never leaves a truncated file.

    Local files go through write-temp-fsync-rename; object stores
    materialize the object only on completed upload, which is already
    atomic (mem:// is a single-process test backend where this cannot
    race).
    """
    parsed = URI.parse(uri)
    if parsed.protocol in ("file://", ""):
        import os

        tmp = parsed.name + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, parsed.name)
        return
    stream = create_stream(uri, "w")
    try:
        stream.write(payload)
    finally:
        stream.close()


def _read_all(uri: str) -> Optional[bytes]:
    stream = create_stream(uri, "r", allow_null=True)
    if stream is None:
        return None
    try:
        parts = []
        while True:
            piece = stream.read(1 << 20)
            if not piece:
                break
            parts.append(piece)
    finally:
        stream.close()
    return b"".join(parts)


class JobSnapshot(CheckpointManager):
    """Two-phase-commit job snapshot: rank parts + a crc-guarded manifest.

    Phase 1: every rank serializes its state tree (model + optimizer +
    data-plane frontier + RNG + audit heads) to ``snap_v{N}.rank{R}``, a
    self-checking part file whose trailer records a crc32 and length of
    the payload. Phase 2: rank 0 waits for all ``world_size`` parts of
    the version to land and verify, fires the ``snap.commit`` faultpoint,
    then atomically writes ``snap_v{N}.manifest`` (crc-guarded, naming
    every part with its size and crc) and bumps LATEST. A crash at any
    point before the manifest lands leaves the previous version the
    newest *committed* one — torn or partial writes are never visible to
    :meth:`restore`.

    The barrier is filesystem-level (rank 0 polls for part files) rather
    than a collective op, so a background snapshot writer thread never
    touches the collective engine's sockets and a just-in-time preemption
    snapshot works even when peers are already tearing down.

    Version numbers must agree across ranks for the barrier to pair the
    right parts — callers that can skip commits (the async writer's
    newest-wins slot) pass an explicit epoch-derived ``version`` to
    :meth:`commit` so a skipped epoch leaves a *gap* in the sequence
    instead of shifting every later version (which would pair different
    epochs under one manifest). Each part write also bumps the rank's
    ``snap.rank{R}.frontier`` marker; the barrier reads the markers of
    still-missing ranks and abandons the commit
    (:class:`SnapshotSuperseded`) when a peer has already moved past the
    awaited version.
    """

    def __init__(
        self,
        uri: str,
        rank: int = 0,
        world_size: int = 1,
        keep: int = 2,
        fallback_uri: Optional[str] = None,
        part_timeout_s: float = 60.0,
    ):
        super().__init__(uri, rank=rank, world_size=world_size,
                         per_rank=True, keep=keep, fallback_uri=fallback_uri)
        self.part_timeout_s = part_timeout_s
        #: serialized payload size of this rank's last written part
        self.last_part_bytes = 0

    # ---- commit --------------------------------------------------------
    def commit(self, state: Any, meta: Optional[Dict[str, Any]] = None,
               version: Optional[int] = None) -> int:
        """Commit ``state`` (this rank's part) as the next version.

        Every rank calls ``commit`` with its own state tree; rank 0
        additionally runs the barrier + manifest phase. Returns the
        version number. Degrades to the fallback URI like
        :meth:`CheckpointManager.checkpoint` (all ranks observe the same
        failing filesystem, so degradation stays coordinated).

        ``version`` (optional) pins the version number explicitly —
        callers whose commit cadence can skip epochs (the async
        :class:`~dmlc_tpu.collective.snapshot.Snapshotter`) derive it
        from the epoch so every rank names the same epoch's part with
        the same version; it must advance past the newest version this
        rank has written. A commit whose barrier learns the version was
        superseded on a peer returns normally without a manifest — the
        newer version carries the durable state.
        """
        if version is None:
            version = self._version + 1
        else:
            version = int(version)
            check(version > self._version,
                  f"job snapshot version {version} must exceed this "
                  f"rank's newest written version {self._version} "
                  "(versions advance monotonically)")
        try:
            self._commit_snapshot(version, state, meta)
        except SnapshotSuperseded as err:
            from dmlc_tpu.obs import flight

            log_warning("%s", err)
            flight.record_event("snap.superseded", version=version)
        except (DMLCError, OSError) as err:
            fb = self._fallback_manager()
            if fb is None or isinstance(
                err, (FileNotFoundError, PermissionError, IsADirectoryError,
                      NotADirectoryError)
            ):
                raise
            log_warning(
                "job snapshot v%d commit to %s failed (%s); degrading to "
                "fallback %s", version, self.uri, err, fb.uri,
            )
            from dmlc_tpu.obs import flight

            flight.record_event("ckpt.fallback", version=version,
                                uri=self.uri, error=str(err))
            fb._version = version - 1
            fb._commit_snapshot(version, state, meta)
            self.last_part_bytes = fb.last_part_bytes
        self._version = version
        if self.rank == 0:
            self._prune(version)
        return version

    def _commit_snapshot(self, version: int, state: Any,
                         meta: Optional[Dict[str, Any]]) -> None:
        payload = self._write_part(version, state)
        if self.rank != 0:
            return
        parts = self._await_parts(version, own_payload=payload)
        from dmlc_tpu.resilience import faultpoint

        faultpoint("snap.commit")
        body = json.dumps({
            "version": version,
            "world_size": self.world_size,
            "parts": parts,
            "meta": meta or {},
        }, sort_keys=True).encode()
        head = b"%08x\n" % (zlib.crc32(body) & 0xFFFFFFFF)
        _atomic_write(self._manifest_uri(version), head + body)
        self._write_latest(version)
        from dmlc_tpu.obs import flight

        flight.record_event(
            "snap.commit", version=version, parts=len(parts),
            bytes=sum(p["size"] for p in parts),
        )

    def _write_part(self, version: int, state: Any) -> bytes:
        buf = MemoryStream()
        save_obj(buf, _to_host(state))
        payload = buf.getvalue()
        self.last_part_bytes = len(payload)
        trailer = _PART_TRAILER.pack(
            PART_MAGIC, zlib.crc32(payload) & 0xFFFFFFFF, len(payload)
        )
        _atomic_write(self._state_uri(version, self.rank), payload + trailer)
        # frontier marker: the newest version this rank wrote a part for.
        # Rank 0's barrier reads it to tell "peer is slow" (frontier
        # behind: keep waiting) from "peer skipped this version"
        # (frontier ahead: the awaited part will never land).
        _atomic_write(self._frontier_uri(self.rank), b"%d" % version)
        return payload

    def _await_parts(self, version: int, own_payload: bytes) -> list:
        """Rank 0 barrier: poll until every rank's part landed and verifies.

        Once a preemption notice is pending the barrier tightens to the
        remaining grace window: a peer that was itself preemption-killed
        behind this rank's epoch frontier will never write its part, and
        burning the full ``part_timeout_s`` would hold the process (and
        therefore the relaunch) hostage past the grace deadline. The
        failed commit degrades to the last committed version, which is
        exactly what resume falls back to.
        """
        from dmlc_tpu.resilience import preempt

        deadline = time.monotonic() + self.part_timeout_s
        entries: Dict[int, Dict[str, Any]] = {
            self.rank: {
                "name": self._part_name(version, self.rank),
                "size": len(own_payload),
                "crc": zlib.crc32(own_payload) & 0xFFFFFFFF,
            }
        }
        pending = [r for r in range(self.world_size) if r != self.rank]
        while pending:
            still = []
            for rank in pending:
                payload = self._read_part_payload(version, rank)
                if payload is None:
                    still.append(rank)
                    continue
                entries[rank] = {
                    "name": self._part_name(version, rank),
                    "size": len(payload),
                    "crc": zlib.crc32(payload) & 0xFFFFFFFF,
                }
            pending = still
            if pending:
                ahead = [r for r in pending
                         if self._read_frontier(r) > version]
                if ahead:
                    raise SnapshotSuperseded(
                        f"job snapshot v{version}: ranks {ahead} moved "
                        f"past this version without writing a part (their "
                        f"capture for it was superseded by a newer epoch); "
                        f"abandoning the v{version} manifest"
                    )
                now = time.monotonic()
                if preempt.requested():
                    deadline = min(
                        deadline, now + preempt.deadline_remaining())
                if now >= deadline:
                    raise DMLCError(
                        f"job snapshot v{version}: ranks {pending} did not "
                        f"write their part within the barrier window "
                        f"({self.part_timeout_s:.0f}s, or the preemption "
                        f"grace remainder once a notice is pending)"
                    )
                time.sleep(0.02)
        return [entries[r] for r in range(self.world_size)]

    # ---- restore -------------------------------------------------------
    def restore(self) -> Tuple[int, Optional[Any], Dict[str, Any]]:
        """(version, state, meta) of the newest committed snapshot.

        Walks the retained window newest-first, skipping versions whose
        manifest is torn or whose part fails its crc — a rank that
        crashed between part-write and manifest commit leaves the older
        version loadable. With a fallback URI configured, whichever
        location holds the newest *committed* (manifest present) version
        wins: a primary LATEST pointing at an uncommitted version does
        not shadow a committed fallback copy. A committed manifest whose
        ``world_size`` differs from this job's raises a clean
        ``DMLCError`` (resharding a per-rank snapshot is not supported).
        """
        fb = self._fallback_manager()
        if fb is not None:
            if self._newest_committed() < fb._newest_committed():
                version, state, meta = fb.restore()
                self._version = max(self._version, version)
                return version, state, meta
        latest = self._read_latest()
        if not latest:
            return 0, None, {}
        # walk the prune window (keep*4), not just `keep` raw numbers:
        # the committed sequence may have gaps (superseded versions), so
        # the previous committed manifest can sit more than `keep`
        # version numbers below LATEST
        floor = max(1, latest - self.keep * 4 + 1)
        for version in range(latest, floor - 1, -1):
            loaded = self._restore_version(version)
            if loaded is None:
                continue
            state, meta = loaded
            self._version = version
            return version, state, meta
        raise DMLCError(
            f"job snapshot LATEST points at v{latest} but no committed "
            f"version is readable in {self.uri} (rank {self.rank})"
        )

    def _newest_committed(self) -> int:
        """Newest version with an intact manifest (0 when none)."""
        latest = self._read_latest()
        if not latest:
            return 0
        floor = max(1, latest - self.keep * 4 + 1)
        for version in range(latest, floor - 1, -1):
            if self._read_manifest(version) is not None:
                return version
        return 0

    def _restore_version(self, version: int):
        manifest = self._read_manifest(version)
        if manifest is None:
            return None
        if manifest["world_size"] != self.world_size:
            raise DMLCError(
                f"job snapshot v{version} in {self.uri} was written by "
                f"world_size={manifest['world_size']} but this job runs "
                f"world_size={self.world_size}; per-rank snapshots cannot "
                "be resharded — restart with the original world size or "
                "point at a fresh snapshot directory"
            )
        entry = manifest["parts"][self.rank]
        payload = self._read_part_payload(version, self.rank)
        if payload is None or len(payload) != entry["size"] \
                or zlib.crc32(payload) & 0xFFFFFFFF != entry["crc"]:
            log_warning(
                "job snapshot v%d part %s missing or corrupt; trying an "
                "older version", version, entry["name"],
            )
            return None
        state = load_obj(MemoryStream(payload))
        meta = manifest.get("meta") or {}
        return state, meta

    def _read_manifest(self, version: int) -> Optional[Dict[str, Any]]:
        raw = _read_all(self._manifest_uri(version))
        if raw is None or b"\n" not in raw:
            return None
        head, body = raw.split(b"\n", 1)
        try:
            want = int(head, 16)
        except ValueError:
            return None
        if zlib.crc32(body) & 0xFFFFFFFF != want:
            return None
        try:
            return json.loads(body)
        except ValueError:
            return None

    def _read_part_payload(self, version: int, rank: int) -> Optional[bytes]:
        raw = _read_all(self._state_uri(version, rank))
        if raw is None or len(raw) < _PART_TRAILER.size:
            return None
        magic, crc, size = _PART_TRAILER.unpack(raw[-_PART_TRAILER.size:])
        payload = raw[:-_PART_TRAILER.size]
        if magic != PART_MAGIC or size != len(payload) \
                or zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return None
        return payload

    # ---- layout / internals --------------------------------------------
    def _frontier_uri(self, rank: int) -> str:
        return f"{self.uri}/snap.rank{rank}.frontier"

    def _read_frontier(self, rank: int) -> int:
        """Newest version ``rank`` wrote a part for (0 when unknown)."""
        raw = _read_all(self._frontier_uri(rank))
        if raw is None:
            return 0
        try:
            return int(raw.decode().strip() or 0)
        except ValueError:
            return 0

    def _part_name(self, version: int, rank: int) -> str:
        return f"snap_v{version}.rank{rank}"

    def _state_uri(self, version: int, rank: int) -> str:
        return f"{self.uri}/{self._part_name(version, rank)}"

    def _manifest_uri(self, version: int) -> str:
        return f"{self.uri}/snap_v{version}.manifest"

    def _fallback_manager(self) -> Optional["JobSnapshot"]:
        if self._fallback is None and self._fallback_uri is not None:
            self._fallback = JobSnapshot(
                self._fallback_uri, rank=self.rank,
                world_size=self.world_size, keep=self.keep,
                fallback_uri="",  # no fallback chains
                part_timeout_s=self.part_timeout_s,
            )
        return self._fallback

    def _prune(self, newest: int) -> None:
        """Best-effort: retain the newest ``keep`` *committed* versions.

        The committed sequence may have gaps (a superseded commit skips
        a version number), so the retention window counts manifests
        rather than raw version numbers — a raw-number window would thin
        the restorable history whenever the cadence skipped an epoch.
        """
        fs = get_filesystem(URI.parse(self.uri))
        delete = getattr(fs, "delete", None)
        if delete is None:
            return
        floor = max(1, newest - self.keep * 4)
        kept = 0
        for version in range(newest, floor - 1, -1):
            if kept < self.keep:
                if self._read_manifest(version) is not None:
                    kept += 1
                continue
            try:
                delete(URI.parse(self._manifest_uri(version)))
            except Exception:
                pass
            for rank in range(self.world_size):
                try:
                    delete(URI.parse(self._state_uri(version, rank)))
                except Exception:
                    pass
