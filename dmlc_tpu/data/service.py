"""Disaggregated ingest: serve parsed RowBlocks over TCP (tf.data-service
pattern).

The reference has no analog — its parallelism unit is one process reading
its own InputSplit part. On TPU pods the accelerator host is often
compute-bound on ingest (parse contends with dispatch on the same cores),
and the standard fix is disaggregation: dedicated CPU hosts parse, the
accelerator hosts consume finished batches over the network (tf.data
service, arXiv:2210.14826 — PAPERS.md). This module is that shape on this
framework's own primitives:

- :class:`BlockService` wraps any parser (URI or instance) and serves its
  RowBlocks to connected consumers with **dynamic sharding**: blocks are
  handed out in arrival order, so a fast consumer takes more — the
  first-come load balancing the tf.data service paper argues for (static
  part k/n sharding remains available by running one service per part).
- :class:`RemoteBlockParser` is a drop-in :class:`~dmlc_tpu.data.parsers.
  Parser`: ``next_block()`` pulls one RowBlock from a service, so
  ``DeviceFeed(RemoteBlockParser(addr), spec)`` and every learner compose
  unchanged.

Wire format (little-endian, per response): u32 field count (0 = end of
stream), then per field u8 name length + name, u8 dtype-string length +
dtype, u64 byte length + raw array bytes. All RowBlock fields are 1-D.
Requests are a single u32: 1 = NEXT, 2 = CLOSE.

Like the parsers it serves, a service is ONE streaming pass (Parser
semantics, data.h:298: "streaming one-pass"); epochs re-create service and
clients, mirroring create_parser per epoch.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple, Union

import numpy as np

from dmlc_tpu import obs
from dmlc_tpu.data.parsers import Parser, create_parser
from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.utils.logging import DMLCError, check, log_warning

_REQ_NEXT = 1
_REQ_CLOSE = 2

# Response sentinel in the u32 field-count slot: server-side parse failure.
# Followed by u32 message length + utf-8 message; consumers raise DMLCError.
_RESP_ERROR = 0xFFFFFFFF

_BLOCK_FIELDS = ("offset", "label", "index", "value", "weight", "qid",
                 "field")


def _pack_arrays(arrays: Dict[str, np.ndarray]) -> bytes:
    parts = [struct.pack("<I", len(arrays))]
    for name, arr in arrays.items():
        data = np.ascontiguousarray(arr).tobytes()
        dt = arr.dtype.str
        parts.append(struct.pack("<B", len(name)) + name.encode())
        parts.append(struct.pack("<B", len(dt)) + dt.encode())
        parts.append(struct.pack("<Q", len(data)))
        parts.append(data)
    return b"".join(parts)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise DMLCError("block service connection closed mid-frame")
        got += r
    return bytes(buf)


def _send_error(sock: socket.socket, msg: str) -> None:
    data = msg.encode()
    sock.sendall(struct.pack("<II", _RESP_ERROR, len(data)) + data)


def _recv_arrays(sock: socket.socket) -> Optional[Dict[str, np.ndarray]]:
    (nfields,) = struct.unpack("<I", _recv_exact(sock, 4))
    if nfields == _RESP_ERROR:
        (mlen,) = struct.unpack("<I", _recv_exact(sock, 4))
        raise DMLCError(
            "block service parse failed: " + _recv_exact(sock, mlen).decode()
        )
    if nfields == 0:
        return None
    out: Dict[str, np.ndarray] = {}
    for _ in range(nfields):
        (nlen,) = struct.unpack("<B", _recv_exact(sock, 1))
        name = _recv_exact(sock, nlen).decode()
        (dlen,) = struct.unpack("<B", _recv_exact(sock, 1))
        dtype = np.dtype(_recv_exact(sock, dlen).decode())
        (nbytes,) = struct.unpack("<Q", _recv_exact(sock, 8))
        out[name] = np.frombuffer(_recv_exact(sock, nbytes), dtype=dtype)
    return out


class BlockService:
    """Serve one parser's RowBlocks to N consumers, dynamically sharded.

    ``parser_kwargs`` pass through to :func:`create_parser` — notably
    ``nthread`` (parse fan-out; defaults to the ``DMLC_TPU_NTHREAD`` env
    knob), so a URI-constructed service gets the same pipelined chunk
    parsing as a local feed."""

    def __init__(
        self,
        source: Union[str, Parser],
        host: str = "127.0.0.1",
        port: int = 0,
        **parser_kwargs,
    ):
        self._parser = (
            create_parser(source, 0, 1, **parser_kwargs)
            if isinstance(source, str)
            else source
        )
        self._lock = threading.Lock()  # serializes parser pulls (the shard
        # point: one block goes to exactly one consumer)
        self._done = False
        self._drained = threading.Event()  # set when the stream is exhausted
        self._pending: list = []  # blocks pulled but undelivered (their
        # consumer died mid-send); redelivered before the next parser pull
        # so those rows stay in the epoch
        self._error: Optional[DMLCError] = None  # parser failure, relayed to
        # every consumer instead of an opaque mid-frame close
        self._error_msg: Optional[str] = None  # plain one-line form of the
        # same failure for the wire (DMLCError's str embeds a server-side
        # stack trace consumers don't need)
        self._responses_done = 0  # monotonic completed-response counter —
        # wait()'s forward-progress signal (a gauge alone cannot tell
        # "steadily delivering" from "wedged"). Control-flow state, so it
        # stays a plain int (must keep working under DMLC_TPU_METRICS=0);
        # the obs registry carries the telemetry mirror.
        self._bytes_sent = 0  # monotonic payload bytes pushed to sockets —
        # makes an in-flight send to a slow consumer visible as progress
        # (responses_done only ticks at completion). Plain int, same
        # reason as _responses_done.
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        # obs metrics, labeled by bound port (one label set per service)
        svc = str(self.address[1])
        reg = obs.registry()
        self._m_served = reg.counter(
            "dmlc_service_blocks_served_total",
            "blocks handed to a consumer", svc=svc)
        self._m_dropped = reg.counter(
            "dmlc_service_blocks_dropped_total",
            "undelivered blocks at close (rows lost to the epoch)", svc=svc)
        self._m_responses = reg.counter(
            "dmlc_service_responses_total",
            "responses completed (telemetry mirror of the wait() signal)",
            svc=svc)
        self._m_sent = reg.counter(
            "dmlc_service_sent_bytes_total",
            "payload bytes pushed to consumer sockets", svc=svc)
        self._threads: list = []
        self._conns: list = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="block-service"
        )
        self._accept_thread.start()

    # ---- server side ---------------------------------------------------

    @property
    def blocks_served(self) -> int:
        return int(self._m_served.value)

    @property
    def blocks_dropped(self) -> int:
        """Undelivered blocks still pending at close() — rows that never
        reached any consumer."""
        return int(self._m_dropped.value)

    def _next_block_arrays(self) -> Optional[Dict[str, np.ndarray]]:
        with self._lock:
            if self._pending:
                return self._pending.pop(0)
            if self._error is not None:
                raise self._error
            if self._done:
                return None
            try:
                block = self._parser.next_block()
            except Exception as exc:  # parser failure ends the stream for
                # everyone — record it so wait() returns and every consumer
                # sees the real error, not a mid-frame close
                self._done = True
                # first line only: a DMLCError's str already embeds the
                # server-side stack trace, which must not ship on the wire
                detail = str(exc).split("\n\nStack trace:")[0]
                self._error_msg = "%s: %s" % (type(exc).__name__, detail)
                self._error = DMLCError(self._error_msg)
                self._drained.set()
                raise self._error
            if block is None:
                self._done = True
                self._drained.set()
                return None
            self._m_served.inc()
        out = {}
        for name in _BLOCK_FIELDS:
            arr = getattr(block, name)
            if arr is not None:
                out[name] = np.asarray(arr)
        # flow context crosses the wire as one extra named field; clients
        # that predate it simply don't .get() it (the format is
        # name-addressed), so the frame stays wire-compatible
        fid = getattr(block, "flow_id", 0)
        if fid:
            out["flow"] = np.asarray([fid], dtype=np.int64)
        return out

    def _stash_undelivered(self, arrays: Dict[str, np.ndarray]) -> None:
        with self._lock:
            self._pending.append(arrays)

    def _send_response(self, conn: socket.socket, data: bytes) -> None:
        """sendall in ≤1 MiB slices, ticking _bytes_sent — so wait() can
        tell a slow-but-live transfer from a wedged one."""
        view = memoryview(data)
        while view:
            sent = conn.send(view[: 1 << 20])
            with self._lock:
                self._bytes_sent += sent
            self._m_sent.inc(sent)
            view = view[sent:]

    def _serve_conn(self, conn: socket.socket) -> None:
        self._conns.append(conn)
        undelivered: Optional[Dict[str, np.ndarray]] = None
        try:
            while True:
                (req,) = struct.unpack("<I", _recv_exact(conn, 4))
                try:
                    if req == _REQ_CLOSE:
                        return
                    check(
                        req == _REQ_NEXT, "bad block service request %d", req
                    )
                    try:
                        undelivered = self._next_block_arrays()
                    except DMLCError:  # parser failure (stream is over)
                        try:
                            _send_error(conn, self._error_msg or "parse "
                                        "failed")
                        except OSError:
                            pass
                        return
                    flow = (undelivered or {}).get("flow")
                    fid = int(flow[0]) if flow is not None and len(flow) \
                        else 0
                    if fid:
                        # the send slice joins the chunk's arrow chain so a
                        # merged trace shows which rank served which chunk
                        with obs.span("service_send", flow=fid):
                            obs.flow_step(fid, "chunk")
                            self._send_response(
                                conn, _pack_arrays(undelivered))
                    else:
                        self._send_response(
                            conn, _pack_arrays(undelivered or {}))
                    if undelivered is None:
                        return
                    undelivered = None
                finally:
                    with self._lock:
                        self._responses_done += 1
                    self._m_responses.inc()
        except (DMLCError, OSError):
            # consumer went away; requeue any block it never received so the
            # stream stays lossless for the remaining consumers
            if undelivered is not None:
                self._stash_undelivered(undelivered)
            return
        finally:
            conn.close()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until the stream is exhausted and every consumer connection
        has finished.

        The library default (``timeout=None``) is UNBOUNDED: a healthy
        consumer may legitimately go silent between its last block and its
        closing request for however long one train step takes (a jit
        compile can be minutes), and the library must never cut such a
        consumer off — ``RemoteBlockParser`` would see a reset instead of
        clean EOF and a previously-clean job would fail.

        With ``timeout`` set, post-drain delivery gets grace windows of
        ``timeout`` seconds — the serve CLI's bounded-exit mode
        (``--grace``): windows extend as long as there is measurable
        progress — a response completed or a connection finished during the
        window. One full window with NO progress ends the wait, cutting off
        consumers that connected but never issued their final request (they
        would otherwise hold a recv forever) — and, by the same clock, any
        consumer that goes silent for longer than ``timeout`` after the
        drain; size ``timeout`` well above plausible per-step consumer
        work. Any stashed undelivered blocks still unclaimed are counted
        and logged as lost by :meth:`close`."""
        self._drained.wait()
        if timeout is None:
            # Unbounded join; the thread list can grow while we drain
            # (late-connecting consumers), so loop until a full pass finds
            # every thread finished.
            while True:
                for t in list(self._threads):
                    t.join()
                if not any(t.is_alive() for t in list(self._threads)):
                    return
        with self._lock:
            last_done, last_sent = self._responses_done, self._bytes_sent
        last_alive = len([t for t in list(self._threads) if t.is_alive()])
        while True:
            deadline = time.monotonic() + timeout
            for t in list(self._threads):
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            alive = len([t for t in list(self._threads) if t.is_alive()])
            if alive == 0:
                return
            with self._lock:
                done, sent = self._responses_done, self._bytes_sent
            if done > last_done or sent > last_sent or alive < last_alive:
                last_done, last_sent, last_alive = done, sent, alive
                continue  # delivery progressed during the window
            return  # a silent window: only stuck/idle connections remain

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        # closing live connections wakes threads blocked in recv — exit is
        # prompt instead of a join-timeout per idle consumer
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)
        # loss accounting AFTER the joins: a send-wedged thread stashes its
        # block only when the conn close above errors its sendall out.
        # Bounded acquire — a thread wedged INSIDE a parser pull holds the
        # lock, and close() must still reach parser.close() (the one call
        # that can unblock such a reader)
        if self._lock.acquire(timeout=1.0):
            try:
                if self._pending:  # redelivery never happened — those rows
                    # left the epoch; surface the loss, don't exit "clean"
                    self._m_dropped.inc(len(self._pending))
                    rows = sum(len(a["offset"]) - 1 for a in self._pending)
                    log_warning(
                        "block service closing with %d undelivered "
                        "block(s) (%d rows never reached a consumer)",
                        len(self._pending), rows,
                    )
                    self._pending.clear()
            finally:
                self._lock.release()
        self._parser.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RemoteBlockParser:
    """Parser-shaped consumer of a :class:`BlockService`.

    Drop-in for create_parser output: next_block()/iteration/bytes_read/
    close. before_first raises — the service is a one-pass stream (re-create
    service + parser per epoch, exactly like a fresh create_parser).
    """

    def __init__(self, address: Tuple[str, int], timeout: float = 60.0):
        from dmlc_tpu.resilience import RetryPolicy, faultpoint

        def dial():
            faultpoint("service.connect")
            return socket.create_connection(address, timeout=timeout)

        # the service may still be binding when a disaggregated client
        # starts (tools/serve host races the training job): retry the
        # dial under the shared policy instead of failing the first race
        self._sock = RetryPolicy(max_attempts=5, base_s=0.2, cap_s=2.0).call(
            dial, "service.connect", display=f"block service {address}"
        )
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.bytes_read = 0  # Parser API surface; obs mirror below
        self._m_read = obs.registry().counter(
            "dmlc_io_read_bytes_total", "payload bytes ingested by source",
            source="service")
        self._closed = False
        self._ended = False

    def next_block(self) -> Optional[RowBlock]:
        from dmlc_tpu.resilience import faultpoint

        if self._ended:
            return None
        faultpoint("service.next")
        self._sock.sendall(struct.pack("<I", _REQ_NEXT))
        try:
            arrays = _recv_arrays(self._sock)
        except DMLCError:
            # error frame or dead socket: the stream is over — a retried
            # next_block() must not mask the original error with a
            # broken-pipe on the closed connection
            self._ended = True
            raise
        if arrays is None:
            self._ended = True
            return None
        nbytes = sum(a.nbytes for a in arrays.values())
        self.bytes_read += nbytes
        self._m_read.inc(nbytes)
        flow = arrays.pop("flow", None)
        fid = int(flow[0]) if flow is not None and len(flow) else 0
        block = RowBlock(
            offset=arrays["offset"],
            label=arrays["label"],
            index=arrays["index"],
            value=arrays.get("value"),
            weight=arrays.get("weight"),
            qid=arrays.get("qid"),
            field=arrays.get("field"),
        )
        if fid:
            # continue the server's flow on this rank: after the plane
            # merges traces, the arrow crosses from the serving rank's
            # service_send slice into this receive
            block.flow_id = fid
            with obs.span("service_recv", nbytes=nbytes, flow=fid):
                obs.flow_step(fid, "chunk")
        return block

    def __iter__(self):
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block

    def before_first(self) -> None:
        raise DMLCError(
            "RemoteBlockParser is a one-pass stream; re-create the service "
            "and parser per epoch (Parser streaming semantics, data.h:298)"
        )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if not self._ended:
                self._sock.sendall(struct.pack("<I", _REQ_CLOSE))
        except OSError:
            pass
        self._sock.close()


def reshard_split(split, rank: Optional[int] = None,
                  world: Optional[int] = None):
    """Recompute an ``InputSplit``'s partition for a new membership
    generation (docs/robustness.md "Elastic membership").

    After ``collective.reenter_elastic`` reassigns rank/world, each worker
    calls this at its next epoch boundary so the input partitions tile the
    new world exactly once. ``reset_partition`` is a pure function of
    ``(rank, world)`` — it recomputes the same aligned boundaries a static
    launch at that world size would produce, which is what makes a
    shrink-then-regrow run bit-identical to a static run at the same
    world. Defaults read the live collective; returns the split."""
    from dmlc_tpu import collective, obs

    if rank is None:
        rank = collective.rank()
    if world is None:
        world = collective.world_size()
    split.reset_partition(rank, world)
    obs.registry().counter(
        "dmlc_data_reshards_total",
        "input partitions recomputed after a membership change").inc()
    return split
