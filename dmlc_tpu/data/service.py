"""Disaggregated ingest: serve parsed RowBlocks over TCP (tf.data-service
pattern).

The reference has no analog — its parallelism unit is one process reading
its own InputSplit part. On TPU pods the accelerator host is often
compute-bound on ingest (parse contends with dispatch on the same cores),
and the standard fix is disaggregation: dedicated CPU hosts parse, the
accelerator hosts consume finished batches over the network (tf.data
service, arXiv:2210.14826 — PAPERS.md). This module is that shape on this
framework's own primitives:

- :class:`BlockService` wraps any parser (URI or instance) and serves its
  RowBlocks to connected consumers with **dynamic sharding**: blocks are
  handed out in arrival order, so a fast consumer takes more — the
  first-come load balancing the tf.data service paper argues for (static
  part k/n sharding remains available by running one service per part).
- :class:`RemoteBlockParser` is a drop-in :class:`~dmlc_tpu.data.parsers.
  Parser`: ``next_block()`` pulls one RowBlock from a service, so
  ``DeviceFeed(RemoteBlockParser(addr), spec)`` and every learner compose
  unchanged.

Wire format (little-endian, per response): u32 field count (0 = end of
stream), then per field u8 name length + name, u8 dtype-string length +
dtype, u64 byte length + raw array bytes. All RowBlock fields are 1-D.
Requests are a single u32: 1 = NEXT, 2 = CLOSE.

Like the parsers it serves, a service is ONE streaming pass (Parser
semantics, data.h:298: "streaming one-pass"); epochs re-create service and
clients, mirroring create_parser per epoch.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, Optional, Tuple, Union

import numpy as np

from dmlc_tpu.data.parsers import Parser, create_parser
from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.utils.logging import DMLCError, check

_REQ_NEXT = 1
_REQ_CLOSE = 2

_BLOCK_FIELDS = ("offset", "label", "index", "value", "weight", "qid",
                 "field")


def _send_arrays(sock: socket.socket, arrays: Dict[str, np.ndarray]) -> None:
    parts = [struct.pack("<I", len(arrays))]
    for name, arr in arrays.items():
        data = np.ascontiguousarray(arr).tobytes()
        dt = arr.dtype.str
        parts.append(struct.pack("<B", len(name)) + name.encode())
        parts.append(struct.pack("<B", len(dt)) + dt.encode())
        parts.append(struct.pack("<Q", len(data)))
        parts.append(data)
    sock.sendall(b"".join(parts))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise DMLCError("block service connection closed mid-frame")
        got += r
    return bytes(buf)


def _recv_arrays(sock: socket.socket) -> Optional[Dict[str, np.ndarray]]:
    (nfields,) = struct.unpack("<I", _recv_exact(sock, 4))
    if nfields == 0:
        return None
    out: Dict[str, np.ndarray] = {}
    for _ in range(nfields):
        (nlen,) = struct.unpack("<B", _recv_exact(sock, 1))
        name = _recv_exact(sock, nlen).decode()
        (dlen,) = struct.unpack("<B", _recv_exact(sock, 1))
        dtype = np.dtype(_recv_exact(sock, dlen).decode())
        (nbytes,) = struct.unpack("<Q", _recv_exact(sock, 8))
        out[name] = np.frombuffer(_recv_exact(sock, nbytes), dtype=dtype)
    return out


class BlockService:
    """Serve one parser's RowBlocks to N consumers, dynamically sharded."""

    def __init__(
        self,
        source: Union[str, Parser],
        host: str = "127.0.0.1",
        port: int = 0,
        **parser_kwargs,
    ):
        self._parser = (
            create_parser(source, 0, 1, **parser_kwargs)
            if isinstance(source, str)
            else source
        )
        self._lock = threading.Lock()  # serializes parser pulls (the shard
        # point: one block goes to exactly one consumer)
        self._done = False
        self._drained = threading.Event()  # set when the stream is exhausted
        self.blocks_served = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        self._threads: list = []
        self._conns: list = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="block-service"
        )
        self._accept_thread.start()

    # ---- server side ---------------------------------------------------

    def _next_block_arrays(self) -> Optional[Dict[str, np.ndarray]]:
        with self._lock:
            if self._done:
                return None
            block = self._parser.next_block()
            if block is None:
                self._done = True
                self._drained.set()
                return None
            self.blocks_served += 1
        out = {}
        for name in _BLOCK_FIELDS:
            arr = getattr(block, name)
            if arr is not None:
                out[name] = np.asarray(arr)
        return out

    def _serve_conn(self, conn: socket.socket) -> None:
        self._conns.append(conn)
        try:
            while True:
                (req,) = struct.unpack("<I", _recv_exact(conn, 4))
                if req == _REQ_CLOSE:
                    return
                check(req == _REQ_NEXT, "bad block service request %d", req)
                arrays = self._next_block_arrays()
                _send_arrays(conn, arrays or {})
                if arrays is None:
                    return
        except (DMLCError, OSError):
            return  # consumer went away; the stream continues for others
        finally:
            conn.close()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def wait(self) -> None:
        """Block until the stream is exhausted AND every connection that
        consumed it has finished — the CLI server's natural exit point."""
        self._drained.wait()
        for t in list(self._threads):
            t.join()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        # closing live connections wakes threads blocked in recv — exit is
        # prompt instead of a join-timeout per idle consumer
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)
        self._parser.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RemoteBlockParser:
    """Parser-shaped consumer of a :class:`BlockService`.

    Drop-in for create_parser output: next_block()/iteration/bytes_read/
    close. before_first raises — the service is a one-pass stream (re-create
    service + parser per epoch, exactly like a fresh create_parser).
    """

    def __init__(self, address: Tuple[str, int], timeout: float = 60.0):
        self._sock = socket.create_connection(address, timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.bytes_read = 0
        self._closed = False
        self._ended = False

    def next_block(self) -> Optional[RowBlock]:
        if self._ended:
            return None
        self._sock.sendall(struct.pack("<I", _REQ_NEXT))
        arrays = _recv_arrays(self._sock)
        if arrays is None:
            self._ended = True
            return None
        self.bytes_read += sum(a.nbytes for a in arrays.values())
        return RowBlock(
            offset=arrays["offset"],
            label=arrays["label"],
            index=arrays["index"],
            value=arrays.get("value"),
            weight=arrays.get("weight"),
            qid=arrays.get("qid"),
            field=arrays.get("field"),
        )

    def __iter__(self):
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block

    def before_first(self) -> None:
        raise DMLCError(
            "RemoteBlockParser is a one-pass stream; re-create the service "
            "and parser per epoch (Parser streaming semantics, data.h:298)"
        )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if not self._ended:
                self._sock.sendall(struct.pack("<I", _REQ_CLOSE))
        except OSError:
            pass
        self._sock.close()
