"""Disaggregated ingest: serve parsed RowBlocks over TCP (tf.data-service
pattern).

The reference has no analog — its parallelism unit is one process reading
its own InputSplit part. On TPU pods the accelerator host is often
compute-bound on ingest (parse contends with dispatch on the same cores),
and the standard fix is disaggregation: dedicated CPU hosts parse, the
accelerator hosts consume finished batches over the network (tf.data
service, arXiv:2210.14826 — PAPERS.md). This module is that shape on this
framework's own primitives:

- :class:`BlockService` wraps any parser (URI or instance) and serves its
  RowBlocks to connected consumers with **dynamic sharding**: blocks are
  handed out in arrival order, so a fast consumer takes more — the
  first-come load balancing the tf.data service paper argues for (static
  part k/n sharding remains available by running one service per part).
- :class:`RemoteBlockParser` is a drop-in :class:`~dmlc_tpu.data.parsers.
  Parser`: ``next_block()`` pulls one RowBlock from a service, so
  ``DeviceFeed(RemoteBlockParser(addr), spec)`` and every learner compose
  unchanged.

Both ends also speak **dispatcher mode** (data/dispatcher.py), the
fault-tolerant fleet shape: ``BlockService(dispatcher=addr)`` turns the
service into a registered data *worker* that leases chunks from a
:class:`~dmlc_tpu.data.dispatcher.DataDispatcher` and heartbeats it,
while ``RemoteBlockParser(addr, dispatcher=True)`` becomes a failover
client that discovers live workers through the dispatcher, re-dials the
next worker when one dies mid-stream, reports receipt/consumption of
each chunk (the exactly-once protocol), and optionally hedges slow
fetches (``DMLC_TPU_DATA_HEDGE_S``; resilience/hedge.py) against a
second worker. Undelivered-block requeues are bounded by
``DMLC_TPU_DATA_PENDING_CAP`` with backpressure, metered as
``dmlc_service_requeued_total`` (distinct from drops).

Multi-tenant fleet mode stacks on top (docs/distributed.md
"Multi-tenant fleet"): a worker serves EVERY job's chunks from one
process, consulting the job-shared :mod:`~dmlc_tpu.data.source_cache` so
N jobs reading the same dataset parse it once, and
``RemoteBlockParser(addr, dispatcher=True, job="name")`` scopes a
consumer to its job's ledger — its fetches carry the job id, so one
tenant's backlog or death never bleeds into another's stream. A worker
the dispatcher retires for scale-down (data/autoscale.py) ends with a
connection drop, never a clean EOS — its consumers fail over to the
surviving workers exactly as if it had died.

Wire format (little-endian, per response): u32 field count (0 = end of
stream), then per field u8 name length + name, u8 dtype-string length +
dtype, u64 byte length + raw array bytes. All RowBlock fields are 1-D.
Requests are a single u32: 1 = NEXT, 2 = CLOSE, 3 = NEXT_JOB followed
by one u32 job id (scope the pull to that job's ledger). The format is
name-addressed, so the dispatcher-mode extras (``seq``, ``flow``,
``job``) are invisible to legacy clients — they simply never ``.get()``
them.

Like the parsers it serves, a service is ONE streaming pass (Parser
semantics, data.h:298: "streaming one-pass"); epochs re-create service and
clients, mirroring create_parser per epoch.
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from dmlc_tpu import obs
from dmlc_tpu.data.dispatcher import DispatcherClient, dispatcher_address
from dmlc_tpu.obs import audit
from dmlc_tpu.data.parsers import Parser, create_parser
from dmlc_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_tpu.data.source_cache import source_cache
from dmlc_tpu.params.knobs import data_hedge_s, data_pending_cap
from dmlc_tpu.utils.logging import DMLCError, check, log_warning

_REQ_NEXT = 1
_REQ_CLOSE = 2
# NEXT scoped to one tenant job: the u32 request code is followed by a
# u32 job id. A source-mode service treats it as plain NEXT (it has no
# job ledgers), so one client codepath speaks to both service shapes.
_REQ_NEXT_JOB = 3

# Response sentinel in the u32 field-count slot: server-side parse failure.
# Followed by u32 message length + utf-8 message; consumers raise DMLCError.
_RESP_ERROR = 0xFFFFFFFF

_BLOCK_FIELDS = ("offset", "label", "index", "value", "weight", "qid",
                 "field")

# how long a full pending stash waits for a consumer to drain it before
# the block is dropped (module constant so tests can shrink it)
_PENDING_WAIT_S = 1.0


class TruncatedFrame(OSError):
    """A peer hung up mid-frame.

    An OSError (not DMLCError) on purpose: mid-frame closes are TRANSPORT
    failures — the failover client re-dials and retries them, while
    DMLCError stays reserved for fatal in-protocol errors (the server's
    explicit error frame)."""


def _pack_arrays(arrays: Dict[str, np.ndarray]) -> bytes:
    parts = [struct.pack("<I", len(arrays))]
    for name, arr in arrays.items():
        data = np.ascontiguousarray(arr).tobytes()
        dt = arr.dtype.str
        parts.append(struct.pack("<B", len(name)) + name.encode())
        parts.append(struct.pack("<B", len(dt)) + dt.encode())
        parts.append(struct.pack("<Q", len(data)))
        parts.append(data)
    return b"".join(parts)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise TruncatedFrame(
                "block service connection closed mid-frame")
        got += r
    return bytes(buf)


def _send_error(sock: socket.socket, msg: str) -> None:
    data = msg.encode()
    sock.sendall(struct.pack("<II", _RESP_ERROR, len(data)) + data)


def _recv_arrays(sock: socket.socket) -> Optional[Dict[str, np.ndarray]]:
    (nfields,) = struct.unpack("<I", _recv_exact(sock, 4))
    if nfields == _RESP_ERROR:
        (mlen,) = struct.unpack("<I", _recv_exact(sock, 4))
        raise DMLCError(
            "block service parse failed: " + _recv_exact(sock, mlen).decode()
        )
    if nfields == 0:
        return None
    out: Dict[str, np.ndarray] = {}
    for _ in range(nfields):
        (nlen,) = struct.unpack("<B", _recv_exact(sock, 1))
        name = _recv_exact(sock, nlen).decode()
        (dlen,) = struct.unpack("<B", _recv_exact(sock, 1))
        dtype = np.dtype(_recv_exact(sock, dlen).decode())
        (nbytes,) = struct.unpack("<Q", _recv_exact(sock, 8))
        out[name] = np.frombuffer(_recv_exact(sock, nbytes), dtype=dtype)
    return out


class BlockService:
    """Serve RowBlocks to N consumers, dynamically sharded.

    Two sources:

    - ``source=`` (URI or Parser instance): the standalone shape — this
      service owns one whole stream. ``parser_kwargs`` pass through to
      :func:`create_parser` — notably ``nthread`` (parse fan-out;
      defaults to the ``DMLC_TPU_NTHREAD`` env knob).
    - ``dispatcher=`` (host:port or (host, port)): the fleet shape —
      this service is a data *worker* registered with a
      :class:`~dmlc_tpu.data.dispatcher.DataDispatcher`. It heartbeats,
      leases chunk descriptors one at a time, parses each leased chunk
      with :func:`create_parser` (any worker can parse any chunk), and
      serves it as one frame tagged with the chunk's ``seq``. A worker
      that dies simply stops heartbeating — its leases expire and the
      dispatcher reassigns them to surviving workers."""

    def __init__(
        self,
        source: Union[str, Parser, None] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        dispatcher: Union[str, Tuple[str, int], None] = None,
        **parser_kwargs,
    ):
        check(
            (source is None) != (dispatcher is None),
            "BlockService takes exactly one of source= or dispatcher=",
        )
        if dispatcher is not None:
            self._parser: Optional[Parser] = None
            self._parser_kwargs = dict(parser_kwargs)
        else:
            self._parser = (
                create_parser(source, 0, 1, **parser_kwargs)
                if isinstance(source, str)
                else source
            )
            self._parser_kwargs = {}
        self._lock = threading.Lock()  # serializes parser pulls (the shard
        # point: one block goes to exactly one consumer)
        self._cond = threading.Condition(self._lock)  # signaled when the
        # pending stash drains (backpressure for _stash_undelivered)
        self._pending_cap = data_pending_cap()
        self._done = False
        self._crashed = False  # injected worker_crash fired: the worker is
        # simulating sudden death (sockets closed, heartbeats stopped)
        self._drained = threading.Event()  # set when the stream is exhausted
        self._pending: Dict[int, list] = {}  # job id (-1 = legacy/source
        # mode) -> blocks pulled but undelivered (their consumer died
        # mid-send); redelivered before the next parser pull so those rows
        # stay in the epoch — and only to a consumer of the SAME job
        self._done_jids: set = set()  # jobs whose ledger this worker saw
        # EOF for while the fleet as a whole still has work (per-job EOS
        # without ending the worker's stream)
        self._chunks_parsed = 0  # chunks actually parsed by THIS worker
        # (source-cache hits excluded) — the cross-job cache proof reads
        # this: a second job over a cached source must not move it
        self._error: Optional[DMLCError] = None  # parser failure, relayed to
        # every consumer instead of an opaque mid-frame close
        self._error_msg: Optional[str] = None  # plain one-line form of the
        # same failure for the wire (DMLCError's str embeds a server-side
        # stack trace consumers don't need)
        self._responses_done = 0  # monotonic completed-response counter —
        # wait()'s forward-progress signal (a gauge alone cannot tell
        # "steadily delivering" from "wedged"). Control-flow state, so it
        # stays a plain int (must keep working under DMLC_TPU_METRICS=0);
        # the obs registry carries the telemetry mirror.
        self._bytes_sent = 0  # monotonic payload bytes pushed to sockets —
        # makes an in-flight send to a slow consumer visible as progress
        # (responses_done only ticks at completion). Plain int, same
        # reason as _responses_done.
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        # obs metrics, labeled by bound port (one label set per service)
        svc = str(self.address[1])
        reg = obs.registry()
        self._m_served = reg.counter(
            "dmlc_service_blocks_served_total",
            "blocks handed to a consumer", svc=svc)
        self._m_dropped = reg.counter(
            "dmlc_service_blocks_dropped_total",
            "undelivered blocks at close (rows lost to the epoch)", svc=svc)
        self._m_requeued = reg.counter(
            "dmlc_service_requeued_total",
            "undelivered blocks stashed for redelivery (rows kept in the "
            "epoch)", svc=svc)
        self._m_responses = reg.counter(
            "dmlc_service_responses_total",
            "responses completed (telemetry mirror of the wait() signal)",
            svc=svc)
        self._m_unconfirmed = reg.counter(
            "dmlc_service_unconfirmed_total",
            "legacy-mode responses fully sent to a consumer that vanished "
            "before its next request (rows possibly lost: TCP cannot "
            "confirm delivery, and without the dispatcher's ack ledger "
            "redelivery could duplicate)", svc=svc)
        self._m_sent = reg.counter(
            "dmlc_service_sent_bytes_total",
            "payload bytes pushed to consumer sockets", svc=svc)
        self._threads: list = []
        self._conns: list = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="block-service"
        )
        self._accept_thread.start()
        self._dispatch: Optional[DispatcherClient] = None
        self._worker_id = -1
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if dispatcher is not None:
            self._dispatch = DispatcherClient(dispatcher_address(dispatcher))
            reply = self._dispatch.call(
                {"op": "register", "addr": list(self.address)})
            self._worker_id = int(reply.get("worker_id", -1))
            self._hb_s = float(reply.get("heartbeat_s", 1.0))
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name="block-service-hb")
            self._hb_thread.start()

    # ---- server side ---------------------------------------------------

    @property
    def blocks_served(self) -> int:
        return int(self._m_served.value)

    @property
    def blocks_dropped(self) -> int:
        """Undelivered blocks still pending at close() — rows that never
        reached any consumer."""
        return int(self._m_dropped.value)

    @property
    def blocks_requeued(self) -> int:
        """Undelivered blocks stashed for redelivery after their consumer
        died mid-send — distinct from drops (those rows stayed in)."""
        return int(self._m_requeued.value)

    @property
    def blocks_unconfirmed(self) -> int:
        """Legacy-mode responses fully sent to a consumer that vanished
        before issuing another request — delivery unknowable (possible
        row loss); dispatcher mode closes this window with recv/ack."""
        return int(self._m_unconfirmed.value)

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self._hb_s):
            if self._crashed:
                return  # a crashed worker goes silent — that IS the signal
            try:
                self._dispatch.call(
                    {"op": "heartbeat", "worker": self._worker_id})
            except DMLCError:
                return  # dispatcher gone; leases expire on their own

    def _simulate_crash(self) -> None:
        """Injected ``service.worker_crash``: die the way a real worker
        does — stop heartbeating and close every socket abruptly, so
        consumers see mid-frame cuts and the dispatcher sees silence."""
        self._crashed = True
        self._hb_stop.set()
        with self._lock:
            self._done = True
            self._drained.set()
        log_warning(
            "block service %s:%d simulating worker crash (injected fault)",
            self.address[0], self.address[1])
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass

    @property
    def chunks_parsed(self) -> int:
        """Chunks this worker parsed for real (cache hits excluded)."""
        return self._chunks_parsed

    def _parse_chunk_fields(self, chunk: Dict) -> Dict[str, np.ndarray]:
        """The actual parse: one chunk descriptor -> the frame's field
        arrays, WITHOUT the per-lease seq/job/flow tags (this is the
        source-cache entry shape — the tags differ per job and per
        lease, the parsed bytes do not)."""
        self._chunks_parsed += 1
        parser = create_parser(
            chunk["uri"], chunk["part"], chunk["nparts"],
            data_format=chunk.get("format", "auto"), **self._parser_kwargs)
        cont = RowBlockContainer()
        try:
            while True:
                block = parser.next_block()
                if block is None:
                    break
                cont.push_block(block)
        finally:
            parser.close()
        block = cont.to_block()
        out = {}
        for name in _BLOCK_FIELDS:
            arr = getattr(block, name)
            if arr is not None:
                out[name] = np.asarray(arr)
        return out

    def _parse_chunk(self, chunk: Dict) -> Dict[str, np.ndarray]:
        """Parse one leased chunk descriptor into a single response frame
        tagged with its ``seq``/``job`` (and the chunk's flow, so a
        reassigned chunk's trace chain spans every worker that touched
        it). Parses go through the job-shared source cache when it is
        enabled: N jobs leasing the same source part pay one parse, and
        an injected ``cache.populate`` fault degrades to a direct
        uncached parse — the tier costs performance, never rows."""
        from dmlc_tpu.resilience import InjectedFault

        fields = None
        cache = source_cache()
        if cache.enabled:
            key = cache.chunk_key(
                chunk["uri"], chunk["part"], chunk["nparts"],
                chunk.get("format", "auto"), self._parser_kwargs)
            try:
                fields = cache.get_or_populate(
                    key, lambda: self._parse_chunk_fields(chunk))
            except InjectedFault:
                fields = None
        if fields is None:
            fields = self._parse_chunk_fields(chunk)
        out = dict(fields)  # the cached dict is shared across jobs —
        # tag a copy, never the entry itself
        out["seq"] = np.asarray([chunk["seq"]], dtype=np.int64)
        out["job"] = np.asarray(
            [int(chunk.get("job", 0))], dtype=np.int64)
        fid = int(chunk.get("flow") or 0)
        if fid:
            out["flow"] = np.asarray([fid], dtype=np.int64)
        return out

    def _pop_pending_locked(self, jid: int) -> Optional[Dict]:
        """Pop one stashed undelivered frame deliverable to a consumer of
        job ``jid`` (-1 = any job: the unscoped legacy pull). Lock held."""
        if jid >= 0:
            stash = self._pending.get(jid)
            if stash:
                return stash.pop(0)
            return None
        for key in sorted(self._pending):
            if self._pending[key]:
                return self._pending[key].pop(0)
        return None

    def _pending_total_locked(self) -> int:
        return sum(len(v) for v in self._pending.values())

    def _next_chunk_arrays(self, jid: int = -1
                           ) -> Optional[Dict[str, np.ndarray]]:
        """Dispatcher-mode source: lease → parse → serve, one chunk per
        call, scoped to job ``jid`` when >= 0. The dispatcher is the
        shard point here (its lease table assigns each chunk exactly
        once), so the local lock is NOT held across the lease RPC or the
        parse — two consumer connections can parse two leased chunks
        concurrently."""
        from dmlc_tpu.resilience import InjectedFault, faultpoint

        while True:
            with self._lock:
                stashed = self._pop_pending_locked(jid)
                if stashed is not None:
                    self._cond.notify()
                    return stashed
                if self._error is not None:
                    raise self._error
                if self._crashed:
                    raise OSError("block service worker crashed")
                if self._done:
                    return None
                if jid >= 0 and jid in self._done_jids:
                    return None  # this job's ledger already hit EOF
            req = {"op": "lease", "worker": self._worker_id}
            if jid >= 0:
                req["job"] = jid
            try:
                reply = self._dispatch.call(req)
            except DMLCError as err:
                # the dispatcher is unreachable past retries. Without the
                # control plane no further lease can be granted, so the
                # stream is over from this worker's view — end it cleanly
                # for consumers rather than relaying an opaque error (the
                # common benign case is the dispatcher exiting the moment
                # the last ack lands, one consumer pull before EOS).
                log_warning(
                    "block service %s:%d lost its dispatcher, ending "
                    "stream: %s", self.address[0], self.address[1],
                    str(err).split("\n\nStack trace:")[0])
                with self._lock:
                    self._done = True
                    self._drained.set()
                return None
            if reply.get("retire"):
                # scale-down: the dispatcher drained and delisted this
                # worker. End the worker's stream and CUT the consumer
                # off (transport error, not clean EOS) — the fleet still
                # has work, and the consumer must fail over to a
                # surviving worker to find it.
                log_warning(
                    "block service %s:%d retired by the dispatcher "
                    "(scale-down)", self.address[0], self.address[1])
                self._hb_stop.set()
                with self._lock:
                    self._done = True
                    self._drained.set()
                raise OSError("data worker retired (scale-down)")
            if reply.get("dead") or (
                    reply.get("eof") and (jid < 0 or reply.get("all"))):
                # eof with "all": EVERY job's chunks are delivered-or-
                # acked — the worker's stream is over. dead: the
                # dispatcher declared this worker dead while it was
                # merely slow — it must not serve leases the table
                # already reassigned.
                with self._lock:
                    self._done = True
                    self._drained.set()
                return None
            if reply.get("eof"):
                # only THIS job is done; the worker keeps serving the
                # rest of the fleet. EOS for this consumer alone.
                with self._lock:
                    self._done_jids.add(jid)
                return None
            if reply.get("wait") or reply.get("busy"):
                # wait: chunks exist but are leased/delivered elsewhere
                # and may yet requeue. busy: the job's in-flight quota is
                # full — backpressure, not failure. Poll either way
                # (each poll heartbeats too).
                time.sleep(0.05)
                continue
            chunk = reply.get("chunk")
            if chunk is None:
                raise DMLCError(
                    "bad dispatcher lease reply: %r"
                    % (reply.get("error") or reply,))
            try:
                faultpoint("service.worker_crash")
            except InjectedFault as err:
                self._simulate_crash()
                raise OSError(str(err))
            try:
                arrays = self._parse_chunk(chunk)
            except Exception as exc:
                with self._lock:
                    self._done = True
                    detail = str(exc).split("\n\nStack trace:")[0]
                    self._error_msg = "%s: %s" % (type(exc).__name__, detail)
                    self._error = DMLCError(self._error_msg)
                    self._drained.set()
                raise self._error
            self._m_served.inc()
            return arrays

    def _next_block_arrays(self, jid: int = -1
                           ) -> Optional[Dict[str, np.ndarray]]:
        if self._dispatch is not None:
            return self._next_chunk_arrays(jid)
        # source mode has no job ledgers: a job-scoped request behaves
        # exactly like NEXT (the jid was consumed off the wire already)
        with self._lock:
            stashed = self._pop_pending_locked(-1)
            if stashed is not None:
                self._cond.notify()
                return stashed
            if self._error is not None:
                raise self._error
            if self._done:
                return None
            try:
                block = self._parser.next_block()
            except Exception as exc:  # parser failure ends the stream for
                # everyone — record it so wait() returns and every consumer
                # sees the real error, not a mid-frame close
                self._done = True
                # first line only: a DMLCError's str already embeds the
                # server-side stack trace, which must not ship on the wire
                detail = str(exc).split("\n\nStack trace:")[0]
                self._error_msg = "%s: %s" % (type(exc).__name__, detail)
                self._error = DMLCError(self._error_msg)
                self._drained.set()
                raise self._error
            if block is None:
                self._done = True
                self._drained.set()
                return None
            self._m_served.inc()
        out = {}
        for name in _BLOCK_FIELDS:
            arr = getattr(block, name)
            if arr is not None:
                out[name] = np.asarray(arr)
        # flow context crosses the wire as one extra named field; clients
        # that predate it simply don't .get() it (the format is
        # name-addressed), so the frame stays wire-compatible
        fid = getattr(block, "flow_id", 0)
        if fid:
            out["flow"] = np.asarray([fid], dtype=np.int64)
        return out

    def _stash_undelivered(self, arrays: Dict[str, np.ndarray]) -> None:
        """Requeue a block whose consumer died mid-send.

        Bounded (``DMLC_TPU_DATA_PENDING_CAP``): a full stash
        backpressures the stashing connection thread for up to
        ``_PENDING_WAIT_S`` waiting for a surviving consumer to drain it,
        then drops the block (metered as a drop, not a requeue) — a crash
        storm must not buffer the whole dataset in one worker's memory.
        Stashed per job (the frame's ``job`` tag; -1 in source mode) so a
        redelivery can only reach a consumer of the same tenant — the cap
        is fleet-wide across jobs."""
        job = arrays.get("job")
        jid = int(job[0]) if job is not None and len(job) else -1
        with self._cond:
            if self._pending_cap > 0:
                deadline = time.monotonic() + _PENDING_WAIT_S
                while (self._pending_total_locked() >= self._pending_cap
                       and not self._done):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                if self._pending_total_locked() >= self._pending_cap:
                    self._m_dropped.inc()
                    log_warning(
                        "block service pending stash full (cap %d); "
                        "dropping an undelivered block (%d rows)",
                        self._pending_cap, len(arrays["offset"]) - 1)
                    return
            self._pending.setdefault(jid, []).append(arrays)
            self._m_requeued.inc()

    def _send_response(self, conn: socket.socket, data: bytes) -> None:
        """sendall in ≤1 MiB slices, ticking _bytes_sent — so wait() can
        tell a slow-but-live transfer from a wedged one."""
        from dmlc_tpu.resilience import faultpoint

        view = memoryview(data)
        while view:
            # an injected service.send fault (or a real send error) cuts
            # the consumer off MID-frame — the client-side truncated-frame
            # handling is what makes this recoverable
            faultpoint("service.send")
            sent = conn.send(view[: 1 << 20])
            with self._lock:
                self._bytes_sent += sent
            self._m_sent.inc(sent)
            view = view[sent:]

    def _serve_conn(self, conn: socket.socket) -> None:
        self._conns.append(conn)
        undelivered: Optional[Dict[str, np.ndarray]] = None
        unconfirmed = False  # a block frame was FULLY sent and no further
        # request (or close) has arrived to prove the consumer read it
        try:
            while True:
                (req,) = struct.unpack("<I", _recv_exact(conn, 4))
                unconfirmed = False  # another request: the consumer read
                # the previous frame (it asked for more on the same pipe)
                jid = -1
                if req == _REQ_NEXT_JOB:
                    (jid,) = struct.unpack("<I", _recv_exact(conn, 4))
                try:
                    if req == _REQ_CLOSE:
                        return
                    check(
                        req in (_REQ_NEXT, _REQ_NEXT_JOB),
                        "bad block service request %d", req
                    )
                    try:
                        undelivered = self._next_block_arrays(jid)
                    except DMLCError:  # parser failure (stream is over)
                        try:
                            _send_error(conn, self._error_msg or "parse "
                                        "failed")
                        except OSError:
                            pass
                        return
                    flow = (undelivered or {}).get("flow")
                    fid = int(flow[0]) if flow is not None and len(flow) \
                        else 0
                    if fid:
                        # the send slice joins the chunk's arrow chain so a
                        # merged trace shows which rank served which chunk
                        with obs.span("service_send", flow=fid):
                            obs.flow_step(fid, "chunk")
                            self._send_response(
                                conn, _pack_arrays(undelivered))
                    else:
                        self._send_response(
                            conn, _pack_arrays(undelivered or {}))
                    if undelivered is None:
                        return
                    undelivered = None
                    unconfirmed = True
                finally:
                    with self._lock:
                        self._responses_done += 1
                    self._m_responses.inc()
        except (DMLCError, OSError):
            # consumer went away; requeue any block it never received so the
            # stream stays lossless for the remaining consumers
            if undelivered is not None:
                self._stash_undelivered(undelivered)
            elif unconfirmed and self._dispatch is None:
                # the kernel took the whole frame but the consumer
                # vanished before asking for more: TCP cannot say whether
                # those rows landed, and legacy mode has no ack ledger to
                # requeue them safely (redelivery could duplicate) — so
                # the frame is counted possibly-lost, loudly. Dispatcher
                # mode closes this window: its recv/ack accounting
                # requeues any chunk the consumer never reported.
                self._m_unconfirmed.inc()
                log_warning(
                    "block service %s:%d: consumer vanished after a fully "
                    "sent block and before its next request — delivery "
                    "unconfirmed, rows may be lost (dispatcher mode "
                    "tracks and requeues these)",
                    self.address[0], self.address[1])
            return
        finally:
            try:
                self._conns.remove(conn)
            except ValueError:
                pass
            conn.close()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            # prune finished handler threads (failover clients re-dial
            # many times under fault storms; dead entries must not pile
            # up for the life of the epoch). Rebind, don't mutate: wait()
            # and close() iterate snapshots of this list concurrently.
            self._threads = [
                th for th in self._threads if th.is_alive()] + [t]

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until the stream is exhausted and every consumer connection
        has finished.

        The library default (``timeout=None``) is UNBOUNDED: a healthy
        consumer may legitimately go silent between its last block and its
        closing request for however long one train step takes (a jit
        compile can be minutes), and the library must never cut such a
        consumer off — ``RemoteBlockParser`` would see a reset instead of
        clean EOF and a previously-clean job would fail.

        With ``timeout`` set, post-drain delivery gets grace windows of
        ``timeout`` seconds — the serve CLI's bounded-exit mode
        (``--grace``): windows extend as long as there is measurable
        progress — a response completed or a connection finished during the
        window. One full window with NO progress ends the wait, cutting off
        consumers that connected but never issued their final request (they
        would otherwise hold a recv forever) — and, by the same clock, any
        consumer that goes silent for longer than ``timeout`` after the
        drain; size ``timeout`` well above plausible per-step consumer
        work. Any stashed undelivered blocks still unclaimed are counted
        and logged as lost by :meth:`close`."""
        self._drained.wait()
        if timeout is None:
            # Unbounded join; the thread list can grow while we drain
            # (late-connecting consumers), so loop until a full pass finds
            # every thread finished.
            while True:
                for t in list(self._threads):
                    t.join()
                if not any(t.is_alive() for t in list(self._threads)):
                    return
        with self._lock:
            last_done, last_sent = self._responses_done, self._bytes_sent
        last_alive = len([t for t in list(self._threads) if t.is_alive()])
        while True:
            deadline = time.monotonic() + timeout
            for t in list(self._threads):
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            alive = len([t for t in list(self._threads) if t.is_alive()])
            if alive == 0:
                return
            with self._lock:
                done, sent = self._responses_done, self._bytes_sent
            if done > last_done or sent > last_sent or alive < last_alive:
                last_done, last_sent, last_alive = done, sent, alive
                continue  # delivery progressed during the window
            return  # a silent window: only stuck/idle connections remain

    def close(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
        try:
            self._sock.close()
        except OSError:
            pass
        # closing live connections wakes threads blocked in recv — exit is
        # prompt instead of a join-timeout per idle consumer
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)
        # loss accounting AFTER the joins: a send-wedged thread stashes its
        # block only when the conn close above errors its sendall out.
        # Bounded acquire — a thread wedged INSIDE a parser pull holds the
        # lock, and close() must still reach parser.close() (the one call
        # that can unblock such a reader)
        if self._lock.acquire(timeout=1.0):
            try:
                npending = self._pending_total_locked()
                if npending:  # redelivery never happened — those rows
                    # left the epoch; surface the loss, don't exit "clean"
                    self._m_dropped.inc(npending)
                    rows = sum(len(a["offset"]) - 1
                               for stash in self._pending.values()
                               for a in stash)
                    log_warning(
                        "block service closing with %d undelivered "
                        "block(s) (%d rows never reached a consumer)",
                        npending, rows,
                    )
                    self._pending.clear()
                self._cond.notify_all()  # release any backpressured stash
            finally:
                self._lock.release()
        if self._parser is not None:
            self._parser.close()
        if self._dispatch is not None:
            self._dispatch.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RemoteBlockParser:
    """Parser-shaped consumer of a :class:`BlockService`.

    Drop-in for create_parser output: next_block()/iteration/bytes_read/
    close. before_first raises — the service is a one-pass stream (re-create
    service + parser per epoch, exactly like a fresh create_parser).

    Legacy mode (``dispatcher=False``) speaks to one service address.
    Mid-stream transport failures (``OSError``, truncated frames) are
    classified transient and retried through the shared ``RetryPolicy``
    by re-dialing the same address — the service's redelivery stash keeps
    the rows in the epoch. The server's explicit error frame stays FATAL
    (``DMLCError``): a parse failure must surface, not retry.

    Dispatcher mode (``dispatcher=True``, ``address`` = the dispatcher):
    a failover client. ``job=`` names the tenant ledger this consumer
    reads (default: the dispatcher's ``default`` job); every fetch is
    scoped to it, so another tenant's chunks can never land here and
    another tenant's EOF never ends this stream. Registration resumes
    the job's ack frontier: seqs already acked by a previous incarnation
    of this client seed the seen-set, so a crash-restart drops their
    redeliveries instead of double-consuming. Live workers are
    discovered via the dispatcher; a worker death mid-fetch rotates to
    the next live worker. Every
    received chunk is receipt-reported (``recv``) — the dispatcher
    REJECTS duplicates of a chunk someone else already holds, and the
    client silently drops rejected copies (exactly-once). Consumed
    chunks are acked: implicitly (the previous chunk is acked right
    before each new fetch — the ack frontier) or explicitly via
    :meth:`ack` once a consumer (DeviceFeed) takes ownership. Slow
    fetches can be hedged against a second worker
    (``DMLC_TPU_DATA_HEDGE_S`` > 0); the loser's chunk is never
    receipt-reported, so its lease expires and requeues — still
    exactly-once, at the cost of one wasted parse."""

    def __init__(
        self,
        address: Tuple[str, int],
        timeout: float = 60.0,
        dispatcher: bool = False,
        job: Optional[str] = None,
    ):
        from dmlc_tpu.resilience import RetryPolicy, faultpoint

        check(job is None or dispatcher,
              "job= names a dispatcher ledger; it needs dispatcher=True")

        self._timeout = float(timeout)
        self.bytes_read = 0  # Parser API surface; obs mirror below
        self._m_read = obs.registry().counter(
            "dmlc_io_read_bytes_total", "payload bytes ingested by source",
            source="service")
        self._closed = False
        self._ended = False
        self._inflight = False  # a _REQ_NEXT is on the wire (close() must
        # drain its response so the server's send completes cleanly)
        self._explicit_ack = False
        self._unacked: List[int] = []
        # determinism audit (obs/audit.py): remember each accepted
        # chunk's content digest so a requeued redelivery can be checked
        # byte-for-byte against the first delivery before it is dropped.
        # The map exists only when the auditor is live — the off path
        # stays allocation-free.
        self._audit = audit.auditor()
        self._audit_digests: Optional[Dict[int, str]] = (
            {} if self._audit.enabled else None)
        self._m_redelivery = (obs.registry().counter(
            "dmlc_audit_redelivery_checked_total",
            "redelivered chunks digest-checked against first delivery")
            if self._audit.enabled else None)
        self._seen: set = set()  # every seq this client ever accepted —
        # a redelivery of rows we already hold (a lease the dispatcher
        # requeued while our dispatcher session was briefly down) is
        # dropped HERE; the server cannot tell that duplicate apart from
        # an idempotent recv retry, but we can
        if dispatcher:
            self.address = dispatcher_address(address)
            self._dispatch: Optional[DispatcherClient] = DispatcherClient(
                self.address, timeout=timeout)
            req = {"op": "client"}
            if job is not None:
                req["job"] = str(job)
            reply = self._dispatch.call(req)
            if not reply.get("ok", True):
                raise DMLCError(
                    "dispatcher refused client registration: %s"
                    % reply.get("error"))
            self._client_id = int(reply.get("client_id", -1))
            self._jid = int(reply.get("jid", 0))
            # the resumed ack frontier: chunks a previous incarnation of
            # this job's client already settled — drop their redeliveries
            self._seen.update(int(s) for s in reply.get("acked", []))
            self._sock: Optional[socket.socket] = None
            self._worker_pos = 0
            self._hedge_s = data_hedge_s()
            return
        self._dispatch = None
        self._client_id = -1
        self._jid = 0
        self._worker_pos = 0
        self._hedge_s = 0.0
        self.address = (str(address[0]), int(address[1]))

        def dial():
            faultpoint("service.connect")
            return socket.create_connection(address, timeout=timeout)

        # the service may still be binding when a disaggregated client
        # starts (tools/serve host races the training job): retry the
        # dial under the shared policy instead of failing the first race
        self._sock = RetryPolicy(max_attempts=5, base_s=0.2, cap_s=2.0).call(
            dial, "service.connect", display=f"block service {address}"
        )
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    # ---- connection management -----------------------------------------

    def _dial_once(self, addr: Tuple[str, int]) -> socket.socket:
        from dmlc_tpu.resilience import faultpoint

        faultpoint("service.connect")
        sock = socket.create_connection(addr, timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _live_workers(self) -> List[Tuple[str, int]]:
        reply = self._dispatch.call({"op": "workers"})
        return [(str(w[0]), int(w[1])) for w in reply.get("workers", [])]

    def _dial_worker(self) -> socket.socket:
        """Rotate over the dispatcher's live-worker list starting at the
        current position. A worker the dispatcher has not yet declared
        dead may still refuse the dial — skip it; the next heartbeat gap
        will get it delisted."""
        workers = self._live_workers()
        if not workers:
            raise OSError("no live data workers registered")
        for i in range(len(workers)):
            pos = (self._worker_pos + i) % len(workers)
            try:
                sock = self._dial_once(workers[pos])
            except OSError:
                continue
            self._worker_pos = pos
            return sock
        raise OSError(
            "no reachable data worker among %d listed" % len(workers))

    def _ensure_sock(self) -> socket.socket:
        if self._sock is None:
            if self._dispatch is None:
                self._sock = self._dial_once(self.address)
            else:
                self._sock = self._dial_worker()
        return self._sock

    def _drop_sock(self, advance: bool = False) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if advance:
            self._worker_pos += 1  # failover: next fetch tries the NEXT
            # live worker first instead of re-hitting the dead one

    # ---- ack protocol ---------------------------------------------------

    def set_explicit_ack(self) -> None:
        """Switch off implicit acking BEFORE the first fetch: the consumer
        (DeviceFeed) owns the ack frontier and will call :meth:`ack` per
        consumed chunk. Must be called at construction time so early
        prefetched chunks are never implicitly acked."""
        self._explicit_ack = True

    def ack(self, seq: int) -> None:
        """Report chunk ``seq`` consumed (explicit-ack mode)."""
        self._explicit_ack = True
        if self._dispatch is None:
            return
        try:
            self._unacked.remove(int(seq))
        except ValueError:
            pass
        self._dispatch.call(
            {"op": "ack", "client": self._client_id, "job": self._jid,
             "seq": int(seq)})

    def _flush_acks(self) -> None:
        """Implicit ack frontier: everything received before this fetch
        was consumed by the caller (Parser pull semantics — the caller
        asked for the next block, so it is done with the previous)."""
        if self._dispatch is None or self._explicit_ack:
            return
        while self._unacked:
            sid = self._unacked[0]
            self._dispatch.call(
                {"op": "ack", "client": self._client_id, "job": self._jid,
                 "seq": sid})
            self._unacked.pop(0)

    # ---- fetch path ------------------------------------------------------

    def _hedged_fetch(self, workers: List[Tuple[str, int]]) -> Optional[
            Dict[str, np.ndarray]]:
        """Race a second worker after ``DMLC_TPU_DATA_HEDGE_S`` of
        silence. Each attempt dials a FRESH connection to a distinct
        worker; the winner's socket becomes the session socket. The
        loser's chunk (if its fetch completes) is never receipt-reported,
        so its lease expires and the dispatcher requeues it — wasted
        work, never duplicated rows."""
        from dmlc_tpu.resilience import hedged_call

        picks = itertools.count(self._worker_pos)
        socks: List[socket.socket] = []

        def fetch():
            sock = self._dial_once(workers[next(picks) % len(workers)])
            socks.append(sock)
            try:
                sock.sendall(
                    struct.pack("<II", _REQ_NEXT_JOB, self._jid))
                return sock, _recv_arrays(sock)
            except Exception:
                try:
                    sock.close()
                except OSError:
                    pass
                raise

        self._drop_sock()
        self._inflight = True
        try:
            winner, arrays = hedged_call(
                fetch, self._hedge_s, site="service.fetch")
        finally:
            self._inflight = False
        self._sock = winner
        for sock in socks:
            if sock is not winner:
                try:
                    sock.close()
                except OSError:
                    pass
        return arrays

    def _fetch_arrays(self) -> Optional[Dict[str, np.ndarray]]:
        """One framed fetch with transport-failure failover.

        Transient = OSError only (truncated frames, resets, injected
        faults). The server's error frame raises DMLCError and is NOT
        retried — RetryPolicy re-raises fatal errors untouched, so the
        existing error-frame semantics hold."""
        from dmlc_tpu.resilience import RetryPolicy, faultpoint

        def attempt():
            faultpoint("service.next")
            # flush the ack frontier BEFORE requesting more: by the time
            # the stream can end, every prior chunk is acked — which is
            # exactly what lets the dispatcher answer this fetch with EOF
            self._flush_acks()
            if (self._dispatch is not None and self._hedge_s > 0):
                workers = self._live_workers()
                if len(workers) > 1:
                    return self._hedged_fetch(workers)
            sock = self._ensure_sock()
            self._inflight = True
            try:
                if self._dispatch is not None:
                    # job-scoped pull: the worker leases from THIS job's
                    # ledger only, so tenants never cross streams
                    sock.sendall(
                        struct.pack("<II", _REQ_NEXT_JOB, self._jid))
                else:
                    sock.sendall(struct.pack("<I", _REQ_NEXT))
                return _recv_arrays(sock)
            except OSError:
                self._drop_sock(advance=True)
                raise
            finally:
                self._inflight = False

        policy = RetryPolicy(
            max_attempts=5, base_s=0.2, cap_s=2.0,
            classify=lambda err: isinstance(err, OSError))
        try:
            return policy.call(
                attempt, "service.next", display="block service fetch")
        except DMLCError:
            # error frame or retry give-up: the stream is over — a retried
            # next_block() must not mask the original error with a
            # broken-pipe on the closed connection
            self._ended = True
            raise

    @staticmethod
    def _content_digest(arrays: Dict[str, np.ndarray]) -> str:
        """Digest of a delivery's content fields — ``flow`` is excluded
        (the server mints a fresh flow id per send, so it legitimately
        differs between a first delivery and its requeued duplicate)."""
        return audit.digest_arrays(
            {k: v for k, v in arrays.items() if k != "flow"})

    def next_block(self) -> Optional[RowBlock]:
        if self._ended:
            return None
        while True:
            arrays = self._fetch_arrays()
            if arrays is None:
                self._ended = True
                try:
                    self._flush_acks()  # defensive: EOF implies all acked
                except (DMLCError, OSError):
                    pass
                return None
            seq = arrays.pop("seq", None)
            arrays.pop("job", None)  # per-job framing tag; this client
            # only ever pulls its own job, so the value is redundant here
            sid = int(seq[0]) if seq is not None and len(seq) else None
            if self._dispatch is not None and sid is not None:
                reply = self._dispatch.call(
                    {"op": "recv", "client": self._client_id,
                     "job": self._jid, "seq": sid})
                if reply.get("reject") or sid in self._seen:
                    # reject: another client already owns this chunk —
                    # the dispatcher's lease table is the exactly-once
                    # arbiter. seen: WE already hold (or consumed) these
                    # rows from an earlier delivery whose lease was
                    # requeued — the recv above re-marks the table
                    # delivered-to-us (stopping further reserves), and
                    # this duplicate copy is dropped; the original's ack
                    # settles the chunk.
                    if (self._audit_digests is not None
                            and sid in self._audit_digests):
                        # audit: the dropped duplicate must carry the
                        # same rows the first delivery did — a fork here
                        # means the requeue path rewrote content
                        self._m_redelivery.inc()
                        self._audit.check_redelivery(
                            sid, self._audit_digests[sid],
                            self._content_digest(arrays))
                    continue
                self._seen.add(sid)
                self._unacked.append(sid)
                if self._audit_digests is not None:
                    self._audit_digests[sid] = self._content_digest(arrays)
            nbytes = sum(a.nbytes for a in arrays.values())
            self.bytes_read += nbytes
            self._m_read.inc(nbytes)
            flow = arrays.pop("flow", None)
            fid = int(flow[0]) if flow is not None and len(flow) else 0
            block = RowBlock(
                offset=arrays["offset"],
                label=arrays["label"],
                index=arrays["index"],
                value=arrays.get("value"),
                weight=arrays.get("weight"),
                qid=arrays.get("qid"),
                field=arrays.get("field"),
            )
            if sid is not None:
                block.seq_id = sid
            if fid:
                # continue the server's flow on this rank: after the plane
                # merges traces, the arrow crosses from the serving rank's
                # service_send slice into this receive
                block.flow_id = fid
                with obs.span("service_recv", nbytes=nbytes, flow=fid):
                    obs.flow_step(fid, "chunk")
            return block

    def __iter__(self):
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block

    def before_first(self) -> None:
        raise DMLCError(
            "RemoteBlockParser is a one-pass stream; re-create the service "
            "and parser per epoch (Parser streaming semantics, data.h:298)"
        )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        sock = self._sock
        if sock is not None and self._inflight:
            # graceful close handshake: a _REQ_NEXT is on the wire — drain
            # its response so the server's send completes cleanly (no
            # OSError on its side, no spurious requeue of a block this
            # client never wanted). Dispatcher mode: the drained chunk is
            # never receipt-reported, so it requeues by lease expiry.
            try:
                sock.settimeout(min(5.0, self._timeout))
                _recv_arrays(sock)
            except (DMLCError, OSError):
                pass
            self._inflight = False
        try:
            if sock is not None and not self._ended:
                sock.sendall(struct.pack("<I", _REQ_CLOSE))
        except OSError:
            pass
        try:
            self._flush_acks()
        except (DMLCError, OSError):
            pass
        if self._dispatch is not None:
            self._dispatch.close()
        if sock is not None:
            sock.close()


def reshard_split(split, rank: Optional[int] = None,
                  world: Optional[int] = None):
    """Recompute an ``InputSplit``'s partition for a new membership
    generation (docs/robustness.md "Elastic membership").

    After ``collective.reenter_elastic`` reassigns rank/world, each worker
    calls this at its next epoch boundary so the input partitions tile the
    new world exactly once. ``reset_partition`` is a pure function of
    ``(rank, world)`` — it recomputes the same aligned boundaries a static
    launch at that world size would produce, which is what makes a
    shrink-then-regrow run bit-identical to a static run at the same
    world. Defaults read the live collective; returns the split."""
    from dmlc_tpu import collective, obs

    if rank is None:
        rank = collective.rank()
    if world is None:
        world = collective.world_size()
    split.reset_partition(rank, world)
    obs.registry().counter(
        "dmlc_data_reshards_total",
        "input partitions recomputed after a membership change").inc()
    return split
