"""Data layer: CSR RowBlocks, text parsers, row iterators.

Reference capabilities mirrored: include/dmlc/data.h (DataIter, Row/RowBlock
CSR batches, Parser/RowBlockIter factories + registry), src/data/ (libsvm /
libfm / csv parsers with thread-parallel chunk parsing, RowBlockContainer,
Basic/Disk row iterators, ThreadedParser prefetch decorator).

The TPU-new part — device-resident CSR batches — lives in dmlc_tpu.device.
"""

from dmlc_tpu.data.row_block import Row, RowBlock, RowBlockContainer
from dmlc_tpu.data.parsers import (
    Parser,
    LibSVMParser,
    LibFMParser,
    CSVParser,
    ThreadedParser,
    create_parser,
    register_parser,
    PARSER_REGISTRY,
)
from dmlc_tpu.data.pipeline import PipelinedParser
from dmlc_tpu.data.row_iter import (
    RowBlockIter,
    BasicRowIter,
    DiskRowIter,
    create_row_block_iter,
)
from dmlc_tpu.data.dispatcher import (DataBusyError, DataDispatcher,
                                      DispatcherClient, register_job)
from dmlc_tpu.data.service import (BlockService, RemoteBlockParser,
                                   TruncatedFrame, reshard_split)
from dmlc_tpu.data.source_cache import (SourceCache, reset_source_cache,
                                        source_cache)
from dmlc_tpu.data.autoscale import WorkerAutoscaler
from dmlc_tpu.data.rowrec import (
    RecordIORowParser,
    convert_to_recordio,
    decode_row_group,
    encode_row_group,
    write_recordio_rows,
)

__all__ = [
    "Row",
    "RowBlock",
    "RowBlockContainer",
    "Parser",
    "LibSVMParser",
    "LibFMParser",
    "CSVParser",
    "ThreadedParser",
    "PipelinedParser",
    "create_parser",
    "register_parser",
    "PARSER_REGISTRY",
    "RowBlockIter",
    "BasicRowIter",
    "DiskRowIter",
    "create_row_block_iter",
    "RecordIORowParser",
    "convert_to_recordio",
    "decode_row_group",
    "encode_row_group",
    "write_recordio_rows",
    "BlockService",
    "RemoteBlockParser",
    "TruncatedFrame",
    "DataBusyError",
    "DataDispatcher",
    "DispatcherClient",
    "register_job",
    "SourceCache",
    "source_cache",
    "reset_source_cache",
    "WorkerAutoscaler",
    "reshard_split",
]
