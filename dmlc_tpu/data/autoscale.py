"""Backlog-driven worker autoscaling for the dispatcher fleet.

The disaggregated-ingest pitch (tf.data service, arXiv 2210.14826) is
that input workers are FUNGIBLE — any worker can parse any chunk — which
makes the fleet elastically sizable: the dispatcher's backlog (queued
chunks nobody is parsing) is a direct demand signal, and adding or
removing a worker needs no data movement at all. This module is the
controller half of that loop:

- **Scale up** when the queued-chunk backlog exceeds
  ``backlog_per_worker`` per live worker: ``spawn()`` (caller-supplied —
  typically ``lambda: BlockService(dispatcher=disp.address)``) brings up
  workers that register themselves through the ordinary PR 9 machinery;
  from the dispatcher's view they are indistinguishable from hand-
  started ones.
- **Scale down** by DRAINING, never killing: the controller picks the
  live worker with the fewest held leases and calls
  :meth:`~dmlc_tpu.data.dispatcher.DataDispatcher.drain_worker` (the
  ``scale.drain`` chaos site). A draining worker takes no new leases,
  its in-flight leases settle or expire normally, and its next idle
  lease poll is answered ``retire`` — so a scale-down event can never
  lose or duplicate a chunk, and per-job aggregates stay bit-identical
  across the scale event (tests/test_chaos.py proves this). Once the
  dispatcher delists the worker, ``retire(handle)`` (default:
  ``handle.close()``) reclaims the process-side resources.
- **One worker per tick** in either direction: the backlog signal is
  sampled, and reacting gradually keeps an ingest burst from
  oscillating the fleet.

``step()`` is one synchronous evaluation (unit-testable, no thread);
``start()`` runs it every ``DMLC_TPU_DATA_SCALE_INTERVAL_S`` seconds on
a daemon thread. Telemetry: the ``dmlc_dispatch_backlog_count`` gauge
(the signal), the ``dmlc_dispatch_scale_events_total`` counter and
``scale.up`` / ``scale.down`` flight events (the actions — ``scale.down``
is recorded by the dispatcher when the drained worker actually retires).
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, Optional

from dmlc_tpu import obs
from dmlc_tpu.obs.flight import record_event
from dmlc_tpu.params.knobs import data_scale_interval_s
from dmlc_tpu.utils.logging import check, log_warning


class WorkerAutoscaler:
    """Size a dispatcher's worker fleet to its queued-chunk backlog."""

    def __init__(
        self,
        dispatcher,
        spawn: Callable[[], object],
        retire: Optional[Callable[[object], None]] = None,
        min_workers: int = 1,
        max_workers: int = 4,
        backlog_per_worker: int = 4,
        interval_s: Optional[float] = None,
    ):
        check(min_workers >= 0, "min_workers must be >= 0")
        check(max_workers >= max(1, min_workers),
              "max_workers must be >= max(1, min_workers)")
        check(backlog_per_worker >= 1, "backlog_per_worker must be >= 1")
        self.dispatcher = dispatcher
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.backlog_per_worker = int(backlog_per_worker)
        self.interval_s = data_scale_interval_s(interval_s)
        self._spawn = spawn
        self._retire = retire
        # worker id -> spawned handle; only workers THIS controller
        # spawned are retired through retire() — hand-started workers
        # can be drained but their lifecycle belongs to their starter
        self._handles: Dict[int, object] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = obs.registry()
        self._g_backlog = reg.gauge(
            "dmlc_dispatch_backlog_count",
            "queued chunks with no worker parsing them (the autoscaler's "
            "demand signal)")
        self._m_scale = reg.counter(
            "dmlc_dispatch_scale_events_total",
            "autoscaler actions taken (spawns + drains initiated)")

    def step(self) -> Dict[str, int]:
        """One control-loop evaluation: sample backlog, take at most one
        scaling action, reap retired workers. Returns the decision for
        tests/telemetry: ``{"backlog", "live", "want", "spawned",
        "draining"}``."""
        snap = self.dispatcher.snapshot()
        backlog = int(snap["chunks"]["queued"])
        self._g_backlog.set(backlog)
        live = {int(wid): w for wid, w in snap["workers"].items()
                if w.get("live")}
        with self._lock:
            for wid in [w for w in self._handles if w not in live]:
                # the dispatcher delisted it (drain completed, or it
                # died): reclaim the process-side handle
                handle = self._handles.pop(wid)
                try:
                    if self._retire is not None:
                        self._retire(handle)
                    else:
                        handle.close()
                except Exception as err:  # noqa: BLE001 — reap must go on
                    log_warning(
                        "autoscaler: retiring worker %d handle failed: %s",
                        wid, err)
        want = max(self.min_workers,
                   min(self.max_workers,
                       math.ceil(backlog / self.backlog_per_worker)))
        spawned = 0
        draining = len([w for w in live.values() if w.get("draining")])
        nlive = len(live)
        if want > nlive:
            handle = self._spawn()
            wid = int(getattr(handle, "_worker_id", -1))
            with self._lock:
                self._handles[wid] = handle
            record_event("scale.up", worker=wid, backlog=backlog,
                         live=nlive + 1)
            self._m_scale.inc()
            spawned = 1
        elif want < nlive - draining:
            # drain the least-loaded live worker; ties to the highest id
            # (the newest spawn retires first — hand-started seed
            # workers survive the autoscaler's churn longest)
            victim = max(
                (wid for wid, w in live.items() if not w.get("draining")),
                key=lambda wid: (-live[wid].get("leased", 0), wid),
                default=None)
            if victim is not None:
                try:
                    self.dispatcher.drain_worker(victim)
                except OSError as err:
                    # injected scale.drain fault: skip this tick, the
                    # backlog signal re-triggers the drain on the next
                    log_warning(
                        "autoscaler: drain of worker %d failed "
                        "(retrying next tick): %s", victim, err)
                else:
                    self._m_scale.inc()
                    draining += 1
        return {"backlog": backlog, "live": nlive, "want": want,
                "spawned": spawned, "draining": draining}

    def start(self) -> "WorkerAutoscaler":
        check(self._thread is None, "autoscaler already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="data-autoscaler")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception as err:  # noqa: BLE001 — the controller is
                # advisory; a failed tick must not kill the loop
                log_warning("autoscaler tick failed: %s", err)

    def close(self, retire_spawned: bool = False) -> None:
        """Stop the control loop. With ``retire_spawned`` the handles
        this controller spawned are closed too (fleet teardown)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if retire_spawned:
            with self._lock:
                handles = list(self._handles.values())
                self._handles.clear()
            for handle in handles:
                try:
                    handle.close()
                except Exception:  # noqa: BLE001
                    pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
