"""Text parsers: libsvm / libfm / csv chunks → CSR RowBlocks.

Capability parity with src/data/ (parser.h, text_parser.h, libsvm_parser.h,
libfm_parser.h, csv_parser.h, strtonum.h):

- ``Parser``: streaming one-pass DataIter over RowBlocks pulled from an
  InputSplit chunk source (parser.h:24-66); tracks ``bytes_read`` for MB/s
  telemetry (text_parser.h:43)
- chunk parsing is parallelized across worker threads by splitting the chunk
  at line boundaries (text_parser.h:94-134 uses OpenMP; here a thread pool +
  numpy-vectorized token conversion, which is both the Python idiom and what
  the native C++ core in cpp/ does with std::thread)
- ``ThreadedParser``: background-thread prefetch of parsed blocks, queue
  depth 8 (parser.h:70-126), applied by default by the factory
- formats: libsvm ``label[:weight] [qid:n] idx[:val]...`` (libsvm_parser.h:
  36-99 — omitted values mean 1, per-row weights, qid supported), libfm
  ``label field:idx:val`` (libfm_parser.h:35-90), dense csv with
  ``label_column`` (csv_parser.h:63-104, CSVParserParam :22-32)
- parser registry + ``create_parser(uri, part, nparts, format)`` resolving
  "auto" through the ``format=`` URI arg, default libsvm (src/data.cc:62-85)
"""

from __future__ import annotations

import concurrent.futures
import os
from typing import Dict, Iterator, List, Optional

import numpy as np

from dmlc_tpu.data import vparse
from dmlc_tpu.data.row_block import (
    INDEX_DTYPE,
    REAL_DTYPE,
    RowBlock,
    RowBlockContainer,
)
from dmlc_tpu.io.input_split import InputSplit, create_input_split
from dmlc_tpu.io.uri_spec import URISpec
from dmlc_tpu.params.knobs import parse_backend, parse_procs
from dmlc_tpu.params.parameter import Parameter, field
from dmlc_tpu.params.registry import Registry
from dmlc_tpu.utils.logging import DMLCError, check
from dmlc_tpu.utils.threaded_iter import ThreadedIter


class Parser:
    """Streaming parser base: DataIter over RowBlocks (data.h:298-316)."""

    def __init__(self, source: InputSplit, nthread: int = 2):
        self._source = source
        self._nthread = max(1, nthread)
        self._pool = (
            concurrent.futures.ThreadPoolExecutor(max_workers=self._nthread)
            if self._nthread > 1
            else None
        )
        self.bytes_read = 0

    # ---- subclass hook -------------------------------------------------
    def parse_chunk(self, chunk: bytes) -> RowBlockContainer:
        raise NotImplementedError

    # ---- iteration -----------------------------------------------------
    def next_chunk(self) -> Optional[bytes]:
        """Next raw chunk from the source (None at end), accounted in
        ``bytes_read`` — the producer half of ``next_block``, split out so
        the cross-chunk pipeline (data/pipeline.py) can pull chunks
        without parsing them inline."""
        chunk = self._source.next_chunk()
        if chunk is not None:
            self.bytes_read += len(chunk)
        return chunk

    def _split_lines(self, chunk: bytes, nparts: int) -> List[bytes]:
        """Split a chunk at line boundaries into ~equal parts
        (text_parser.h:104-118 / BackFindEndLine :71-77)."""
        if nparts <= 1 or len(chunk) < 4096:
            return [chunk]
        step = len(chunk) // nparts
        bounds = [0]
        for i in range(1, nparts):
            pos = chunk.rfind(b"\n", bounds[-1], i * step)
            pos2 = chunk.rfind(b"\r", bounds[-1], i * step)
            pos = max(pos, pos2)
            bounds.append(pos + 1 if pos > 0 else bounds[-1])
        bounds.append(len(chunk))
        return [chunk[bounds[i] : bounds[i + 1]] for i in range(nparts)]

    def next_block(self) -> Optional[RowBlock]:
        """Parse the next chunk into one RowBlock; None at end of data."""
        while True:
            chunk = self.next_chunk()
            if chunk is None:
                return None
            parts = self._split_lines(chunk, self._nthread)
            if self._pool is not None and len(parts) > 1:
                containers = list(self._pool.map(self.parse_chunk, parts))
            else:
                containers = [self.parse_chunk(p) for p in parts]
            merged = containers[0]
            for extra in containers[1:]:
                if len(extra):
                    merged.push_block(extra.to_block())
            if len(merged):
                return merged.to_block()
            # empty chunk (e.g. all blank lines): keep pulling

    def __iter__(self) -> Iterator[RowBlock]:
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block

    def before_first(self) -> None:
        self._source.before_first()

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self._source.close()


def _tokens_to_floats(tokens: List[bytes]) -> np.ndarray:
    """Vectorized bytes→float64 conversion (the strtonum.h hot loop,
    done as one C-level astype instead of per-token strtof)."""
    if not tokens:
        return np.empty(0, dtype=np.float64)
    return np.asarray(tokens, dtype="S").astype(np.float64)


def _native_libsvm(chunk: bytes) -> Optional[RowBlockContainer]:
    """Native-core libsvm chunk parse (cpp/parse.cc); None → python path."""
    from dmlc_tpu import native

    parsed = native.parse_libsvm_chunk(chunk)
    if parsed is None:
        return None
    out = RowBlockContainer()
    if len(parsed["labels"]) == 0:
        return out
    flags = parsed["flags"]
    out.push_arrays(
        parsed["labels"],
        parsed["counts"],
        parsed["indices"],
        value=parsed["values"] if flags & native.HAS_VALUE else None,
        weight=parsed["weights"] if flags & native.HAS_WEIGHT else None,
        qid=parsed["qids"] if flags & native.HAS_QID else None,
    )
    return out


def _native_libfm(chunk: bytes) -> Optional[RowBlockContainer]:
    from dmlc_tpu import native

    parsed = native.parse_libfm_chunk(chunk)
    if parsed is None:
        return None
    out = RowBlockContainer()
    if len(parsed["labels"]) == 0:
        return out
    out.push_arrays(
        parsed["labels"],
        parsed["counts"],
        parsed["indices"],
        value=parsed["values"],
        field=parsed["fields"],
    )
    return out


class LibSVMParser(Parser):
    """``label[:weight] [qid:n] index[:value]...`` (libsvm_parser.h).

    Chunk parsing routes through ``DMLC_TPU_PARSE_BACKEND``
    (params/knobs.py): native C++ core first under auto/native, then the
    columnar vectorized tokenizer (data/vparse.py), with the scalar line
    loop as the semantic oracle (``backend=scalar`` or vparse's own
    grammar fallback)."""

    def parse_chunk(self, chunk: bytes) -> RowBlockContainer:
        backend = parse_backend()
        if backend in ("auto", "native"):
            native_out = _native_libsvm(chunk)
            if native_out is not None:
                return native_out
        out = RowBlockContainer()
        if backend == "scalar":
            vparse.parse_libsvm_scalar(chunk, out)
        else:
            vparse.parse_libsvm_vector(chunk, out)
        return out

    def _parse_general(self, chunk: bytes, out: RowBlockContainer) -> None:
        """Scalar oracle path (qid, bare indices, mixed weights — the full
        grammar). Kept as a hook for subclasses; delegates to vparse."""
        vparse.parse_libsvm_scalar(chunk, out)


class LibFMParser(Parser):
    """``label field:index:value`` triples (libfm_parser.h:35-90)."""

    def parse_chunk(self, chunk: bytes) -> RowBlockContainer:
        native_out = _native_libfm(chunk)
        if native_out is not None:
            return native_out
        out = RowBlockContainer()
        lines = [ln for ln in chunk.splitlines() if ln.strip()]
        if not lines:
            return out
        flat: List[bytes] = []
        counts = np.empty(len(lines), dtype=np.int64)
        for i, line in enumerate(lines):
            toks = line.replace(b":", b" ").split()
            check(
                (len(toks) - 1) % 3 == 0,
                "invalid libfm line: %s",
                line[:80].decode(errors="replace"),
            )
            counts[i] = (len(toks) - 1) // 3
            flat.extend(toks)
        values = _tokens_to_floats(flat)
        pos = 0
        labels = np.empty(len(lines), dtype=np.float64)
        fld_parts, idx_parts, val_parts = [], [], []
        for i in range(len(lines)):
            nfeat = int(counts[i])
            labels[i] = values[pos]
            triples = values[pos + 1 : pos + 1 + 3 * nfeat].reshape(nfeat, 3)
            fld_parts.append(triples[:, 0])
            idx_parts.append(triples[:, 1])
            val_parts.append(triples[:, 2])
            pos += 1 + 3 * nfeat
        out.push_arrays(
            labels.astype(REAL_DTYPE),
            counts,
            np.concatenate(idx_parts).astype(INDEX_DTYPE)
            if idx_parts
            else np.empty(0, dtype=INDEX_DTYPE),
            value=np.concatenate(val_parts).astype(REAL_DTYPE)
            if val_parts
            else np.empty(0, dtype=REAL_DTYPE),
            field=np.concatenate(fld_parts).astype(INDEX_DTYPE)
            if fld_parts
            else None,
        )
        return out


class CSVParserParam(Parameter):
    """URI args for the csv parser (csv_parser.h:22-32)."""

    format = field(str, "csv", description="File format.")
    label_column = field(
        int, -1, description="Column index that will be put into label."
    )
    weight_column = field(
        int, -1, description="Column index for per-row weights (TPU-new)."
    )


class CSVParser(Parser):
    """Dense CSV → CSR with running column indices (csv_parser.h:63-104)."""

    def __init__(self, source: InputSplit, args: Dict[str, str] = None, nthread: int = 2):
        super().__init__(source, nthread)
        self.param = CSVParserParam()
        self.param.init(args or {}, allow_unknown=True)
        check(self.param.format == "csv", "CSVParser requires format=csv")

    def parse_chunk(self, chunk: bytes) -> RowBlockContainer:
        out = RowBlockContainer()
        backend = parse_backend()
        if backend in ("auto", "native"):
            from dmlc_tpu import native

            table = native.parse_csv_chunk(chunk)
            if table is not None:
                if len(table) == 0:
                    return out
                return self._table_to_block(table, out)
        # vparse cell spans come straight from comma/newline offset arrays
        # (no b",".join re-join); the scalar table is the semantic oracle
        if backend == "scalar":
            table = vparse.parse_csv_scalar_table(chunk)
        else:
            table = vparse.parse_csv_vector_table(chunk)
        if table.shape[0] == 0:
            return out
        return self._table_to_block(table, out)

    def _table_to_block(
        self, table: np.ndarray, out: RowBlockContainer
    ) -> RowBlockContainer:
        return _csv_table_to_block(
            table, self.param.label_column, self.param.weight_column, out
        )


def _csv_table_to_block(
    table: np.ndarray,
    label_col: int,
    weight_col: int,
    out: RowBlockContainer,
) -> RowBlockContainer:
    """Split label/weight columns out of a dense table → CSR block."""
    nrows, ncols = table.shape
    keep = np.ones(ncols, dtype=bool)
    labels = np.zeros(nrows, dtype=REAL_DTYPE)
    weight = None
    if 0 <= label_col < ncols:
        labels = table[:, label_col].astype(REAL_DTYPE)
        keep[label_col] = False
    if 0 <= weight_col < ncols:
        weight = table[:, weight_col].astype(REAL_DTYPE)
        keep[weight_col] = False
    data = table[:, keep]
    nfeat = data.shape[1]
    counts = np.full(nrows, nfeat, dtype=np.int64)
    index = np.tile(np.arange(nfeat, dtype=INDEX_DTYPE), nrows)
    out.push_arrays(
        labels,
        counts,
        index,
        value=np.ascontiguousarray(data).reshape(-1).astype(REAL_DTYPE),
        weight=weight,
    )
    return out


def _feed_pipeline(pipe, reader, error_holder: list) -> None:
    """Remote-ingest feeder thread: in-order readahead buffers → native
    push ABI. ``push`` blocks for backpressure; a *fetch* failure is
    recorded in ``error_holder`` and aborts the pipeline so a consumer
    blocked in next_block wakes with an error instead of hanging. A push
    failure means the pipeline itself already failed (parse error, close)
    — nothing is recorded, the consumer sees the pipeline's own error.

    Module-level on purpose: the thread must hold no reference to the
    parser object so an abandoned parser can still be collected.
    """
    from dmlc_tpu.utils.logging import DMLCError as _DMLCError

    try:
        if getattr(reader, "prefers_direct_feed", False) and hasattr(
            pipe, "push_reserve"
        ):
            from dmlc_tpu.io.readahead import PushRejected

            # single connection: stream each range straight into native
            # push memory (readinto), no per-range Python buffers. Fetch
            # errors fall through to the abort path below; a rejected
            # push means the pipeline already failed — record nothing so
            # its own error wins at the consumer (same contract as the
            # pipe.push loop).
            try:
                reader.feed_into(pipe)
            except PushRejected:
                return
        else:
            for buf in reader:
                try:
                    pipe.push(buf)
                except _DMLCError:
                    return
        try:
            pipe.push_eof()
        except _DMLCError:
            return
    except BaseException as err:  # noqa: BLE001 — must reach the consumer
        error_holder.append(err)
        try:
            pipe.push_abort()
        except Exception:
            pass


class NativePipelineParser:
    """All-native ingest: cpp/pipeline.cc reader + parse workers.

    Drop-in for ``ThreadedParser(LibSVM/LibFM/CSVParser(...))`` when the
    native library is loaded: record-boundary chunking, threaded parse, and
    the ordered prefetch queue all run in C++ with no Python in the parse
    loop — Python only wraps the finished CSR arrays. Same exactly-once
    partition semantics as ``create_input_split``
    (input_split_base.cc:30-64).

    Two byte sources feed the same native machinery:

    - local files: the C++ reader thread (``ingest_open``);
    - any registered remote filesystem (``gs://``, ``s3://``, ``hdfs://``,
      ...): parallel range-GET readahead (io/readahead.py) on Python
      threads pushing the partition stream through ``ingest_push`` — the
      multi-connection generalization of the reference's native S3 reader
      (s3_filesys.cc:219-445).
    """

    def __init__(
        self,
        paths: List[str],
        sizes: List[int],
        data_format: str,
        part_index: int,
        num_parts: int,
        nthread: int = 2,
        args: Optional[Dict[str, str]] = None,
        remote_fs=None,
        remote_uris=None,
        shuffle_seed: int = -1,
    ):
        from dmlc_tpu import native

        self._fmt_name = data_format
        self._fmt = {
            "libsvm": native.INGEST_LIBSVM,
            "libfm": native.INGEST_LIBFM,
            "csv": native.INGEST_CSV,
            "recordio": native.INGEST_RECORDIO,
        }[data_format]
        self._open_args = (paths, sizes, part_index, num_parts, nthread)
        self._shuffle_seed = shuffle_seed
        self._epoch = 0  # advances the shuffle permutation per epoch
        self._remote_fs = remote_fs
        self._remote_uris = remote_uris
        self._csv_param = None
        if data_format == "csv":
            self._csv_param = CSVParserParam()
            self._csv_param.init(args or {}, allow_unknown=True)
        self._pipe = None
        self._feeder = None
        self._reader = None
        self._feed_error_holder: list = []
        self._bytes_read_done = 0
        self._open()

    def _open(self) -> None:
        import os
        import threading

        from dmlc_tpu import native

        paths, sizes, part, nparts, nthread = self._open_args
        if self._remote_fs is None:
            if self._shuffle_seed >= 0:
                # shuffle granularity is the chunk: 1 MB chunks give a
                # ~100MB file >=100 visit-order permutation slots (the
                # reference's InputSplitShuffle uses 16 sub-splits per
                # part) at a small throughput cost vs 8 MB chunks.
                # seed+epoch: each before_first() visits a FRESH
                # permutation, regenerated like the reference's per-epoch
                # reshuffle (indexed_recordio_split.cc BeforeFirst) yet
                # replayable from the base seed
                self._pipe = native.IngestPipeline(
                    paths, sizes, self._fmt, part, nparts,
                    nthread=nthread, chunk_bytes=1 << 20,
                    shuffle_seed=_mix_epoch_seed(
                        self._shuffle_seed, self._epoch),
                )
            else:
                self._pipe = native.IngestPipeline(
                    paths, sizes, self._fmt, part, nparts, nthread=nthread
                )
            return
        from dmlc_tpu.io.readahead import (
            DEFAULT_CONNECTIONS,
            DEFAULT_RANGE_BYTES,
            RemotePartitionReader,
        )

        reader = RemotePartitionReader(
            self._remote_fs,
            list(zip(self._remote_uris, sizes)),
            part,
            nparts,
            range_bytes=int(
                os.environ.get(
                    "DMLC_TPU_READAHEAD_MB", DEFAULT_RANGE_BYTES >> 20
                )
            ) << 20,
            connections=int(
                os.environ.get("DMLC_TPU_READAHEAD_CONNS", DEFAULT_CONNECTIONS)
            ),
            record_format=(
                "recordio" if self._fmt_name == "recordio" else "text"
            ),
        )
        self._pipe = native.IngestPipeline(
            None, None, self._fmt, 0, 1, nthread=nthread, push=True
        )
        # the feeder must hold no reference to this parser (or __del__
        # could never run and an abandoned parser would leak the thread
        # and the native pipeline); errors travel through a shared holder
        self._feed_error_holder: list = []
        self._reader = reader
        self._feeder = threading.Thread(
            target=_feed_pipeline,
            args=(self._pipe, reader, self._feed_error_holder),
            name="remote-ingest-feeder", daemon=True,
        )
        self._feeder.start()

    @property
    def _feed_error(self) -> Optional[BaseException]:
        return self._feed_error_holder[0] if self._feed_error_holder else None

    @property
    def bytes_read(self) -> int:
        return self._bytes_read_done + (
            self._pipe.bytes_read if self._pipe is not None else 0
        )

    def next_block(self) -> Optional[RowBlock]:
        from dmlc_tpu import native

        while True:
            try:
                parsed = self._pipe.next_block()
            except DMLCError:
                if self._feed_error is not None:
                    raise DMLCError(
                        f"remote ingest feeder failed: {self._feed_error}"
                    ) from self._feed_error
                raise
            if parsed is None:
                return None
            if self._fmt == native.INGEST_CSV:
                table = parsed["table"]
                if table.shape[0] == 0:
                    continue
                out = RowBlockContainer()
                _csv_table_to_block(
                    table,
                    self._csv_param.label_column,
                    self._csv_param.weight_column,
                    out,
                )
                return out.to_block()
            if len(parsed["labels"]) == 0:
                continue
            flags = parsed.get("flags", 0)
            has_value = self._fmt == native.INGEST_LIBFM or (
                flags & native.HAS_VALUE
            )
            return RowBlock(
                offset=parsed["offsets"],
                label=parsed["labels"],
                index=parsed["indices"],
                value=parsed["values"] if has_value else None,
                weight=parsed.get("weights"),
                qid=parsed.get("qids"),
                field=parsed.get("fields"),
            )

    def __iter__(self) -> Iterator[RowBlock]:
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block

    # ---- native fixed-shape batch path (the TPU feed fast path) -------
    # Re-batching to [batch_size] rows and densify/COO-pad run in C++
    # (pipeline.cc StageBatch/FetchBatch*), so the per-batch Python work is
    # one ctypes call + device_put. libsvm/libfm only (csv densifies via
    # its table layout already).

    @property
    def supports_batch_fetch(self) -> bool:
        from dmlc_tpu import native

        return self._fmt in (
            native.INGEST_LIBSVM, native.INGEST_LIBFM,
            native.INGEST_RECORDIO,
        )

    def _stage(self, batch_size: int):
        try:
            return self._pipe.stage_batch(batch_size)
        except DMLCError:
            if self._feed_error is not None:
                raise DMLCError(
                    f"remote ingest feeder failed: {self._feed_error}"
                ) from self._feed_error
            raise

    def read_batch_dense(self, batch_size: int, num_features: int):
        """→ (x [batch,F] f32, labels, weights, valid_rows) or None at end
        of stream. Short final batch is zero-padded (weight 0 rows)."""
        if self._stage(batch_size) is None:
            return None
        return self._pipe.fetch_batch_dense(batch_size, num_features)

    def read_batch_coo(
        self, batch_size: int, nnz_bucket=None, nnz_floor: int = 256
    ):
        """→ DeviceCSRBatch or None at end of stream. The nnz bucket is
        fixed when given, else device/csr.round_up_bucket's
        sixteenth-octave policy."""
        from dmlc_tpu.device.csr import DeviceCSRBatch, round_up_bucket

        staged = self._stage(batch_size)
        if staged is None:
            return None
        _rows, nnz = staged
        bucket = (
            nnz_bucket if nnz_bucket is not None
            else round_up_bucket(nnz, nnz_floor)
        )
        labels, weights, indices, values, row_ids, offsets, rows = (
            self._pipe.fetch_batch_coo(batch_size, bucket)
        )
        return DeviceCSRBatch(
            labels=labels, weights=weights, indices=indices, values=values,
            row_ids=row_ids, offsets=offsets, num_rows=rows, num_nonzero=nnz,
        )

    def read_batch_coo_sharded(
        self,
        batch_size: int,
        num_shards: int,
        nnz_bucket=None,
        nnz_floor: int = 256,
    ):
        """→ ShardedCSRBatch (per-shard entry sections, local row ids) or
        None at end of stream. Bucket = round_up_bucket (sixteenth-octave
        steps) over the max shard nnz unless fixed."""
        from dmlc_tpu.device.csr import ShardedCSRBatch, round_up_bucket

        staged = self._stage(batch_size)
        if staged is None:
            return None
        _rows, nnz = staged
        bucket = (
            nnz_bucket if nnz_bucket is not None
            else round_up_bucket(
                self._pipe.staged_max_shard_nnz(batch_size, num_shards),
                nnz_floor,
            )
        )
        labels, weights, indices, values, row_ids, offsets, rows = (
            self._pipe.fetch_batch_coo_sharded(batch_size, num_shards, bucket)
        )
        return ShardedCSRBatch(
            labels=labels, weights=weights, indices=indices, values=values,
            row_ids=row_ids, offsets=offsets, num_rows=rows, num_nonzero=nnz,
            num_shards=num_shards, nnz_bucket=bucket,
        )

    def stats(self) -> Optional[dict]:
        """Per-stage pipeline counters (ns), or None when closed."""
        return self._pipe.stats() if self._pipe is not None else None

    def _teardown(self) -> None:
        if self._pipe is None:
            return
        if self._feeder is not None:
            # abort first: a feeder blocked in push() wakes with an error,
            # and cancelled fetch retries stop at their next checkpoint —
            # both before the native handle is freed
            self._reader.cancel()
            self._pipe.push_abort()
            self._feeder.join()
            self._feeder = None
            self._reader = None
        self._bytes_read_done += self._pipe.bytes_read
        self._pipe.close()
        self._pipe = None

    def before_first(self) -> None:
        self._teardown()
        self._epoch += 1
        self._open()

    def close(self) -> None:
        self._teardown()

    def __del__(self):
        # ordering matters: the feeder must be joined before the native
        # handle is freed (a feeder blocked in push() touches it)
        try:
            self._teardown()
        except Exception:
            pass


def _try_native_cached(
    spec: URISpec,
    data_format: str,
    part_index: int,
    num_parts: int,
    nthread: int,
) -> Optional["NativePipelineParser"]:
    """``#cachefile`` on a local libsvm uri, the TPU-native way.

    DiskRowIter's build-then-stream contract
    (/root/reference/src/data/disk_row_iter.h:95-141: BuildCache spills
    parsed pages, TryLoadCache streams them back per epoch) with the
    cache in the binary row-group format (data/rowrec.py): the first
    parser instance parses its text part through the native pipeline and
    spills row groups; every later epoch — and every later parser
    instance over the same uri — ingests the cache with the scan-free
    recordio path (~5-9x the text parse on this host class). The cache
    carries a sidecar meta with the source signature so a changed source
    rebuilds instead of silently serving stale rows (the reference
    reuses blindly; cheap to do better). Scope: libsvm only — libfm
    carries fields the row-group layout omits, csv has a table layout,
    recordio is already binary.
    """
    if data_format != "libsvm":
        return None
    files = _native_local_files(spec)
    if files is None:
        return None
    import json as _json

    # a DISTINCT path from the user's #cachefile name: the Python stack's
    # CachedInputSplit/DiskRowIter use that exact path in incompatible
    # formats and reuse whatever exists — a later fallback run (native
    # lib unavailable) must find ITS cache absent, not misparse
    # row-group binary as framed text chunks
    cache = spec.cache_file + ".rowrec"
    meta_path = cache + ".meta"
    import uuid

    # unique per BUILDER (pid alone shares a name across threads of one
    # process): concurrent builders must not interleave writes into one
    # shared tmp; last atomic replace wins
    tmp_tag = ".tmp.%d.%s" % (os.getpid(), uuid.uuid4().hex[:8])
    try:
        sig = {
            "format": "rowrec-v1",
            "src_bytes": int(sum(info.size for info in files)),
            # ns-resolution mtime: a same-length in-place rewrite within
            # the same second must still invalidate
            "src_mtime_ns": max(
                os.stat(info.path.name).st_mtime_ns for info in files
            ),
            "part": part_index,
            "num_parts": num_parts,
        }
        valid = False
        if os.path.exists(cache) and os.path.exists(meta_path):
            try:
                with open(meta_path) as fh:
                    valid = _json.load(fh) == sig
            except (OSError, ValueError):
                valid = False
        if not valid:
            from dmlc_tpu.data.rowrec import RowGroupWriter
            from dmlc_tpu.io.filesystem import create_stream

            base = NativePipelineParser(
                [info.path.name for info in files],
                [info.size for info in files],
                "libsvm", part_index, num_parts,
                nthread=nthread, args=spec.args,
            )
            try:
                with create_stream(cache + tmp_tag, "w") as out:
                    writer = RowGroupWriter(out, rows_per_group=4096)
                    for block in base:
                        writer.write_block(block)
            finally:
                base.close()
            os.replace(cache + tmp_tag, cache)
            with open(meta_path + tmp_tag, "w") as fh:
                _json.dump(sig, fh)
            os.replace(meta_path + tmp_tag, meta_path)
        # the cache holds exactly THIS part's rows: serve it whole
        # (shuffle_chunks applies to the cached epochs as well — the
        # cache is one local file, the mmap reader's best case)
        return NativePipelineParser(
            [cache], [os.path.getsize(cache)], "recordio", 0, 1,
            nthread=nthread, args=spec.args,
            shuffle_seed=_shuffle_seed_arg(spec),
        )
    except Exception:
        for tmp in (cache + tmp_tag, meta_path + tmp_tag):
            try:
                os.remove(tmp)
            except OSError:
                pass
        return None


def _native_local_files(spec: URISpec):
    """Listable, all-local split files when the native lib is usable, else
    None — the shared precondition of every native routing decision."""
    from dmlc_tpu import native

    if not native.available():
        return None
    from dmlc_tpu.io.filesystem import list_split_files

    try:
        files = list_split_files(spec.uri)
    except Exception:
        return None
    if not files or not all(
        info.path.protocol in ("file://", "") for info in files
    ):
        return None
    return files


def _mix_epoch_seed(seed: int, epoch: int) -> int:
    """(base seed, epoch) → decorrelated per-epoch seed (splitmix64
    finalizer, masked non-negative int64). Plain ``seed + epoch`` would
    make adjacent base seeds share permutation sequences offset by one
    epoch — correlated "independent" runs."""
    mask = (1 << 64) - 1
    x = (seed * 0x9E3779B97F4A7C15 + epoch + 1) & mask
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & mask
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & mask
    x ^= x >> 31
    return x & ((1 << 62) - 1)


def _shuffle_seed_arg(spec: URISpec) -> int:
    """``?shuffle_chunks=SEED`` URI arg → seed int, or -1 when absent.
    The native mmap reader visits the part's chunks in seeded random
    order (input_split_shuffle.h semantics at chunk granularity); the
    Python stack maps the same request onto InputSplitShuffle. Both
    backends regenerate the permutation each epoch (``before_first``
    advances it, like the reference's per-epoch reshuffle), and the whole
    epoch sequence is replayable from the one base seed: a fresh parser
    over the same uri repeats epoch 0, its first ``before_first`` repeats
    epoch 1, and so on."""
    raw = spec.args.get("shuffle_chunks")
    if raw is None:
        return -1
    try:
        seed = int(raw)
    except ValueError:
        raise DMLCError(
            f"shuffle_chunks must be an integer seed, got {raw!r}"
        ) from None
    check(seed >= 0, "shuffle_chunks seed must be >= 0, got %d", seed)
    return seed


def _try_native_pipeline(
    spec: URISpec,
    data_format: str,
    part_index: int,
    num_parts: int,
    nthread: int,
) -> Optional[NativePipelineParser]:
    """Route to the all-native pipeline when the dataset allows it.

    Local files take the C++ reader; any single remote filesystem takes
    the parallel-readahead push path. Mixed/unlistable datasets fall back
    to the Python InputSplit stack.
    """
    if data_format not in ("libsvm", "libfm", "csv", "recordio"):
        return None
    if spec.cache_file:
        return _try_native_cached(
            spec, data_format, part_index, num_parts, nthread
        )
    from dmlc_tpu import native

    if not native.available():
        return None
    from dmlc_tpu.io.filesystem import get_filesystem, list_split_files

    try:
        files = list_split_files(spec.uri)
    except Exception:
        return None
    if not files:
        return None
    local = all(info.path.protocol in ("file://", "") for info in files)
    sizes = [info.size for info in files]
    shuffle_seed = _shuffle_seed_arg(spec)
    try:
        if local:
            return NativePipelineParser(
                [info.path.name for info in files], sizes,
                data_format, part_index, num_parts,
                nthread=nthread, args=spec.args,
                shuffle_seed=shuffle_seed,
            )
        if shuffle_seed >= 0:
            return None  # remote push path streams sequentially; the
            # Python stack's InputSplitShuffle takes the request
        # one remote filesystem for the whole dataset
        keys = {(info.path.protocol, info.path.host) for info in files}
        if len(keys) != 1 or any(s <= 0 for s in sizes):
            return None
        fs = get_filesystem(files[0].path)
        return NativePipelineParser(
            [], sizes, data_format, part_index, num_parts,
            nthread=nthread, args=spec.args,
            remote_fs=fs, remote_uris=[info.path for info in files],
        )
    except Exception:
        return None


class ThreadedParser:
    """Background-thread parse prefetch, depth 8 (parser.h:70-126)."""

    def __init__(self, base: Parser, max_capacity: int = 8):
        self._base = base
        self._iter = ThreadedIter(
            self._produce, max_capacity=max_capacity, name="threaded-parser"
        )

    def _produce(self) -> Iterator[RowBlock]:
        while True:
            block = self._base.next_block()
            if block is None:
                return
            yield block

    @property
    def bytes_read(self) -> int:
        return self._base.bytes_read

    def next_block(self) -> Optional[RowBlock]:
        return self._iter.next()

    def __iter__(self) -> Iterator[RowBlock]:
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block

    def before_first(self) -> None:
        self._iter.close()
        self._base.before_first()
        self._iter.before_first()

    def close(self) -> None:
        self._iter.close()
        self._base.close()


# ---------------------------------------------------------------------------
# Registry + factory (src/data.cc:62-85,150-158; data.h:317-350)
# ---------------------------------------------------------------------------

PARSER_REGISTRY: Registry = Registry.get("parser")


def register_parser(name: str, factory=None):
    """DMLC_REGISTER_DATA_PARSER equivalent; factory(source, args, nthread)."""
    return PARSER_REGISTRY.register(name, factory) if factory else PARSER_REGISTRY.register(name)


def _make_recordio_parser(source, args, nthread):
    from dmlc_tpu.data.rowrec import RecordIORowParser

    return RecordIORowParser(source, args, nthread)


register_parser("libsvm", lambda source, args, nthread: LibSVMParser(source, nthread))
register_parser("libfm", lambda source, args, nthread: LibFMParser(source, nthread))
register_parser("csv", lambda source, args, nthread: CSVParser(source, args, nthread))
register_parser("recordio", _make_recordio_parser)

# InputSplit record type per format ("text" unless registered here): the
# recordio parser consumes whole framed records, not lines
_SPLIT_TYPE = {"recordio": "recordio"}


def create_parser(
    uri: str,
    part_index: int = 0,
    num_parts: int = 1,
    data_format: str = "auto",
    nthread: Optional[int] = None,
    threaded: bool = True,
) -> Parser:
    """Parser<I>::Create (src/data.cc:62-85,132-138).

    "auto" resolves through the ``format=`` URI arg, defaulting to libsvm.
    ``nthread=None`` resolves through the ``DMLC_TPU_NTHREAD`` knob
    (params/knobs.py; default 2). Threaded text parsers take the
    cross-chunk pipeline (data/pipeline.PipelinedParser: N parse workers
    + bounded ordered queue) when the native C++ pipeline declines;
    non-chunk parsers (registry plugins) keep the ThreadedParser block
    prefetch.
    """
    from dmlc_tpu.params.knobs import default_nthread

    nthread = default_nthread(nthread)
    spec = URISpec(uri, part_index, num_parts)
    if data_format == "auto":
        data_format = spec.args.get("format")
        if data_format is None:
            from dmlc_tpu.io.shard import is_shard_uri

            data_format = "shard" if is_shard_uri(spec.uri) else "libsvm"
    if data_format == "shard":
        # baked columnar shards (io/shard.py): pre-tokenized, so there is
        # no parse stage to fan out — the ShardParser decodes windows as
        # frombuffer slices and owns its audit/flow wiring (including the
        # shard signature, which it salts per epoch when shuffle is
        # armed), so DMLC_TPU_AUDIT gets native digest points here and
        # never forces a text re-parse of baked input
        from dmlc_tpu.io.shard import ShardParser

        base = ShardParser(
            spec.uri, part_index, num_parts, args=spec.args, nthread=nthread
        )
        return ThreadedParser(base) if threaded else base
    entry = PARSER_REGISTRY.find(data_format)
    if entry is None:
        raise DMLCError(
            f"unknown data format {data_format!r}; known: "
            f"{PARSER_REGISTRY.list_all_names()}"
        )
    # stamp the determinism auditor's shard signature so digest chains
    # only compare across runs/ranks reading the same (uri, part) slice
    # (obs/audit.py; no-op child when DMLC_TPU_AUDIT is off)
    from dmlc_tpu.obs import audit

    audit.auditor().set_shard(uri, part_index, num_parts)
    if (threaded and parse_backend() in ("auto", "native")
            and parse_procs() == 0 and not audit.auditor().enabled):
        # Built-in formats over local files take the all-native pipeline
        # (reader + parse + prefetch in C++); everything else composes the
        # Python InputSplit stack with native chunk parses inside. A
        # vector/scalar backend override or a process-pool request
        # (DMLC_TPU_PARSE_PROCS>0) keeps the Python PipelinedParser so the
        # selected engine actually runs. An enabled determinism auditor
        # does too: the all-native pipeline has no io_read/parse digest
        # points, and an armed audit plane that silently observes nothing
        # is worse than the Python pipeline's (native-chunk-parse) cost.
        native_parser = _try_native_pipeline(
            spec, data_format, part_index, num_parts, nthread
        )
        if native_parser is not None:
            return native_parser
    shuffle_seed = _shuffle_seed_arg(spec)
    source = create_input_split(
        uri, part_index, num_parts, _SPLIT_TYPE.get(data_format, "text"),
        # the Python stack answers shuffle_chunks with InputSplitShuffle
        # (sub-split visit order — the same reference semantic the native
        # mmap reader implements at chunk granularity)
        num_shuffle_parts=16 if shuffle_seed >= 0 else 0,
        seed=max(shuffle_seed, 0),
    )
    base = entry(source, spec.args, nthread)
    if not threaded:
        return base
    if isinstance(base, Parser):
        # chunk-level fan-out + ordered prefetch in one stage; the base's
        # intra-chunk pool stays idle (ThreadPoolExecutor spawns lazily)
        from dmlc_tpu.data.pipeline import PipelinedParser

        return PipelinedParser(base, nthread=nthread)
    return ThreadedParser(base)
