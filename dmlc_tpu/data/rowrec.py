"""Binary row-group records: CSR blocks inside RecordIO framing.

The reference splits RecordIO natively (src/io/recordio_split.cc:9-82) but
its data parsers are text-only — every Criteo-class ingest pays a byte-scan
tax per epoch. The TPU build makes binary shards the fast path: a row group
is a serialized CSR slice, so decode is framing + memcpy with no scanning.
``cpp/pipeline.cc`` ParseRecordIOChunk is the native decoder; this module is
its Python twin plus the writer/converter tooling.

Payload layout (little-endian), mirrored in pipeline.cc RowGroupHeader:

    u8  tag 0x52 ('R')
    u8  flags: 1=weights, 2=qids, 4=values
    u16 reserved (0)
    u32 nrows
    u32 nnz
    labels  f32[nrows]
    weights f32[nrows]      (iff flags & 1)
    qids    i64[nrows]      (iff flags & 2)
    row_nnz u32[nrows]
    indices u32[nnz]
    values  f32[nnz]        (iff flags & 4)

libfm ``field`` arrays are not carried: the row-group format targets the
libsvm-style CSR contract (data.h:170-230); field-aware datasets stay on
the text path.
"""

from __future__ import annotations

import struct
from typing import Iterable, Optional

import numpy as np

from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.io.recordio import RecordIOWriter
from dmlc_tpu.utils.logging import DMLCError, check

ROW_GROUP_TAG = 0x52
HAS_WEIGHT = 1
HAS_QID = 2
HAS_VALUE = 4

_HEADER = struct.Struct("<BBHII")


def encode_row_group(block: RowBlock) -> bytes:
    """Serialize a RowBlock slice into one row-group payload."""
    n = len(block)
    nnz = block.num_nonzero
    check(
        block.field is None,
        "row-group records do not carry libfm fields",
    )
    index = np.ascontiguousarray(block.index, dtype=np.uint32)
    flags = 0
    parts = []
    if block.weight is not None:
        flags |= HAS_WEIGHT
    if block.qid is not None:
        flags |= HAS_QID
    if block.value is not None:
        flags |= HAS_VALUE
    parts.append(_HEADER.pack(ROW_GROUP_TAG, flags, 0, n, nnz))
    parts.append(np.ascontiguousarray(block.label, np.float32).tobytes())
    if block.weight is not None:
        parts.append(np.ascontiguousarray(block.weight, np.float32).tobytes())
    if block.qid is not None:
        parts.append(np.ascontiguousarray(block.qid, np.int64).tobytes())
    row_nnz = np.diff(np.asarray(block.offset, np.int64)).astype(np.uint32)
    parts.append(row_nnz.tobytes())
    parts.append(index.tobytes())
    if block.value is not None:
        parts.append(np.ascontiguousarray(block.value, np.float32).tobytes())
    return b"".join(parts)


def decode_row_group(payload: bytes) -> RowBlock:
    """Pure-Python twin of pipeline.cc ParseRecordIOChunk's per-record
    decode (the no-native fallback)."""
    if len(payload) < _HEADER.size:
        raise DMLCError("row-group record too short")
    tag, flags, _resv, n, nnz = _HEADER.unpack_from(payload, 0)
    if tag != ROW_GROUP_TAG:
        raise DMLCError("not a row-group record (bad tag)")
    pos = _HEADER.size

    def take(count: int, dtype) -> np.ndarray:
        nonlocal pos
        nbytes = count * np.dtype(dtype).itemsize
        if pos + nbytes > len(payload):
            raise DMLCError("truncated row-group record")
        out = np.frombuffer(payload, dtype=dtype, count=count, offset=pos)
        pos += nbytes
        return out

    label = take(n, np.float32)
    weight = take(n, np.float32) if flags & HAS_WEIGHT else None
    qid = take(n, np.int64) if flags & HAS_QID else None
    row_nnz = take(n, np.uint32)
    index = take(nnz, np.uint32)
    value = take(nnz, np.float32) if flags & HAS_VALUE else None
    if pos != len(payload):
        raise DMLCError("row-group record has trailing bytes")
    offset = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(row_nnz, out=offset[1:])
    if int(offset[-1]) != nnz:
        raise DMLCError("row-group nnz mismatch")
    return RowBlock(
        offset=offset, label=label, index=index,
        value=value, weight=weight, qid=qid,
    )


class RowGroupWriter:
    """Write RowBlocks as row-group records over a Stream.

    ``rows_per_group`` bounds record size so partitioning stays balanced
    (the recordio splitter partitions by records).
    """

    def __init__(self, stream, rows_per_group: int = 1024):
        check(rows_per_group > 0, "rows_per_group must be positive")
        self._writer = RecordIOWriter(stream)
        self._rows_per_group = rows_per_group

    def write_block(self, block: RowBlock) -> None:
        for start in range(0, len(block), self._rows_per_group):
            stop = min(start + self._rows_per_group, len(block))
            self._writer.write_record(encode_row_group(block.slice(start, stop)))


def write_recordio_rows(
    uri: str, blocks: Iterable[RowBlock], rows_per_group: int = 1024
) -> None:
    """Write an iterable of RowBlocks to ``uri`` as a row-group RecordIO
    file (any registered filesystem)."""
    from dmlc_tpu.io.filesystem import create_stream

    with create_stream(uri, "w") as out:
        writer = RowGroupWriter(out, rows_per_group=rows_per_group)
        for block in blocks:
            writer.write_block(block)


def convert_to_recordio(
    src_uri: str,
    dst_uri: str,
    data_format: str = "auto",
    rows_per_group: int = 1024,
    nthread: int = 2,
) -> int:
    """Convert any parseable dataset to the binary row-group format
    (the one-time cost that buys scan-free epochs). Returns rows written."""
    from dmlc_tpu.data.parsers import create_parser

    parser = create_parser(src_uri, 0, 1, data_format=data_format,
                           nthread=nthread)
    rows = 0

    def _blocks():
        nonlocal rows
        for block in parser:
            rows += len(block)
            yield block

    try:
        write_recordio_rows(dst_uri, _blocks(), rows_per_group=rows_per_group)
    finally:
        parser.close()
    return rows


class RecordIORowParser:
    """Python-stack parser for row-group RecordIO datasets (no-native
    fallback; the native path is pipeline.cc format=3)."""

    def __init__(self, source, args=None, nthread: int = 2):
        self._source = source
        self._bytes_read = 0

    @property
    def bytes_read(self) -> int:
        # payload bytes consumed (InputSplit sources don't expose a byte
        # counter; framing overhead is excluded)
        return self._bytes_read

    def next_block(self) -> Optional[RowBlock]:
        while True:
            rec = self._source.next_record()
            if rec is None:
                return None
            self._bytes_read += len(rec)
            block = decode_row_group(bytes(rec))
            if len(block):
                return block

    def __iter__(self):
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block

    def before_first(self) -> None:
        self._source.before_first()

    def close(self) -> None:
        self._source.close()
