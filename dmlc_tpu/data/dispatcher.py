"""Lease-based chunk dispatch: the fault-tolerant half of the data service.

``data/service.py`` serves parsed RowBlocks; this module owns *who parses
what* when there is a fleet of data workers instead of one. The tf.data
service paper (arXiv:2210.14826 — PAPERS.md) frames the hard requirement:
first-come-first-served sharding is easy, but a *visitation guarantee*
under input-worker failure is what multi-epoch training actually needs.
The :class:`DataDispatcher` provides it:

- The dataset is split into ``nchunks`` deterministic chunk descriptors
  ``(seq, uri, part, nparts)`` — InputSplit parts, so ANY worker can
  parse a reassigned chunk (chunks are never bound to a worker's
  memory). ``seq`` is the monotonic sequence id the exactly-once
  accounting keys on.
- :class:`~dmlc_tpu.data.service.BlockService` data workers register and
  heartbeat; each ``lease`` hands the lowest-seq queued chunk to one
  worker with a deadline. A worker that dies (heartbeat silence >
  ``DMLC_TPU_DATA_DEAD_S``) or overruns its lease
  (``DMLC_TPU_DATA_LEASE_S``) gets its chunks requeued — deterministic
  reassignment to whichever surviving worker leases next.
- Consumers report receipt (``recv``) when a chunk's frame lands and
  ``ack`` once the chunk is consumed. The chunk state machine is
  ``queued → leased → delivered → acked``; only the dispatcher decides
  who wins when a requeue races a late delivery, so every chunk is
  consumed exactly once per epoch (a duplicate delivery is *rejected*
  and the consumer drops it).
- Each chunk gets one obs flow id, minted at first lease and carried
  through every (re)assignment — a requeued chunk's Perfetto arrow chain
  shows both workers that touched it.

**Multi-tenant fleet mode** (docs/distributed.md "Multi-tenant fleet"):
one dispatcher carries N *jobs* over one shared worker pool. Each job is
a named, independent chunk ledger with its own epoch counter and
exactly-once visitation state; :meth:`DataDispatcher.add_job` registers
one (idempotent by name — a client that re-registers after a crash
resumes the existing ack frontier instead of minting a fresh ledger),
:meth:`remove_job` tears one down by releasing its leases without
touching any other ledger. Lease requests that name a job are scoped to
it (per-job in-flight quotas answer ``busy`` — backpressure, not
failure); requests that don't are scheduled weighted-fair-share across
jobs with queued work (lowest ``granted/weight`` first), so a hot job
degrades gracefully instead of monopolizing the fleet. Admission above
``DMLC_TPU_DATA_MAX_JOBS`` is refused with :class:`DataBusyError` — an
``OSError`` on purpose, so the client's ``RetryPolicy`` already
classifies it transient. Lease grants are cache-aware: among a job's
queued chunks, a worker that already parsed a chunk's source part (the
shared :mod:`~dmlc_tpu.data.source_cache` tier keeps it hot) is
preferred for the re-serve. Workers can be *drained* for scale-down
(:meth:`drain_worker` → autoscaler, data/autoscale.py): a draining
worker gets no new leases and its next idle lease poll is answered
``retire``.

Lease deadlines trade exactly-once bookkeeping for liveness under false
suspicion: a worker that is merely slow past its lease gets its chunk
requeued, and the late delivery is then rejected — the chunk is still
consumed once, but the slow worker's parse work is wasted. Size
``DMLC_TPU_DATA_LEASE_S`` well above one chunk's parse+serve time.
DELIVERED chunks are different: the consumer already HOLDS the rows, so
redelivering them would duplicate data, not waste work. A delivered
chunk therefore requeues only once its holder's dispatcher connection
is gone (a crashed consumer drops its TCP session; a slow-but-live one
— a jit compile can take minutes — keeps it open and keeps the chunk),
and the consumer side additionally drops any sequence id it has already
received (``RemoteBlockParser`` tracks its seen set), closing the
reconnect race.

Transport is a tiny framed protocol (u32 length + JSON object per
message) over one persistent TCP connection per peer;
:class:`DispatcherClient` is the shared RPC shim (workers and failover
consumers both use it) with transparent reconnect under the resilience
``RetryPolicy``. The default chunk count when a caller passes none comes
from ``DMLC_TPU_DATA_CHUNKS``.

The live worker/lease/requeue view is exported two ways: ``snapshot()``
(the ``/data`` status-plane endpoint — see ``attach_plane``; per-job
ledgers under its ``jobs`` key, old top-level keys kept byte-stable as
cross-job aggregates) and the ``dmlc_dispatch_*`` metrics (per-job
counters labeled ``job=``); requeues, worker deaths, job registrations
and scale events are also flight-recorder events (``service.requeue`` /
``service.worker_dead`` / ``dispatch.job_register`` / ``scale.down``).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple, Union

from dmlc_tpu import obs
from dmlc_tpu.obs.flight import record_event
from dmlc_tpu.params.knobs import (
    data_chunks,
    data_dead_after_s,
    data_job_inflight,
    data_lease_s,
    data_max_jobs,
)
from dmlc_tpu.utils.logging import DMLCError, check, log_warning

# one framed message: u32 little-endian byte length + a JSON object.
# Length cap so a stray connection speaking another protocol cannot make
# the dispatcher allocate gigabytes off four garbage bytes.
_MAX_MSG = 1 << 20

_QUEUED = "queued"
_LEASED = "leased"
_DELIVERED = "delivered"
_ACKED = "acked"

# rows the lease table ships to /data (full accounting stays in the
# counters; the table is a human debugging view)
_SNAPSHOT_ROWS = 512

# the implicit job every single-tenant dispatcher carries (jid 0): the
# pre-multi-tenant RPC surface maps onto it, so legacy workers/clients
# keep working byte-for-byte
DEFAULT_JOB = "default"


class DataBusyError(OSError):
    """Admission refused under load (job cap reached).

    An ``OSError`` on purpose: the resilience layer's
    ``classify_transient`` already marks OSErrors retryable, so a caller
    registering a job under the shared ``RetryPolicy`` backs off and
    retries without any new classification plumbing — backpressure,
    not failure."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise OSError("dispatcher connection closed mid-frame")
        got += r
    return bytes(buf)


def _send_msg(sock: socket.socket, obj: Dict) -> None:
    data = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> Dict:
    (nbytes,) = struct.unpack("<I", _recv_exact(sock, 4))
    if nbytes > _MAX_MSG:
        raise ValueError("dispatcher frame too large: %d bytes" % nbytes)
    obj = json.loads(_recv_exact(sock, nbytes).decode())
    if not isinstance(obj, dict):
        raise ValueError("dispatcher frame is not an object")
    return obj


class DispatcherClient:
    """Framed-JSON RPC shim onto a :class:`DataDispatcher`.

    One persistent connection, one in-flight request at a time (the
    internal lock serializes callers — a feed's producer thread and its
    consumer's ack path share one client safely). A dead connection is
    re-dialed transparently under the shared ``RetryPolicy``; the caller
    sees either a reply dict or a ``DMLCError`` give-up."""

    def __init__(self, address: Tuple[str, int], timeout: float = 30.0):
        self.address = (str(address[0]), int(address[1]))
        self._timeout = float(timeout)
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _ensure_locked(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(
                self.address, timeout=self._timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
        return self._sock

    def _drop_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call(self, obj: Dict, site: str = "service.dispatch") -> Dict:
        from dmlc_tpu.resilience import RetryPolicy

        def attempt() -> Dict:
            with self._lock:
                try:
                    sock = self._ensure_locked()
                    _send_msg(sock, obj)
                    return _recv_msg(sock)
                except ValueError as err:
                    # garbled frame: reconnect and retry like a dead socket
                    self._drop_locked()
                    raise OSError(str(err)) from err
                except OSError:
                    self._drop_locked()
                    raise

        return RetryPolicy(max_attempts=5, base_s=0.05, cap_s=0.5).call(
            attempt, site,
            display="data dispatcher %s:%d" % self.address)

    def close(self) -> None:
        with self._lock:
            self._drop_locked()


def register_job(
    client: DispatcherClient,
    name: str,
    uri: str,
    nchunks: Optional[int] = None,
    data_format: str = "auto",
    weight: float = 1.0,
    max_inflight: Optional[int] = None,
) -> Dict:
    """Register (or resume) job ``name`` over the RPC surface.

    Idempotent: an existing ledger under the same name is resumed — the
    reply's ``created`` is False and ``acked`` lists the seqs already
    past the ack frontier, so a crashed client picks up where it left
    off instead of re-reading the epoch. Raises :class:`DataBusyError`
    when the dispatcher is at its ``DMLC_TPU_DATA_MAX_JOBS`` cap (the
    caller's RetryPolicy classifies it transient) and ``DMLCError`` on
    any other refusal."""
    req = {"op": "job", "name": str(name), "uri": str(uri),
           "data_format": str(data_format), "weight": float(weight)}
    if nchunks is not None:
        req["nchunks"] = int(nchunks)
    if max_inflight is not None:
        req["max_inflight"] = int(max_inflight)
    reply = client.call(req)
    if reply.get("busy"):
        raise DataBusyError(
            "dispatcher refused job %r: at its job cap "
            "(DMLC_TPU_DATA_MAX_JOBS)" % name)
    if not reply.get("ok"):
        raise DMLCError(
            "job registration %r failed: %s" % (name, reply.get("error")))
    return reply


def job_frontier(client: DispatcherClient, name: str) -> Dict:
    """Fetch job ``name``'s ledger frontier (epoch + acked seqs) over
    RPC — what a job snapshot persists so a relaunched run never
    re-leases settled chunks."""
    reply = client.call({"op": "job", "name": str(name),
                         "export_frontier": True})
    if not reply.get("ok"):
        raise DMLCError(
            "frontier export for job %r failed: %s"
            % (name, reply.get("error")))
    return {"epoch": reply["epoch"], "acked": reply["acked"]}


def restore_job_frontier(client: DispatcherClient, name: str,
                         frontier: Dict) -> int:
    """Re-seed job ``name``'s ledger from a snapshotted frontier over
    RPC; returns the number of seqs settled as acked."""
    reply = client.call({"op": "job", "name": str(name),
                         "restore_frontier": dict(frontier)})
    if not reply.get("ok"):
        raise DMLCError(
            "frontier restore for job %r failed: %s"
            % (name, reply.get("error")))
    return int(reply.get("acked", 0))


class DataDispatcher:
    """Registry of data workers + per-job lease tables over one fleet.

    ``uri`` is the single-tenant convenience: when given, it becomes the
    ``default`` job (jid 0) split into ``nchunks`` InputSplit parts —
    the exact pre-multi-tenant surface. ``uri=None`` starts an empty
    fleet manager; jobs arrive via :meth:`add_job` or the ``job`` RPC.
    ``lease_s``/``dead_after_s`` default through the
    ``DMLC_TPU_DATA_LEASE_S``/``DMLC_TPU_DATA_DEAD_S`` knobs. Expiry is
    scanned on every RPC (workers poll ``lease`` while idle, so a
    dispatcher with any live worker needs no timer thread).

    A job's ledger is ONE epoch's pass; :meth:`reset_job` starts the
    next epoch over the same ledger (all chunks requeued, epoch counter
    bumped) once the previous one is fully acked."""

    def __init__(
        self,
        uri: Optional[str] = None,
        nchunks: Optional[int] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        lease_s: Optional[float] = None,
        dead_after_s: Optional[float] = None,
        data_format: str = "auto",
        plane=None,
        max_jobs: Optional[int] = None,
    ):
        self.uri = str(uri) if uri is not None else None
        self.lease_s = data_lease_s(lease_s)
        self.dead_after_s = data_dead_after_s(dead_after_s)
        self.max_jobs = data_max_jobs(max_jobs)
        self._lock = threading.Lock()
        self._jobs: Dict[int, Dict] = {}
        self._job_names: Dict[str, int] = {}
        self._next_jid = 0
        self._workers: Dict[int, Dict] = {}
        self._next_worker = 0
        self._next_client = 0
        # chunk-source key -> worker ids that parsed it (their shared
        # source-cache tier holds it hot); lease grants prefer a hot
        # worker's chunks so a second job re-reading a source lands on
        # the worker that can serve it without re-parsing
        self._hot: Dict[Tuple, set] = {}
        # client id -> ids of live dispatcher connections that spoke for
        # it. A DELIVERED chunk requeues only when its holder has NO live
        # connection: consumer death is a dropped session, consumer
        # slowness is not — redelivering rows a live consumer already
        # holds would break exactly-once.
        self._client_conns: Dict[int, set] = {}
        # plain-int accounting (truthful under DMLC_TPU_METRICS=0; the
        # registry carries the telemetry mirror)
        self._requeued = 0
        self._acked = 0
        self._rejects = 0
        self._dup_acks = 0
        self._all_acked = threading.Event()
        reg = obs.registry()
        self._m_chunks = reg.counter(
            "dmlc_dispatch_chunks_total",
            "chunks registered for lease-based dispatch")
        self._m_requeued = reg.counter(
            "dmlc_dispatch_requeued_total",
            "chunk leases requeued after expiry or worker death")
        self._m_acked = reg.counter(
            "dmlc_dispatch_acked_total",
            "chunks acked by consumers (the exactly-once frontier)")
        self._m_rejects = reg.counter(
            "dmlc_dispatch_rejects_total",
            "duplicate chunk deliveries refused by the lease table")
        self._g_workers = reg.gauge(
            "dmlc_dispatch_workers_count", "live registered data workers")
        self._g_workers.set(0)
        self._g_jobs = reg.gauge(
            "dmlc_dispatch_jobs_count", "registered tenant jobs")
        self._g_jobs.set(0)
        if uri is not None:
            self.add_job(DEFAULT_JOB, uri, nchunks=nchunks,
                         data_format=data_format)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        self._conns: List[socket.socket] = []
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="data-dispatcher")
        self._accept_thread.start()
        if plane is not None:
            self.attach_plane(plane)

    # ---- job ledgers -----------------------------------------------------

    def add_job(
        self,
        name: str,
        uri: str,
        nchunks: Optional[int] = None,
        data_format: str = "auto",
        weight: float = 1.0,
        max_inflight: Optional[int] = None,
    ) -> Dict:
        """Register job ``name`` (idempotent) and return its ledger info.

        A name already registered resumes the EXISTING ledger — chunk
        states, epoch counter and ack frontier intact — so a client that
        crashed and re-registered continues the epoch instead of
        corrupting it with a fresh one (``created`` False, ``acked``
        lists the settled seqs). A genuinely new job above the
        ``DMLC_TPU_DATA_MAX_JOBS`` cap raises :class:`DataBusyError`.
        ``weight`` biases the fair-share lease scheduler;
        ``max_inflight`` caps the job's leased+delivered chunks (default
        via ``DMLC_TPU_DATA_JOB_INFLIGHT``; 0 = uncapped)."""
        name = str(name)
        with self._lock:
            jid = self._job_names.get(name)
            if jid is not None:
                job = self._jobs[jid]
                return {
                    "jid": jid, "epoch": job["epoch"], "created": False,
                    "acked": [c["seq"] for c in job["chunks"]
                              if c["state"] == _ACKED],
                }
            if len(self._jobs) >= self.max_jobs:
                raise DataBusyError(
                    "job cap reached (%d; DMLC_TPU_DATA_MAX_JOBS): "
                    "cannot admit %r" % (self.max_jobs, name))
            n = data_chunks(nchunks)
            check(n >= 1, "nchunks must be >= 1, got %d", n)
            jid = self._next_jid
            self._next_jid += 1
            reg = obs.registry()
            job = {
                "jid": jid,
                "name": name,
                "uri": str(uri),
                "format": str(data_format),
                "weight": max(0.001, float(weight)),
                "max_inflight": (data_job_inflight()
                                 if max_inflight is None
                                 else max(0, int(max_inflight))),
                "epoch": 1,
                "granted": 0,
                "requeued": 0,
                "rejects": 0,
                "dup_acks": 0,
                "busy": 0,
                "all_acked": threading.Event(),
                "chunks": [
                    {
                        "seq": k,
                        "job": jid,
                        "uri": str(uri),
                        "part": k,
                        "nparts": n,
                        "format": str(data_format),
                        "state": _QUEUED,
                        "worker": -1,
                        "client": -1,
                        "deadline": 0.0,
                        "requeues": 0,
                        "flow": 0,
                    }
                    for k in range(n)
                ],
                "m_acked": reg.counter(
                    "dmlc_dispatch_job_acked_total",
                    "chunks acked per tenant job", job=name),
                "m_requeued": reg.counter(
                    "dmlc_dispatch_job_requeued_total",
                    "chunk leases requeued per tenant job", job=name),
                "m_busy": reg.counter(
                    "dmlc_dispatch_job_busy_total",
                    "lease requests deferred by the job's in-flight quota",
                    job=name),
            }
            reg.counter(
                "dmlc_dispatch_job_chunks_total",
                "chunks registered per tenant job", job=name).inc(n)
            self._jobs[jid] = job
            self._job_names[name] = jid
            self._m_chunks.inc(n)
            self._g_jobs.set(len(self._jobs))
            self._all_acked.clear()
        record_event("dispatch.job_register", job=name, jid=jid, chunks=n)
        return {"jid": jid, "epoch": 1, "created": True, "acked": []}

    def remove_job(self, name: str) -> bool:
        """Tear down job ``name``: drop its ledger and release its leases
        without touching any other job's accounting. False when the name
        is unknown (teardown is idempotent too)."""
        with self._lock:
            jid = self._job_names.pop(str(name), None)
            if jid is None:
                return False
            del self._jobs[jid]
            self._g_jobs.set(len(self._jobs))
            self._update_all_acked_locked()
        return True

    def reset_job(self, name: str) -> int:
        """Start job ``name``'s next epoch: requeue every chunk of a
        FULLY-ACKED ledger and bump the epoch counter (fresh flows, fresh
        requeue counts). Returns the new epoch number; raises when the
        current epoch has unsettled chunks — an epoch boundary is an ack
        frontier, not a reset button."""
        with self._lock:
            jid = self._job_names.get(str(name))
            check(jid is not None, "unknown job %r", name)
            job = self._jobs[jid]
            check(all(c["state"] == _ACKED for c in job["chunks"]),
                  "job %r has unacked chunks; an epoch resets only at a "
                  "full ack frontier", name)
            for c in job["chunks"]:
                c["state"] = _QUEUED
                c["worker"] = -1
                c["client"] = -1
                c["deadline"] = 0.0
                c["requeues"] = 0
                c["flow"] = 0
            job["epoch"] += 1
            job["granted"] = 0
            job["all_acked"].clear()
            self._all_acked.clear()
            return job["epoch"]

    def export_frontier(self, name: str) -> Dict:
        """Job ``name``'s resumable ledger frontier for a job snapshot:
        the epoch counter and the seqs settled (acked) so far. Leased or
        delivered-but-unacked chunks are deliberately NOT exported — a
        restart replays them (at-least-once lease, exactly-once ack)."""
        with self._lock:
            jid = self._job_names.get(str(name))
            check(jid is not None, "unknown job %r", name)
            job = self._jobs[jid]
            return {
                "epoch": job["epoch"],
                "acked": [c["seq"] for c in job["chunks"]
                          if c["state"] == _ACKED],
            }

    def restore_frontier(self, name: str, frontier: Dict) -> int:
        """Re-seed job ``name``'s ledger from a snapshot frontier: the
        epoch counter is restored and every snapshotted acked seq is
        settled — those chunks are never leased again (exactly-once).
        Everything else returns to queued, dropping the dead attempt's
        leases. Returns the count of seqs marked acked."""
        epoch = max(1, int(frontier.get("epoch", 1)))
        acked = {int(s) for s in frontier.get("acked", ())}
        with self._lock:
            jid = self._job_names.get(str(name))
            check(jid is not None, "unknown job %r", name)
            job = self._jobs[jid]
            bad = acked - {c["seq"] for c in job["chunks"]}
            check(not bad,
                  "frontier for job %r names unknown seqs %s", name,
                  sorted(bad)[:8])
            for c in job["chunks"]:
                c["state"] = _ACKED if c["seq"] in acked else _QUEUED
                c["worker"] = -1
                c["client"] = -1
                c["deadline"] = 0.0
                c["flow"] = 0
            job["epoch"] = epoch
            job["granted"] = 0
            if all(c["state"] == _ACKED for c in job["chunks"]):
                job["all_acked"].set()
            else:
                job["all_acked"].clear()
                self._all_acked.clear()
            self._update_all_acked_locked()
        record_event("dispatch.frontier_restore", job=str(name),
                     epoch=epoch, acked=len(acked))
        return len(acked)

    def drain_worker(self, wid: int) -> None:
        """Mark worker ``wid`` draining for scale-down: it gets no new
        leases, and once its in-flight leases settle, its next idle
        lease poll is answered ``retire`` (the worker ends its stream
        and the dispatcher delists it). The autoscaler calls this before
        retiring a worker so no leased chunk is lost to the retirement."""
        from dmlc_tpu.resilience import faultpoint

        faultpoint("scale.drain")
        with self._lock:
            w = self._workers.get(int(wid))
            check(w is not None, "unknown worker %d", wid)
            if not w["dead"]:
                w["draining"] = True

    # ---- transport ------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True)
            t.start()
            # prune finished handler threads: a fault storm reconnects
            # DispatcherClients many times per epoch, and the list must
            # not grow one dead entry per reconnect
            self._threads = [
                th for th in self._threads if th.is_alive()] + [t]

    def _serve_conn(self, conn: socket.socket) -> None:
        from dmlc_tpu.resilience import InjectedFault

        self._conns.append(conn)
        bound: set = set()  # client ids this connection spoke for
        try:
            while True:
                try:
                    obj = _recv_msg(conn)
                except (OSError, ValueError):
                    return  # peer gone / garbled — drop the connection
                try:
                    reply = self._handle(obj)
                except InjectedFault:
                    # injected lease-path fault: kill the connection,
                    # exactly like a dispatcher transport failure — the
                    # peer's DispatcherClient reconnects and retries
                    return
                except Exception as err:  # noqa: BLE001 — relay, don't die
                    reply = {"ok": False,
                             "error": "%s: %s" % (type(err).__name__, err)}
                # liveness binding: any op that names a client id ties
                # that client to this connection for delivered-chunk
                # requeue gating (see _expire_locked)
                try:
                    cid = int(reply.get("client_id", obj.get("client", -1)))
                except (TypeError, ValueError):
                    cid = -1
                if cid >= 0 and cid not in bound:
                    bound.add(cid)
                    with self._lock:
                        self._client_conns.setdefault(cid, set()).add(
                            id(conn))
                try:
                    _send_msg(conn, reply)
                except OSError:
                    return
        finally:
            with self._lock:
                for cid in bound:
                    conns = self._client_conns.get(cid)
                    if conns is not None:
                        conns.discard(id(conn))
                        if not conns:
                            del self._client_conns[cid]
            try:
                self._conns.remove(conn)
            except ValueError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    # ---- the chunk state machine ---------------------------------------

    def _handle(self, obj: Dict) -> Dict:
        op = obj.get("op")
        if op == "register":
            return self._op_register(obj)
        if op == "client":
            return self._op_client(obj)
        if op == "job":
            return self._op_job(obj)
        if op == "heartbeat":
            with self._lock:
                w = self._workers.get(int(obj.get("worker", -1)))
                if w is not None and not w["dead"]:
                    w["last_seen"] = time.monotonic()
                self._expire_locked()
            return {"ok": True}
        if op == "lease":
            return self._op_lease(obj)
        if op == "recv":
            return self._op_recv(obj)
        if op == "ack":
            return self._op_ack(obj)
        if op == "workers":
            with self._lock:
                self._expire_locked()
                live = [
                    [w["addr"][0], w["addr"][1], wid]
                    for wid, w in sorted(self._workers.items())
                    if not w["dead"]
                ]
            return {"ok": True, "workers": live}
        if op == "stats":
            return dict(self.snapshot(), ok=True)
        return {"ok": False, "error": "unknown op %r" % (op,)}

    def _op_job(self, obj: Dict) -> Dict:
        name = str(obj.get("name") or "")
        if not name:
            return {"ok": False, "error": "job op needs a name"}
        if obj.get("remove"):
            return {"ok": True, "removed": self.remove_job(name)}
        if obj.get("reset"):
            return {"ok": True, "epoch": self.reset_job(name)}
        if obj.get("export_frontier"):
            return dict(self.export_frontier(name), ok=True)
        frontier = obj.get("restore_frontier")
        if frontier is not None:
            return {"ok": True,
                    "acked": self.restore_frontier(name, frontier)}
        uri = obj.get("uri")
        if uri is None:
            return {"ok": False, "error": "job registration needs a uri"}
        try:
            info = self.add_job(
                name, str(uri),
                nchunks=obj.get("nchunks"),
                data_format=str(obj.get("data_format", "auto")),
                weight=float(obj.get("weight", 1.0)),
                max_inflight=obj.get("max_inflight"),
            )
        except DataBusyError:
            # typed backpressure on the wire: the registering client's
            # register_job() raises DataBusyError (an OSError) locally,
            # which its RetryPolicy already classifies transient
            return {"ok": False, "busy": True}
        return dict(info, ok=True)

    def _op_client(self, obj: Dict) -> Dict:
        name = obj.get("job")
        with self._lock:
            jid = 0
            epoch = 1
            acked: List[int] = []
            if name is not None:
                jid = self._job_names.get(str(name), -1)
                if jid < 0:
                    return {"ok": False,
                            "error": "unknown job %r" % (name,)}
            job = self._jobs.get(jid)
            if job is not None:
                epoch = job["epoch"]
                # the resumed ack frontier: a client re-registering after
                # a crash seeds its seen-set from this instead of
                # re-reading settled chunks
                acked = [c["seq"] for c in job["chunks"]
                         if c["state"] == _ACKED]
            cid = self._next_client
            self._next_client += 1
        return {"ok": True, "client_id": cid, "jid": jid, "epoch": epoch,
                "acked": acked}

    def _op_register(self, obj: Dict) -> Dict:
        raw = obj.get("addr") or ("", 0)
        addr = (str(raw[0]), int(raw[1]))
        with self._lock:
            # idempotent by serving address: register rides the retrying
            # DispatcherClient, so a lost reply re-sends it — minting a
            # fresh id each time would leave an orphan that never
            # heartbeats, later firing a spurious worker_dead and
            # skewing the workers gauge. Only one live worker can hold a
            # host:port, so the live match IS the earlier registration.
            wid = next(
                (known for known, w in self._workers.items()
                 if w["addr"] == addr and not w["dead"] and addr[1]),
                None)
            if wid is not None:
                self._workers[wid]["last_seen"] = time.monotonic()
            else:
                wid = self._next_worker
                self._next_worker += 1
                self._workers[wid] = {
                    "addr": addr,
                    "last_seen": time.monotonic(),
                    "dead": False,
                    "draining": False,
                }
            self._expire_locked()
        return {
            "ok": True,
            "worker_id": wid,
            # workers heartbeat a few times per death threshold so one
            # lost beat never reads as a crash
            "heartbeat_s": max(0.05, self.dead_after_s / 3.0),
        }

    def _all_chunks_locked(self) -> Iterable[Dict]:
        for jid in sorted(self._jobs):
            for c in self._jobs[jid]["chunks"]:
                yield c

    @staticmethod
    def _chunk_key(c: Dict) -> Tuple:
        return (c["uri"], c["part"], c["nparts"], c["format"])

    def _unhot_worker_locked(self, wid: int) -> None:
        for wids in self._hot.values():
            wids.discard(wid)

    def _drained_locked(self) -> bool:
        """Every job's every chunk is delivered-or-acked (EOF for an
        unrestricted lease; True for an empty dispatcher too)."""
        return all(c["state"] in (_ACKED, _DELIVERED)
                   for c in self._all_chunks_locked())

    def _pick_job_locked(self) -> Optional[Dict]:
        """Weighted fair-share: among jobs with queued work and headroom
        under their in-flight cap, the fewest granted-leases-per-weight
        wins (ties to the lowest jid — deterministic)."""
        best = None
        best_key = None
        for jid in sorted(self._jobs):
            job = self._jobs[jid]
            if not any(c["state"] == _QUEUED for c in job["chunks"]):
                continue
            cap = job["max_inflight"]
            if cap > 0:
                inflight = sum(1 for c in job["chunks"]
                               if c["state"] in (_LEASED, _DELIVERED))
                if inflight >= cap:
                    continue
            key = job["granted"] / job["weight"]
            if best is None or key < best_key:
                best, best_key = job, key
        return best

    def _op_lease(self, obj: Dict) -> Dict:
        from dmlc_tpu.resilience import faultpoint

        faultpoint("service.lease")
        jid = int(obj.get("job", -1))
        if jid >= 0:
            # the job-scoped admission path has its own chaos site: a
            # fault here kills one tenant's lease RPC without touching
            # the shared service.lease plumbing
            faultpoint("dispatch.lease_job")
        wid = int(obj.get("worker", -1))
        with self._lock:
            now = time.monotonic()
            w = self._workers.get(wid)
            if w is not None:
                if w["dead"]:
                    # declared dead: its registration is gone; a zombie
                    # must not take leases the table thinks are safe
                    return {"ok": False, "dead": True}
                w["last_seen"] = now
            self._expire_locked()
            if w is not None and w.get("draining"):
                if any(c["state"] == _LEASED and c["worker"] == wid
                       for c in self._all_chunks_locked()):
                    # in-flight leases settle (deliver or expire) first;
                    # the worker keeps polling, which keeps it live
                    return {"ok": True, "wait": True}
                w["draining"] = False
                w["dead"] = True
                self._unhot_worker_locked(wid)
                record_event("scale.down", worker=wid,
                             addr="%s:%d" % w["addr"])
                self._g_workers.set(len(
                    [x for x in self._workers.values() if not x["dead"]]))
                return {"ok": True, "retire": True}
            if jid >= 0:
                job = self._jobs.get(jid)
                if job is None:
                    return {"ok": False, "error": "unknown job id %d" % jid}
                queued = [c for c in job["chunks"] if c["state"] == _QUEUED]
                if not queued:
                    # EOF once every chunk is delivered-or-acked: an
                    # explicit-ack consumer (DeviceFeed) may hold
                    # received rows across many batches before acking,
                    # and gating EOF on acks would deadlock it against
                    # its own worker. join() still waits for the full
                    # ack frontier. "all" tells the worker whether the
                    # WHOLE fleet is drained (it may serve other jobs).
                    if all(c["state"] in (_ACKED, _DELIVERED)
                           for c in job["chunks"]):
                        return {"ok": True, "eof": True,
                                "all": self._drained_locked()}
                    return {"ok": True, "wait": True}
                cap = job["max_inflight"]
                if cap > 0:
                    inflight = sum(1 for c in job["chunks"]
                                   if c["state"] in (_LEASED, _DELIVERED))
                    if inflight >= cap:
                        # quota backpressure, not an error: the worker
                        # polls again, the consumer just waits
                        job["busy"] += 1
                        job["m_busy"].inc()
                        return {"ok": True, "busy": True}
            else:
                job = self._pick_job_locked()
                if job is None:
                    if self._drained_locked():
                        return {"ok": True, "eof": True, "all": True}
                    # leased chunks may still requeue; the worker polls
                    # (each poll doubles as a heartbeat + expiry scan)
                    return {"ok": True, "wait": True}
                queued = [c for c in job["chunks"] if c["state"] == _QUEUED]
            # cache-aware routing: this worker's hot chunks first (its
            # source-cache tier already holds the parsed part), lowest
            # seq otherwise — which keeps a cold fleet's assignment
            # order deterministic
            chunk = next(
                (c for c in queued
                 if wid in self._hot.get(self._chunk_key(c), ())),
                queued[0])
            if not chunk["flow"]:
                # one flow per chunk, minted at FIRST lease and carried
                # through every reassignment — the merged trace's arrow
                # chain then shows every worker that touched the chunk
                chunk["flow"] = obs.new_flow()
                obs.flow_start(chunk["flow"], "chunk")
            chunk["state"] = _LEASED
            chunk["worker"] = wid
            chunk["client"] = -1
            chunk["deadline"] = now + self.lease_s
            job["granted"] += 1
            self._hot.setdefault(
                self._chunk_key(chunk), set()).add(wid)
            return {
                "ok": True,
                "chunk": {
                    "seq": chunk["seq"],
                    "job": job["jid"],
                    "uri": chunk["uri"],
                    "part": chunk["part"],
                    "nparts": chunk["nparts"],
                    "format": chunk["format"],
                    "flow": chunk["flow"],
                },
            }

    def _chunk_locked(self, jid: int, seq: int) -> Optional[Dict]:
        job = self._jobs.get(jid)
        if job is not None and 0 <= seq < len(job["chunks"]):
            return job["chunks"][seq]
        return None

    def _op_recv(self, obj: Dict) -> Dict:
        cid = int(obj.get("client", -1))
        jid = int(obj.get("job", 0))
        seq = int(obj.get("seq", -1))
        with self._lock:
            self._expire_locked()
            c = self._chunk_locked(jid, seq)
            if c is None:
                return {"ok": False, "reject": True,
                        "error": "unknown seq %d" % seq}
            if c["state"] in (_LEASED, _QUEUED):
                # a requeued-but-not-releases chunk whose original send
                # did land is reclaimed here: the bytes arrived, so this
                # delivery wins and the requeue is undone
                c["state"] = _DELIVERED
                c["client"] = cid
                c["deadline"] = time.monotonic() + self.lease_s
                return {"ok": True}
            if c["state"] == _DELIVERED and c["client"] == cid:
                return {"ok": True}  # same consumer re-reporting (hedge)
            # delivered to someone else or already acked: the reporter
            # must DROP this copy — that is the exactly-once guarantee
            self._rejects += 1
            self._jobs[jid]["rejects"] += 1
            self._m_rejects.inc()
            return {"ok": True, "reject": True}

    def _op_ack(self, obj: Dict) -> Dict:
        jid = int(obj.get("job", 0))
        seq = int(obj.get("seq", -1))
        with self._lock:
            self._expire_locked()
            c = self._chunk_locked(jid, seq)
            if c is None:
                return {"ok": False, "error": "unknown seq %d" % seq}
            job = self._jobs[jid]
            if c["state"] == _ACKED:
                self._dup_acks += 1
                job["dup_acks"] += 1
                return {"ok": True, "dup": True}
            # an ack is authoritative from ANY state: the consumer holds
            # the rows, so even a chunk the expiry scan already requeued
            # is done — acking it here is what stops a second serve
            c["state"] = _ACKED
            c["worker"] = -1
            c["deadline"] = 0.0
            self._acked += 1
            self._m_acked.inc()
            job["m_acked"].inc()
            if c["flow"]:
                obs.flow_step(c["flow"], "chunk")
            if all(ch["state"] == _ACKED for ch in job["chunks"]):
                job["all_acked"].set()
            self._update_all_acked_locked()
            return {"ok": True}

    def _update_all_acked_locked(self) -> None:
        if self._jobs and all(c["state"] == _ACKED
                              for c in self._all_chunks_locked()):
            self._all_acked.set()

    def _expire_locked(self) -> None:
        now = time.monotonic()
        for wid, w in self._workers.items():
            if not w["dead"] and now - w["last_seen"] > self.dead_after_s:
                w["dead"] = True
                w["draining"] = False
                self._unhot_worker_locked(wid)
                record_event("service.worker_dead", worker=wid,
                             addr="%s:%d" % w["addr"])
                log_warning(
                    "data worker %d (%s:%d) declared dead (%.1fs silent)",
                    wid, w["addr"][0], w["addr"][1], now - w["last_seen"])
        for jid in sorted(self._jobs):
            job = self._jobs[jid]
            for c in job["chunks"]:
                if c["state"] == _LEASED:
                    w = self._workers.get(c["worker"])
                    expired = (now > c["deadline"]
                               or w is None or w["dead"])
                elif c["state"] == _DELIVERED:
                    # the holder already HAS the rows — requeueing while
                    # it is alive would serve them twice. Its dispatcher
                    # session is the liveness signal: a crashed consumer
                    # drops the TCP connection; a slow one (jit compiles
                    # take minutes) keeps it open and keeps the chunk,
                    # however long past the deadline. The deadline still
                    # applies once the holder is gone.
                    expired = (now > c["deadline"]
                               and c["client"] not in self._client_conns)
                else:
                    continue
                if not expired:
                    continue
                record_event("service.requeue", seq=c["seq"],
                             job=job["name"], state=c["state"],
                             worker=c["worker"], client=c["client"],
                             requeues=c["requeues"] + 1)
                c["state"] = _QUEUED
                c["worker"] = -1
                c["client"] = -1
                c["deadline"] = 0.0
                c["requeues"] += 1
                self._requeued += 1
                job["requeued"] += 1
                self._m_requeued.inc()
                job["m_requeued"].inc()
        self._g_workers.set(
            len([w for w in self._workers.values() if not w["dead"]]))

    # ---- read side ------------------------------------------------------

    @staticmethod
    def _counts(chunks: List[Dict]) -> Dict[str, int]:
        counts = {_QUEUED: 0, _LEASED: 0, _DELIVERED: 0, _ACKED: 0}
        for c in chunks:
            counts[c["state"]] += 1
        return {
            "total": len(chunks),
            "queued": counts[_QUEUED],
            "leased": counts[_LEASED],
            "delivered": counts[_DELIVERED],
            "acked": counts[_ACKED],
        }

    @staticmethod
    def _table(chunks: List[Dict], cap: int = _SNAPSHOT_ROWS) -> List[Dict]:
        return [
            {
                "seq": c["seq"],
                "state": c["state"],
                "worker": c["worker"],
                "client": c["client"],
                "requeues": c["requeues"],
            }
            for c in chunks[:cap]
        ]

    def snapshot(self) -> Dict:
        """The live worker/lease/requeue view (the ``/data`` endpoint
        body). Top-level keys are the pre-multi-tenant surface —
        aggregates across every job, byte-stable for existing consumers;
        per-job ledgers live under ``jobs``. Exactly-once invariant at
        end of epoch: ``chunks.acked == chunks.total`` with ``queued ==
        leased == delivered == 0`` and every requeue drained."""
        with self._lock:
            self._expire_locked()
            now = time.monotonic()
            all_chunks = list(self._all_chunks_locked())
            jobs = {}
            for jid in sorted(self._jobs):
                job = self._jobs[jid]
                jobs[job["name"]] = {
                    "jid": jid,
                    "uri": job["uri"],
                    "epoch": job["epoch"],
                    "weight": job["weight"],
                    "max_inflight": job["max_inflight"],
                    "granted": job["granted"],
                    "busy": job["busy"],
                    "requeued": job["requeued"],
                    "rejects": job["rejects"],
                    "duplicate_acks": job["dup_acks"],
                    "chunks": self._counts(job["chunks"]),
                    "lease_table": self._table(job["chunks"]),
                }
            workers = {
                str(wid): {
                    "addr": "%s:%d" % w["addr"],
                    "live": not w["dead"],
                    "draining": bool(w.get("draining")),
                    "lag_s": round(now - w["last_seen"], 3),
                    "leased": len([
                        c for c in all_chunks
                        if c["state"] == _LEASED and c["worker"] == wid
                    ]),
                }
                for wid, w in sorted(self._workers.items())
            }
        return {
            "chunks": self._counts(all_chunks),
            "requeued": self._requeued,
            "rejects": self._rejects,
            "duplicate_acks": self._dup_acks,
            "workers": workers,
            "jobs": jobs,
            "lease_table": self._table(all_chunks),
        }

    def attach_plane(self, plane) -> None:
        """Expose :meth:`snapshot` as the status plane's ``/data``
        endpoint (``StatusPlane.set_data_provider``)."""
        plane.set_data_provider(self.snapshot)

    def join(self, timeout: Optional[float] = None,
             job: Optional[str] = None) -> bool:
        """Block until every chunk is acked — of job ``job`` when named,
        of EVERY registered job otherwise (the epoch is complete); True
        on completion, False on timeout."""
        if job is not None:
            with self._lock:
                jid = self._job_names.get(str(job))
                check(jid is not None, "unknown job %r", job)
                event = self._jobs[jid]["all_acked"]
            return event.wait(timeout)
        return self._all_acked.wait(timeout)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def dispatcher_address(spec: Union[str, Tuple[str, int]]) -> Tuple[str, int]:
    """Normalize a ``host:port`` string or ``(host, port)`` pair — the
    accepted ``dispatcher=`` argument shapes of BlockService and
    RemoteBlockParser."""
    if isinstance(spec, str):
        host, _, port = spec.rpartition(":")
        check(bool(host) and port.isdigit(),
              "bad dispatcher address %r (want host:port)", spec)
        return host, int(port)
    return str(spec[0]), int(spec[1])
