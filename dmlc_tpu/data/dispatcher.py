"""Lease-based chunk dispatch: the fault-tolerant half of the data service.

``data/service.py`` serves parsed RowBlocks; this module owns *who parses
what* when there is a fleet of data workers instead of one. The tf.data
service paper (arXiv:2210.14826 — PAPERS.md) frames the hard requirement:
first-come-first-served sharding is easy, but a *visitation guarantee*
under input-worker failure is what multi-epoch training actually needs.
The :class:`DataDispatcher` provides it:

- The dataset is split into ``nchunks`` deterministic chunk descriptors
  ``(seq, uri, part, nparts)`` — InputSplit parts, so ANY worker can
  parse a reassigned chunk (chunks are never bound to a worker's
  memory). ``seq`` is the monotonic sequence id the exactly-once
  accounting keys on.
- :class:`~dmlc_tpu.data.service.BlockService` data workers register and
  heartbeat; each ``lease`` hands the lowest-seq queued chunk to one
  worker with a deadline. A worker that dies (heartbeat silence >
  ``DMLC_TPU_DATA_DEAD_S``) or overruns its lease
  (``DMLC_TPU_DATA_LEASE_S``) gets its chunks requeued — deterministic
  reassignment to whichever surviving worker leases next.
- Consumers report receipt (``recv``) when a chunk's frame lands and
  ``ack`` once the chunk is consumed. The chunk state machine is
  ``queued → leased → delivered → acked``; only the dispatcher decides
  who wins when a requeue races a late delivery, so every chunk is
  consumed exactly once per epoch (a duplicate delivery is *rejected*
  and the consumer drops it).
- Each chunk gets one obs flow id, minted at first lease and carried
  through every (re)assignment — a requeued chunk's Perfetto arrow chain
  shows both workers that touched it.

Lease deadlines trade exactly-once bookkeeping for liveness under false
suspicion: a worker that is merely slow past its lease gets its chunk
requeued, and the late delivery is then rejected — the chunk is still
consumed once, but the slow worker's parse work is wasted. Size
``DMLC_TPU_DATA_LEASE_S`` well above one chunk's parse+serve time.
DELIVERED chunks are different: the consumer already HOLDS the rows, so
redelivering them would duplicate data, not waste work. A delivered
chunk therefore requeues only once its holder's dispatcher connection
is gone (a crashed consumer drops its TCP session; a slow-but-live one
— a jit compile can take minutes — keeps it open and keeps the chunk),
and the consumer side additionally drops any sequence id it has already
received (``RemoteBlockParser`` tracks its seen set), closing the
reconnect race.

Transport is a tiny framed protocol (u32 length + JSON object per
message) over one persistent TCP connection per peer;
:class:`DispatcherClient` is the shared RPC shim (workers and failover
consumers both use it) with transparent reconnect under the resilience
``RetryPolicy``. The default chunk count when a caller passes none comes
from ``DMLC_TPU_DATA_CHUNKS``.

The live worker/lease/requeue view is exported two ways: ``snapshot()``
(the ``/data`` status-plane endpoint — see ``attach_plane``) and the
``dmlc_dispatch_*`` metrics; requeues and worker deaths are also flight-
recorder events (``service.requeue`` / ``service.worker_dead``).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple, Union

from dmlc_tpu import obs
from dmlc_tpu.obs.flight import record_event
from dmlc_tpu.params.knobs import (
    data_chunks,
    data_dead_after_s,
    data_lease_s,
)
from dmlc_tpu.utils.logging import check, log_warning

# one framed message: u32 little-endian byte length + a JSON object.
# Length cap so a stray connection speaking another protocol cannot make
# the dispatcher allocate gigabytes off four garbage bytes.
_MAX_MSG = 1 << 20

_QUEUED = "queued"
_LEASED = "leased"
_DELIVERED = "delivered"
_ACKED = "acked"

# rows the lease table ships to /data (full accounting stays in the
# counters; the table is a human debugging view)
_SNAPSHOT_ROWS = 512


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise OSError("dispatcher connection closed mid-frame")
        got += r
    return bytes(buf)


def _send_msg(sock: socket.socket, obj: Dict) -> None:
    data = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> Dict:
    (nbytes,) = struct.unpack("<I", _recv_exact(sock, 4))
    if nbytes > _MAX_MSG:
        raise ValueError("dispatcher frame too large: %d bytes" % nbytes)
    obj = json.loads(_recv_exact(sock, nbytes).decode())
    if not isinstance(obj, dict):
        raise ValueError("dispatcher frame is not an object")
    return obj


class DispatcherClient:
    """Framed-JSON RPC shim onto a :class:`DataDispatcher`.

    One persistent connection, one in-flight request at a time (the
    internal lock serializes callers — a feed's producer thread and its
    consumer's ack path share one client safely). A dead connection is
    re-dialed transparently under the shared ``RetryPolicy``; the caller
    sees either a reply dict or a ``DMLCError`` give-up."""

    def __init__(self, address: Tuple[str, int], timeout: float = 30.0):
        self.address = (str(address[0]), int(address[1]))
        self._timeout = float(timeout)
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _ensure_locked(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(
                self.address, timeout=self._timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
        return self._sock

    def _drop_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call(self, obj: Dict, site: str = "service.dispatch") -> Dict:
        from dmlc_tpu.resilience import RetryPolicy

        def attempt() -> Dict:
            with self._lock:
                try:
                    sock = self._ensure_locked()
                    _send_msg(sock, obj)
                    return _recv_msg(sock)
                except ValueError as err:
                    # garbled frame: reconnect and retry like a dead socket
                    self._drop_locked()
                    raise OSError(str(err)) from err
                except OSError:
                    self._drop_locked()
                    raise

        return RetryPolicy(max_attempts=5, base_s=0.05, cap_s=0.5).call(
            attempt, site,
            display="data dispatcher %s:%d" % self.address)

    def close(self) -> None:
        with self._lock:
            self._drop_locked()


class DataDispatcher:
    """Registry of data workers + the lease table for one epoch's chunks.

    ``uri`` is the dataset every worker can reach; it is split into
    ``nchunks`` InputSplit parts served as one response frame each.
    ``lease_s``/``dead_after_s`` default through the
    ``DMLC_TPU_DATA_LEASE_S``/``DMLC_TPU_DATA_DEAD_S`` knobs. Expiry is
    scanned on every RPC (workers poll ``lease`` while idle, so a
    dispatcher with any live worker needs no timer thread).

    Like the service it coordinates, a dispatcher is ONE epoch's pass:
    re-create it per epoch, exactly like ``create_parser``."""

    def __init__(
        self,
        uri: str,
        nchunks: Optional[int] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        lease_s: Optional[float] = None,
        dead_after_s: Optional[float] = None,
        data_format: str = "auto",
        plane=None,
    ):
        nchunks = data_chunks(nchunks)
        check(nchunks >= 1, "nchunks must be >= 1, got %d", nchunks)
        self.uri = str(uri)
        self.lease_s = data_lease_s(lease_s)
        self.dead_after_s = data_dead_after_s(dead_after_s)
        self._lock = threading.Lock()
        self._chunks: List[Dict] = [
            {
                "seq": k,
                "uri": self.uri,
                "part": k,
                "nparts": nchunks,
                "format": data_format,
                "state": _QUEUED,
                "worker": -1,
                "client": -1,
                "deadline": 0.0,
                "requeues": 0,
                "flow": 0,
            }
            for k in range(nchunks)
        ]
        self._workers: Dict[int, Dict] = {}
        self._next_worker = 0
        self._next_client = 0
        # client id -> ids of live dispatcher connections that spoke for
        # it. A DELIVERED chunk requeues only when its holder has NO live
        # connection: consumer death is a dropped session, consumer
        # slowness is not — redelivering rows a live consumer already
        # holds would break exactly-once.
        self._client_conns: Dict[int, set] = {}
        # plain-int accounting (truthful under DMLC_TPU_METRICS=0; the
        # registry carries the telemetry mirror)
        self._requeued = 0
        self._acked = 0
        self._rejects = 0
        self._dup_acks = 0
        self._all_acked = threading.Event()
        reg = obs.registry()
        self._m_chunks = reg.counter(
            "dmlc_dispatch_chunks_total",
            "chunks registered for lease-based dispatch")
        self._m_chunks.inc(nchunks)
        self._m_requeued = reg.counter(
            "dmlc_dispatch_requeued_total",
            "chunk leases requeued after expiry or worker death")
        self._m_acked = reg.counter(
            "dmlc_dispatch_acked_total",
            "chunks acked by consumers (the exactly-once frontier)")
        self._m_rejects = reg.counter(
            "dmlc_dispatch_rejects_total",
            "duplicate chunk deliveries refused by the lease table")
        self._g_workers = reg.gauge(
            "dmlc_dispatch_workers_count", "live registered data workers")
        self._g_workers.set(0)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        self._conns: List[socket.socket] = []
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="data-dispatcher")
        self._accept_thread.start()
        if plane is not None:
            self.attach_plane(plane)

    # ---- transport ------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True)
            t.start()
            # prune finished handler threads: a fault storm reconnects
            # DispatcherClients many times per epoch, and the list must
            # not grow one dead entry per reconnect
            self._threads = [
                th for th in self._threads if th.is_alive()] + [t]

    def _serve_conn(self, conn: socket.socket) -> None:
        from dmlc_tpu.resilience import InjectedFault

        self._conns.append(conn)
        bound: set = set()  # client ids this connection spoke for
        try:
            while True:
                try:
                    obj = _recv_msg(conn)
                except (OSError, ValueError):
                    return  # peer gone / garbled — drop the connection
                try:
                    reply = self._handle(obj)
                except InjectedFault:
                    # service.lease fault: kill the connection, exactly
                    # like a dispatcher transport failure — the peer's
                    # DispatcherClient reconnects and retries
                    return
                except Exception as err:  # noqa: BLE001 — relay, don't die
                    reply = {"ok": False,
                             "error": "%s: %s" % (type(err).__name__, err)}
                # liveness binding: any op that names a client id ties
                # that client to this connection for delivered-chunk
                # requeue gating (see _expire_locked)
                try:
                    cid = int(reply.get("client_id", obj.get("client", -1)))
                except (TypeError, ValueError):
                    cid = -1
                if cid >= 0 and cid not in bound:
                    bound.add(cid)
                    with self._lock:
                        self._client_conns.setdefault(cid, set()).add(
                            id(conn))
                try:
                    _send_msg(conn, reply)
                except OSError:
                    return
        finally:
            with self._lock:
                for cid in bound:
                    conns = self._client_conns.get(cid)
                    if conns is not None:
                        conns.discard(id(conn))
                        if not conns:
                            del self._client_conns[cid]
            try:
                self._conns.remove(conn)
            except ValueError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    # ---- the chunk state machine ---------------------------------------

    def _handle(self, obj: Dict) -> Dict:
        op = obj.get("op")
        if op == "register":
            return self._op_register(obj)
        if op == "client":
            with self._lock:
                cid = self._next_client
                self._next_client += 1
            return {"ok": True, "client_id": cid}
        if op == "heartbeat":
            with self._lock:
                w = self._workers.get(int(obj.get("worker", -1)))
                if w is not None and not w["dead"]:
                    w["last_seen"] = time.monotonic()
                self._expire_locked()
            return {"ok": True}
        if op == "lease":
            return self._op_lease(obj)
        if op == "recv":
            return self._op_recv(obj)
        if op == "ack":
            return self._op_ack(obj)
        if op == "workers":
            with self._lock:
                self._expire_locked()
                live = [
                    [w["addr"][0], w["addr"][1], wid]
                    for wid, w in sorted(self._workers.items())
                    if not w["dead"]
                ]
            return {"ok": True, "workers": live}
        if op == "stats":
            return dict(self.snapshot(), ok=True)
        return {"ok": False, "error": "unknown op %r" % (op,)}

    def _op_register(self, obj: Dict) -> Dict:
        raw = obj.get("addr") or ("", 0)
        addr = (str(raw[0]), int(raw[1]))
        with self._lock:
            # idempotent by serving address: register rides the retrying
            # DispatcherClient, so a lost reply re-sends it — minting a
            # fresh id each time would leave an orphan that never
            # heartbeats, later firing a spurious worker_dead and
            # skewing the workers gauge. Only one live worker can hold a
            # host:port, so the live match IS the earlier registration.
            wid = next(
                (known for known, w in self._workers.items()
                 if w["addr"] == addr and not w["dead"] and addr[1]),
                None)
            if wid is not None:
                self._workers[wid]["last_seen"] = time.monotonic()
            else:
                wid = self._next_worker
                self._next_worker += 1
                self._workers[wid] = {
                    "addr": addr,
                    "last_seen": time.monotonic(),
                    "dead": False,
                }
            self._expire_locked()
        return {
            "ok": True,
            "worker_id": wid,
            # workers heartbeat a few times per death threshold so one
            # lost beat never reads as a crash
            "heartbeat_s": max(0.05, self.dead_after_s / 3.0),
        }

    def _op_lease(self, obj: Dict) -> Dict:
        from dmlc_tpu.resilience import faultpoint

        faultpoint("service.lease")
        wid = int(obj.get("worker", -1))
        with self._lock:
            now = time.monotonic()
            w = self._workers.get(wid)
            if w is not None:
                if w["dead"]:
                    # declared dead: its registration is gone; a zombie
                    # must not take leases the table thinks are safe
                    return {"ok": False, "dead": True}
                w["last_seen"] = now
            self._expire_locked()
            chunk = next(
                (c for c in self._chunks if c["state"] == _QUEUED), None)
            if chunk is None:
                # EOF once every chunk is delivered-or-acked: an
                # explicit-ack consumer (DeviceFeed) may hold received
                # rows across many batches before acking, and gating EOF
                # on acks would deadlock it against its own worker. The
                # expiry scan above ran first, so every delivered chunk
                # here is either within its deadline or held by a
                # consumer whose session is still alive; join() still
                # waits for the full ack frontier.
                if all(c["state"] in (_ACKED, _DELIVERED)
                       for c in self._chunks):
                    return {"ok": True, "eof": True}
                # leased chunks may still requeue; the worker polls
                # (each poll doubles as a heartbeat + expiry scan)
                return {"ok": True, "wait": True}
            if not chunk["flow"]:
                # one flow per chunk, minted at FIRST lease and carried
                # through every reassignment — the merged trace's arrow
                # chain then shows every worker that touched the chunk
                chunk["flow"] = obs.new_flow()
                obs.flow_start(chunk["flow"], "chunk")
            chunk["state"] = _LEASED
            chunk["worker"] = wid
            chunk["client"] = -1
            chunk["deadline"] = now + self.lease_s
            return {
                "ok": True,
                "chunk": {
                    "seq": chunk["seq"],
                    "uri": chunk["uri"],
                    "part": chunk["part"],
                    "nparts": chunk["nparts"],
                    "format": chunk["format"],
                    "flow": chunk["flow"],
                },
            }

    def _chunk_locked(self, seq: int) -> Optional[Dict]:
        if 0 <= seq < len(self._chunks):
            return self._chunks[seq]
        return None

    def _op_recv(self, obj: Dict) -> Dict:
        cid = int(obj.get("client", -1))
        seq = int(obj.get("seq", -1))
        with self._lock:
            self._expire_locked()
            c = self._chunk_locked(seq)
            if c is None:
                return {"ok": False, "reject": True,
                        "error": "unknown seq %d" % seq}
            if c["state"] in (_LEASED, _QUEUED):
                # a requeued-but-not-relesed chunk whose original send
                # did land is reclaimed here: the bytes arrived, so this
                # delivery wins and the requeue is undone
                c["state"] = _DELIVERED
                c["client"] = cid
                c["deadline"] = time.monotonic() + self.lease_s
                return {"ok": True}
            if c["state"] == _DELIVERED and c["client"] == cid:
                return {"ok": True}  # same consumer re-reporting (hedge)
            # delivered to someone else or already acked: the reporter
            # must DROP this copy — that is the exactly-once guarantee
            self._rejects += 1
            self._m_rejects.inc()
            return {"ok": True, "reject": True}

    def _op_ack(self, obj: Dict) -> Dict:
        seq = int(obj.get("seq", -1))
        with self._lock:
            self._expire_locked()
            c = self._chunk_locked(seq)
            if c is None:
                return {"ok": False, "error": "unknown seq %d" % seq}
            if c["state"] == _ACKED:
                self._dup_acks += 1
                return {"ok": True, "dup": True}
            # an ack is authoritative from ANY state: the consumer holds
            # the rows, so even a chunk the expiry scan already requeued
            # is done — acking it here is what stops a second serve
            c["state"] = _ACKED
            c["worker"] = -1
            c["deadline"] = 0.0
            self._acked += 1
            self._m_acked.inc()
            if c["flow"]:
                obs.flow_step(c["flow"], "chunk")
            if all(ch["state"] == _ACKED for ch in self._chunks):
                self._all_acked.set()
            return {"ok": True}

    def _expire_locked(self) -> None:
        now = time.monotonic()
        for wid, w in self._workers.items():
            if not w["dead"] and now - w["last_seen"] > self.dead_after_s:
                w["dead"] = True
                record_event("service.worker_dead", worker=wid,
                             addr="%s:%d" % w["addr"])
                log_warning(
                    "data worker %d (%s:%d) declared dead (%.1fs silent)",
                    wid, w["addr"][0], w["addr"][1], now - w["last_seen"])
        for c in self._chunks:
            if c["state"] == _LEASED:
                w = self._workers.get(c["worker"])
                expired = (now > c["deadline"] or w is None or w["dead"])
            elif c["state"] == _DELIVERED:
                # the holder already HAS the rows — requeueing while it
                # is alive would serve them twice. Its dispatcher session
                # is the liveness signal: a crashed consumer drops the
                # TCP connection; a slow one (jit compiles take minutes)
                # keeps it open and keeps the chunk, however long past
                # the deadline. The deadline still applies once the
                # holder is gone.
                expired = (now > c["deadline"]
                           and c["client"] not in self._client_conns)
            else:
                continue
            if not expired:
                continue
            record_event("service.requeue", seq=c["seq"], state=c["state"],
                         worker=c["worker"], client=c["client"],
                         requeues=c["requeues"] + 1)
            c["state"] = _QUEUED
            c["worker"] = -1
            c["client"] = -1
            c["deadline"] = 0.0
            c["requeues"] += 1
            self._requeued += 1
            self._m_requeued.inc()
        self._g_workers.set(
            len([w for w in self._workers.values() if not w["dead"]]))

    # ---- read side ------------------------------------------------------

    def snapshot(self) -> Dict:
        """The live worker/lease/requeue view (the ``/data`` endpoint
        body). Exactly-once invariant at end of epoch:
        ``chunks.acked == chunks.total`` with ``queued == leased ==
        delivered == 0`` and every requeue drained."""
        with self._lock:
            self._expire_locked()
            now = time.monotonic()
            counts = {_QUEUED: 0, _LEASED: 0, _DELIVERED: 0, _ACKED: 0}
            table = []
            for c in self._chunks:
                counts[c["state"]] += 1
                if len(table) < _SNAPSHOT_ROWS:
                    table.append({
                        "seq": c["seq"],
                        "state": c["state"],
                        "worker": c["worker"],
                        "client": c["client"],
                        "requeues": c["requeues"],
                    })
            workers = {
                str(wid): {
                    "addr": "%s:%d" % w["addr"],
                    "live": not w["dead"],
                    "lag_s": round(now - w["last_seen"], 3),
                    "leased": len([
                        c for c in self._chunks
                        if c["state"] == _LEASED and c["worker"] == wid
                    ]),
                }
                for wid, w in sorted(self._workers.items())
            }
        return {
            "chunks": {
                "total": len(self._chunks),
                "queued": counts[_QUEUED],
                "leased": counts[_LEASED],
                "delivered": counts[_DELIVERED],
                "acked": counts[_ACKED],
            },
            "requeued": self._requeued,
            "rejects": self._rejects,
            "duplicate_acks": self._dup_acks,
            "workers": workers,
            "lease_table": table,
        }

    def attach_plane(self, plane) -> None:
        """Expose :meth:`snapshot` as the status plane's ``/data``
        endpoint (``StatusPlane.set_data_provider``)."""
        plane.set_data_provider(self.snapshot)

    def join(self, timeout: Optional[float] = None) -> bool:
        """Block until every chunk is acked (the epoch is complete);
        True on completion, False on timeout."""
        return self._all_acked.wait(timeout)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def dispatcher_address(spec: Union[str, Tuple[str, int]]) -> Tuple[str, int]:
    """Normalize a ``host:port`` string or ``(host, port)`` pair — the
    accepted ``dispatcher=`` argument shapes of BlockService and
    RemoteBlockParser."""
    if isinstance(spec, str):
        host, _, port = spec.rpartition(":")
        check(bool(host) and port.isdigit(),
              "bad dispatcher address %r (want host:port)", spec)
        return host, int(port)
    return str(spec[0]), int(spec[1])
