"""Job-shared source cache: N tenants reading one dataset parse it once.

The tf.data service paper's multi-tenant pitch (PAPERS.md, arXiv
2210.14826) only pays off when concurrent jobs SHARE ingest work — the
single-job cache win is already measured (BENCH_r05: ``sgd_e2e_cached``
~2.6x over uncached), and this module makes it cross-job: a data worker
that parses a chunk keeps the parsed column arrays in a process-wide,
size-bounded LRU keyed by the *source spec digest* — URI, part, nparts,
format and the parser kwargs — so a second job leasing the same part of
the same dataset is served from memory with zero parse work
(``cache_cross_job_hit_ratio`` = 1.0 in the bench tier). The dispatcher
completes the picture with cache-aware lease routing: it remembers which
workers parsed which parts and prefers them on re-serve, so the hit is
not left to luck.

Properties the robustness story needs:

- **Single-flight population.** Concurrent first readers of one key
  elect a leader; followers wait on its event instead of stampeding the
  parse path. A leader that FAILS (parse error, injected
  ``cache.populate`` fault) wakes the followers to re-elect — a crash
  during population never wedges a waiter, and the cache never stores a
  half-parsed entry.
- **Bounded memory.** The byte budget comes from
  ``DMLC_TPU_DATA_CACHE_MB`` (0 disables the tier entirely — every
  parse goes direct); least-recently-used entries evict first, and an
  entry bigger than the whole budget is served uncached rather than
  flushing everything else.
- **Degradation, not failure.** The cache is an accelerator tier: the
  service falls back to a direct parse when population faults, so chaos
  specs against ``cache.populate`` cost performance, never correctness.

Entries are dicts of 1-D numpy arrays (the block-service frame fields,
BEFORE the per-lease ``seq``/``job``/``flow`` tags are applied) and are
shared read-only across jobs — consumers only ever ``tobytes()`` them
onto the wire.

Counters: ``dmlc_source_cache_hits_total`` / ``_misses_total`` /
``_evictions_total`` and the ``dmlc_source_cache_bytes`` gauge; plain-int
mirrors (``hits``/``misses``/``evictions``) stay truthful under
``DMLC_TPU_METRICS=0`` and feed the bench tier's hit-ratio math.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from typing import Callable, Dict, Optional

import numpy as np

from dmlc_tpu import obs
from dmlc_tpu.params.knobs import data_cache_mb


class SourceCache:
    """Process-wide LRU of parsed chunk frames, single-flight populated.

    One instance is shared by every :class:`~dmlc_tpu.data.service.
    BlockService` in the process (see :func:`source_cache`); a dedicated
    instance with its own ``cap_bytes`` is constructible for tests."""

    def __init__(self, cap_bytes: Optional[int] = None):
        self._cap = (data_cache_mb() << 20) if cap_bytes is None \
            else max(0, int(cap_bytes))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Dict[str, np.ndarray]]" = \
            OrderedDict()
        self._bytes = 0
        # key -> population-in-progress event (single-flight election)
        self._inflight: Dict[str, threading.Event] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        reg = obs.registry()
        self._m_hits = reg.counter(
            "dmlc_source_cache_hits_total",
            "chunk parses skipped: the parsed frame was cache-resident")
        self._m_misses = reg.counter(
            "dmlc_source_cache_misses_total",
            "chunk frames parsed and admitted to the source cache")
        self._m_evictions = reg.counter(
            "dmlc_source_cache_evictions_total",
            "cached chunk frames evicted by the LRU byte budget")
        self._g_bytes = reg.gauge(
            "dmlc_source_cache_bytes",
            "bytes of parsed chunk frames resident in the source cache")

    @property
    def enabled(self) -> bool:
        """False when ``DMLC_TPU_DATA_CACHE_MB=0`` disabled the tier."""
        return self._cap > 0

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @staticmethod
    def chunk_key(uri: str, part: int, nparts: int,
                  data_format: str = "auto",
                  parser_kwargs: Optional[Dict] = None) -> str:
        """Digest of the full source spec. Two jobs share an entry ONLY
        when every input that could change the parsed bytes matches —
        same URI, same split geometry, same declared format, same parser
        kwargs — so a cache hit is bit-identical to a fresh parse by
        construction. Baked shards fold in a content token (format
        version + per-file footer crc32 + the armed shuffle seed/window,
        io/shard.py ``cache_token``): the URI of a re-baked or re-seeded
        shard no longer names the same parsed bytes, so it must not hit
        the old entry."""
        from dmlc_tpu.io import shard

        spec = json.dumps(
            [str(uri), int(part), int(nparts), str(data_format),
             sorted((parser_kwargs or {}).items()),
             shard.cache_token(uri, str(data_format))],
            sort_keys=True, default=repr)
        return hashlib.sha256(spec.encode()).hexdigest()

    def get_or_populate(
        self,
        key: str,
        populate: Callable[[], Dict[str, np.ndarray]],
    ) -> Dict[str, np.ndarray]:
        """Return the cached frame for ``key``, parsing at most once.

        The first caller of a key becomes the population leader (the
        ``cache.populate`` chaos site fires on its path); concurrent
        callers block until the leader finishes and then read the entry.
        A leader failure propagates to the leader AND wakes the
        followers, which re-elect and retry — so an injected fault
        delays followers by one election, never deadlocks them. The
        returned dict is SHARED: treat it read-only."""
        from dmlc_tpu.resilience import faultpoint

        while True:
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    self._m_hits.inc()
                    return entry
                event = self._inflight.get(key)
                if event is None:
                    event = threading.Event()
                    self._inflight[key] = event
                    leader = True
                else:
                    leader = False
            if not leader:
                event.wait()
                continue  # cache hit now — or re-elect if the leader died
            try:
                faultpoint("cache.populate")
                fields = populate()
            except BaseException:
                with self._lock:
                    self._inflight.pop(key, None)
                event.set()  # wake followers to re-elect
                raise
            with self._lock:
                self.misses += 1
                self._m_misses.inc()
                self._store_locked(key, fields)
                self._inflight.pop(key, None)
            event.set()
            return fields

    def _store_locked(self, key: str,
                      fields: Dict[str, np.ndarray]) -> None:
        nbytes = sum(int(a.nbytes) for a in fields.values())
        if nbytes > self._cap:
            # bigger than the whole budget: serving it uncached beats
            # flushing every other tenant's working set for one entry
            return
        old = self._entries.pop(key, None)
        if old is not None:
            self._bytes -= sum(int(a.nbytes) for a in old.values())
        self._entries[key] = fields
        self._bytes += nbytes
        while self._bytes > self._cap and len(self._entries) > 1:
            _, victim = self._entries.popitem(last=False)
            self._bytes -= sum(int(a.nbytes) for a in victim.values())
            self.evictions += 1
            self._m_evictions.inc()
        self._g_bytes.set(self._bytes)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._g_bytes.set(0)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


_CACHE: Optional[SourceCache] = None
_CACHE_LOCK = threading.Lock()


def source_cache() -> SourceCache:
    """The process-wide shared cache (lazily built so the byte budget
    reads ``DMLC_TPU_DATA_CACHE_MB`` at first use, not import time)."""
    global _CACHE
    with _CACHE_LOCK:
        if _CACHE is None:
            _CACHE = SourceCache()
        return _CACHE


def reset_source_cache() -> None:
    """Drop the shared cache (tests re-knob the budget between cases)."""
    global _CACHE
    with _CACHE_LOCK:
        if _CACHE is not None:
            _CACHE.clear()
        _CACHE = None
