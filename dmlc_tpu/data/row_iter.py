"""Re-iterable RowBlock iterators: in-memory and external-memory.

Capability parity with src/data/basic_row_iter.h (whole dataset in one
in-memory RowBlock, MB/s progress logging :61-82), src/data/disk_row_iter.h
(64MB page spill to a cache file on first pass, ThreadedIter page replay per
epoch :32,95-141) and the RowBlockIter::Create factory (data.h:260,
src/data.cc:87-128 — cache file present selects the disk iterator).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

from dmlc_tpu.data.parsers import Parser, create_parser
from dmlc_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_tpu.io.filesystem import create_stream
from dmlc_tpu.io.uri_spec import URISpec
from dmlc_tpu.utils.logging import DMLCError, check, log_info
from dmlc_tpu.utils.threaded_iter import ThreadedIter
from dmlc_tpu.utils.timer import get_time

# 64 MB page (disk_row_iter.h:32)
PAGE_BYTES = 64 << 20


class RowBlockIter:
    """Re-iterable data iterator interface (data.h:232-260)."""

    def before_first(self) -> None:
        raise NotImplementedError

    def next_block(self) -> Optional[RowBlock]:
        raise NotImplementedError

    def num_col(self) -> int:
        raise NotImplementedError

    def __iter__(self) -> Iterator[RowBlock]:
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block


class BasicRowIter(RowBlockIter):
    """Load the whole partition into memory once (basic_row_iter.h)."""

    def __init__(self, parser: Parser):
        start = get_time()
        container = RowBlockContainer()
        bytes_seen = 0
        last_log = 0
        for block in parser:
            container.push_block(block)
            bytes_seen = parser.bytes_read
            if bytes_seen - last_log >= (10 << 20):  # log every 10MB (:66-75)
                elapsed = get_time() - start
                log_info(
                    "BasicRowIter: read %.1f MB at %.2f MB/sec",
                    bytes_seen / 1e6,
                    bytes_seen / 1e6 / max(elapsed, 1e-9),
                )
                last_log = bytes_seen
        parser.close()
        self._block = container.to_block()
        self._done = False
        elapsed = get_time() - start
        log_info(
            "BasicRowIter: loaded %d rows, %.1f MB in %.2f sec",
            len(self._block),
            bytes_seen / 1e6,
            elapsed,
        )

    def before_first(self) -> None:
        self._done = False

    def next_block(self) -> Optional[RowBlock]:
        if self._done:
            return None
        self._done = True
        return self._block

    def num_col(self) -> int:
        return self._block.num_col()


class DiskRowIter(RowBlockIter):
    """External-memory iterator: spill 64MB CSR pages to a cache file on the
    first pass, stream pages back with prefetch on later epochs
    (disk_row_iter.h:95-141)."""

    def __init__(
        self,
        parser,
        cache_file: str,
        reuse_cache: bool = True,
    ):
        """``parser`` may be a Parser or a zero-arg callable returning one —
        the callable form defers (and skips) parser construction entirely
        when a warm cache makes the input pass unnecessary."""
        self._cache_file = cache_file
        self._num_col = 0
        if reuse_cache and os.path.exists(cache_file):
            if not self._try_load_cache():
                raise DMLCError(f"invalid cache file {cache_file!r}")
        else:
            check(parser is not None, "parser required to build cache")
            if callable(parser):
                parser = parser()
            self._build_cache(parser)
            check(self._try_load_cache(), "cache build failed")
        self._iter = ThreadedIter(self._page_source, max_capacity=4, name="disk-row-iter")

    def _build_cache(self, parser: Parser) -> None:
        start = get_time()
        bytes_out = 0
        with create_stream(self._cache_file + ".tmp", "w") as out:
            container = RowBlockContainer()
            npages = 0
            for block in parser:
                container.push_block(block)
                self._num_col = max(self._num_col, block.num_col())
                if container.mem_cost_bytes() >= PAGE_BYTES:
                    container.save(out)
                    npages += 1
                    bytes_out += container.mem_cost_bytes()
                    container.clear()
            if len(container):
                container.save(out)
                npages += 1
                bytes_out += container.mem_cost_bytes()
            # trailer: num_col metadata
        parser.close()
        os.replace(self._cache_file + ".tmp", self._cache_file)
        with create_stream(self._cache_file + ".meta", "w") as meta:
            meta.write_uint64(self._num_col)
        log_info(
            "DiskRowIter: cached %d pages (%.1f MB) in %.2f sec",
            npages,
            bytes_out / 1e6,
            get_time() - start,
        )

    def _try_load_cache(self) -> bool:
        if not os.path.exists(self._cache_file):
            return False
        meta_path = self._cache_file + ".meta"
        if os.path.exists(meta_path):
            with create_stream(meta_path, "r") as meta:
                self._num_col = meta.read_uint64()
        return True

    def _page_source(self) -> Iterator[RowBlock]:
        with create_stream(self._cache_file, "r") as stream:
            while True:
                try:
                    container = RowBlockContainer.load(stream)
                except EOFError:
                    return
                yield container.to_block()

    def before_first(self) -> None:
        self._iter.before_first()

    def next_block(self) -> Optional[RowBlock]:
        return self._iter.next()

    def num_col(self) -> int:
        return self._num_col

    def close(self) -> None:
        self._iter.close()


def create_row_block_iter(
    uri: str,
    part_index: int = 0,
    num_parts: int = 1,
    data_format: str = "auto",
    nthread: Optional[int] = None,
) -> RowBlockIter:
    """RowBlockIter<I>::Create (src/data.cc:87-128): a ``#cachefile`` suffix
    selects DiskRowIter (external memory), else BasicRowIter (in memory).

    ``nthread=None`` defers to the ``DMLC_TPU_NTHREAD`` env knob
    (params.knobs) inside create_parser."""
    spec = URISpec(uri, part_index, num_parts)

    def make_parser():
        return create_parser(
            spec.uri if not spec.args else uri.split("#")[0],
            part_index,
            num_parts,
            data_format,
            nthread,
        )

    if spec.cache_file:
        # Lazy: with a warm cache DiskRowIter never builds (or leaks) the
        # parser and its prefetch threads.
        return DiskRowIter(make_parser, spec.cache_file)
    return BasicRowIter(make_parser())
