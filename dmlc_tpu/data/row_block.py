"""CSR row batches.

Capability parity with include/dmlc/data.h + src/data/row_block.h:

- ``RowBlock``: a CSR batch {offset[n+1], label[n], optional weight[n],
  optional qid[n], optional field[nnz], index[nnz], optional value[nnz]}
  (data.h:170-230). A missing ``value`` means "all ones" and a missing
  ``weight`` means "all 1.0", exactly like the reference's NULL pointers
  (data.h:120-158).
- ``Row``: a zero-copy view of one row with ``sdot``/dot helpers
  (data.h:70-158).
- ``RowBlockContainer``: growable builder with push/merge and binary
  Save/Load over a Stream — the cache-file page format (row_block.h:26-215).

Arrays are numpy (the host twin); ``dmlc_tpu.device`` lifts them into padded
static-shape XLA buffers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from dmlc_tpu.io.stream import Stream
from dmlc_tpu.io.serializer import load_obj, save_obj
from dmlc_tpu.utils.logging import check, check_eq

# reference data.h:23-29: real_t = float, index_t = unsigned (u64 variant
# instantiated too, src/data.cc:112-147)
REAL_DTYPE = np.float32
INDEX_DTYPE = np.uint32


@dataclass
class Row:
    """One sparse row view (data.h:70-158)."""

    label: float
    index: np.ndarray
    value: Optional[np.ndarray] = None
    weight: float = 1.0
    qid: Optional[int] = None
    field: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return len(self.index)

    def get_value(self, i: int) -> float:
        """value == NULL means 1 (data.h:146-151)."""
        return 1.0 if self.value is None else float(self.value[i])

    def sdot(self, weight: np.ndarray) -> float:
        """Sparse dot with a dense vector (data.h:152-158)."""
        if self.value is None:
            return float(weight[self.index].sum())
        return float(weight[self.index] @ self.value)


class RowBlock:
    """Immutable CSR batch (data.h:170-230)."""

    def __init__(
        self,
        offset: np.ndarray,
        label: np.ndarray,
        index: np.ndarray,
        value: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        qid: Optional[np.ndarray] = None,
        field: Optional[np.ndarray] = None,
    ):
        self.offset = np.asarray(offset, dtype=np.int64)
        self.label = np.asarray(label, dtype=REAL_DTYPE)
        self.index = np.asarray(index)
        self.value = None if value is None else np.asarray(value, dtype=REAL_DTYPE)
        self.weight = None if weight is None else np.asarray(weight, dtype=REAL_DTYPE)
        self.qid = None if qid is None else np.asarray(qid, dtype=np.int64)
        self.field = None if field is None else np.asarray(field)
        check_eq(len(self.offset), len(self.label) + 1, "offset/label mismatch")
        if len(self.offset):
            check_eq(int(self.offset[-1]), len(self.index), "offset/index mismatch")

    def __len__(self) -> int:
        return len(self.label)

    @property
    def size(self) -> int:
        return len(self.label)

    @property
    def num_nonzero(self) -> int:
        return len(self.index)

    def __getitem__(self, i: int) -> Row:
        """Zero-copy row view (data.h:354-383)."""
        lo, hi = int(self.offset[i]), int(self.offset[i + 1])
        return Row(
            label=float(self.label[i]),
            index=self.index[lo:hi],
            value=None if self.value is None else self.value[lo:hi],
            weight=1.0 if self.weight is None else float(self.weight[i]),
            qid=None if self.qid is None else int(self.qid[i]),
            field=None if self.field is None else self.field[lo:hi],
        )

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def slice(self, begin: int, end: int) -> "RowBlock":
        """Sub-range view sharing data (data.h:210-230)."""
        check(0 <= begin <= end <= len(self), "bad slice range")
        lo, hi = int(self.offset[begin]), int(self.offset[end])
        return RowBlock(
            offset=self.offset[begin : end + 1] - lo,
            label=self.label[begin:end],
            index=self.index[lo:hi],
            value=None if self.value is None else self.value[lo:hi],
            weight=None if self.weight is None else self.weight[begin:end],
            qid=None if self.qid is None else self.qid[begin:end],
            field=None if self.field is None else self.field[lo:hi],
        )

    def mem_cost_bytes(self) -> int:
        """Approximate memory cost (data.h:194-208)."""
        cost = self.offset.nbytes + self.label.nbytes + self.index.nbytes
        for arr in (self.value, self.weight, self.qid, self.field):
            if arr is not None:
                cost += arr.nbytes
        return cost

    def audit_arrays(self):
        """Canonical field-major array stream for the determinism-audit
        digest (obs/audit.py): ``[(tag, [array, ...]), ...]``.

        The stream is defined over the block's *logical* content — per-row
        lengths instead of cumulative offsets (slice-rebase invariant),
        and the reference's NULL-pointer defaults materialized (missing
        value/weight → ones, missing qid → zeros, data.h:120-158) — so a
        :class:`RowBlockContainer` hashes byte-identically to the
        ``to_block()`` it would produce, and two pipelines that deliver
        the same rows digest equal no matter how the rows were chunked,
        sliced, or which parse backend produced them."""
        n = len(self.label)
        nnz = len(self.index)
        out = [
            (b"label", [self.label]),
            (b"counts", [np.diff(self.offset)]),
            (b"index", [self.index]),
            (b"value", [np.ones(nnz, dtype=REAL_DTYPE)
                        if self.value is None else self.value]),
            (b"weight", [np.ones(n, dtype=REAL_DTYPE)
                         if self.weight is None else self.weight]),
            (b"qid", [np.zeros(n, dtype=np.int64)
                      if self.qid is None else self.qid]),
        ]
        if self.field is not None:
            out.append((b"field", [self.field]))
        return out

    def num_col(self) -> int:
        """max feature index + 1 (basic_row_iter.h:46)."""
        return int(self.index.max()) + 1 if len(self.index) else 0

    def to_dense(self, num_col: Optional[int] = None) -> np.ndarray:
        """Densify (TPU-new convenience for tests/small data)."""
        ncol = num_col if num_col is not None else self.num_col()
        out = np.zeros((len(self), ncol), dtype=REAL_DTYPE)
        values = (
            np.ones(len(self.index), dtype=REAL_DTYPE)
            if self.value is None
            else self.value
        )
        rows = np.repeat(np.arange(len(self)), np.diff(self.offset))
        out[rows, self.index] = values
        return out


class RowBlockContainer:
    """Growable CSR builder (src/data/row_block.h:26-215).

    Internals are lists of numpy array *parts* concatenated once at
    ``to_block`` — pushes are O(1) appends with no Python-object conversion
    (the host ingest hot path runs through here; list-of-float accumulation
    was the original bottleneck). weight/qid/value follow an any-present
    policy: omitted entries get neutral defaults (1.0 / 0 / ones) rather than
    being silently dropped (the reference CHECK-fails on count mismatch,
    row_block.h GetBlock).
    """

    def __init__(self, index_dtype=INDEX_DTYPE):
        self.index_dtype = index_dtype
        self.clear()

    def clear(self) -> None:
        self._count_parts: List[np.ndarray] = []
        self._label_parts: List[np.ndarray] = []
        self._weight_parts: List[Optional[np.ndarray]] = []
        self._qid_parts: List[Optional[np.ndarray]] = []
        self._index_parts: List[np.ndarray] = []
        self._value_parts: List[Optional[np.ndarray]] = []
        self._field_parts: List[Optional[np.ndarray]] = []
        self._any_weight = False
        self._any_qid = False
        self._any_value = False
        self.max_index = 0
        self._nrows = 0
        self._nnz = 0

    @property
    def size(self) -> int:
        return self._nrows

    def __len__(self) -> int:
        return self.size

    def push_row(
        self,
        label: float,
        index: Sequence[int],
        value: Optional[Sequence[float]] = None,
        weight: Optional[float] = None,
        qid: Optional[int] = None,
        field: Optional[Sequence[int]] = None,
    ) -> None:
        self.push_arrays(
            np.asarray([label], dtype=REAL_DTYPE),
            np.asarray([len(index)], dtype=np.int64),
            np.asarray(index, dtype=self.index_dtype),
            value=None if value is None else np.asarray(value, dtype=REAL_DTYPE),
            weight=None if weight is None else np.asarray([weight], dtype=REAL_DTYPE),
            qid=None if qid is None else np.asarray([qid], dtype=np.int64),
            field=None if field is None else np.asarray(field),
        )

    def push_arrays(
        self,
        labels: np.ndarray,
        counts: np.ndarray,
        index: np.ndarray,
        value: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        qid: Optional[np.ndarray] = None,
        field: Optional[np.ndarray] = None,
    ) -> None:
        """Bulk append many rows at once (the vectorized parser path)."""
        check_eq(len(labels), len(counts), "labels/counts mismatch")
        if weight is not None:
            check_eq(len(weight), len(labels), "weight/labels mismatch")
            self._any_weight = True
        if qid is not None:
            check_eq(len(qid), len(labels), "qid/labels mismatch")
            self._any_qid = True
        self._any_value = self._any_value or value is not None
        self._label_parts.append(np.asarray(labels, dtype=REAL_DTYPE))
        self._count_parts.append(np.asarray(counts, dtype=np.int64))
        self._weight_parts.append(weight)
        self._qid_parts.append(qid)
        idx = np.asarray(index, dtype=self.index_dtype)
        if len(idx):
            self.max_index = max(self.max_index, int(idx.max()))
        self._index_parts.append(idx)
        self._value_parts.append(
            None if value is None else np.asarray(value, dtype=REAL_DTYPE)
        )
        self._field_parts.append(None if field is None else np.asarray(field))
        self._nrows += len(labels)
        self._nnz += len(idx)

    def push_block(self, block: RowBlock) -> None:
        """Append a whole RowBlock (row_block.h Push(RowBlock))."""
        counts = np.diff(block.offset)
        self.push_arrays(
            block.label,
            counts,
            block.index,
            value=block.value,
            weight=block.weight,
            qid=block.qid,
            field=block.field,
        )

    @staticmethod
    def _cat(parts, empty_dtype):
        """Concatenate parts, returning the lone part itself when there is
        exactly one — the whole-chunk vectorized parser pushes once, so the
        common case hands its arrays to the RowBlock without a copy (parts
        are append-only and never mutated after push, so sharing is safe)."""
        if len(parts) == 1:
            return parts[0]
        if not parts:
            return np.empty(0, dtype=empty_dtype)
        return np.concatenate(parts)

    def to_block(self) -> RowBlock:
        """Finalize into a RowBlock view (row_block.h GetBlock :169-188)."""
        nrows = self._nrows
        counts = self._cat(self._count_parts, np.int64)
        offset = np.zeros(nrows + 1, dtype=np.int64)
        np.cumsum(counts, out=offset[1:])
        index = self._cat(self._index_parts, self.index_dtype)
        label = self._cat(self._label_parts, REAL_DTYPE)
        # optional arrays: fill neutral defaults for parts that omitted them
        value = None
        if self._any_value:
            value = self._cat(
                [
                    np.ones(len(idx), dtype=REAL_DTYPE) if v is None else v
                    for v, idx in zip(self._value_parts, self._index_parts)
                ],
                REAL_DTYPE,
            )
        fields_present = [f for f in self._field_parts if f is not None]
        field = self._cat(fields_present, INDEX_DTYPE) if fields_present else None
        weight = None
        if self._any_weight and nrows:
            weight = self._cat(
                [
                    np.ones(len(lbl), dtype=REAL_DTYPE) if w is None else w
                    for w, lbl in zip(self._weight_parts, self._label_parts)
                ],
                REAL_DTYPE,
            )
        qid = None
        if self._any_qid and nrows:
            qid = self._cat(
                [
                    np.zeros(len(lbl), dtype=np.int64) if q is None else q
                    for q, lbl in zip(self._qid_parts, self._label_parts)
                ],
                np.int64,
            )
        return RowBlock(
            offset=offset,
            label=label,
            index=index,
            value=value,
            weight=weight,
            qid=qid,
            field=field,
        )

    @property
    def num_nonzero(self) -> int:
        return self._nnz

    def audit_arrays(self):
        """The container twin of :meth:`RowBlock.audit_arrays`: the same
        canonical stream walked part-by-part, *without* materializing
        ``to_block``'s concatenation — field-major over parts, neutral
        defaults filled per part. Concatenation-invariance of the hash
        (parts are hashed back to back within a field) makes this
        byte-identical to ``self.to_block().audit_arrays()``, which is
        what lets the device-resident feed digest its pending container
        while the legacy feed digests the sliced block, and still agree."""
        out = [
            (b"label", list(self._label_parts)),
            (b"counts", list(self._count_parts)),
            (b"index", list(self._index_parts)),
            (b"value", [
                np.ones(len(idx), dtype=REAL_DTYPE) if v is None else v
                for v, idx in zip(self._value_parts, self._index_parts)
            ]),
            (b"weight", [
                np.ones(len(lbl), dtype=REAL_DTYPE) if w is None else w
                for w, lbl in zip(self._weight_parts, self._label_parts)
            ]),
            (b"qid", [
                np.zeros(len(lbl), dtype=np.int64) if q is None else q
                for q, lbl in zip(self._qid_parts, self._label_parts)
            ]),
        ]
        fields_present = [f for f in self._field_parts if f is not None]
        if fields_present:
            out.append((b"field", fields_present))
        return out

    def emit_csr_into(
        self,
        labels: np.ndarray,
        weights: np.ndarray,
        indices: np.ndarray,
        values: np.ndarray,
        offsets: np.ndarray,
    ) -> tuple:
        """Write the accumulated rows straight into caller-provided CSR
        staging arrays, skipping ``to_block``'s materialization.

        This is the device-resident fast path's single copy: each pushed
        part lands sequentially in the (pre-sized, typically pooled)
        destination arrays — no intermediate concatenate, no second pad
        copy. ``offsets`` must have room for ``size + 1`` entries and is
        written rebased to 0; missing per-part weight/value arrays emit
        the neutral 1.0 defaults ``to_block`` would have filled. Returns
        ``(nrows, nnz)`` actually written; the caller owns zeroing any
        pad tail beyond them. qid/field do not ride the device batch and
        are intentionally not emitted.
        """
        check(len(labels) >= self._nrows, "labels staging too small")
        check(len(offsets) >= self._nrows + 1, "offsets staging too small")
        check(len(indices) >= self._nnz, "indices staging too small")
        row = 0
        ent = 0
        offsets[0] = 0
        for i, lbl in enumerate(self._label_parts):
            n = len(lbl)
            idx = self._index_parts[i]
            m = len(idx)
            labels[row : row + n] = lbl
            w = self._weight_parts[i]
            weights[row : row + n] = 1.0 if w is None else w
            if n:
                offsets[row + 1 : row + n + 1] = ent + np.cumsum(
                    self._count_parts[i]
                )
            indices[ent : ent + m] = idx
            v = self._value_parts[i]
            values[ent : ent + m] = 1.0 if v is None else v
            row += n
            ent += m
        check_eq(row, self._nrows, "emit_csr_into row count drift")
        check_eq(ent, self._nnz, "emit_csr_into nnz drift")
        return row, ent

    def emit_dense_into(
        self,
        x: np.ndarray,
        labels: np.ndarray,
        weights: np.ndarray,
    ) -> int:
        """Scatter the accumulated rows straight into a caller-provided
        (pre-zeroed) dense ``[batch, num_features]`` array — the dense
        twin of :meth:`emit_csr_into`, fusing ``to_block`` +
        ``device/csr.block_to_dense`` into one pass over the parts.
        Out-of-range feature ids are dropped, matching ``block_to_dense``.
        Returns the row count written; the caller owns the pad tail."""
        check(len(labels) >= self._nrows, "labels staging too small")
        check(x.shape[0] >= self._nrows, "dense staging too small")
        num_features = x.shape[1]
        row = 0
        for i, lbl in enumerate(self._label_parts):
            n = len(lbl)
            labels[row : row + n] = lbl
            w = self._weight_parts[i]
            weights[row : row + n] = 1.0 if w is None else w
            idx = self._index_parts[i]
            rows = row + np.repeat(
                np.arange(n, dtype=np.int64), self._count_parts[i]
            )
            v = self._value_parts[i]
            vals = (
                np.ones(len(idx), dtype=REAL_DTYPE) if v is None
                else v
            )
            keep = idx < num_features
            x[rows[keep], idx[keep]] = vals[keep]
            row += n
        check_eq(row, self._nrows, "emit_dense_into row count drift")
        return row

    # ---- binary page format (row_block.h:189-215) ----------------------
    def save(self, stream: Stream) -> None:
        block = self.to_block()
        save_obj(
            stream,
            {
                "offset": block.offset,
                "label": block.label,
                "index": block.index,
                "value": block.value,
                "weight": block.weight,
                "qid": block.qid,
                "field": block.field,
                "max_index": self.max_index,
            },
        )

    @classmethod
    def load(cls, stream: Stream) -> "RowBlockContainer":
        payload = load_obj(stream)
        out = cls()
        block = RowBlock(
            offset=payload["offset"],
            label=payload["label"],
            index=payload["index"],
            value=payload["value"],
            weight=payload["weight"],
            qid=payload["qid"],
            field=payload["field"],
        )
        out.push_block(block)
        out.max_index = int(payload["max_index"])
        return out

    def mem_cost_bytes(self) -> int:
        """Incremental size estimate of the finalized block — O(1), no
        materialization (data.h MemCostBytes:194-208)."""
        nrows = self._nrows
        idx_item = np.dtype(self.index_dtype).itemsize
        cost = (nrows + 1) * 8 + nrows * 4 + self._nnz * idx_item
        if self._any_value:
            cost += self._nnz * 4
        if self._any_weight:
            cost += nrows * 4
        if self._any_qid:
            cost += nrows * 8
        if any(f is not None for f in self._field_parts):
            cost += self._nnz * idx_item
        return cost
