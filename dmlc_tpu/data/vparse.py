"""Vectorized text parse: whole-chunk byte tokenization, columnar output.

The scalar Python parsers (data/parsers.py ``_parse_general`` and the csv
line loop) materialize one Python object per line and per token — at GB/s
targets the interpreter dominates the cost. This module restructures the
same grammar the way the native AVX2 engine (cpp/parse_simd.cc) does, but
in numpy, so the pure-Python stack keeps a vectorized hot path when the
native library is unavailable (non-x86 hosts, sandboxed builds):

1. **Tokenize the whole chunk at once.** ``np.frombuffer`` views the
   chunk as a ``uint8`` array; separator classification is a handful of
   fused compares over the whole chunk, and token start/end offset arrays
   fall out of shifted boolean masks (``flatnonzero`` on the sep→nonsep
   boundaries). No per-line Python objects exist anywhere in the token
   path.

2. **Convert grouped by width.** Tokens of equal byte length gather into
   an exact-width ``(n, l)`` matrix via a sliding-window row take (5×
   faster than an index-matrix gather), digits become an int mantissa via
   one BLAS gemv against a power-of-ten vector, and one correctly-rounded
   divide by 10^decimals lands the float — bit-identical to strtod while
   the mantissa is exact in float64 (< 2^53), the same argument the
   native engine's convert tile rests on. Exponents, inf/nan, over-long
   mantissas fall back per-token to numpy's bytes→float64 ``astype``,
   which matches ``float()`` exactly (including ValueError on junk).

3. **Assemble columnar.** Token roles (label / weight / index / value /
   bare index) are boolean masks derived from "is the byte after the
   token a ':'" plus adjacency; per-row feature offsets come from
   ``searchsorted`` over the token/row boundary arrays (this host runs
   ``np.cumsum`` at 0.08 G/s — boundary searches are ~100× cheaper); the
   finished columns go to ``RowBlockContainer.push_arrays`` in one
   zero-copy push per contiguous run of clean rows.

Anything outside the vectorized grammar — ``qid:`` groups, ``1:2:3``
shapes, over-long tokens — flags its ROW, and flagged rows are re-parsed
by the scalar line parser (:func:`parse_libsvm_line`, the single source
of truth) spliced in order between the columnar runs. Orphan colons
(colon preceded by a separator: the scalar path materializes a ``b":"``
token and raises) punt the whole chunk to the scalar path — they cannot
occur in well-formed data. The randomized parity suite
(tests/test_parse_parity.py) holds every path byte-identical over
adversarial corpora.

Backend selection lives in data/parsers.py behind the
``DMLC_TPU_PARSE_BACKEND`` knob (auto | native | vector | scalar).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
from numpy.lib.stride_tricks import sliding_window_view

from dmlc_tpu.data.row_block import (
    INDEX_DTYPE,
    REAL_DTYPE,
    RowBlockContainer,
)

_NL = 0x0A
_CR = 0x0D
_COLON = 0x3A

# tokens longer than this route their row to the scalar parser: the gather
# matrix is (ntok, l) bytes, so l must stay bounded for pathological input
# (float() handles thousand-digit literals; the matrix should not)
_MAX_TOKEN = 48

# fast mantissa/divide conversion needs every 10^k involved exact in
# float64 (true up to 10^22); wider tokens convert via astype
_MAX_FAST_LEN = 17

_POW10 = 10.0 ** np.arange(_MAX_FAST_LEN + 1)
_TWO53 = float(1 << 53)


# ---------------------------------------------------------------------------
# token → float64 conversion
# ---------------------------------------------------------------------------


def _astype_convert(mat: np.ndarray, out: np.ndarray,
                    ix: np.ndarray) -> None:
    """Per-token conversion through numpy's bytes→float64 astype — the
    same parsing (and ValueError behavior) as ``float()``."""
    out[ix] = (
        np.ascontiguousarray(mat).view("S%d" % mat.shape[1])
        .ravel().astype(np.float64)
    )


def _convert_group_general(mat: np.ndarray, out: np.ndarray,
                           ix: np.ndarray) -> None:
    """Per-token fast/slow split for width groups with mixed byte layouts
    (adversarial corpora; real datasets take the uniform-column path).

    Builds per-token validity and dot position from (n, l) matrices, then
    converts valid tokens per dot-position subgroup with the same exact
    mantissa/divide scheme as the uniform path.
    """
    n, l = mat.shape
    if l > _MAX_FAST_LEN:
        _astype_convert(mat, out, ix)
        return
    F = mat.astype(np.float64)
    D = F - 48.0
    isd = (D >= 0.0) & (D <= 9.0)
    isdot = D == -2.0
    c0 = mat[:, 0]
    neg = c0 == 0x2D
    sgn = neg | (c0 == 0x2B)
    ones = np.ones(l)
    nbad = (~(isd | isdot)).astype(np.float64) @ ones
    If = isdot.astype(np.float64)
    ndot = If @ ones
    psum = If @ np.arange(l, dtype=np.float64)
    valid = (nbad - sgn <= 0.0) & (ndot <= 1.0) & (ndot + nbad < l)
    done = np.zeros(n, dtype=bool)
    if valid.any():
        Dd = np.where(isd, D, 0.0)
        p = np.where(ndot == 1.0, psum, -1.0)
        for pv in np.unique(p[valid]):
            pvi = int(pv)
            e = l - 1 - np.arange(l)
            if pvi >= 0:
                e = e - (np.arange(l) < pvi)
            sub = valid & (p == pv)
            mant = Dd[sub] @ _POW10[e]
            ok = mant < _TWO53
            d = l - 1 - pvi if pvi >= 0 else 0
            val = mant / _POW10[d] if d > 0 else mant
            nsub = neg[sub]
            val[nsub] = -val[nsub]
            six = np.flatnonzero(sub)[ok]
            out[ix[six]] = val[ok]
            done[six] = True
    slow = np.flatnonzero(~done)
    if slow.size:
        _astype_convert(mat[slow], out, ix[slow])


def _convert_group(mat: np.ndarray, out: np.ndarray, ix: np.ndarray) -> None:
    """Convert one equal-width (n, l) byte matrix of tokens into out[ix].

    Fast path: classify COLUMNS, not tokens. Fixed-format numeric data
    ("0.655750", 6-digit ids) puts the dot/sign/digit layout in the same
    byte position for every token of a given width, so a handful of tiny
    per-column ``.all()`` checks prove the whole group well-formed and
    the mantissa accumulates column-by-column — never materializing an
    (n, l) float64 matrix (the memory traffic that sinks the per-token
    variant). Digits weight 10^(l-1-j), one power less left of the dot;
    mantissa and 10^decimals are both exact in float64 (mantissa checked
    < 2^53, powers exact to 10^22; partial sums are nonnegative integers
    bounded by the final mantissa, so any accumulation order is exact),
    and the single correctly-rounded divide reproduces strtod
    bit-for-bit. Groups with mixed layouts fall back per-token.
    """
    n, l = mat.shape
    if l > _MAX_FAST_LEN:
        _astype_convert(mat, out, ix)
        return
    du = mat - np.uint8(48)  # digit→0..9, '.'→254, '-'→253, '+'→251
    cls = []
    for j in range(l):
        cj = du[:, j]
        if bool((cj < 10).all()):
            cls.append("d")
            continue
        if bool((cj == 254).all()):
            cls.append(".")
            continue
        if j == 0 and bool((cj == 253).all()):
            cls.append("-")
            continue
        if j == 0 and bool((cj == 251).all()):
            cls.append("+")
            continue
        cls = None
        break
    if cls is None or cls.count(".") > 1 or "d" not in cls:
        _convert_group_general(mat, out, ix)
        return
    p = cls.index(".") if "." in cls else -1
    mant = np.zeros(n, dtype=np.float64)
    for j, c in enumerate(cls):
        if c != "d":
            continue
        e = l - 1 - j - (1 if 0 <= p and j < p else 0)
        mant += du[:, j].astype(np.float64) * _POW10[e]
    d = l - 1 - p if p >= 0 else 0
    val = mant / _POW10[d] if d > 0 else mant
    if cls[0] == "-":
        val = -val
    exact = mant < _TWO53
    if exact.all():
        out[ix] = val
        return
    out[ix[exact]] = val[exact]
    rest = ~exact
    _astype_convert(mat[rest], out, ix[rest])


def _gather_floats(a: np.ndarray, starts: np.ndarray,
                   lens: np.ndarray) -> np.ndarray:
    """Convert the given token spans to float64, vectorized.

    Tokens group by length so each group gathers an exact-width (n, l)
    byte matrix — a row take on a sliding-window view, no index matrix,
    no masking — and converts via :func:`_convert_group`. Raises
    ValueError on non-numeric tokens, exactly like ``float()`` would.
    """
    n = len(starts)
    out = np.empty(n, dtype=np.float64)
    if n == 0:
        return out
    lmax = int(lens.max())
    counts = np.bincount(lens, minlength=lmax + 1)
    for l in np.flatnonzero(counts):
        l = int(l)
        if l == 0:
            continue
        ix = np.flatnonzero(lens == l) if counts[l] != n else np.arange(n)
        mat = sliding_window_view(a, l)[starts[ix]]
        _convert_group(mat, out, ix)
    return out


# ---------------------------------------------------------------------------
# libsvm: scalar line oracle
# ---------------------------------------------------------------------------


def parse_libsvm_line(line: bytes, out: RowBlockContainer) -> None:
    """One ``label[:weight] [qid:n] idx[:val]...`` line → ``out``.

    The single scalar source of truth: the general Python path
    (parsers.LibSVMParser) loops over this, and the vectorized path
    defers flagged rows to it, so every backend agrees byte-for-byte.
    """
    toks = line.split()
    if not toks:
        return
    head = toks[0].split(b":")
    label = float(head[0])
    weight = float(head[1]) if len(head) > 1 else None
    qid = None
    feats_idx = []
    feats_val = []
    has_vals = False
    for tok in toks[1:]:
        if tok.startswith(b"qid:"):
            qid = int(tok[4:])
            continue
        pair = tok.split(b":")
        feats_idx.append(float(pair[0]))
        if len(pair) > 1:
            feats_val.append(float(pair[1]))
            has_vals = True
        else:
            feats_val.append(1.0)
    out.push_row(
        label,
        np.asarray(feats_idx, dtype=np.float64).astype(INDEX_DTYPE),
        value=(
            np.asarray(feats_val, dtype=REAL_DTYPE) if has_vals else None
        ),
        weight=weight,
        qid=qid,
    )


def parse_libsvm_scalar(chunk: bytes, out: RowBlockContainer) -> None:
    """Reference scalar chunk parse: one :func:`parse_libsvm_line` per
    line (the ``DMLC_TPU_PARSE_BACKEND=scalar`` backend and the parity
    oracle)."""
    for line in chunk.splitlines():
        parse_libsvm_line(line, out)


# ---------------------------------------------------------------------------
# libsvm: vectorized chunk parse
# ---------------------------------------------------------------------------


def parse_libsvm_vector(chunk: bytes, out: RowBlockContainer) -> None:
    """Vectorized libsvm chunk parse, bit-identical to the scalar path.

    Columnar outputs are pushed as whole-array runs; rows outside the
    vectorized grammar are re-parsed by :func:`parse_libsvm_line` at
    their in-order position.
    """
    a = np.frombuffer(chunk, dtype=np.uint8)
    if a.size == 0:
        return

    # --- tokenize: boundary masks from fused compares ---
    is_eol = (a == _NL) | (a == _CR)
    c58 = a == _COLON
    sep = (a == 0x20) | (a == 0x09) | c58 | is_eol
    nonsep = ~sep
    sm = nonsep.copy()
    sm[1:] &= sep[:-1]
    em = nonsep.copy()
    em[:-1] &= sep[1:]
    starts = np.flatnonzero(sm)
    ends = np.flatnonzero(em) + 1
    n = starts.size
    if n == 0:
        # all-separator chunk: whitespace-only is empty, but str.split()
        # keeps ':' (not whitespace to it) — a lone colon line raises in
        # the scalar path, so defer to it
        if c58.any():
            parse_libsvm_scalar(chunk, out)
        return
    lens = ends - starts

    # --- rows: first-token flags via reverse searchsorted ---
    nlpos = np.flatnonzero(is_eol)
    first = np.zeros(n + 1, dtype=bool)
    first[np.searchsorted(starts, nlpos)] = True
    first = first[:n]
    first[0] = True
    row_start_tok = np.flatnonzero(first)
    nrows = row_start_tok.size
    row_bnd = np.append(row_start_tok, n)

    # --- roles from colon-follow + adjacency ---
    fc = np.zeros(n, dtype=bool)
    inb = ends < a.size
    fc[inb] = c58[ends[inb]]
    # orphan colon (separator or chunk start before it): invisible to the
    # boundary masks, but the scalar path materializes a b":" token and
    # raises — impossible in well-formed data, so punt the whole chunk.
    # Every non-orphan colon sits right after exactly one token end, so
    # orphans exist iff the counts disagree.
    if int(c58.sum()) != int(fc.sum()):
        parse_libsvm_scalar(chunk, out)
        return
    adj = np.zeros(n, dtype=bool)
    adj[:-1] = starts[1:] == ends[:-1] + 1  # bridged by exactly the ':'
    wcand = first & fc & adj  # label token with adjacent weight
    is_weight = np.zeros(n, dtype=bool)
    is_weight[1:] = wcand[:-1]
    rest = ~first & ~is_weight
    idx_cand = rest & fc
    is_val = np.zeros(n, dtype=bool)
    is_val[1:] = (idx_cand & adj)[:-1]
    idx_cand &= ~is_val  # a value can't open a pair ("i:v:x" flags below)
    feat = idx_cand | (rest & ~fc & ~is_val)

    # --- rows the vector grammar can't express → scalar fallback ---
    bad_tok = (
        (first & fc & ~adj)  # "1: 2" / "1:" at end of line
        | (is_weight & fc)  # "1:2:3" as the head token
        | (idx_cand & ~adj)  # "3: 4" / "3:" at end of line
        | (is_val & fc)  # "i:v:extra" feature shapes
        | (lens > _MAX_TOKEN)  # bound the gather matrix width
    )
    qm = np.flatnonzero((lens == 3) & fc)  # qid: groups stay scalar
    if qm.size:
        qs = starts[qm]
        bad_tok[qm[(a[qs] == 0x71) & (a[qs + 1] == 0x69)
                   & (a[qs + 2] == 0x64)]] = True

    bad_ix = np.flatnonzero(bad_tok)
    good_tok = None
    bad = None
    if bad_ix.size:
        bad = np.zeros(nrows, dtype=bool)
        bad[np.searchsorted(row_start_tok, bad_ix, side="right") - 1] = True
        good_tok = np.ones(n, dtype=bool)
        for r in np.flatnonzero(bad):
            good_tok[row_bnd[r]:row_bnd[r + 1]] = False

    # --- one-shot convert (good rows' tokens are exhaustively classed) ---
    v = np.empty(n, dtype=np.float64)
    if good_tok is None:
        v = _gather_floats(a, starts, lens)
    else:
        gix = np.flatnonzero(good_tok)
        v[gix] = _gather_floats(a, starts[gix], lens[gix])

    # --- columnar assembly, row-ordered by construction ---
    labels = v[row_start_tok]
    has_w = wcand[row_start_tok]
    weights = None
    if has_w.any():
        weights = np.ones(nrows, dtype=np.float64)
        wr = np.flatnonzero(has_w)
        weights[wr] = v[row_start_tok[wr] + 1]
    feat_ix = np.flatnonzero(feat if good_tok is None else feat & good_tok)
    feat_off = np.searchsorted(feat_ix, row_bnd)
    index = v[feat_ix]
    has_v = idx_cand[feat_ix]  # bare features read 1.0
    values = None
    if has_v.any():
        values = np.ones(feat_ix.size, dtype=np.float64)
        hv = np.flatnonzero(has_v)
        values[hv] = v[feat_ix[hv] + 1]

    def push_run(r0: int, r1: int) -> None:
        f0, f1 = int(feat_off[r0]), int(feat_off[r1])
        w = weights
        if w is not None and not bool(has_w[r0:r1].any()):
            w = None
        val = values
        if val is not None and not bool(has_v[f0:f1].any()):
            val = None
        out.push_arrays(
            labels[r0:r1].astype(REAL_DTYPE),
            np.diff(feat_off[r0:r1 + 1]),
            index[f0:f1].astype(INDEX_DTYPE),
            value=None if val is None else val[f0:f1].astype(REAL_DTYPE),
            weight=None if w is None else w[r0:r1].astype(REAL_DTYPE),
        )

    if bad is None:
        push_run(0, nrows)
        return

    # splice: columnar runs between scalar-parsed rows, in order
    r = 0
    while r < nrows:
        if bad[r]:
            s0 = int(starts[row_bnd[r]])
            k = int(np.searchsorted(nlpos, s0))
            lo = int(nlpos[k - 1]) + 1 if k > 0 else 0
            hi = int(nlpos[k]) if k < nlpos.size else a.size
            parse_libsvm_line(chunk[lo:hi], out)
            r += 1
            continue
        r1 = r
        while r1 < nrows and not bad[r1]:
            r1 += 1
        push_run(r, r1)
        r = r1


# ---------------------------------------------------------------------------
# csv
# ---------------------------------------------------------------------------


def _csv_line_spans(a: np.ndarray):
    """splitlines-equivalent (start, end) spans: ``\\r\\n`` is one break,
    lone ``\\r`` and ``\\n`` each break, no phantom final line."""
    brk = np.flatnonzero((a == _CR) | (a == _NL))
    if brk.size:
        # a '\n' directly after a '\r' belongs to the same break
        drop = (a[brk] == _NL) & (brk > 0)
        drop[drop] &= a[brk[drop] - 1] == _CR
        ends = brk[~drop]
        two = (a[ends] == _CR) & (ends + 1 < a.size)
        if two.any():
            two[two] &= a[ends[two] + 1] == _NL
        starts = np.concatenate(([0], ends + 1 + two))
        ends = np.concatenate((ends, [a.size]))
    else:
        starts = np.zeros(1, dtype=np.int64)
        ends = np.full(1, a.size, dtype=np.int64)
    keep = starts < ends  # chunk ending in a newline has no final line
    return starts[keep], ends[keep]


def parse_csv_scalar_table(chunk: bytes) -> np.ndarray:
    """Reference scalar csv parse → dense float64 table.

    Semantics shared by every backend (pinned by the parity suite):
    blank / whitespace-only lines are skipped; empty cells — including a
    blank last column from a trailing comma — read 0.0 (strtof-on-empty);
    ragged rows right-pad with 0.0 to the widest; anything non-numeric
    (quoted cells included) raises ValueError, same as ``float()``.
    """
    rows = [
        [float(c or b"0") for c in ln.split(b",")]
        for ln in chunk.splitlines()
        if ln.strip()
    ]
    if not rows:
        return np.zeros((0, 0), dtype=np.float64)
    width = max(len(r) for r in rows)
    table = np.zeros((len(rows), width), dtype=np.float64)
    for i, r in enumerate(rows):
        table[i, : len(r)] = r
    return table


def parse_csv_vector_table(chunk: bytes) -> np.ndarray:
    """Vectorized csv parse → dense float64 table, bit-identical to
    :func:`parse_csv_scalar_table`.

    Cell spans come straight from comma/newline offset arrays — this
    replaces the old ``b",".join(lines).split(b",")`` re-join, which
    rebuilt the whole chunk as Python objects just to split it again.
    """
    a = np.frombuffer(chunk, dtype=np.uint8)
    if a.size == 0:
        return np.zeros((0, 0), dtype=np.float64)
    ls, le = _csv_line_spans(a)
    if ls.size == 0:
        return np.zeros((0, 0), dtype=np.float64)
    # keep lines with any comma or any non-whitespace byte (the scalar
    # path's `if ln.strip()` keeps b",," — commas aren't whitespace);
    # counts come from boundary searches over the offset arrays, not
    # cumsums over the chunk
    cm = np.flatnonzero(a == 0x2C)
    nonws = ~((a == 0x20) | (a == 0x09) | (a == _CR) | (a == _NL)
              | (a == 0x0B) | (a == 0x0C))
    nwpos = np.flatnonzero(nonws)
    ncomma = (np.searchsorted(cm, le) - np.searchsorted(cm, ls))
    has_text = (np.searchsorted(nwpos, le) - np.searchsorted(nwpos, ls)) > 0
    keep = has_text | (ncomma > 0)
    ls, le, ncomma = ls[keep], le[keep], ncomma[keep]
    nrows = ls.size
    if nrows == 0:
        return np.zeros((0, 0), dtype=np.float64)
    cm = cm[np.searchsorted(cm, ls[0]):]

    # cells: line starts and comma+1 open, commas and line ends close;
    # scatter each into its global cell slot (row-major by construction)
    ncols = ncomma + 1
    row_first = np.zeros(nrows + 1, dtype=np.int64)
    np.cumsum(ncols, out=row_first[1:])
    total = int(row_first[-1])
    cs = np.empty(total, dtype=np.int64)
    ce = np.empty(total, dtype=np.int64)
    cs[row_first[:-1]] = ls
    ce[row_first[1:] - 1] = le
    if cm.size:
        line_of_cm = np.searchsorted(le, cm, side="left")
        cslot = (
            row_first[line_of_cm]
            + (np.arange(cm.size) - np.searchsorted(cm, ls)[line_of_cm])
        )
        ce[cslot] = cm
        cs[cslot + 1] = cm + 1
    clen = ce - cs
    vals = np.empty(total, dtype=np.float64)
    ne = np.flatnonzero(clen)
    vals[np.flatnonzero(clen == 0)] = 0.0  # strtof-on-empty: blank cell
    vals[ne] = _gather_floats(a, cs[ne], clen[ne])

    if int(ncols.min()) == int(ncols.max()):
        return vals.reshape(nrows, int(ncols[0]))
    # ragged: right-pad with 0.0 to the widest row
    cell_row = np.repeat(np.arange(nrows), ncols)
    col = np.arange(total, dtype=np.int64) - row_first[cell_row]
    table = np.zeros((nrows, int(ncols.max())), dtype=np.float64)
    table[cell_row, col] = vals
    return table


# ---------------------------------------------------------------------------
# Optional Pallas tokenizer (DMLC_TPU_PALLAS gate)
# ---------------------------------------------------------------------------


def token_boundary_masks(a: np.ndarray):
    """(starts_mask, ends_mask) boolean arrays for libsvm tokens — the
    tokenizer core shared by the numpy path above and the Pallas variant
    (ops/pallas_kernels.tokenize_boundaries). Exposed so the parity test
    can hold the two implementations identical."""
    sep = ((a == 0x20) | (a == 0x09) | (a == _COLON)
           | (a == _NL) | (a == _CR))
    nonsep = ~sep
    starts = nonsep.copy()
    starts[1:] &= sep[:-1]
    ends = nonsep.copy()
    ends[:-1] &= sep[1:]
    return starts, ends


def pallas_token_spans(a: np.ndarray) -> Optional[tuple]:
    """Token spans via the Pallas boundary kernel when the
    ``DMLC_TPU_PALLAS`` knob asks for it and a jax backend is usable;
    None → caller stays on the numpy tokenizer. The kernel only computes
    the boundary masks (the data-parallel part); offset extraction stays
    in numpy — flatnonzero has no fixed-shape device analog."""
    import os

    if os.environ.get("DMLC_TPU_PALLAS", "") not in ("1", "parse"):
        return None
    try:
        from dmlc_tpu.ops.pallas_kernels import tokenize_boundaries

        starts_mask, ends_mask = tokenize_boundaries(a)
    except Exception:
        return None
    return np.flatnonzero(starts_mask), np.flatnonzero(ends_mask) + 1
