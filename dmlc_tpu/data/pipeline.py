"""Cross-chunk pipelined parsing: N workers + a bounded ordered queue.

The base :class:`~dmlc_tpu.data.parsers.Parser` parallelizes WITHIN one
chunk (split at line boundaries, pool.map, merge) and is synchronous
ACROSS chunks: while the consumer holds block k, no part of chunk k+1 is
being parsed. :class:`PipelinedParser` inverts that: each chunk is one
parse task fanned over ``nthread`` workers through an
:class:`~dmlc_tpu.io.readahead.OrderedWindow` — the bounded ordered
queue that keeps up to ``window`` chunks in flight or buffered ahead of
the consumer while delivering blocks strictly in chunk order. Record
order is therefore bit-identical to serial iteration (the parity
contract the tf.data input pipeline calls determinism, arXiv:2101.12127
§3.2), parse of chunks k+1..k+W overlaps the consumer's work on chunk
k, and a full queue blocks the producer side (backpressure) instead of
growing without bound.

This is the Python-stack twin of the native C++ pipeline's
reader→workers→ordered-prefetch design (cpp/pipeline.cc): it serves the
formats and sources the native router declines (custom registry
parsers, mixed filesystems, native lib unavailable) with the same
concurrency shape.

``DMLC_TPU_PARSE_PROCS`` (params/knobs.py) escalates the workers from
threads to a shared spawn-start process pool: each worker thread ships
its chunk to a pool process and blocks on the future, so the
OrderedWindow still owns ordering, backpressure, poisoning and flow
tracing while the actual byte crunching escapes the GIL. See
docs/pipeline.md "Vectorized parse".

Stage accounting mirrors the native pipeline's counters: ``stats()``
reports worker parse time, consumer wait on the queue head, and chunk
count — surfaced by ``DeviceFeed.stats()["pipeline"]`` next to the
feed's own host/dispatch/wait split.

When tracing is armed each chunk also gets a flow id (``obs.new_flow``)
at read time: the ``io_read`` span starts the flow, ``parse`` steps it,
and the id rides the emitted :class:`RowBlock` (``block.flow_id``) so
downstream stages (DeviceFeed, BlockService) can extend the arrow chain
— see docs/observability.md "Flow tracing".
"""

from __future__ import annotations

import itertools
import time
from typing import Iterator, Optional

from dmlc_tpu import obs
from dmlc_tpu.data.parsers import Parser
from dmlc_tpu.obs import audit
from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.io.readahead import OrderedWindow
from dmlc_tpu.params.knobs import default_nthread, parse_procs
from dmlc_tpu.utils.logging import check

_PIPE_IDS = itertools.count()  # per-instance obs label (pipe="c0")


class _NullSource:
    """Chunk-less InputSplit stand-in for process-pool parser replicas:
    ``parse_chunk`` never touches the source, which cannot cross a
    process boundary anyway (open files, sockets)."""

    def next_chunk(self):
        return None

    def before_first(self):
        pass

    def close(self):
        pass


# parser replica per (class, args) per worker process, built on first use
_PROC_PARSERS: dict = {}


def _proc_parse(spec, chunk):
    """Parse one chunk in a pool process. ``spec`` rebuilds a replica of
    the parent's parser (class path + stringified params); module-level
    and import-driven so it pickles under the spawn start method."""
    parser = _PROC_PARSERS.get(spec)
    if parser is None:
        import importlib

        mod_name, cls_name, args = spec
        cls = getattr(importlib.import_module(mod_name), cls_name)
        try:
            parser = cls(_NullSource(), dict(args), nthread=1)
        except TypeError:  # (source, nthread) signature
            parser = cls(_NullSource(), nthread=1)
        _PROC_PARSERS[spec] = parser
    return parser.parse_chunk(chunk)


def _corrupt_chunk(chunk):
    """``audit.corrupt`` payload: nudge the first ASCII digit so the
    chunk stays parseable but its content forks — the parse-stage digest
    diverges while the io_read digest (taken before this point) stays
    clean, localizing the fault to ``parse``."""
    text = isinstance(chunk, str)
    try:
        buf = bytearray(chunk.encode() if text else chunk)
    except (TypeError, ValueError):
        return chunk
    for i, c in enumerate(buf):
        if 0x30 <= c <= 0x38:  # '0'..'8': +1 keeps it a digit
            buf[i] = c + 1
            return buf.decode() if text else bytes(buf)
    return chunk


def _proc_spec(base: Parser):
    """Picklable replica recipe for ``base``, or None when the parser
    can't be rebuilt from (class, params) alone — then chunks stay on
    the worker threads."""
    cls = type(base)
    if cls.__qualname__ != cls.__name__:  # nested/local class: no import path
        return None
    param = getattr(base, "param", None)
    args = tuple(sorted(param.to_dict().items())) if param is not None else ()
    return (cls.__module__, cls.__name__, args)


class PipelinedParser:
    """Parse chunks of ``base`` on ``nthread`` workers, delivered in order.

    ``base`` must be a :class:`Parser` (it supplies ``next_chunk`` and
    ``parse_chunk``); construct it with ``nthread=1`` so the two levels
    of parallelism don't nest — chunk-level fan-out replaces the
    intra-chunk split. ``window`` bounds chunks in flight or parsed but
    unconsumed (default 2×nthread). Exceptions raised by a worker
    surface from ``next_block`` at the failed chunk's in-order position
    and poison the queue; ``close`` (and ``before_first``) cancel all
    pending work.
    """

    def __init__(
        self,
        base: Parser,
        nthread: Optional[int] = None,
        window: int = 0,
    ):
        check(isinstance(base, Parser),
              "PipelinedParser requires a Parser base (got %s)",
              type(base).__name__)
        self._base = base
        self._nthread = default_nthread(nthread)
        self._window_arg = window
        # stage counters live in the obs registry (docs/observability.md);
        # parse time is summed across workers (can exceed wall time),
        # consumer_wait is time next_block blocked on the queue head
        pid = "c%d" % next(_PIPE_IDS)
        reg = obs.registry()
        self._m_chunks = reg.counter(
            "dmlc_pipeline_chunks_total", "chunks submitted to the window",
            pipe=pid)
        self._h_parse = reg.histogram(
            "dmlc_pipeline_parse_ns", "per-chunk worker parse time",
            pipe=pid)
        self._h_wait = reg.histogram(
            "dmlc_pipeline_consumer_wait_ns",
            "per-pop consumer wait on the queue head", pipe=pid)
        # DMLC_TPU_PARSE_PROCS>0: worker threads submit chunks to a shared
        # process pool and block on the future, so ordering, backpressure
        # and poisoning ride the same OrderedWindow machinery. The pool is
        # created lazily (first chunk) with the spawn start method — fork
        # would duplicate JAX/native state. Latched at construction, like
        # nthread.
        self._procs = parse_procs()
        self._proc_recipe = _proc_spec(base) if self._procs > 0 else None
        if self._proc_recipe is None:
            self._procs = 0  # non-rebuildable parser: parse on threads
        self._executor = None
        self._win: Optional[OrderedWindow] = None
        self._seq = 0  # in-order chunk id (span labels), not telemetry
        # the determinism auditor keys chunk digests on epoch-relative
        # seq (self._seq - _epoch_base) so chains line up across epochs
        # and ranks; the no-op child when DMLC_TPU_AUDIT is off
        self._audit = audit.auditor()
        self._epoch_base = 0
        self._eof = False
        self._closed = False
        self._open()

    def _open(self) -> None:
        self._win = OrderedWindow(
            self._parse_timed, workers=self._nthread,
            window=self._window_arg, name="pipelined-parse",
        )
        self._eof = False

    def _ensure_executor(self):
        if self._executor is None:
            import concurrent.futures
            import multiprocessing

            self._executor = concurrent.futures.ProcessPoolExecutor(
                max_workers=self._procs,
                mp_context=multiprocessing.get_context("spawn"),
            )
        return self._executor

    def _parse_timed(self, task):
        from dmlc_tpu.resilience import InjectedFault, faultpoint

        seq, fid, chunk = task
        t0 = time.monotonic_ns()
        try:
            with obs.span("parse", chunk=seq, flow=fid):
                obs.flow_step(fid, "chunk")
                # fires on the parent's worker thread in both modes, so an
                # injected fault poisons the window at the chunk's in-order
                # position whether or not a process pool is behind it
                faultpoint("parse.chunk")
                # audit smoke fault: flip one byte AFTER the io_read
                # digest so only the parse chain forks (localization)
                try:
                    faultpoint("audit.corrupt")
                except InjectedFault:
                    chunk = _corrupt_chunk(chunk)
                if self._procs > 0:
                    container = self._ensure_executor().submit(
                        _proc_parse, self._proc_recipe, chunk
                    ).result()
                else:
                    container = self._base.parse_chunk(chunk)
            container.flow_id = fid
            self._audit.note_parse(seq - self._epoch_base, container)
            return container
        finally:
            self._h_parse.observe(time.monotonic_ns() - t0)

    def _fill(self) -> None:
        """Top the window up with fresh chunks (the producer half; runs on
        the consumer thread, so a full window — backpressure — simply
        stops the chunk reads)."""
        while not self._eof and self._win.free_slots > 0:
            fid = obs.new_flow()
            with obs.span("io_read", chunk=self._seq, flow=fid):
                chunk = self._base.next_chunk()
                if chunk is not None:
                    obs.flow_start(fid, "chunk")
            if chunk is None:
                self._eof = True
                return
            self._m_chunks.inc()
            self._audit.note_chunk(self._seq - self._epoch_base, chunk)
            self._win.submit((self._seq, fid, chunk))
            self._seq += 1

    # ---- Parser surface -------------------------------------------------
    @property
    def bytes_read(self) -> int:
        return self._base.bytes_read

    def next_block(self) -> Optional[RowBlock]:
        check(not self._closed, "parser is closed")
        while True:
            self._fill()
            if len(self._win) == 0:
                return None
            t0 = time.monotonic_ns()
            try:
                container = self._win.pop()
            finally:
                self._h_wait.observe(time.monotonic_ns() - t0)
            if len(container):
                block = container.to_block()
                fid = getattr(container, "flow_id", 0)
                if fid:
                    block.flow_id = fid
                return block
            # empty chunk (blank lines): keep pulling

    def __iter__(self) -> Iterator[RowBlock]:
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block

    def before_first(self) -> None:
        """Restart for a fresh epoch: cancel in-flight work, rewind the
        source, reopen the window. Counters keep accumulating (like
        ``bytes_read``: stats describe the parser's lifetime)."""
        self._win.close()
        self._base.before_first()
        self._open()
        self._epoch_base = self._seq
        self._closed = False

    def stats(self) -> dict:
        """Python-pipeline stage counters, shaped like the native
        pipeline's (ns): parse = worker time (summed), consumer_wait =
        time ``next_block`` blocked on the queue head. Timings read off
        the obs registry children (lifetime totals, like bytes_read);
        chunks off ``_seq`` so the count stays live under
        DMLC_TPU_METRICS=0."""
        return {
            "chunks": int(self._seq),
            "parse_ns": int(self._h_parse.sum),
            "consumer_wait_ns": int(self._h_wait.sum),
            "nthread": self._nthread,
            "procs": self._procs,
            "window": self._win.window if self._win is not None else 0,
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._win.close()
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None
        self._base.close()

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
