"""Logging and check/assert layer.

Capability parity with the reference's glog-compatible mini-logger
(``include/dmlc/logging.h``): CHECK/CHECK_op macros that raise a rich
``DMLCError`` carrying a stack trace (reference ``logging.h:121-132,322-339``),
severity-leveled LOG with timestamps, a pluggable custom sink (reference
``CustomLogMessage::Log``, ``logging.h:253-272``), and env-controlled verbosity.

Idiomatic-Python differences (deliberate): the check macros are functions, the
Error type integrates with Python exception chaining, and LOG rides the stdlib
``logging`` module so downstream apps can route/filter with standard tooling.
"""

from __future__ import annotations

import logging as _pylogging
import os
import sys
import time
import traceback
from typing import Any, Callable, Optional

_LOGGER_NAME = "dmlc_tpu"


class DMLCError(RuntimeError):
    """Error raised by failed checks and FATAL logs.

    Mirrors ``dmlc::Error`` (reference ``logging.h:31``). When
    ``DMLC_LOG_STACK_TRACE`` is truthy (default on), the message includes a
    captured Python stack trace, mirroring ``StackTrace()`` capture at
    ``logging.h:322-339``.
    """

    def __init__(self, msg: str):
        if _stack_trace_enabled():
            tb = "".join(traceback.format_stack()[:-2])
            msg = f"{msg}\n\nStack trace:\n{tb}"
        super().__init__(msg)


def _stack_trace_enabled() -> bool:
    val = os.environ.get("DMLC_LOG_STACK_TRACE", "1").lower()
    return val not in ("0", "false", "")


def get_logger() -> _pylogging.Logger:
    """The package logger; lazily configured with a stderr handler."""
    logger = _pylogging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = _pylogging.StreamHandler(sys.stderr)
        handler.setFormatter(
            _pylogging.Formatter(
                fmt="[%(asctime)s] %(levelname)s %(filename)s:%(lineno)d: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
        logger.addHandler(handler)
        level = os.environ.get("DMLC_LOG_LEVEL", "INFO").upper()
        logger.setLevel(getattr(_pylogging, level, _pylogging.INFO))
    return logger


# Pluggable sink: if set, all log lines go through it instead of the stdlib
# logger (reference: DMLC_LOG_CUSTOMIZE / CustomLogMessage, logging.h:253-272).
_custom_sink: Optional[Callable[[str, str], None]] = None


def set_log_sink(sink: Optional[Callable[[str, str], None]]) -> None:
    """Install a custom sink ``sink(severity, message)``; None restores default."""
    global _custom_sink
    _custom_sink = sink


def _emit(severity: str, msg: str) -> None:
    if _custom_sink is not None:
        _custom_sink(severity, msg)
        return
    logger = get_logger()
    logger.log(getattr(_pylogging, severity, _pylogging.INFO), msg, stacklevel=3)


def log_debug(msg: str, *args: Any) -> None:
    _emit("DEBUG", msg % args if args else msg)


def log_info(msg: str, *args: Any) -> None:
    _emit("INFO", msg % args if args else msg)


def log_warning(msg: str, *args: Any) -> None:
    _emit("WARNING", msg % args if args else msg)


def log_error(msg: str, *args: Any) -> None:
    _emit("ERROR", msg % args if args else msg)


def log_fatal(msg: str, *args: Any) -> None:
    """LOG(FATAL): emits then raises DMLCError (reference logging.h:379-405,
    behavior of DMLC_LOG_FATAL_THROW=1, which is the mode every DMLC-based
    library ships with)."""
    text = msg % args if args else msg
    _emit("ERROR", text)
    raise DMLCError(text)


def check(cond: Any, msg: str = "", *args: Any) -> None:
    """CHECK(cond): raise DMLCError when cond is falsy (logging.h:121)."""
    if not cond:
        text = msg % args if args else msg
        raise DMLCError(f"Check failed: {text}" if text else "Check failed")


def _check_op(op_name: str, ok: bool, x: Any, y: Any, msg: str) -> None:
    if not ok:
        detail = f" {msg}" if msg else ""
        raise DMLCError(f"Check failed: {x!r} {op_name} {y!r}{detail}")


def check_eq(x: Any, y: Any, msg: str = "") -> None:
    _check_op("==", x == y, x, y, msg)


def check_ne(x: Any, y: Any, msg: str = "") -> None:
    _check_op("!=", x != y, x, y, msg)


def check_lt(x: Any, y: Any, msg: str = "") -> None:
    _check_op("<", x < y, x, y, msg)


def check_le(x: Any, y: Any, msg: str = "") -> None:
    _check_op("<=", x <= y, x, y, msg)


def check_gt(x: Any, y: Any, msg: str = "") -> None:
    _check_op(">", x > y, x, y, msg)


def check_ge(x: Any, y: Any, msg: str = "") -> None:
    _check_op(">=", x >= y, x, y, msg)


def check_notnull(x: Any, msg: str = "") -> Any:
    """CHECK_NOTNULL: raise if None, else return x (logging.h:159-166)."""
    if x is None:
        raise DMLCError(f"Check notnull failed: {msg}" if msg else "Check notnull failed")
    return x


class LogOncePer:
    """Rate-limited logging helper: at most one emit per ``period`` seconds.

    TPU-new convenience used by throughput telemetry (the reference logs every
    10MB instead; basic_row_iter.h:66-75)."""

    def __init__(self, period: float = 10.0):
        self.period = period
        self._last = 0.0

    def __call__(self, msg: str, *args: Any) -> bool:
        now = time.monotonic()
        if now - self._last >= self.period:
            self._last = now
            log_info(msg, *args)
            return True
        return False
