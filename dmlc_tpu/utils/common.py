"""Small shared helpers (reference: include/dmlc/common.h)."""

from __future__ import annotations

from typing import List


def split_string(s: str, delim: str) -> List[str]:
    """Split on a single-char delimiter, dropping empty segments.

    Matches dmlc::Split semantics (common.h:20-31): `std::getline` over a
    stringstream drops empty fields.
    """
    return [part for part in s.split(delim) if part != ""]


def hash_combine(seed: int, value: int) -> int:
    """Boost-style hash combine (common.h:33-46), 64-bit wrapped."""
    mask = (1 << 64) - 1
    return (seed ^ (value + 0x9E3779B9 + ((seed << 6) & mask) + (seed >> 2))) & mask
