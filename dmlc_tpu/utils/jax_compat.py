"""Version-compat shims for the jax API surface this package uses.

``shard_map`` was promoted from ``jax.experimental.shard_map`` to
``jax.shard_map`` in newer jax releases; the keyword signature this
package uses (``mesh=``, ``in_specs=``, ``out_specs=``) is identical in
both homes, so resolving the symbol once here keeps every mesh code
path working across the versions the container may carry.
"""

from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map  # jax >= 0.6
except AttributeError:  # pragma: no cover - depends on installed jax
    import functools

    from jax.experimental import shard_map as _esm

    @functools.wraps(_esm.shard_map)
    def shard_map(f, **kwargs):
        # newer callers say check_vma; the experimental API calls the same
        # thing check_rep
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        # the experimental rewrite machinery chokes on symbolic-Zero
        # cotangents (grad through a shard_map whose aux output is
        # unused); skipping the replication check sidesteps it and only
        # costs the rep-based transpose optimization
        kwargs.setdefault("check_rep", False)
        return _esm.shard_map(f, **kwargs)


def pcast(x, axis_name, *, to):
    """``jax.lax.pcast`` where available (the explicit replicated→varying
    cast newer check-vma shard_map requires); identity on older jax,
    whose shard_map tracks replication implicitly."""
    cast = getattr(jax.lax, "pcast", None)
    if cast is None:
        return x
    return cast(x, axis_name, to=to)


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` where available; otherwise the classic
    ``psum(1, axis)`` idiom (constant-folded at trace time)."""
    size = getattr(jax.lax, "axis_size", None)
    if size is not None:
        return size(axis_name)
    return jax.lax.psum(1, axis_name)


__all__ = ["shard_map", "axis_size", "pcast"]
