"""Utility layer: logging/CHECK/Error, timing, small helpers.

Reference capabilities mirrored: include/dmlc/logging.h (CHECK/LOG/Error with
stack traces, pluggable sink), include/dmlc/timer.h (GetTime), and
include/dmlc/common.h (Split, HashCombine).
"""

from dmlc_tpu.utils.logging import (
    DMLCError,
    check,
    check_eq,
    check_ne,
    check_lt,
    check_le,
    check_gt,
    check_ge,
    check_notnull,
    log_debug,
    log_info,
    log_warning,
    log_error,
    log_fatal,
    set_log_sink,
    get_logger,
)
from dmlc_tpu.utils.timer import get_time, Timer
from dmlc_tpu.utils.common import split_string, hash_combine
from dmlc_tpu.utils.thread_group import (
    BlockingQueueThread,
    ManualEvent,
    ThreadGroup,
    TimerThread,
)

__all__ = [
    "DMLCError",
    "check",
    "check_eq",
    "check_ne",
    "check_lt",
    "check_le",
    "check_gt",
    "check_ge",
    "check_notnull",
    "log_debug",
    "log_info",
    "log_warning",
    "log_error",
    "log_fatal",
    "set_log_sink",
    "get_logger",
    "get_time",
    "Timer",
    "split_string",
    "hash_combine",
    "BlockingQueueThread",
    "ManualEvent",
    "ThreadGroup",
    "TimerThread",
]
