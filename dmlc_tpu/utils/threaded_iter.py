"""Bounded producer/consumer prefetch iterator.

Capability parity with ``dmlc::ThreadedIter`` (include/dmlc/threadediter.h):
a background producer thread fills a bounded queue (default capacity 8,
threadediter.h:80) ahead of the consumer; ``before_first`` restarts the
producer for a new epoch (the kBeforeFirst signal, threadediter.h:211-215);
exceptions thrown in the producer are captured and re-raised in the consumer
(threadediter.h:374-404,456-466). The reference's free-cell ``Recycle`` buffer
pool (threadediter.h:442-454) exists to reach zero steady-state allocation in
C++; the Python twin relies on refcounting (the native C++ core in cpp/ keeps
the recycling design).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Generic, Iterable, Iterator, Optional, TypeVar

T = TypeVar("T")

_END = object()


class _Exc:
    def __init__(self, err: BaseException):
        self.err = err


class ThreadedIter(Generic[T]):
    """Prefetch items of ``make_iter()`` in a background thread.

    ``make_iter`` is called once per epoch (at construction and at each
    ``before_first``) and must return a fresh iterator — the analog of the
    reference's ``next``/``beforefirst`` producer closures
    (threadediter.h:300-408).
    """

    def __init__(
        self,
        make_iter: Callable[[], Iterable[T]],
        max_capacity: int = 8,
        name: str = "threaded-iter",
    ):
        self._make_iter = make_iter
        self._cap = max_capacity
        self._name = name
        self._thread: Optional[threading.Thread] = None
        self._queue: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._finished = False
        self.before_first()

    # ---- producer ------------------------------------------------------
    def _run(self, q: "queue.Queue", stop: threading.Event) -> None:
        try:
            for item in self._make_iter():
                while True:
                    if stop.is_set():
                        return
                    try:
                        q.put(item, timeout=0.05)
                        break
                    except queue.Full:
                        continue
            while not stop.is_set():
                try:
                    q.put(_END, timeout=0.05)
                    return
                except queue.Full:
                    continue
        except BaseException as err:  # noqa: BLE001 — propagate to consumer
            while not stop.is_set():
                try:
                    q.put(_Exc(err), timeout=0.05)
                    return
                except queue.Full:
                    continue

    def _shutdown_producer(self) -> None:
        if self._thread is not None:
            self._stop.set()
            # Drain so a blocked put() notices the stop flag promptly.
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join()
            self._thread = None

    # ---- consumer API --------------------------------------------------
    def before_first(self) -> None:
        """Restart the producer for a fresh epoch."""
        self._shutdown_producer()
        self._queue = queue.Queue(self._cap)
        self._stop = threading.Event()
        self._finished = False
        self._thread = threading.Thread(
            target=self._run,
            args=(self._queue, self._stop),
            name=self._name,
            daemon=True,
        )
        self._thread.start()

    def next(self) -> Optional[T]:
        """Next item, or None at end of epoch. Re-raises producer errors."""
        if self._finished:
            return None
        item = self._queue.get()
        if item is _END:
            self._finished = True
            return None
        if isinstance(item, _Exc):
            self._finished = True
            raise item.err
        return item

    def __iter__(self) -> Iterator[T]:
        while True:
            item = self.next()
            if item is None:
                return
            yield item

    def close(self) -> None:
        self._shutdown_producer()

    def __del__(self) -> None:  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
