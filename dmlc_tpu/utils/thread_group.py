"""Managed thread lifecycle helpers.

Capability parity with include/dmlc/thread_group.h:

- ``ManualEvent``: manual-reset gate (thread_group.h:31-69) — ``set`` wakes
  every waiter and stays signalled until ``reset``.
- ``ThreadGroup``: named, joinable thread registry (thread_group.h:92-520)
  with auto-remove on exit, group shutdown request, and join-all.
- ``BlockingQueueThread``: a thread pumping items off a blocking queue into
  an item handler (thread_group.h:527-640).
- ``TimerThread``: periodic callback until stopped (thread_group.h:642-795).

The TPU build keeps these as the host-side lifecycle layer around ingest
pipelines and trackers; device-side concurrency belongs to XLA, not threads.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional

from dmlc_tpu.utils.logging import check

__all__ = [
    "ManualEvent",
    "ThreadGroup",
    "GroupThread",
    "BlockingQueueThread",
    "TimerThread",
]


class ManualEvent:
    """Manual-reset event (thread_group.h ManualEvent :31-69)."""

    def __init__(self, signaled: bool = False):
        self._event = threading.Event()
        if signaled:
            self._event.set()

    def set(self) -> None:
        self._event.set()

    def reset(self) -> None:
        self._event.clear()

    def is_set(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)


class GroupThread:
    """One managed thread (thread_group.h ThreadGroup::Thread :98-420).

    The run function receives this object; long-running loops should poll
    ``stop_requested`` (the CreateThread launch contract) so group shutdown
    can interrupt them.
    """

    def __init__(
        self,
        name: str,
        group: "ThreadGroup",
        target: Callable[..., Any],
        args: Iterable[Any] = (),
        auto_remove: bool = True,
    ):
        self.name = name
        self._group = group
        self._stop_requested = threading.Event()
        self._auto_remove = auto_remove
        run_args = tuple(args)

        def _run():
            try:
                target(self, *run_args)
            finally:
                if self._auto_remove:
                    group._remove(self)

        self._thread = threading.Thread(target=_run, name=name, daemon=True)
        self._thread.start()

    @property
    def stop_requested(self) -> bool:
        return self._stop_requested.is_set()

    def request_shutdown(self) -> None:
        self._stop_requested.set()

    def wait_for_shutdown(self, timeout: Optional[float] = None) -> bool:
        """Block until a shutdown request arrives (worker-side idle wait)."""
        return self._stop_requested.wait(timeout)

    def join(self, timeout: Optional[float] = None) -> bool:
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def is_alive(self) -> bool:
        return self._thread.is_alive()


class ThreadGroup:
    """Named thread registry with group-wide shutdown and join
    (thread_group.h:92-520)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._threads: Dict[str, GroupThread] = {}

    def create(
        self,
        name: str,
        target: Callable[..., Any],
        *args: Any,
        auto_remove: bool = True,
    ) -> GroupThread:
        """Launch a named thread; names are unique within the group."""
        with self._lock:
            check(name not in self._threads, "duplicate thread name %s", name)
            thread = GroupThread(name, self, target, args, auto_remove)
            self._threads[name] = thread
            return thread

    def get(self, name: str) -> Optional[GroupThread]:
        with self._lock:
            return self._threads.get(name)

    def size(self) -> int:
        with self._lock:
            return len(self._threads)

    def _remove(self, thread: GroupThread) -> None:
        with self._lock:
            if self._threads.get(thread.name) is thread:
                del self._threads[thread.name]

    def request_shutdown_all(self) -> None:
        with self._lock:
            threads = list(self._threads.values())
        for t in threads:
            t.request_shutdown()

    def join_all(self, timeout: Optional[float] = None) -> bool:
        """Request shutdown and join every thread; True when all exited."""
        self.request_shutdown_all()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            threads = list(self._threads.values())
        ok = True
        for t in threads:
            left = None if deadline is None else max(0.0, deadline - time.monotonic())
            ok = t.join(left) and ok
        return ok


class BlockingQueueThread:
    """Thread pumping a blocking queue into an item handler
    (thread_group.h BlockingQueueThread :527-640)."""

    _SENTINEL = object()

    def __init__(
        self,
        name: str,
        handler: Callable[[Any], None],
        group: Optional[ThreadGroup] = None,
        max_size: int = 0,
    ):
        self._queue: "queue.Queue[Any]" = queue.Queue(maxsize=max_size)
        self._handler = handler
        self._group = group or ThreadGroup()
        self._thread = self._group.create(name, self._pump, auto_remove=True)

    def _pump(self, thread: GroupThread) -> None:
        # Poll with a timeout so a group-wide request_shutdown (which cannot
        # enqueue the sentinel) still terminates the pump; shutdown() keeps
        # drain semantics by queueing the sentinel behind pending items.
        while True:
            try:
                item = self._queue.get(timeout=0.05)
            except queue.Empty:
                if thread.stop_requested:
                    return
                continue
            if item is BlockingQueueThread._SENTINEL:
                return
            self._handler(item)

    def enqueue(self, item: Any) -> None:
        self._queue.put(item)

    def shutdown(self, timeout: Optional[float] = None) -> bool:
        """Drain-then-exit: the sentinel queues behind pending items."""
        self._queue.put(BlockingQueueThread._SENTINEL)
        return self._thread.join(timeout)


class TimerThread:
    """Periodic callback every ``interval`` seconds until stopped
    (thread_group.h TimerThread :642-795)."""

    def __init__(
        self,
        name: str,
        interval: float,
        callback: Callable[[], None],
        group: Optional[ThreadGroup] = None,
    ):
        check(interval > 0, "timer interval must be positive")
        self.interval = interval
        self._callback = callback
        self._group = group or ThreadGroup()
        self._thread = self._group.create(name, self._loop, auto_remove=True)

    def _loop(self, thread: GroupThread) -> None:
        while not thread.wait_for_shutdown(self.interval):
            self._callback()

    def stop(self, timeout: Optional[float] = None) -> bool:
        self._thread.request_shutdown()
        return self._thread.join(timeout)
