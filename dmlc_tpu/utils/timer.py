"""Timing helpers (reference: include/dmlc/timer.h GetTime, timer.h:27-47)."""

from __future__ import annotations

import time
from typing import Optional

from dmlc_tpu.utils.logging import check


def get_time() -> float:
    """Seconds from a monotonic high-resolution clock, as double.

    The reference prefers chrono high_resolution_clock (timer.h:29-33); the
    Python equivalent is time.perf_counter().
    """
    return time.perf_counter()


class Timer:
    """Context-manager stopwatch with accumulated elapsed time.

    TPU-new: the reference only has GetTime(); pipelines here want per-stage
    timers (SURVEY §5.1 — and obs.span durations), so this accumulates
    across multiple enters.
    """

    def __init__(self) -> None:
        self.elapsed = 0.0
        self._start: Optional[float] = None

    def __enter__(self) -> "Timer":
        self._start = get_time()
        return self

    def __exit__(self, *exc) -> None:
        # a library-surface misuse, not an internal invariant: raise the
        # catchable DMLCError, never a stripped-out assert
        check(self._start is not None,
              "Timer.__exit__ without a matching __enter__")
        self.elapsed += get_time() - self._start
        self._start = None

    def reset(self) -> None:
        """Zero the accumulated time. Safe mid-timing: an in-flight
        enter restarts from now instead of being forgotten (its exit
        would otherwise raise)."""
        self.elapsed = 0.0
        if self._start is not None:
            self._start = get_time()
