"""DeviceFeed: the host-parse → H2D → mesh-sharded batch pipeline.

The reference's ThreadedIter pipeline ends with host RowBlocks
(threadediter.h + parser.h); DeviceFeed is its TPU continuation (SURVEY §3.1
"TPU build" note): a background thread re-batches parser output into
fixed-shape batches, transfers them with async ``jax.device_put`` (or
``jax.make_array_from_process_local_data`` when a multi-host mesh is given),
and keeps ``spec.prefetch`` batches in flight (default 1 — the classic
double-buffer; deeper windows pin more HBM but hide per-batch dispatch/DMA
latency) so H2D DMA overlaps both host parsing and the previous step's
compute. ``host_prefetch`` separately bounds the host-side ThreadedIter
queue of parsed-but-undispatched blocks.

Batch layouts:
- "dense": [batch, num_features] f32 + labels/weights — the MXU-friendly
  layout for small dense feature spaces (HIGGS, Criteo-dense)
- "csr": DeviceCSRBatch arrays (CSR offsets shipped; row ids expanded on
  device for segment-sum SpMV) with nnz
  bucketing — for genuinely sparse data (see dmlc_tpu.ops.spmv)
"""

from __future__ import annotations

import itertools
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_tpu import obs
from dmlc_tpu.obs import audit, device_telemetry, flight
from dmlc_tpu.data.parsers import Parser, ThreadedParser, create_parser
from dmlc_tpu.data.row_block import RowBlockContainer
from dmlc_tpu.device.csr import (
    DeviceCSRBatch,
    ShardedCSRBatch,
    block_to_dense,
    emit_to_bucket,
    pad_to_bucket,
    pad_to_bucket_sharded,
)
from dmlc_tpu.params.knobs import (
    default_host_prefetch,
    default_prefetch,
    device_resident,
)
from dmlc_tpu.utils.logging import check
from dmlc_tpu.utils.threaded_iter import ThreadedIter

# obs label values: each feed/pool instance gets its own metric children
# ("feed=f3"), so concurrent feeds never clobber each other's windows and
# SPMD hosts (same construction order) produce host-comparable vectors
_FEED_IDS = itertools.count()
_POOL_IDS = itertools.count()


def _available_cpus() -> int:
    """CPUs actually usable by this process: cgroup/affinity-aware
    (os.cpu_count() reports the machine and would spawn a useless
    producer thread in a 1-CPU container on a big host)."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):  # non-linux
        return os.cpu_count() or 1


class _SyncIter:
    """ThreadedIter-shaped adapter running the producer inline (no
    thread): `host_prefetch=0`. Same consumer surface — iteration,
    close(), before_first() restart."""

    def __init__(self, factory):
        self._factory = factory
        self._gen = factory()

    def __iter__(self):
        return self._gen

    def next(self):
        return next(self._gen, None)

    def before_first(self) -> None:
        # close the old generator first (ThreadedIter.before_first fully
        # shuts down its producer): a suspended generator would keep a
        # staged native batch and parser state pinned alongside the new one
        self.close()
        self._gen = self._factory()

    def close(self) -> None:
        gen, self._gen = self._gen, iter(())
        if hasattr(gen, "close"):
            gen.close()


@dataclass
class BatchSpec:
    """Static-shape contract for one feed."""

    batch_size: int
    layout: str = "dense"  # "dense" | "csr"
    num_features: int = 0  # required for dense
    nnz_bucket: Optional[int] = None  # fixed bucket for csr (else auto)
    drop_remainder: bool = False
    # device transfers in flight ahead of the consumer. jax dispatch is
    # async, so a deeper window hides per-batch dispatch/DMA latency (the
    # tunneled-chip profile especially) at the cost of pinning that many
    # extra batches in HBM. 1 = the classic double-buffer; None resolves
    # through the DMLC_TPU_PREFETCH knob (params/knobs.py).
    prefetch: Optional[int] = None


@dataclass
class _ResidentDense:
    """A dense batch already scattered into pooled staging by the
    device-resident producer (``RowBlockContainer.emit_dense_into``) —
    carries its staging arrays so ``_to_device`` can retire them."""

    x: np.ndarray  # [batch, num_features] f32
    labels: np.ndarray  # [batch] f32
    weights: np.ndarray  # [batch] f32 (0.0 for padded rows)
    num_rows: int


def _transfer_done(arr) -> bool:
    """True once ``arr``'s async H2D copy no longer reads its host source
    (jax.Array.is_ready without blocking; absent API → assume in flight)."""
    ready = getattr(arr, "is_ready", None)
    if ready is None:
        return False
    try:
        return bool(ready())
    except Exception:
        return False


class FixedShapePool:
    """Host staging buffers keyed by (shape, dtype) bucket, reused across
    batches.

    Two jobs, per the static-shape discipline (device/csr.py header):

    1. **Shape accounting.** Every ``acquire`` records its (shape, dtype)
       key; ``shape_keys``/``stats()["shapes"]`` expose exactly the set of
       distinct buffer shapes a feed produced — the contract a jitted
       consumer compiles against (one trace per shape bucket, no
       per-batch recompilation; proven by test).

    2. **Buffer reuse.** With ``recycle=True`` the allocation per batch is
       retired: ``retire(bufs, guards)`` offers a delivered batch's host
       arrays back, and ``acquire`` hands them out again once their guard
       device arrays report the async H2D copy complete (``is_ready``,
       never blocking — a buffer whose transfer is still in flight is
       simply left retired and a fresh one allocated, so the pool grows
       to the in-flight depth and then stops allocating). ``recycle``
       must be False when the transfer may alias the host buffer instead
       of copying it (the cpu backend's zero-copy jit ingest,
       ``DeviceFeed._put_tree``): there the consumer owns the buffer and
       reuse would rewrite batches already delivered — bit-parity over
       reuse.
    """

    # retired batches whose guards never report ready are dropped (GC'd)
    # beyond this depth so a readiness-API-less runtime degrades to plain
    # allocation, not a leak
    MAX_RETIRED = 32
    # leak sentinel: every this many acquires, compare the outstanding
    # buffer count (handed out, not yet returned) against its previous
    # high-water mark; this many CONSECUTIVE new highs means a consumer
    # is acquiring without ever retiring — a staging leak, not churn
    LEAK_CHECK_EVERY = 64
    LEAK_STRIKES = 4

    def __init__(self, recycle: bool = True):
        self.recycle = recycle
        self._free: dict = {}  # key -> [np.ndarray]
        self._retired: deque = deque()  # (bufs, guard arrays)
        pid = "p%d" % next(_POOL_IDS)
        reg = obs.registry()
        self._m_allocated = reg.counter(
            "dmlc_pool_allocated_total",
            "staging buffers newly allocated", pool=pid)
        self._m_reused = reg.counter(
            "dmlc_pool_reused_total",
            "staging buffers recycled from the free list", pool=pid)
        # plain ints next to the registry mirrors: the hit-rate surface
        # (stats(), tests, bench) stays truthful under DMLC_TPU_METRICS=0
        self.allocated = 0
        self.reused = 0
        self.retired = 0  # buffers accepted back through retire()
        self.double_retired = 0  # duplicate retire() offers rejected
        self._shapes: set = set()
        # id()s of buffers currently owned by the pool (_free/_retired):
        # a second retire() of one of these would hand the same memory to
        # two future acquirers — the guard drops the duplicate instead
        self._pooled_ids: set = set()
        self._acquires = 0
        self._leak_high = 0
        self._leak_strikes = 0
        self._leak_reported = False

    @staticmethod
    def _key(shape, dtype):
        if isinstance(shape, int):
            shape = (shape,)
        return (tuple(shape), np.dtype(dtype).str)

    @property
    def shape_keys(self) -> frozenset:
        return frozenset(self._shapes)

    def acquire(self, shape, dtype) -> np.ndarray:
        key = self._key(shape, dtype)
        self._shapes.add(key)
        if self.recycle:
            self._acquires += 1
            if self._acquires % self.LEAK_CHECK_EVERY == 0:
                self._leak_check()
            self._drain()
            free = self._free.get(key)
            if free:
                buf = free.pop()
                self._pooled_ids.discard(id(buf))
                self.reused += 1
                self._m_reused.inc()
                return buf
        self.allocated += 1
        self._m_allocated.inc()
        return np.empty(key[0], dtype=dtype)

    @property
    def outstanding(self) -> int:
        """Buffers handed out (allocated + reused) and not yet returned
        through :meth:`retire` — the quantity the leak sentinel watches."""
        return (self.allocated + self.reused) - self.retired

    def retire(self, bufs, guards) -> None:
        """Offer a delivered batch's staging buffers back, guarded by the
        device arrays their transfer produced. A buffer the pool already
        holds (double-retire — two delivery paths returning one batch) is
        dropped rather than queued twice: queuing it again would hand the
        same memory to two future acquirers and silently corrupt an
        in-flight batch."""
        if not self.recycle:
            return
        accepted = []
        for buf in bufs:
            bid = id(buf)
            if bid in self._pooled_ids:
                self.double_retired += 1
                continue
            self._pooled_ids.add(bid)
            accepted.append(buf)
        if not accepted:
            return
        self.retired += len(accepted)
        self._retired.append((accepted, list(guards)))
        while len(self._retired) > self.MAX_RETIRED:
            # degrade to allocation, never leak; the dropped buffers are
            # GC'd, so forget their ids (id() values can be recycled)
            dropped, _ = self._retired.popleft()
            for buf in dropped:
                self._pooled_ids.discard(id(buf))

    def _leak_check(self) -> None:
        """Fire one ``pool.leak`` flight event when the outstanding buffer
        count keeps making new highs — acquires without matching retires
        grow host memory linearly with the fit and this is the earliest
        observable signature."""
        if self._leak_reported:
            return
        out = self.outstanding
        if out > self._leak_high:
            self._leak_high = out
            self._leak_strikes += 1
            if self._leak_strikes >= self.LEAK_STRIKES:
                self._leak_reported = True
                flight.record_event(
                    "pool.leak",
                    outstanding=out,
                    allocated=self.allocated,
                    reused=self.reused,
                    retired=self.retired,
                )
        else:
            self._leak_strikes = 0

    def _drain(self) -> None:
        # strictly oldest-first: a younger batch ready before an older one
        # just waits its turn (the window is small; ordering keeps the
        # free-list hot in cache and the logic obvious)
        while self._retired:
            bufs, guards = self._retired[0]
            if not all(_transfer_done(g) for g in guards):
                return
            self._retired.popleft()
            for buf in bufs:
                self._free.setdefault(
                    self._key(buf.shape, buf.dtype), []
                ).append(buf)

    def stats(self) -> dict:
        return {
            "shapes": len(self._shapes),
            "allocated": self.allocated,
            "reused": self.reused,
            "retired": self.retired,
            "double_retired": self.double_retired,
            "outstanding": self.outstanding,
            "pending_retire": len(self._retired),
        }


def stall_breakdown(stats: dict) -> str:
    """One-line human summary of :meth:`DeviceFeed.stats` — where the
    epoch's wall time sat (ms per stage) plus pool reuse, for fit-loop
    logging and bench extra fields. ``host_wait`` ≈ 0 means the feed kept
    up with the consumer; ``host_wait`` ≈ ``host_batch`` means the
    consumer was ingest-bound.

    Purely a formatter: the numbers come from the obs registry
    (``dmlc_feed_*`` / ``dmlc_pool_*`` / ``dmlc_pipeline_*`` metrics,
    epoch-windowed by ``stats()`` — docs/observability.md has the name
    table)."""
    ms = 1e6
    parts = [
        "feed[%d batches]" % stats.get("batches", 0),
        "host_batch %.1fms" % (stats.get("host_batch_ns", 0) / ms),
        "dispatch %.1fms" % (stats.get("dispatch_ns", 0) / ms),
        "host_wait %.1fms" % (stats.get("host_wait_ns", 0) / ms),
        "consume %.1fms" % (stats.get("consume_ns", 0) / ms),
    ]
    pool = stats.get("pool") or {}
    if pool.get("allocated"):
        parts.append(
            "pool %d shapes %d alloc %d reuse"
            % (pool.get("shapes", 0), pool["allocated"],
               pool.get("reused", 0))
        )
    pipe = stats.get("pipeline") or {}
    if pipe.get("chunks"):
        parts.append(
            "parse[%d chunks x%d] %.1fms (+%.1fms wait)"
            % (pipe["chunks"], pipe.get("nthread", 1),
               pipe.get("parse_ns", 0) / ms,
               pipe.get("consumer_wait_ns", 0) / ms)
        )
    return " | ".join(parts)


class DeviceFeed:
    """Iterate device-resident batches from a parser or URI.

    With a ``mesh``, batches are sharded over its ``axis`` (default "dp") on
    the leading dimension; each process feeds its local shard (multi-host:
    pass the per-host InputSplit part via the parser's uri part/num_parts).
    """

    def __init__(
        self,
        source: Parser | ThreadedParser | str,
        spec: BatchSpec,
        mesh: Optional[Mesh] = None,
        axis: str = "dp",
        part_index: int = 0,
        num_parts: int = 1,
        host_prefetch: Optional[int] = None,  # ThreadedIter queue depth
        # (host blocks); 0 = synchronous (no producer thread); None =
        # the DMLC_TPU_HOST_PREFETCH knob, else auto: 0 on a 1-core
        # host, else 2
    ):
        if host_prefetch is None:
            host_prefetch = default_host_prefetch()
        if host_prefetch is None:
            host_prefetch = 0 if _available_cpus() <= 1 else 2
        if isinstance(source, str):
            source = create_parser(source, part_index, num_parts)
        self._parser = source
        self.spec = spec
        self._mesh = mesh
        self._axis = axis
        # computed once: mesh/axis are immutable, and the multi-process
        # branch scans the mesh's device array
        self._shards = self._axis_shards()
        if mesh is not None:
            # the per-PROCESS batch divides over this process's shards
            # along the axis (== the full axis extent single-process)
            check(
                spec.batch_size % self._shards == 0,
                "batch_size %d must divide over this process's %d shards "
                "of mesh axis %s",
                spec.batch_size,
                self._shards,
                axis,
            )
            if jax.process_count() > 1 and spec.layout == "csr":
                # auto bucketing sizes from LOCAL data; different hosts
                # would pick different buckets and the global assembly
                # needs identical local shapes — make the bucket explicit
                check(
                    spec.nnz_bucket is not None,
                    "multi-process csr feeds require an explicit "
                    "spec.nnz_bucket (auto bucketing is per-host)",
                )
        # the transfer window: spec value or the DMLC_TPU_PREFETCH knob
        self._prefetch = default_prefetch(spec.prefetch)
        # host staging buffers recycle only where the device transfer
        # provably COPIES (accelerator H2D lands in device memory); the
        # cpu backend may alias numpy buffers zero-copy through the jit
        # boundary (_put_tree), where reuse would rewrite delivered
        # batches — there the pool only does shape accounting
        self.pool = FixedShapePool(recycle=jax.default_backend() != "cpu")
        # per-stage wall time (SURVEY §5.1: "where does feed time go?")
        # lives in the obs registry as per-batch histograms; the host stage
        # observes on the ThreadedIter thread, the rest on the consuming
        # thread — registered BEFORE the producer thread starts. stats()
        # windows the monotonic registry totals with _epoch_base so it
        # still describes the current epoch.
        fid = "f%d" % next(_FEED_IDS)
        reg = obs.registry()
        self._stage = {
            "host_batch_ns": reg.histogram(
                "dmlc_feed_host_batch_ns",
                "per-batch host production (parse + densify/pad)", feed=fid),
            "dispatch_ns": reg.histogram(
                "dmlc_feed_dispatch_ns",
                "per-batch async device transfer submission", feed=fid),
            "host_wait_ns": reg.histogram(
                "dmlc_feed_host_wait_ns",
                "per-batch consumer wait on the host producer", feed=fid),
            "consume_ns": reg.histogram(
                "dmlc_feed_consume_ns",
                "per-batch time the consumer held the batch", feed=fid),
        }
        self._m_batches = reg.counter(
            "dmlc_feed_batches_total", "device batches delivered", feed=fid)
        # rows delivered — the goodput ledger's examples/s numerator
        # (obs/goodput.py windows it against wall time)
        self._m_rows = reg.counter(
            "dmlc_feed_rows_total", "examples delivered to device",
            feed=fid)
        # device_put calls per feed: the sentry gates this against the
        # batch count — per-array dispatch regressions (N calls where one
        # pytree put would do) surface as dispatches/batch > 1
        self._m_dispatches = reg.counter(
            "dmlc_feed_h2d_dispatches_total",
            "device_put dispatch calls (one per batched pytree put; "
            "per-array regressions show up as dispatches/batch > 1)",
            feed=fid)
        # device-resident fast path (DMLC_TPU_DEVICE_RESIDENT): parsed
        # RowBlock parts emit straight into pooled staging (pad-in-place,
        # device/csr.emit_to_bucket) instead of materialize+pad — python
        # re-batch paths only (the native pipeline already stages without
        # container copies; sharded csr keeps its partition path)
        self._resident = (
            device_resident()
            and spec.layout in ("dense", "csr")
            and self._shards == 1
        )
        # H2D accounting around _put_tree: None when device telemetry is
        # off, and then the dispatch path has no byte walk and no timer.
        self._h2d = device_telemetry.h2d_meter(feed=fid)
        device_telemetry.maybe_start_hbm_poller()
        # determinism audit: batch-stage digests at pool emit, keyed by
        # per-epoch batch index (obs/audit.py; the canonical audit_arrays
        # stream makes the resident container and the legacy sliced block
        # hash identically for the same rows). The shared no-op child
        # when DMLC_TPU_AUDIT is off.
        self._audit = audit.auditor()
        self._epoch_base: dict = {}
        # exactly-once ack emission (dispatcher-mode RemoteBlockParser):
        # switch the parser to explicit acks BEFORE the producer thread
        # can issue its first fetch, so prefetched chunks are acked only
        # when their rows are consumed (or dropped) by this feed
        self._ack = getattr(self._parser, "ack", None)
        set_explicit = getattr(self._parser, "set_explicit_ack", None)
        if callable(self._ack) and callable(set_explicit):
            set_explicit()
        else:
            self._ack = None
        self._sync_host = host_prefetch <= 0
        if self._sync_host:
            # synchronous host stage: on a 1-core host the prefetch
            # thread cannot overlap anything and only adds context
            # switches (~5% of the recordio->SGD epoch); a real TPU host
            # (many cores) keeps the thread and the overlap
            self._host_iter = _SyncIter(self._host_batches)
        else:
            self._host_iter = ThreadedIter(
                self._host_batches, max_capacity=host_prefetch,
                name="device-feed"
            )

    def _axis_shards(self) -> int:
        """How many shard sections THIS process builds along the batch
        axis (mesh-geometry logic shared with the GBDT learner —
        ``parallel.local_axis_shards`` carries the multi-process
        rationale; getting it wrong interleaves hosts' shards and feeds
        every device garbage row offsets)."""
        if self._mesh is None:
            return 1
        from dmlc_tpu.parallel import local_axis_shards

        return local_axis_shards(self._mesh, self._axis)

    # ---- host side: re-batch parser blocks into fixed-size slices ------
    def _use_native_batches(self) -> bool:
        """Native C++ re-batch + densify/COO-pad (pipeline.cc StageBatch):
        no RowBlockContainer copies, no numpy scatter — the feed-side answer
        to the parse-vs-feed throughput cliff (BASELINE.md)."""
        return (
            getattr(self._parser, "supports_batch_fetch", False)
            and self.spec.layout in ("dense", "csr")
        )

    def _host_batches(self) -> Iterator:
        from dmlc_tpu.resilience import faultpoint

        if self._use_native_batches():
            producer = self._host_batches_native()
        elif self._resident:
            producer = self._host_batches_resident()
        else:
            producer = self._host_batches_python()
        while True:
            faultpoint("device.feed")
            t0 = time.monotonic_ns()
            try:
                item = next(producer)
            except StopIteration:
                return
            finally:
                self._stage["host_batch_ns"].observe(
                    time.monotonic_ns() - t0)
            yield item

    def _host_batches_python(self) -> Iterator:
        bs = self.spec.batch_size
        bidx = 0  # per-epoch batch index (audit batch-chain key)
        pending = RowBlockContainer()
        # flow ids (and dispatcher chunk seq ids) of parser chunks not yet
        # represented in an emitted batch; rebatching is N:M, so each
        # chunk's ids ride the first slice it contributes rows to
        flows = []
        seqs = []
        for block in self._parser:
            fid = getattr(block, "flow_id", 0)
            if fid:
                flows.append(fid)
            sid = getattr(block, "seq_id", None)
            if sid is not None:
                seqs.append(sid)
            pending.push_block(block)
            if len(pending) < bs:
                continue
            # Finalize once, emit every full slice, keep only the tail.
            whole = pending.to_block()
            nfull = len(whole) // bs
            for k in range(nfull):
                piece = whole.slice(k * bs, (k + 1) * bs)
                if flows:
                    piece.flow_ids = tuple(flows)
                    flows = []
                if seqs:
                    piece.seq_ids = tuple(seqs)
                    seqs = []
                self._audit.note_batch(bidx, piece)
                bidx += 1
                yield piece
            pending = RowBlockContainer()
            if len(whole) > nfull * bs:
                pending.push_block(whole.slice(nfull * bs, len(whole)))
        if len(pending) and not self.spec.drop_remainder:
            tail = pending.to_block()
            if flows:
                tail.flow_ids = tuple(flows)
            if seqs:
                tail.seq_ids = tuple(seqs)
                seqs = []
            self._audit.note_batch(bidx, tail)
            yield tail
        if seqs and self._ack is not None:
            # chunks whose rows only ever reached a dropped remainder (or
            # an empty chunk) still count as visited — ack them here or
            # the dispatcher would requeue them forever
            for sid in seqs:
                self._ack_seq(sid)

    def _emit_resident(self, pending, flows, seqs):
        """Finalize one accumulated container straight into pooled
        staging — the device-resident single copy (no ``to_block``
        concatenate, no second pad copy)."""
        spec = self.spec
        with obs.span("stage", rows=len(pending)):
            for fid in flows:
                obs.flow_step(fid, "chunk")
            if spec.layout == "csr":
                batch = emit_to_bucket(
                    pending, spec.batch_size, nnz_bucket=spec.nnz_bucket,
                    pool=self.pool,
                )
                batch.staging_bufs = (
                    batch.labels, batch.weights, batch.indices,
                    batch.values, batch.offsets,
                )
            else:
                x = self.pool.acquire(
                    (spec.batch_size, spec.num_features), np.float32)
                x.fill(0)  # the scatter only writes present entries
                labels = self.pool.acquire(spec.batch_size, np.float32)
                weights = self.pool.acquire(spec.batch_size, np.float32)
                n = pending.emit_dense_into(x, labels, weights)
                labels[n:] = 0.0
                weights[n:] = 0.0
                batch = _ResidentDense(
                    x=x, labels=labels, weights=weights, num_rows=n)
        if flows:
            batch.flow_ids = tuple(flows)
        if seqs:
            batch.seq_ids = tuple(seqs)
        return batch

    def _host_batches_resident(self) -> Iterator:
        """The device-resident re-batch producer: parser blocks are
        split at batch boundaries with zero-copy ``slice()`` views into
        an accumulating container, and each full batch is emitted
        directly into ``FixedShapePool`` staging via the pad-in-place
        path (``RowBlockContainer.emit_csr_into`` /
        ``emit_dense_into``). vs ``_host_batches_python``: the
        ``to_block()`` concatenate copy and the separate pad copy fuse
        into ONE write that lands where ``device_put`` reads."""
        spec = self.spec
        bs = spec.batch_size
        if spec.layout == "dense":
            check(spec.num_features > 0, "dense layout requires num_features")
        pending = RowBlockContainer()
        bidx = 0  # per-epoch batch index (audit batch-chain key); the
        # container digests BEFORE emit consumes it, and hashes the same
        # bytes as the legacy path's sliced block for the same rows
        flows = []
        seqs = []
        for block in self._parser:
            fid = getattr(block, "flow_id", 0)
            if fid:
                flows.append(fid)
            sid = getattr(block, "seq_id", None)
            if sid is not None:
                seqs.append(sid)
            start = 0
            n = len(block)
            while len(pending) + (n - start) >= bs:
                take = bs - len(pending)
                if take:
                    pending.push_block(block.slice(start, start + take))
                    start += take
                self._audit.note_batch(bidx, pending)
                bidx += 1
                yield self._emit_resident(pending, flows, seqs)
                pending = RowBlockContainer()
                flows = []
                seqs = []
            if start < n:
                pending.push_block(block.slice(start, n))
        if len(pending) and not spec.drop_remainder:
            self._audit.note_batch(bidx, pending)
            yield self._emit_resident(pending, flows, seqs)
            seqs = []
        if seqs and self._ack is not None:
            # chunks whose rows only reached a dropped remainder still
            # count as visited (see _host_batches_python)
            for sid in seqs:
                self._ack_seq(sid)

    def _host_batches_native(self) -> Iterator:
        spec = self.spec
        bs = spec.batch_size
        shards = self._shards
        while True:
            if spec.layout == "dense":
                check(spec.num_features > 0,
                      "dense layout requires num_features")
                out = self._parser.read_batch_dense(bs, spec.num_features)
            elif shards > 1:
                # mesh csr: entries partitioned per shard on the host so
                # each device receives only its own nnz
                out = self._parser.read_batch_coo_sharded(
                    bs, shards, nnz_bucket=spec.nnz_bucket
                )
            else:
                out = self._parser.read_batch_coo(
                    bs, nnz_bucket=spec.nnz_bucket
                )
            if out is None:
                return
            rows = out[3] if spec.layout == "dense" else out.num_rows
            if rows < bs and spec.drop_remainder:
                return
            yield out

    # ---- device side ---------------------------------------------------
    def _sharding(self, spec: P) -> Optional[NamedSharding]:
        if self._mesh is None:
            return None
        return NamedSharding(self._mesh, spec)

    def _put_tree(self, arrays: dict, specs: dict) -> dict:
        """One batched transfer for all of a batch's arrays: per-array
        device_put pays the dispatch overhead N times (measured ~5 ms/call
        through a tunneled runtime); a pytree device_put batches them.
        With device telemetry on, the put is metered: payload bytes →
        ``dmlc_feed_h2d_bytes_total``, submission MB/s →
        ``dmlc_feed_h2d_mbps``."""
        meter = self._h2d
        if meter is None:
            return self._put_tree_raw(arrays, specs)
        nbytes = 0
        for v in arrays.values():
            nbytes += getattr(v, "nbytes", 0)
        t0 = time.monotonic_ns()
        out = self._put_tree_raw(arrays, specs)
        meter.note(nbytes, time.monotonic_ns() - t0)
        return out

    def _put_tree_raw(self, arrays: dict, specs: dict) -> dict:
        if self._mesh is None:
            if jax.default_backend() == "cpu" and \
                    os.environ.get("DMLC_TPU_FEED_PUT") != "1":
                # CPU single-device: the jit boundary performs the
                # (aligned, possibly zero-copy) ingest itself — an eager
                # device_put is one extra full copy on the same core the
                # parse/densify pipeline runs on (measured ~15% of the
                # recordio->SGD epoch). On an accelerator the eager put
                # IS the async H2D overlap, so only cpu skips.
                # DMLC_TPU_FEED_PUT=1 restores the put for A/B.
                return arrays
            self._m_dispatches.inc()
            return jax.device_put(arrays)
        if jax.process_count() > 1:
            return self._put_tree_multihost(arrays, specs)
        shardings = {k: self._sharding(specs[k]) for k in arrays}
        self._m_dispatches.inc()
        return jax.device_put(arrays, shardings)

    def _global_shape(self, arr, spec: P) -> tuple:
        """Global shape of ``arr`` under ``spec``: the leading dim sharded
        over the mesh axis multiplies by total/local shard sections
        (each process contributes ``self._shards`` contiguous sections);
        replicated arrays keep their local shape."""
        if len(spec) and spec[0] == self._axis:
            total = self._mesh.shape[self._axis]
            return (arr.shape[0] * (total // self._shards),) + arr.shape[1:]
        return arr.shape

    def _put_tree_multihost(self, arrays: dict, specs: dict) -> dict:
        """Multi-host assembly through ONE batched ``device_put``.

        ``jax.make_array_from_process_local_data`` is per-array by API
        shape — N dispatch round trips per batch (the overhead the
        ``dmlc_feed_h2d_dispatches_total``/batch ratio gates). Instead:
        compute each array's global shape, slice this process's
        addressable per-device shards as host views
        (``addressable_devices_indices_map`` rebased by the local block's
        global offset), ship every shard of every array through one
        batched ``device_put``, and assemble the global arrays with
        ``make_array_from_single_device_arrays`` — metadata only, no
        further transfer."""
        shardings = {k: self._sharding(specs[k]) for k in arrays}
        try:
            views, devs, plans = [], [], []
            for k, v in arrays.items():
                sh = shardings[k]
                gshape = self._global_shape(v, specs[k])
                ndim = len(gshape)
                idx_map = sh.addressable_devices_indices_map(gshape)
                devices = list(idx_map)
                norm = {
                    d: tuple(idx_map[d]) + (slice(None),) * (
                        ndim - len(idx_map[d]))
                    for d in devices
                }
                # this process's local block is contiguous in global
                # coords: its offset per dim is the min start over the
                # process's own shards
                offs = [
                    min((norm[d][dim].start or 0) for d in devices)
                    for dim in range(ndim)
                ]
                for d in devices:
                    local = tuple(
                        slice(
                            (s.start or 0) - off,
                            (s.stop if s.stop is not None else size) - off,
                        )
                        for s, off, size in zip(norm[d], offs, gshape)
                    )
                    views.append(v[local])
                    devs.append(d)
                plans.append((k, gshape, sh, len(devices)))
            self._m_dispatches.inc()
            shards = jax.device_put(views, devs)
            out, pos = {}, 0
            for k, gshape, sh, n in plans:
                out[k] = jax.make_array_from_single_device_arrays(
                    gshape, sh, list(shards[pos: pos + n])
                )
                pos += n
            return out
        except Exception:  # noqa: BLE001 — exotic sharding/runtime: keep
            # feeding through the per-array path rather than kill the fit
            # (the dispatch counter records the N-call cost honestly)
            self._m_dispatches.inc(len(arrays))
            return {
                k: jax.make_array_from_process_local_data(shardings[k], v)
                for k, v in arrays.items()
            }

    def _to_device(self, block, flows=()):
        """→ (device batch, staging buffers to retire — () when the host
        arrays came from the native pipeline or no pooled path).
        ``flows``: flow ids of the chunks in ``block`` — stepped inside
        the ``stage`` span so the pool staging slice joins the arrow
        chain (python paths only; native batches carry no flows)."""
        spec = self.spec
        if isinstance(block, tuple):  # native dense batch, pre-densified
            x, labels, weights, rows = block
            out = self._put_tree(
                {"x": x, "label": labels, "weight": weights},
                {"x": P(self._axis), "label": P(self._axis),
                 "weight": P(self._axis)},
            )
            out["num_rows"] = rows
            return out, ()
        if isinstance(block, (DeviceCSRBatch, ShardedCSRBatch)):
            # native COO batch (no staging to retire) or the resident
            # emit path (its pooled staging rides along for retire)
            return self._put_csr(block), getattr(block, "staging_bufs", ())
        if isinstance(block, _ResidentDense):
            out = self._put_tree(
                {"x": block.x, "label": block.labels,
                 "weight": block.weights},
                {"x": P(self._axis), "label": P(self._axis),
                 "weight": P(self._axis)},
            )
            out["num_rows"] = block.num_rows
            return out, (block.x, block.labels, block.weights)
        if spec.layout == "dense":
            check(spec.num_features > 0, "dense layout requires num_features")
            with obs.span("stage", rows=len(block)):
                for fid in flows:
                    obs.flow_step(fid, "chunk")
                x, labels, weights = block_to_dense(
                    block, spec.batch_size, spec.num_features, pool=self.pool
                )
            out = self._put_tree(
                {"x": x, "label": labels, "weight": weights},
                {"x": P(self._axis), "label": P(self._axis),
                 "weight": P(self._axis)},
            )
            out["num_rows"] = len(block)
            return out, (x, labels, weights)
        if spec.layout == "csr":
            shards = self._shards
            with obs.span("stage", rows=len(block)):
                for fid in flows:
                    obs.flow_step(fid, "chunk")
                if shards > 1:
                    batch = pad_to_bucket_sharded(
                        block, spec.batch_size, shards,
                        nnz_bucket=spec.nnz_bucket,
                    )
                    bufs = ()
                else:
                    batch = pad_to_bucket(
                        block, spec.batch_size, nnz_bucket=spec.nnz_bucket,
                        pool=self.pool,
                    )
                    bufs = (batch.labels, batch.weights, batch.indices,
                            batch.values, batch.row_ids, batch.offsets)
            return self._put_csr(batch), bufs
        raise ValueError(f"unknown layout {spec.layout!r}")

    def _put_csr(self, batch):
        # ShardedCSRBatch: per-shard entry sections — P(axis) on the flat
        # entry arrays ships each device only its own nnz (H2D ∝
        # global_nnz / world). DeviceCSRBatch (no mesh / single shard):
        # entries replicated. Either way the row mapping crosses H2D as
        # the small CSR ``offsets`` array (∝ rows), NOT the per-entry
        # ``row_ids`` (∝ nnz); the train step expands row ids on device
        # (ops.spmv.expand_row_ids) where the cumsum is effectively free.
        sharded = isinstance(batch, ShardedCSRBatch)
        entry_spec = P(self._axis) if sharded else P()
        out = self._put_tree(
            {
                "label": batch.labels,
                "weight": batch.weights,
                "indices": batch.indices,
                "values": batch.values,
                "offsets": batch.offsets,
            },
            {
                "label": P(self._axis),
                "weight": P(self._axis),
                "indices": entry_spec,
                "values": entry_spec,
                "offsets": entry_spec,
            },
        )
        out["num_rows"] = batch.num_rows
        out["num_nonzero"] = batch.num_nonzero
        return out

    def _ack_seq(self, sid) -> None:
        """Report one dispatcher chunk consumed; best-effort — a dead
        dispatcher must not kill the training loop (the lease deadline
        covers a lost ack; the duplicate-ack path makes a retried one
        harmless)."""
        try:
            self._ack(sid)
        except Exception:  # noqa: BLE001 — see docstring
            pass

    def _deliver(self, entry):
        """Retire a pending batch's staging buffers (guarded by its own
        device arrays: acquire() reuses them only once the async H2D copy
        is done) and hand the batch to the consumer."""
        batch, bufs = entry[0], entry[1]
        if bufs:
            self.pool.retire(
                bufs, [v for v in batch.values() if isinstance(v, jax.Array)]
            )
        return batch

    def __iter__(self):
        """Yield device batches with ``spec.prefetch`` transfers in flight
        ahead of the consumer (async dispatch pipelining). A parser/host
        error propagates at its in-order position after the batches before
        it; the feed stays closeable afterwards (close() joins the
        producer and parser threads)."""
        window = self._prefetch
        pending = deque()
        it = iter(self._host_iter)
        nbatch = 0
        ndelivered = 0

        def _consume(entry):
            nonlocal ndelivered
            batch = self._deliver(entry)
            flows = entry[2]
            t2 = time.monotonic_ns()
            # the consume span covers the yield: its duration IS the time
            # the consumer held the batch (generator suspended). The
            # thread-local current flow is set for that same window so
            # fit-loop spans (train_step, collective ops) can mark the
            # in-flight chunk; flow_end fires inside the span, closing
            # the arrow chain on the consume slice.
            with obs.span("consume", batch=ndelivered):
                if flows:
                    obs.set_current_flow(flows[0])
                try:
                    yield batch
                finally:
                    if flows:
                        obs.set_current_flow(0)
                    for fid in flows:
                        obs.flow_end(fid, "chunk")
            self._stage["consume_ns"].observe(time.monotonic_ns() - t2)
            if self._ack is not None:
                # the consumer released the batch: every chunk whose rows
                # first appeared in it is now consumed — advance the
                # exactly-once ack frontier
                for sid in entry[3]:
                    self._ack_seq(sid)
            ndelivered += 1

        while True:
            with obs.span("feed_batch", batch=nbatch):
                t0 = time.monotonic_ns()
                try:
                    block = next(it)
                except StopIteration:
                    break
                finally:
                    # sync mode has no producer thread to wait on: the time
                    # inside next() IS host production and already accrues
                    # to the host_batch stage — also counting it here would
                    # double-book the stage breakdown
                    if not self._sync_host:
                        self._stage["host_wait_ns"].observe(
                            time.monotonic_ns() - t0)
                t1 = time.monotonic_ns()
                flows = getattr(block, "flow_ids", ())
                seqs = getattr(block, "seq_ids", ())
                with obs.span("dispatch", batch=nbatch):
                    for fid in flows:
                        obs.flow_step(fid, "chunk")
                    batch_bufs = self._to_device(block, flows)
                    # async dispatch; the entry keeps the chunk ids so
                    # _consume can close flows and ack seqs on delivery
                    pending.append(batch_bufs + (flows, seqs))
                self._stage["dispatch_ns"].observe(time.monotonic_ns() - t1)
                self._m_batches.inc()
                # row accounting across block shapes: native dense tuple
                # carries its count at [3], padded batches as num_rows,
                # python RowBlocks via len()
                if isinstance(block, tuple):
                    self._m_rows.inc(int(block[3]))
                else:
                    self._m_rows.inc(
                        int(getattr(block, "num_rows", 0) or len(block)))
                nbatch += 1
            if len(pending) > window:
                yield from _consume(pending.popleft())
        while pending:
            yield from _consume(pending.popleft())

    def stats(self) -> dict:
        """Per-stage wall time (ns): host batch production (parse+densify),
        device dispatch, time this consumer spent waiting on the host
        thread, and time the consumer held each batch (its step work) —
        plus the staging-pool counters and the parser pipeline's own stage
        counters when it exposes them (SURVEY §5.1). Together these
        decompose an epoch: overlap-bound means host_wait ≈ 0 and
        consume dominates; sum-of-stages-bound shows up as host_wait ≈
        host_batch."""
        base = self._epoch_base
        out = {
            "batches": int(self._m_batches.value - base.get("batches", 0)),
            "pool": self.pool.stats(),
        }
        for key, hist in self._stage.items():
            out[key] = int(hist.sum - base.get(key, 0))
        parser_stats = getattr(self._parser, "stats", None)
        if callable(parser_stats):
            pipeline = parser_stats()
            if pipeline:
                out["pipeline"] = pipeline
        return out

    def before_first(self) -> None:
        self._host_iter.close()
        self._parser.before_first()
        # registry metrics are monotonic (Prometheus semantics); stats()
        # windows them against this baseline so it always describes the
        # current epoch, aligned with the native pipeline's per-reopen
        # counters
        self._epoch_base = {
            key: hist.sum for key, hist in self._stage.items()
        }
        self._epoch_base["batches"] = self._m_batches.value
        self._host_iter.before_first()

    @property
    def bytes_read(self) -> int:
        return self._parser.bytes_read

    def close(self) -> None:
        self._host_iter.close()
        self._parser.close()
