"""Device-resident CSR batches with static-shape padding/bucketing.

The reference's ``RowBlock`` (data.h:170) is variable-length CSR on the host.
XLA wants static shapes: a new shape means a new compilation, and a stream of
ragged batches would cause a recompilation storm (SURVEY §7 "hard parts").

Policy here:
- row count is fixed per feed (``batch_size``; the final short batch is
  padded with zero-weight rows so loss/grad contributions vanish),
- nnz is rounded up to a bucket (default: next power of two above a floor),
  padded entries point at index 0 with value 0 so they are arithmetic no-ops,
- the row-mapping is carried as a per-entry ``row_ids`` array (COO-style),
  which is what TPU-friendly ``segment_sum`` SpMV consumes — instead of the
  host CSR ``offset`` array, whose per-row dynamic slicing XLA can't tile.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.utils.logging import check


def round_up_bucket(n: int, floor: int = 256) -> int:
    """Next power-of-two ≥ n (with a floor) — the nnz bucketing policy."""
    n = max(n, floor, 1)
    return 1 << (n - 1).bit_length()


@dataclass
class DeviceCSRBatch:
    """A static-shape, device-ready sparse batch (host numpy twin).

    Shapes: labels/weights/row_valid are [batch]; indices/values/row_ids are
    [nnz_bucket]. Padded nnz entries have value 0 at feature 0 and row_id
    pointing at the first padded row (or row 0 with value 0 — a no-op either
    way for segment-sum SpMV).
    """

    labels: np.ndarray  # [batch] f32
    weights: np.ndarray  # [batch] f32 (0.0 for padded rows)
    indices: np.ndarray  # [nnz_bucket] i32 feature ids
    values: np.ndarray  # [nnz_bucket] f32 (0.0 for padded entries)
    row_ids: np.ndarray  # [nnz_bucket] i32 row of each entry
    num_rows: int  # valid rows
    num_nonzero: int  # valid entries

    @property
    def batch_size(self) -> int:
        return len(self.labels)

    @property
    def nnz_bucket(self) -> int:
        return len(self.indices)


def pad_to_bucket(
    block: RowBlock,
    batch_size: int,
    nnz_bucket: Optional[int] = None,
    nnz_floor: int = 256,
) -> DeviceCSRBatch:
    """Pad a host RowBlock slice into a static-shape DeviceCSRBatch."""
    n = len(block)
    check(n <= batch_size, "block larger than batch_size")
    nnz = block.num_nonzero
    bucket = nnz_bucket if nnz_bucket is not None else round_up_bucket(nnz, nnz_floor)
    check(nnz <= bucket, "nnz exceeds bucket")

    labels = np.zeros(batch_size, dtype=np.float32)
    labels[:n] = block.label
    weights = np.zeros(batch_size, dtype=np.float32)
    weights[:n] = 1.0 if block.weight is None else block.weight

    indices = np.zeros(bucket, dtype=np.int32)
    values = np.zeros(bucket, dtype=np.float32)
    row_ids = np.zeros(bucket, dtype=np.int32)
    indices[:nnz] = block.index
    values[:nnz] = (
        np.ones(nnz, dtype=np.float32) if block.value is None else block.value
    )
    row_ids[:nnz] = np.repeat(
        np.arange(n, dtype=np.int32), np.diff(block.offset).astype(np.int64)
    )
    return DeviceCSRBatch(
        labels=labels,
        weights=weights,
        indices=indices,
        values=values,
        row_ids=row_ids,
        num_rows=n,
        num_nonzero=nnz,
    )


def block_to_dense(
    block: RowBlock, batch_size: int, num_features: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Densify a RowBlock into fixed [batch, num_features] — the right layout
    when the feature dim is small/dense (e.g. HIGGS's 28), letting the MXU do
    a plain matmul instead of gather+segment-sum."""
    n = len(block)
    check(n <= batch_size, "block larger than batch_size")
    x = np.zeros((batch_size, num_features), dtype=np.float32)
    rows = np.repeat(np.arange(n), np.diff(block.offset).astype(np.int64))
    vals = (
        np.ones(block.num_nonzero, dtype=np.float32)
        if block.value is None
        else block.value
    )
    keep = block.index < num_features
    x[rows[keep], block.index[keep]] = vals[keep]
    labels = np.zeros(batch_size, dtype=np.float32)
    labels[:n] = block.label
    weights = np.zeros(batch_size, dtype=np.float32)
    weights[:n] = 1.0 if block.weight is None else block.weight
    return x, labels, weights
