"""Device-resident CSR batches with static-shape padding/bucketing.

The reference's ``RowBlock`` (data.h:170) is variable-length CSR on the host.
XLA wants static shapes: a new shape means a new compilation, and a stream of
ragged batches would cause a recompilation storm (SURVEY §7 "hard parts").

Policy here:
- row count is fixed per feed (``batch_size``; the final short batch is
  padded with zero-weight rows so loss/grad contributions vanish),
- nnz is rounded up to a bucket (default: round_up_bucket's
  sixteenth-octave steps above a floor),
  padded entries point at index 0 with value 0 so they are arithmetic no-ops,
- the row-mapping is carried as a per-entry ``row_ids`` array (COO-style),
  which is what TPU-friendly ``segment_sum`` SpMV consumes — instead of the
  host CSR ``offset`` array, whose per-row dynamic slicing XLA can't tile.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.utils.logging import check


def round_up_bucket(n: int, floor: int = 256) -> int:
    """Static-shape nnz bucket ≥ n: the next multiple of a sixteenth of
    the enclosing power of two (with a floor).

    Pure powers of two waste up to ~50% of the segment-sum/SpMV work on
    padding (measured: the Criteo-shape csr SGD ran 22% faster with a
    tight bucket vs the pow2 one). Sixteenth-of-octave steps bound the
    waste at ~12.5% of n (the worst case sits just above a power of two,
    where the step is n/8) while keeping the number of distinct shapes
    XLA compiles small (a steady-state feed with
    stable per-batch nnz sees one, plus one for the short final
    batch). An octave spans pow2/2, so its step of pow2/16 yields at
    most 8 distinct buckets inside it."""
    n = max(n, floor, 1)
    pow2 = 1 << (n - 1).bit_length()
    step = max(floor, pow2 >> 4)
    return ((n + step - 1) // step) * step


@dataclass
class DeviceCSRBatch:
    """A static-shape, device-ready sparse batch (host numpy twin).

    Shapes: labels/weights/row_valid are [batch]; indices/values/row_ids are
    [nnz_bucket]. Padded nnz entries have value 0 at feature 0 and row_id
    pointing at the first padded row (or row 0 with value 0 — a no-op either
    way for segment-sum SpMV).
    """

    labels: np.ndarray  # [batch] f32
    weights: np.ndarray  # [batch] f32 (0.0 for padded rows)
    indices: np.ndarray  # [nnz_bucket] i32 feature ids
    values: np.ndarray  # [nnz_bucket] f32 (0.0 for padded entries)
    row_ids: Optional[np.ndarray]  # [nnz_bucket] i32 row of each entry;
    # None on the device-resident emit path (never shipped — the device
    # expands offsets itself, so the resident stager skips building it)
    offsets: np.ndarray  # [batch + 1] i32 CSR twin of row_ids (shipped to
    # device instead of row_ids: H2D ∝ rows, not nnz; padded rows repeat
    # the valid nnz)
    num_rows: int  # valid rows
    num_nonzero: int  # valid entries

    @property
    def batch_size(self) -> int:
        return len(self.labels)

    @property
    def nnz_bucket(self) -> int:
        return len(self.indices)


def _staging(pool, shape, dtype):
    """A zeroed staging array: from the feed's FixedShapePool when given
    (host-buffer reuse — the allocation retired, the zero-fill kept),
    else a fresh np.zeros."""
    if pool is None:
        return np.zeros(shape, dtype=dtype)
    buf = pool.acquire(shape, dtype)
    buf.fill(0)
    return buf


def pad_to_bucket(
    block: RowBlock,
    batch_size: int,
    nnz_bucket: Optional[int] = None,
    nnz_floor: int = 256,
    pool=None,
) -> DeviceCSRBatch:
    """Pad a host RowBlock slice into a static-shape DeviceCSRBatch.
    ``pool`` (device/feed.FixedShapePool) recycles the staging arrays."""
    n = len(block)
    check(n <= batch_size, "block larger than batch_size")
    nnz = block.num_nonzero
    bucket = nnz_bucket if nnz_bucket is not None else round_up_bucket(nnz, nnz_floor)
    check(nnz <= bucket, "nnz exceeds bucket")

    labels = _staging(pool, batch_size, np.float32)
    labels[:n] = block.label
    weights = _staging(pool, batch_size, np.float32)
    weights[:n] = 1.0 if block.weight is None else block.weight

    indices = _staging(pool, bucket, np.int32)
    values = _staging(pool, bucket, np.float32)
    row_ids = _staging(pool, bucket, np.int32)
    indices[:nnz] = block.index
    values[:nnz] = (
        np.ones(nnz, dtype=np.float32) if block.value is None else block.value
    )
    row_ids[:nnz] = np.repeat(
        np.arange(n, dtype=np.int32), np.diff(block.offset).astype(np.int64)
    )
    if pool is None:
        offsets = np.full(batch_size + 1, nnz, dtype=np.int32)
    else:
        offsets = pool.acquire(batch_size + 1, np.int32)
        offsets.fill(nnz)
    offsets[: n + 1] = np.asarray(block.offset[: n + 1], dtype=np.int32)
    return DeviceCSRBatch(
        labels=labels,
        weights=weights,
        indices=indices,
        values=values,
        row_ids=row_ids,
        offsets=offsets,
        num_rows=n,
        num_nonzero=nnz,
    )


def _staging_raw(pool, shape, dtype):
    """An UNCLEARED staging array (pooled or fresh np.empty) — for the
    emit path, which overwrites the valid prefix and zeroes only the pad
    tail instead of paying a full fill before a full overwrite."""
    if pool is None:
        return np.empty(shape, dtype=dtype)
    return pool.acquire(shape, dtype)


def emit_to_bucket(
    container,
    batch_size: int,
    nnz_bucket: Optional[int] = None,
    nnz_floor: int = 256,
    pool=None,
) -> DeviceCSRBatch:
    """Pad-in-place: emit a ``RowBlockContainer``'s rows straight into a
    static-shape DeviceCSRBatch's (pooled) staging arrays.

    The legacy path is ``container.to_block()`` (a concatenate copy)
    followed by :func:`pad_to_bucket` (a second copy into staging); this
    fuses both into ``RowBlockContainer.emit_csr_into`` — the parsed
    parts' only copy lands directly where ``device_put`` reads. Staging
    is acquired uncleared and only the pad tails are zeroed (padded
    entries stay arithmetic no-ops: value 0 at feature 0, zero-weight
    rows). ``row_ids`` is None: the resident feed never ships it — the
    device expands ``offsets`` itself (ops/spmv.expand_row_ids).
    """
    n = container.size
    check(n <= batch_size, "container larger than batch_size")
    nnz = container.num_nonzero
    bucket = (
        nnz_bucket if nnz_bucket is not None else round_up_bucket(nnz, nnz_floor)
    )
    check(nnz <= bucket, "nnz exceeds bucket")

    labels = _staging_raw(pool, batch_size, np.float32)
    weights = _staging_raw(pool, batch_size, np.float32)
    indices = _staging_raw(pool, bucket, np.int32)
    values = _staging_raw(pool, bucket, np.float32)
    offsets = _staging_raw(pool, batch_size + 1, np.int32)
    container.emit_csr_into(labels, weights, indices, values, offsets)
    labels[n:] = 0.0
    weights[n:] = 0.0
    indices[nnz:] = 0
    values[nnz:] = 0.0
    offsets[n + 1 :] = nnz
    return DeviceCSRBatch(
        labels=labels,
        weights=weights,
        indices=indices,
        values=values,
        row_ids=None,
        offsets=offsets,
        num_rows=n,
        num_nonzero=nnz,
    )


@dataclass
class ShardedCSRBatch:
    """A static-shape COO batch partitioned by destination mesh shard.

    indices/values/row_ids are flat [num_shards * nnz_bucket] with
    contiguous per-shard sections and LOCAL row ids (shard s owns rows
    [s*rows_per_shard, (s+1)*rows_per_shard)); sharding the leading dim
    with P(axis) ships each device only its own entries, so per-device
    H2D is ∝ global_nnz / world — the Criteo-scale requirement the
    replicated layout breaks (every device paying global nnz).
    """

    labels: np.ndarray  # [batch] f32
    weights: np.ndarray  # [batch] f32 (0.0 for padded rows)
    indices: np.ndarray  # [num_shards * nnz_bucket] i32
    values: np.ndarray  # [num_shards * nnz_bucket] f32
    row_ids: np.ndarray  # [num_shards * nnz_bucket] i32, LOCAL per shard
    offsets: np.ndarray  # [num_shards * (rows_per_shard + 1)] i32 per-shard
    # LOCAL CSR offsets into the shard's entry section (shipped instead of
    # row_ids)
    num_rows: int
    num_nonzero: int
    num_shards: int
    nnz_bucket: int  # per shard

    @property
    def batch_size(self) -> int:
        return len(self.labels)


def pad_to_bucket_sharded(
    block: RowBlock,
    batch_size: int,
    num_shards: int,
    nnz_bucket: Optional[int] = None,
    nnz_floor: int = 256,
) -> ShardedCSRBatch:
    """Partition a RowBlock's entries by destination shard (row-range
    split) into per-shard padded sections — the pure-Python twin of
    pipeline.cc FetchBatchCooSharded."""
    n = len(block)
    check(n <= batch_size, "block larger than batch_size")
    check(batch_size % num_shards == 0,
          "batch_size %d must divide over %d shards", batch_size, num_shards)
    rows_per_shard = batch_size // num_shards

    labels = np.zeros(batch_size, dtype=np.float32)
    labels[:n] = block.label
    weights = np.zeros(batch_size, dtype=np.float32)
    weights[:n] = 1.0 if block.weight is None else block.weight

    rows = np.repeat(
        np.arange(n, dtype=np.int64), np.diff(block.offset).astype(np.int64)
    )
    vals = (
        np.ones(block.num_nonzero, dtype=np.float32)
        if block.value is None
        else np.asarray(block.value, np.float32)
    )
    shard_of = rows // rows_per_shard
    counts = np.bincount(shard_of, minlength=num_shards) if len(rows) else (
        np.zeros(num_shards, dtype=np.int64)
    )
    bucket = (
        nnz_bucket if nnz_bucket is not None
        else round_up_bucket(int(counts.max()) if len(rows) else 0, nnz_floor)
    )
    check(int(counts.max() if len(rows) else 0) <= bucket,
          "a shard's nnz exceeds the bucket")

    indices = np.zeros(num_shards * bucket, dtype=np.int32)
    values = np.zeros(num_shards * bucket, dtype=np.float32)
    row_ids = np.zeros(num_shards * bucket, dtype=np.int32)
    offsets = np.zeros(num_shards * (rows_per_shard + 1), dtype=np.int32)
    # entries arrive row-major, so each shard's entries are contiguous
    start = 0
    for s in range(num_shards):
        c = int(counts[s])
        seg = slice(start, start + c)
        out = slice(s * bucket, s * bucket + c)
        indices[out] = block.index[seg]
        values[out] = vals[seg]
        local = rows[seg] - s * rows_per_shard
        row_ids[out] = local
        # local CSR offsets for this shard's section (padded rows repeat c)
        obase = s * (rows_per_shard + 1)
        offsets[obase: obase + rows_per_shard + 1] = np.searchsorted(
            local, np.arange(rows_per_shard + 1), side="left"
        ).astype(np.int32)
        start += c
    return ShardedCSRBatch(
        labels=labels,
        weights=weights,
        indices=indices,
        values=values,
        row_ids=row_ids,
        offsets=offsets,
        num_rows=n,
        num_nonzero=block.num_nonzero,
        num_shards=num_shards,
        nnz_bucket=bucket,
    )


def block_to_dense(
    block: RowBlock, batch_size: int, num_features: int, pool=None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Densify a RowBlock into fixed [batch, num_features] — the right layout
    when the feature dim is small/dense (e.g. HIGGS's 28), letting the MXU do
    a plain matmul instead of gather+segment-sum. ``pool``
    (device/feed.FixedShapePool) recycles the staging arrays."""
    n = len(block)
    check(n <= batch_size, "block larger than batch_size")
    x = _staging(pool, (batch_size, num_features), np.float32)
    rows = np.repeat(np.arange(n), np.diff(block.offset).astype(np.int64))
    vals = (
        np.ones(block.num_nonzero, dtype=np.float32)
        if block.value is None
        else block.value
    )
    keep = block.index < num_features
    x[rows[keep], block.index[keep]] = vals[keep]
    labels = _staging(pool, batch_size, np.float32)
    labels[:n] = block.label
    weights = _staging(pool, batch_size, np.float32)
    weights[:n] = 1.0 if block.weight is None else block.weight
    return x, labels, weights
