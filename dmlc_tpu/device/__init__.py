"""Device bridge: host CSR RowBlocks → static-shape XLA device buffers.

This is the TPU-new subsystem (SURVEY §7 stage 7): the reference's pipeline
ends at host CSR (`RowBlock`); here batches are padded/bucketed to static
shapes (so XLA compiles once per bucket, not per batch), transferred with
async ``jax.device_put`` overlapped with parsing via the ThreadedIter
prefetcher (the ThreadedIter role from threadediter.h, now hiding H2D DMA),
and laid out with per-host batch sharding over a jax.sharding.Mesh.
"""

from dmlc_tpu.device.csr import (
    DeviceCSRBatch,
    ShardedCSRBatch,
    pad_to_bucket,
    pad_to_bucket_sharded,
    round_up_bucket,
)
from dmlc_tpu.device.feed import DeviceFeed, BatchSpec, FixedShapePool

__all__ = [
    "DeviceCSRBatch",
    "ShardedCSRBatch",
    "pad_to_bucket",
    "pad_to_bucket_sharded",
    "round_up_bucket",
    "DeviceFeed",
    "BatchSpec",
    "FixedShapePool",
]
