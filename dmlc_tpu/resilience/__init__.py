"""dmlc_tpu.resilience — the unified fault-handling layer.

Three pieces, one contract:

- :mod:`~dmlc_tpu.resilience.retry` — :class:`RetryPolicy` (decorrelated
  jitter, per-call deadline, process-wide budget, transient/fatal
  classifier) behind every remote retry loop, with per-site
  ``dmlc_retry_*`` metrics.
- :mod:`~dmlc_tpu.resilience.faults` — deterministic
  :func:`faultpoint` hooks armed by ``DMLC_TPU_FAULTS``; a shared no-op
  when disabled.
- :mod:`~dmlc_tpu.resilience.hedge` — :func:`hedged_call` backup
  requests for tail-latency degradation (``DMLC_TPU_HEDGE_S``).
- :mod:`~dmlc_tpu.resilience.preempt` — SIGTERM preemption notices,
  the :data:`EXIT_PREEMPTED` relaunch contract, and the injectable
  ``preempt.notice`` faultpoint (see docs/robustness.md "Preemption &
  resume").

See ``docs/robustness.md`` for the fault model, the faultpoint catalog,
and the chaos-suite how-to.
"""

from dmlc_tpu.resilience.faults import (
    FaultSpecError,
    InjectedFault,
    NOOP,
    configure,
    faultpoint,
    injector,
    parse_spec,
    reset,
)
from dmlc_tpu.resilience.hedge import hedged_call
from dmlc_tpu.resilience.preempt import EXIT_PREEMPTED, Preempted
from dmlc_tpu.resilience.retry import (
    RetryBudget,
    RetryPolicy,
    RetryState,
    TRANSIENT_HTTP_CODES,
    backoff_sleep,
    classify_transient,
    global_budget,
    reset_global_budget,
    retry_call,
)

__all__ = [
    "EXIT_PREEMPTED",
    "FaultSpecError",
    "InjectedFault",
    "NOOP",
    "Preempted",
    "RetryBudget",
    "RetryPolicy",
    "RetryState",
    "TRANSIENT_HTTP_CODES",
    "backoff_sleep",
    "classify_transient",
    "configure",
    "faultpoint",
    "global_budget",
    "hedged_call",
    "injector",
    "parse_spec",
    "reset",
    "reset_global_budget",
    "retry_call",
]
