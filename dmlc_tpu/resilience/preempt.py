"""Preemption notices: SIGTERM-with-deadline made survivable.

TPU fleets preempt whole hosts: the process gets a SIGTERM and a grace
window, then a hard kill. This module turns that into a cooperative
protocol:

- :func:`install` registers a SIGTERM handler (main thread only,
  idempotent) that records a *preemption notice* instead of dying.
- The training loop calls :func:`poll` between steps; once a notice is
  pending it stops cleanly, asks its :class:`~dmlc_tpu.collective.snapshot.Snapshotter`
  to finalize a just-in-time coordinated snapshot within
  :func:`~dmlc_tpu.params.knobs.preempt_deadline_s` seconds, and raises
  :class:`Preempted`.
- :class:`Preempted` is a ``SystemExit`` with :data:`EXIT_PREEMPTED`
  (75, ``EX_TEMPFAIL``): left uncaught it exits the process with that
  code, which the local launcher recognizes and relaunches *without*
  consuming a retry attempt (tracker/launchers/local.py).

For deterministic tests, :func:`poll` also fires the ``preempt.notice``
faultpoint — ``DMLC_TPU_FAULTS="preempt.notice:nth=K"`` simulates a
preemption notice on the K-th poll without any signal plumbing.

See docs/robustness.md "Preemption & resume" for the full signal flow.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Optional

from dmlc_tpu.resilience.faults import InjectedFault, faultpoint
from dmlc_tpu.utils.logging import log_info

#: Exit code signalling "preempted after a committed snapshot — relaunch
#: me" (sysexits EX_TEMPFAIL). Distinct from crash codes so the launcher
#: can relaunch without burning a retry attempt.
EXIT_PREEMPTED = 75


class Preempted(SystemExit):
    """Raised by the training loop after the just-in-time snapshot.

    A ``SystemExit`` subclass: uncaught, the interpreter exits with
    :data:`EXIT_PREEMPTED` — no traceback, no crash-path teardown.
    """

    def __init__(self, message: str = "preempted"):
        super().__init__(EXIT_PREEMPTED)
        self.message = message


_lock = threading.Lock()
_requested = threading.Event()
_notice_at: Optional[float] = None
_deadline_s: Optional[float] = None
_installed = False
_prev_handler = None


def install(deadline_s: Optional[float] = None) -> bool:
    """Arm the SIGTERM preemption handler; returns True when installed.

    Only the main thread may set signal handlers — elsewhere this is a
    no-op (the faultpoint path in :func:`poll` still works). Idempotent:
    a second call just updates the deadline. The handler does NOT chain
    to a previously installed one: a preemption notice means "drain and
    snapshot", which supersedes dump-and-die handlers (the flight
    recorder still dumps on the clean exit path).
    """
    global _installed, _prev_handler, _deadline_s
    with _lock:
        if deadline_s is not None:
            _deadline_s = deadline_s
        if _installed:
            return True
        try:
            _prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:  # not the main thread
            return False
        _installed = True
        return True


def uninstall() -> None:
    """Restore the pre-:func:`install` SIGTERM disposition (tests)."""
    global _installed, _prev_handler
    with _lock:
        if not _installed:
            return
        try:
            signal.signal(signal.SIGTERM, _prev_handler or signal.SIG_DFL)
        except ValueError:
            pass
        _installed = False
        _prev_handler = None


def _on_sigterm(signum, frame) -> None:
    notice("sigterm")


def notice(source: str) -> None:
    """Record a preemption notice (signal handler or injected fault)."""
    global _notice_at
    if _requested.is_set():
        return
    _notice_at = time.monotonic()
    _requested.set()
    # signal-safe enough: counters are plain ints behind a lock-free inc,
    # and the flight recorder appends to a deque
    from dmlc_tpu import obs
    from dmlc_tpu.obs import flight

    obs.registry().counter(
        "dmlc_preempt_notices_total",
        "preemption notices received (SIGTERM or injected)",
    ).inc()
    flight.record_event("preempt.notice", source=source,
                        deadline_s=deadline_s())
    log_info("preemption notice (%s): snapshot deadline %.1fs",
             source, deadline_s())


def poll() -> bool:
    """True once a preemption notice is pending (call between steps).

    Also the injection point for simulated preemptions: each poll fires
    the ``preempt.notice`` faultpoint, so
    ``DMLC_TPU_FAULTS="preempt.notice:nth=K"`` turns the K-th poll into
    a notice — deterministic chaos without signals.
    """
    if _requested.is_set():
        return True
    try:
        faultpoint("preempt.notice")
    except InjectedFault:
        notice("injected")
        return True
    return False


def requested() -> bool:
    return _requested.is_set()


def deadline_s() -> float:
    """The configured grace window (install() override or the knob)."""
    if _deadline_s is not None:
        return _deadline_s
    from dmlc_tpu.params.knobs import preempt_deadline_s

    return preempt_deadline_s()


def deadline_remaining() -> float:
    """Seconds left in the grace window (full window when no notice)."""
    if _notice_at is None:
        return deadline_s()
    return max(0.0, deadline_s() - (time.monotonic() - _notice_at))


def reset() -> None:
    """Clear notice state (tests). Does not touch the signal handler."""
    global _notice_at
    with _lock:
        _requested.clear()
        _notice_at = None
