"""Policy-driven retry/backoff: the one copy of "try again" for every
remote surface.

Before this module each failure surface hand-rolled its own loop
(``_retry_call`` in io/object_store.py, the reconnect loops in
io/filesystem.py, the tracker dial loop in collective/socket_engine.py)
and they disagreed on everything that matters in production: backoff
shape (linear vs fixed), jitter (none — synchronized retry storms),
deadlines (none — a dead endpoint wedged a worker for minutes), retry
budgets (none — 50 retries × N threads amplifies an outage), and error
classification (``_retry_call`` treated HTTP 429/408 as fatal because
``code < 500``). tf.data service (arXiv:2210.14826) and the TensorFlow
system paper (arXiv:1605.08695) both treat transparent fault handling as
a design axis, not an afterthought; this module is that axis.

Design:

- **Classification first.** ``classify_transient(err)`` splits transient
  (HTTP 5xx/429/408, ``URLError``, ``OSError``, ``HTTPException``,
  ``DMLCError``) from fatal (other 4xx, filesystem-shaped config errors
  like ``FileNotFoundError``). Fatal errors re-raise immediately — a 403
  must never burn a retry budget.
- **Decorrelated jitter** (the AWS-architecture-blog shape):
  ``sleep = min(cap, uniform(base, prev * 3))`` — retries desynchronize
  across threads/hosts instead of hammering a recovering endpoint in
  lockstep.
- **Per-call deadline** (``deadline_s`` / ``DMLC_TPU_RETRY_DEADLINE_S``):
  wall-clock bound on one logical operation including sleeps.
- **Process-wide retry budget** (``DMLC_TPU_RETRY_BUDGET``): a token
  bucket shared by every policy in the process; when a systemic outage
  drains it, calls fail fast instead of every thread independently
  running out its full attempt count.
- **Observable.** Every retry ticks ``dmlc_retry_attempts_total{site=}``
  and every give-up ``dmlc_retry_giveups_total{site=}`` in the obs
  registry, so an outage is a metrics query, not a log grep.

Two call shapes: :meth:`RetryPolicy.call` wraps a closure (the
``_retry_call`` replacement); :meth:`RetryPolicy.start` hands loop-style
callers (``read_range_with_retry``'s progress-tracking reconnect loop) a
:class:`RetryState` whose ``failed(err)`` does classify/count/sleep/raise
so the loop keeps its own structure but shares the policy machinery.
"""

from __future__ import annotations

import http.client
import random
import threading
import time
import urllib.error
from typing import Callable, Optional

from dmlc_tpu.params.knobs import retry_budget_tokens, retry_deadline_s
from dmlc_tpu.utils.logging import DMLCError, check

# config mistakes dressed as OSError: retrying cannot fix a missing file
# or a permission wall (same split collective.run_with_recovery makes)
_CONFIG_ERRORS = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
    FileExistsError,
)

# HTTP statuses below 500 that are still transient: request timeout and
# throttling. Parallel readahead makes 429 likelier, and aborting ingest
# on rate limiting would be a regression vs single-connection readers.
TRANSIENT_HTTP_CODES = frozenset({408, 429})


def classify_transient(err: BaseException) -> bool:
    """True when retrying ``err`` can plausibly succeed."""
    if isinstance(err, _CONFIG_ERRORS):
        return False
    if isinstance(err, urllib.error.HTTPError):
        return err.code >= 500 or err.code in TRANSIENT_HTTP_CODES
    return isinstance(
        err,
        (urllib.error.URLError, OSError, http.client.HTTPException,
         DMLCError),
    )


class RetryBudget:
    """Token bucket bounding retries across the whole process.

    ``capacity`` tokens, refilled continuously at ``capacity`` per
    ``refill_s`` seconds. ``capacity <= 0`` means unlimited (the
    default): individual policies still bound their own attempts; the
    budget exists so a systemic outage costs O(budget) retries, not
    O(call sites × attempts).
    """

    def __init__(self, capacity: int = 0, refill_s: float = 60.0):
        self.capacity = int(capacity)
        self._refill_s = float(refill_s)
        self._tokens = float(self.capacity)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def take(self) -> bool:
        """Consume one retry token; False = budget exhausted."""
        if self.capacity <= 0:
            return True
        now = time.monotonic()
        with self._lock:
            rate = self.capacity / self._refill_s
            self._tokens = min(
                float(self.capacity), self._tokens + (now - self._last) * rate
            )
            self._last = now
            if self._tokens < 1.0:
                return False
            self._tokens -= 1.0
            return True


_GLOBAL_BUDGET: Optional[RetryBudget] = None
_GLOBAL_LOCK = threading.Lock()


def global_budget() -> RetryBudget:
    """The process-wide budget (capacity from ``DMLC_TPU_RETRY_BUDGET``)."""
    global _GLOBAL_BUDGET
    with _GLOBAL_LOCK:
        if _GLOBAL_BUDGET is None:
            _GLOBAL_BUDGET = RetryBudget(retry_budget_tokens())
        return _GLOBAL_BUDGET


def reset_global_budget() -> None:
    """Re-read the budget knob (tests; a fresh process state)."""
    global _GLOBAL_BUDGET
    with _GLOBAL_LOCK:
        _GLOBAL_BUDGET = None


def _site_metrics(site: str):
    from dmlc_tpu import obs  # deferred: resilience is below obs's deps

    reg = obs.registry()
    return (
        reg.counter("dmlc_retry_attempts_total",
                    "retries performed, by call site", site=site),
        reg.counter("dmlc_retry_giveups_total",
                    "operations abandoned after exhausting retries",
                    site=site),
    )


class RetryState:
    """One logical operation's retry bookkeeping (see RetryPolicy.start).

    The owner loop calls :meth:`failed` on each error; it either sleeps
    (retry granted) or raises. ``progressed=True`` refills the attempt
    count — a long transfer over a flaky link that keeps delivering bytes
    must not exhaust its budget while advancing (the reconnect shape of
    the reference's s3_filesys.cc:319-342) — bounded by an absolute
    attempt ceiling so a server dripping one byte per connection cannot
    turn into a multi-day hang.
    """

    def __init__(self, policy: "RetryPolicy", site: str, display: str,
                 cancelled: Optional[Callable[[], bool]]):
        self._policy = policy
        self.site = site
        self.display = display or site
        self._cancelled = cancelled
        self.attempts_left = policy.max_attempts
        self.total_attempts = 0
        self._deadline = (
            policy.clock() + policy.deadline_s if policy.deadline_s else None
        )
        self._prev_sleep = policy.base_s
        self._m_attempts, self._m_giveups = _site_metrics(site)

    def _give_up(self, err: BaseException, why: str):
        self._m_giveups.inc()
        # flight-recorder tail: the give-up is exactly the moment whose
        # preceding seconds a post-mortem wants; injected faults also
        # trigger the chaos-suite dump
        from dmlc_tpu.obs import flight

        flight.record_event("retry.giveup", site=self.site, why=why,
                            error=str(err))
        flight.dump_if_injected(err)
        raise DMLCError(
            f"{self.display}: gave up after {self.total_attempts} "
            f"attempt(s) ({why}): {err}"
        ) from err

    def failed(self, err: BaseException, progressed: bool = False) -> None:
        """Record one failed attempt: re-raise fatal errors, give up when
        out of attempts/deadline/budget, otherwise sleep with jitter."""
        if not self._policy.classify(err):
            raise err  # fatal: surface untouched, burn nothing
        if self._cancelled is not None and self._cancelled():
            raise DMLCError(f"{self.display}: cancelled") from err
        if progressed:
            self.attempts_left = self._policy.max_attempts
        self.attempts_left -= 1
        self.total_attempts += 1
        if self.attempts_left <= 0:
            self._give_up(err, "attempts exhausted")
        if self.total_attempts >= self._policy.max_attempts * 10:
            self._give_up(err, "absolute attempt ceiling")
        if not self._policy.budget.take():
            self._give_up(err, "process retry budget exhausted")
        delay = self._policy.next_sleep(self._prev_sleep)
        self._prev_sleep = delay
        if self._deadline is not None and \
                self._policy.clock() + delay > self._deadline:
            self._give_up(err, f"deadline {self._policy.deadline_s}s")
        self._m_attempts.inc()
        self._policy.sleep(delay)


class RetryPolicy:
    """The knobs of one retry discipline; cheap to construct per call.

    ``max_attempts`` counts tries (1 = no retry). ``base_s``/``cap_s``
    bound the decorrelated-jitter sleep. ``deadline_s`` (None → the
    ``DMLC_TPU_RETRY_DEADLINE_S`` knob; 0 = unbounded) is the wall-clock
    bound per logical call. ``budget`` defaults to the process-wide
    bucket. ``classify``/``rng``/``sleep`` are injectable for tests.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        base_s: float = 0.1,
        cap_s: float = 2.0,
        deadline_s: Optional[float] = None,
        budget: Optional[RetryBudget] = None,
        classify: Callable[[BaseException], bool] = classify_transient,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        check(max_attempts >= 1, "max_attempts must be >= 1, got %d",
              max_attempts)
        self.max_attempts = int(max_attempts)
        self.base_s = float(base_s)
        self.cap_s = max(float(cap_s), self.base_s)
        if deadline_s is None:
            deadline_s = retry_deadline_s()
        self.deadline_s = float(deadline_s) if deadline_s else 0.0
        self.budget = budget if budget is not None else global_budget()
        self.classify = classify
        self._rng = rng or random
        self.sleep = sleep
        self.clock = clock

    def next_sleep(self, prev: float) -> float:
        """Decorrelated jitter: uniform over [base, prev*3], capped."""
        return min(self.cap_s, self._rng.uniform(self.base_s, prev * 3))

    def start(self, site: str, display: str = "",
              cancelled: Optional[Callable[[], bool]] = None) -> RetryState:
        """A fresh :class:`RetryState` for a caller-owned loop."""
        return RetryState(self, site, display, cancelled)

    def call(self, fn: Callable, site: str, display: str = "",
             cancelled: Optional[Callable[[], bool]] = None):
        """Run ``fn()`` under this policy; the ``_retry_call`` shape.

        Unlike the helper it replaces, there is no sleep after the final
        failed attempt (the old loop wasted a full backoff before
        raising) and 429/408 retry like 5xx.
        """
        state = self.start(site, display=display, cancelled=cancelled)
        while True:
            try:
                return fn()
            except Exception as err:  # noqa: BLE001 — classify() decides
                state.failed(err)


def retry_call(fn: Callable, site: str, display: str = "",
               max_attempts: int = 3, base_s: float = 0.1,
               cap_s: float = 2.0):
    """One-shot convenience: ``RetryPolicy(...).call(fn, site)``."""
    return RetryPolicy(
        max_attempts=max_attempts, base_s=base_s, cap_s=cap_s
    ).call(fn, site, display=display)


def backoff_sleep(attempt: int, site: str, base_s: float = 0.5,
                  cap_s: float = 5.0) -> None:
    """Jittered sleep for orchestration loops that retry outside the
    call/raise shape (e.g. the recover-rendezvous loop): records the
    retry in the site's metrics and sleeps with decorrelated jitter
    seeded off the attempt number."""
    m_attempts, _ = _site_metrics(site)
    m_attempts.inc()
    prev = base_s * (2 ** max(0, attempt - 1))
    time.sleep(min(cap_s, random.uniform(base_s, max(base_s, prev * 3))))
