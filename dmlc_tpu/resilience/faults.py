"""Deterministic fault injection: `faultpoint("site")` hooks that are a
shared no-op until `DMLC_TPU_FAULTS` arms them.

The recovery plane (tracker `cmd='recover'`, checkpoint-replay in
`collective.run_with_recovery`) is only trustworthy if it is *exercised*,
and monkeypatching internals from tests is both fragile and impossible
across the `dmlc-submit` process boundary. Instead, the production code
carries named faultpoints at every failure surface (catalog in
`docs/robustness.md`, enforced by `scripts/check_faultpoints.py`) and an
env spec arms them — so a chaos test is just an environment variable on
a real training run.

Spec grammar (``;``-separated site clauses, ``:``-separated options)::

    DMLC_TPU_FAULTS="io.read:p=0.02:seed=7;collective.send:nth=3"

- ``p=<float>``   — fire with probability p per pass, drawn from a
  per-site ``random.Random(crc32(site) ^ seed)``. Same spec + seed ⇒
  the same ops fault, run after run, regardless of which *other* sites
  are armed (per-site streams don't perturb each other).
- ``seed=<int>``  — seed for that site's stream (default 0).
- ``nth=<int>``   — scripted: fire exactly on the Nth pass through the
  site (1-based), once. ``times=<k>`` repeats it for the next k-1
  passes too (``nth=3:times=2`` → passes 3 and 4).

A fired faultpoint raises :class:`InjectedFault` — an ``OSError``
subclass, so the retry classifier treats it as transient and the
collective plane treats it as a peer failure, exactly like the real
faults it stands in for.

Disabled path: with ``DMLC_TPU_FAULTS`` unset, every ``faultpoint()``
call dispatches to the module-level shared :data:`NOOP` injector whose
``check`` is ``pass`` — no allocation, no branching on spec state —
mirroring the ``DMLC_TPU_METRICS=0`` no-op-child pattern in
``obs/metrics.py``.
"""

from __future__ import annotations

import random
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from dmlc_tpu.params.knobs import faults_spec
from dmlc_tpu.utils.logging import DMLCError


class InjectedFault(OSError):
    """The error a fired faultpoint raises (transient + peer-failure)."""


class FaultSpecError(DMLCError):
    """A malformed ``DMLC_TPU_FAULTS`` spec (fail loud, not silently)."""


class _SiteRule:
    """One armed site: either probabilistic (p/seed) or scripted (nth)."""

    __slots__ = ("site", "p", "nth", "times", "_rng", "_passes", "_lock")

    def __init__(self, site: str, p: float, seed: int, nth: int, times: int):
        self.site = site
        self.p = p
        self.nth = nth
        self.times = times
        # per-site stream: crc32(site) decorrelates sites sharing a seed,
        # and keeps each site's draw sequence independent of which other
        # sites are armed (the determinism the chaos tests rely on)
        self._rng = random.Random(zlib.crc32(site.encode()) ^ seed)
        self._passes = 0
        self._lock = threading.Lock()

    def should_fire(self) -> bool:
        with self._lock:
            self._passes += 1
            n = self._passes
            if self.nth > 0:
                return self.nth <= n < self.nth + self.times
            return self._rng.random() < self.p


class FaultInjector:
    """The armed implementation behind :func:`faultpoint`."""

    def __init__(self, rules: Dict[str, _SiteRule]):
        self._rules = rules
        self.fired: List[Tuple[str, int]] = []  # (site, pass#) log
        self._lock = threading.Lock()

    def check(self, site: str) -> None:
        rule = self._rules.get(site)
        if rule is None or not rule.should_fire():
            return
        with self._lock:
            self.fired.append((site, rule._passes))
        self._count(site)
        self._record(site, rule._passes)
        raise InjectedFault(f"injected fault at {site} "
                            f"(pass {rule._passes})")

    @staticmethod
    def _count(site: str) -> None:
        from dmlc_tpu import obs  # deferred; only on the (rare) fire path

        obs.registry().counter(
            "dmlc_fault_injected_total",
            "faults fired by the injection harness", site=site).inc()

    @staticmethod
    def _record(site: str, passes: int) -> None:
        from dmlc_tpu.obs import flight  # deferred; only on the fire path

        flight.record_event("fault.injected", site=site, n=passes)

    def sites(self) -> List[str]:
        return sorted(self._rules)


class _NoopInjector:
    """Shared disabled-path injector: ``check`` must stay allocation-free."""

    __slots__ = ()

    def check(self, site: str) -> None:
        pass

    def sites(self) -> List[str]:
        return []


NOOP = _NoopInjector()


def parse_spec(spec: str) -> Dict[str, _SiteRule]:
    """Parse a ``DMLC_TPU_FAULTS`` string into per-site rules."""
    rules: Dict[str, _SiteRule] = {}
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        site = parts[0].strip()
        if not site:
            raise FaultSpecError(f"empty site in fault spec clause "
                                 f"{clause!r}")
        p, seed, nth, times = 0.0, 0, 0, 1
        for opt in parts[1:]:
            if "=" not in opt:
                raise FaultSpecError(
                    f"fault option {opt!r} at {site!r} is not key=value")
            key, _, val = opt.partition("=")
            try:
                if key == "p":
                    p = float(val)
                elif key == "seed":
                    seed = int(val)
                elif key == "nth":
                    nth = int(val)
                elif key == "times":
                    times = int(val)
                else:
                    raise FaultSpecError(
                        f"unknown fault option {key!r} at {site!r} "
                        f"(want p/seed/nth/times)")
            except ValueError as err:
                raise FaultSpecError(
                    f"bad value for {key!r} at {site!r}: {val!r}") from err
        if nth <= 0 and not (0.0 < p <= 1.0):
            raise FaultSpecError(
                f"site {site!r} needs nth=<N> or p in (0, 1], got "
                f"p={p} nth={nth}")
        rules[site] = _SiteRule(site, p=p, seed=seed, nth=nth,
                                times=max(1, times))
    return rules


_INJECTOR = NOOP
_INIT_LOCK = threading.Lock()
_INITIALIZED = False


def _ensure_init() -> None:
    global _INJECTOR, _INITIALIZED
    if _INITIALIZED:
        return
    with _INIT_LOCK:
        if _INITIALIZED:
            return
        spec = faults_spec()
        if spec:
            _INJECTOR = FaultInjector(parse_spec(spec))
        _INITIALIZED = True


def configure(spec: Optional[str]) -> None:
    """(Re)arm the injector from an explicit spec — the in-process test
    hook; production arms via ``DMLC_TPU_FAULTS`` at first use."""
    global _INJECTOR, _INITIALIZED
    with _INIT_LOCK:
        _INJECTOR = FaultInjector(parse_spec(spec)) if spec else NOOP
        _INITIALIZED = True


def reset() -> None:
    """Disarm and forget: the next :func:`faultpoint` re-reads the env."""
    global _INJECTOR, _INITIALIZED
    with _INIT_LOCK:
        _INJECTOR = NOOP
        _INITIALIZED = False


def injector():
    """The live injector (NOOP when disabled) — for tests/introspection."""
    _ensure_init()
    return _INJECTOR


def faultpoint(site: str) -> None:
    """Maybe raise :class:`InjectedFault` at ``site``.

    The disabled fast path is one global load, one cheap ``_INITIALIZED``
    check, and a no-op method call — safe to leave on hot paths.
    """
    if not _INITIALIZED:
        _ensure_init()
    _INJECTOR.check(site)
