"""Hedged requests: one backup attempt after a latency threshold.

Tail-latency degradation (a straggling object-store GET) is a failure
mode retries never see — nothing errored, the reply is just slow, and a
synchronous readahead window stalls behind it. The classic fix ("The
Tail at Scale") is to hedge: after ``threshold_s`` without a reply,
issue one duplicate request and take whichever finishes first.

``hedged_call(fn, threshold_s, site)`` is deliberately narrow:

- ``threshold_s <= 0`` (the ``DMLC_TPU_HEDGE_S`` default) calls ``fn()``
  inline — zero threads, zero overhead, hedging strictly opt-in.
- ``fn`` must be side-effect-free to duplicate (an idempotent range
  GET). Callers that write into caller-owned buffers (the ``into=``
  readinto paths) must NOT hedge — two winners racing one buffer is
  memory corruption, which is why only the allocating fetch path in
  ``io/readahead.py`` opts in.
- The loser is abandoned, not cancelled (urllib has no cancel); it
  finishes in a daemon thread and its result is dropped.

Hedges and hedge-wins are visible as
``dmlc_readahead_hedges_total`` / ``dmlc_readahead_hedge_wins_total``.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, TypeVar

T = TypeVar("T")


def _metrics(site: str):
    from dmlc_tpu import obs  # deferred: keep io importable without obs

    reg = obs.registry()
    return (
        reg.counter("dmlc_readahead_hedges_total",
                    "backup requests issued after the hedge threshold",
                    site=site),
        reg.counter("dmlc_readahead_hedge_wins_total",
                    "hedged backups that beat the primary request",
                    site=site),
    )


def hedged_call(fn: Callable[[], T], threshold_s: float,
                site: str = "readahead.fetch") -> T:
    """Run ``fn()``; if it takes longer than ``threshold_s``, launch one
    duplicate and return the first result (first error if both fail)."""
    if threshold_s <= 0:
        return fn()

    results: "queue.Queue[tuple]" = queue.Queue()

    def run(is_backup: bool) -> None:
        try:
            results.put((is_backup, ("ok", fn())))
        except BaseException as err:  # noqa: BLE001 — relayed to caller
            results.put((is_backup, ("err", err)))

    threading.Thread(target=run, args=(False,), daemon=True,
                     name=f"hedge-primary-{site}").start()
    try:
        first_is_backup, outcome = results.get(timeout=threshold_s)
    except queue.Empty:
        m_hedges, m_wins = _metrics(site)
        m_hedges.inc()
        threading.Thread(target=run, args=(True,), daemon=True,
                         name=f"hedge-backup-{site}").start()
        first_is_backup, outcome = results.get()
        if outcome[0] == "err":
            # first finisher failed; the other attempt may still win
            first_is_backup, outcome = results.get()
        if first_is_backup and outcome[0] == "ok":
            m_wins.inc()
    if outcome[0] == "err":
        raise outcome[1]
    return outcome[1]
