"""dmlc_tpu.obs — unified metrics + tracing.

One observability surface for the whole stack (the tf.data lesson,
arXiv:2101.12127: uniform per-stage metrics are the precondition for
bottleneck diagnosis and auto-tuning):

- :func:`registry` — the process-wide label-aware Counter/Gauge/Histogram
  store every stage counter lives in (``DMLC_TPU_METRICS=0`` disables;
  see obs/metrics.py)
- :func:`span` / :func:`step_span` — Chrome-trace span context managers
  gated by ``DMLC_TPU_TRACE=<path>`` (see obs/trace.py)
- :func:`new_flow` / :func:`flow_start` / :func:`flow_step` /
  :func:`flow_end` — causal dataflow arrows (Chrome-trace flow events)
  connecting a chunk's io→parse→stage→dispatch→consume journey across
  threads and ranks; :func:`current_flow` / :func:`set_current_flow`
  carry the in-flight chunk's id through the fit loop
- exporters — JSONL / Prometheus textfile / log-sink summary, driven at
  epoch boundaries by :func:`export_epoch` via ``DMLC_TPU_METRICS_EXPORT``
- :func:`cross_host_snapshot` / :func:`report_skew` — per-host
  min/median/max over a ``collective.DeviceEngine`` allreduce
- ``obs.plane`` — the job-wide observability plane: workers piggyback
  metric/span payloads on tracker heartbeats; the tracker serves
  ``/healthz /workers /metrics /trace`` over HTTP when
  ``DMLC_TPU_STATUS_PORT`` is set (see obs/plane.py)
- ``obs.flight`` — crash flight recorder: a bounded ring of recent
  spans/metric deltas/resilience events dumped to
  ``flightrec-rank<k>.json`` on fatal error when ``DMLC_TPU_FLIGHTREC``
  names a directory (see obs/flight.py)
- ``obs.device_telemetry`` — the device side: :func:`instrumented_jit`
  recompile sentinel, HBM/live-buffer gauges, H2D bandwidth metering,
  and on-demand ``jax.profiler`` capture through the status plane
  (``DMLC_TPU_DEVICE_TELEMETRY``; see obs/device_telemetry.py)
- ``obs.goodput`` — the runtime goodput ledger: per-window stage
  budgets, roofline attribution, and the live binding-constraint
  verdict served by ``/goodput``, obs-top, obs-report, and bench
  (see obs/goodput.py)
- ``obs.watchdog`` — the in-run SLO watchdog over ledger windows:
  throughput collapse, recompile storms, pipeline stalls, straggler
  ranks, non-finite numerics; fires ``watchdog.alert`` flight events
  (see obs/watchdog.py)
- ``obs.audit`` — the cross-rank determinism audit plane: streaming
  per-stage content-digest chains (io_read/parse/batch/model), epoch
  self-checks, tracker-side cross-rank comparison behind ``/audit``,
  and ``audit-rank<k>.json`` replay bundles on the first fork
  (``DMLC_TPU_AUDIT``; see obs/audit.py)

Metric names follow ``dmlc_<area>_<name>_<unit>`` and every registered
name is documented in docs/observability.md (enforced by
``scripts/check_metric_names.py`` / tests/test_metric_lint.py).
"""

from dmlc_tpu.obs.aggregate import cross_host_snapshot, report_skew
from dmlc_tpu.obs.device_telemetry import instrumented_jit
from dmlc_tpu.obs.goodput import GoodputLedger, attribute, ledger
from dmlc_tpu.obs.watchdog import Watchdog, make_watchdog
from dmlc_tpu.obs.exporters import (
    export_epoch,
    export_jsonl,
    export_prometheus,
    prometheus_lines,
    summary_line,
)
from dmlc_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    registry,
)
from dmlc_tpu.obs.trace import (
    clear as clear_trace,
    current_flow,
    events as trace_events,
    flow_end,
    flow_start,
    flow_step,
    flush as flush_trace,
    new_flow,
    set_current_flow,
    span,
    step_span,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "registry",
    "span",
    "step_span",
    "new_flow",
    "flow_start",
    "flow_step",
    "flow_end",
    "current_flow",
    "set_current_flow",
    "trace_events",
    "clear_trace",
    "flush_trace",
    "export_epoch",
    "export_jsonl",
    "export_prometheus",
    "prometheus_lines",
    "summary_line",
    "cross_host_snapshot",
    "report_skew",
    "instrumented_jit",
    "GoodputLedger",
    "attribute",
    "ledger",
    "Watchdog",
    "make_watchdog",
]
