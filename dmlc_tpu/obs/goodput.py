"""Runtime goodput ledger and roofline attribution.

ROADMAP item 4 asks for "attributing every remaining MB/s" of the e2e
SGD gap — but until this module, attribution only existed offline
(bench-gate in CI, obs-report over a dump). This is the *runtime* half:
decompose rolling wall-clock into per-stage budgets by reading the
counters and span timers the tree already maintains, compute goodput
(useful examples/s and MB/s over wall time, vs a "badput" residual of
waiting + unattributed time), compare each stage's achieved rate to its
roofline ceiling, and name the live **binding constraint** per window.

One code path serves every surface: :func:`attribute` produces the
window verdict consumed by the ``/goodput`` status endpoint
(obs/plane.py computes it per rank from heartbeat metric snapshots),
the ``obs-top`` goodput columns, ``obs-report --attribution``, the
``goodput`` section of bench detail JSON, and the fit loops' epoch log
line (models/fitloop.py) — so a throttled-parse run names ``parse``
binding everywhere or nowhere.

Stage budgets come from the flat registry deltas (metrics.flat_values):

- ``parse``       — ``dmlc_feed_host_batch_ns`` (host production:
  parse + densify/pad; io_read time is folded in here — the readahead
  layer overlaps reads, so a read-bound pipeline surfaces as host
  production time)
- ``h2d``         — ``dmlc_feed_dispatch_ns`` (async device submission;
  the staging-pool walk rides inside it)
- ``device_step`` — ``dmlc_feed_consume_ns`` (time the consumer held
  each batch: the optimizer step). A feed-less fit (GBDT's binned
  matrix) falls back to ``dmlc_fit_epoch_ns``.
- ``collective``  — ``dmlc_collective_op_ns`` (socket/D2H fallback ops;
  in-graph psums live inside the device step)
- ``checkpoint``  — ``dmlc_snap_capture_ns`` (job-snapshot state
  capture on the training thread; the serialize + two-phase commit runs
  on the async writer thread off the step path, so this stage staying
  tiny is the *proof* the snapshotter is off the critical path)
- ``host_wait``   — ``dmlc_feed_host_wait_ns`` (consumer starved by the
  host producer — the classic input-bound signature)
- ``idle``        — residual wall not covered by the serial-stage sum

Roofline ceilings (MB/s unless noted), merged over
:func:`default_ceilings`:

- ``parse_mbps``  — the parse_only bench tier's ceiling
  (``DMLC_TPU_PARSE_PEAK_MBPS``, default 1000 — the ~1 GB/s vectorized
  parse tier in docs/performance.md)
- ``h2d_mbps``    — measured, not configured: bench passes
  ``device_feed_probe_gbps`` through ``ceilings=`` (0 = unknown)
- ``step_mbps``   — device-step byte-rate ceiling
  (``DMLC_TPU_STEP_PEAK_MBPS``, default 0 = unknown; set it from the
  model's measured FLOP rate to get step utilization)
- ``ici_gbps``    — per-direction per-link ICI peak in GB/s
  (``DMLC_TPU_ICI_PEAK_GBPS``, default 45 — same knob
  bench_collective.py scores against)

Those ceilings are all *measured-probe* style (a bench tier, a feed
probe, a spec sheet). The compiled-step cost records (obs/xla_cost.py)
add the *model-based* pair: the window's flop/byte estimate is steps ×
the hot step's per-call XLA analytics (``dmlc_xla_flops{fn=}`` /
``dmlc_xla_bytes_accessed{fn=}`` over the ``*.step``/``*.step_mp``
sites, read from the ``current`` snapshot — gauges, so never from a
clamped delta), scored against ``peak_flops``
(``DMLC_TPU_PEAK_FLOPS``, default = the measured matmul probe) and
``hbm_gbps`` (``DMLC_TPU_PEAK_HBM_GBPS``, default = the measured
streaming probe). When computable the verdict gains ``mfu`` (model
FLOP utilization ∈ (0, 1]), ``hbm_fraction``, and a ``compute`` block
naming device_step's model-predicted floor seconds next to its
measured budget — all keys absent otherwise, so surfaces that render
conditionally (obs-top's mfu column) stay byte-stable.

The per-step :class:`GoodputLedger` is the in-run form: ``note_step()``
on the hot path (one integer add), ``tick()`` at window boundaries
(epoch ends) snapshots the registry, attributes the delta, updates the
``dmlc_goodput_ratio_value`` gauge, and returns the window for the SLO
watchdog (obs/watchdog.py). Under ``DMLC_TPU_METRICS=0``
:func:`ledger` hands back the shared no-op child (metrics.NOOP) so the
hot loop stays allocation-free — pinned by tests/test_goodput.py.
"""

from __future__ import annotations

import collections
import time
from typing import Deque, Dict, List, Optional, Sequence

from dmlc_tpu.obs.metrics import (
    NOOP,
    Registry,
    metrics_enabled,
    registry,
)
from dmlc_tpu.params import knobs

#: window history kept by a ledger (rolling; the watchdog keeps its own)
DEFAULT_HISTORY = 64

# flat-registry families feeding the stage budgets (histogram ns sums)
_STAGE_SOURCES = {
    "parse": "dmlc_feed_host_batch_ns",
    "h2d": "dmlc_feed_dispatch_ns",
    "host_wait": "dmlc_feed_host_wait_ns",
    "device_step": "dmlc_feed_consume_ns",
    "collective": "dmlc_collective_op_ns",
    "checkpoint": "dmlc_snap_capture_ns",
}
_FIT_EPOCH = "dmlc_fit_epoch_ns"

#: every stage key an attribution's ``budget_s`` carries, in report order
STAGES = ("parse", "h2d", "device_step", "collective", "checkpoint",
          "host_wait", "idle")


def _sum_named(flat: Dict[str, float], name: str, suffix: str = "") -> float:
    """Sum one family across its label sets: ``name`` +
    ``name{...}`` flat keys, with an optional ``:sum``/``:count``
    histogram suffix."""
    exact = name + suffix
    prefix = name + "{"
    total = 0.0
    for key, v in flat.items():
        if key == exact:
            total += v
        elif key.startswith(prefix) and key.endswith(suffix):
            total += v
    return total


def _max_named(flat: Dict[str, float], name: str, default: float) -> float:
    prefix = name + "{"
    best = None
    for key, v in flat.items():
        if key == name or key.startswith(prefix):
            best = v if best is None else max(best, v)
    return default if best is None else best


def flat_delta(cur: Dict[str, float],
               prev: Dict[str, float]) -> Dict[str, float]:
    """Windowed registry view: ``cur − prev`` per flat key, clamped at 0
    (a restarted worker's counters reset; a negative delta is a rebase,
    not negative work)."""
    out: Dict[str, float] = {}
    for key, v in cur.items():
        try:
            d = float(v) - float(prev.get(key, 0.0))
        except (TypeError, ValueError):
            continue
        out[key] = d if d > 0.0 else 0.0
    return out


def stage_seconds(delta: Dict[str, float]) -> Dict[str, float]:
    """Per-stage second budgets from one flat-registry delta."""
    out = {}
    for stage, family in _STAGE_SOURCES.items():
        out[stage] = _sum_named(delta, family, ":sum") / 1e9
    if out["device_step"] <= 0.0:
        # feed-less fits (GBDT's binned matrix) time the whole fit as
        # one epoch histogram; book it as device-step work
        out["device_step"] = _sum_named(delta, _FIT_EPOCH, ":sum") / 1e9
    return out


def progress_counters(delta: Dict[str, float]) -> Dict[str, float]:
    """The window's useful-work counters from one flat-registry delta."""
    h2d_bytes = _sum_named(delta, "dmlc_feed_h2d_bytes_total")
    io_bytes = _sum_named(delta, "dmlc_io_read_bytes_total")
    return {
        "steps": _sum_named(delta, "dmlc_fit_steps_total"),
        "batches": _sum_named(delta, "dmlc_feed_batches_total"),
        "rows": _sum_named(delta, "dmlc_feed_rows_total"),
        "bytes": h2d_bytes if h2d_bytes > 0 else io_bytes,
        "io_bytes": io_bytes,
        "collective_bytes": _sum_named(
            delta, "dmlc_collective_moved_bytes_total"),
        "recompiles": _sum_named(delta, "dmlc_xla_recompiles_total"),
    }


def default_ceilings() -> Dict[str, float]:
    """Roofline ceilings from the env knobs (see module docstring);
    callers overlay measured values (``device_feed_probe_gbps``)."""
    return {
        "parse_mbps": knobs.parse_peak_mbps(),
        "h2d_mbps": 0.0,
        "step_mbps": knobs.step_peak_mbps(),
        "ici_gbps": knobs.ici_peak_gbps(),
        "peak_flops": knobs.peak_flops(),
        "hbm_gbps": knobs.peak_hbm_gbps(),
    }


def _rate_mbps(num_bytes: float, seconds: float) -> float:
    return num_bytes / seconds / 1e6 if seconds > 0 else 0.0


def _roofline(stages: Dict[str, float], counters: Dict[str, float],
              ceilings: Dict[str, float]) -> Dict[str, Dict]:
    """Per-stage achieved rate vs ceiling; ``utilization`` is None when
    the ceiling is unknown (0)."""
    nbytes = counters.get("bytes", 0.0)
    out: Dict[str, Dict] = {}
    for stage, ceiling_key in (("parse", "parse_mbps"),
                               ("h2d", "h2d_mbps"),
                               ("device_step", "step_mbps")):
        achieved = _rate_mbps(nbytes, stages.get(stage, 0.0))
        ceiling = float(ceilings.get(ceiling_key, 0.0) or 0.0)
        out[stage] = {
            "achieved_mbps": round(achieved, 3),
            "ceiling_mbps": round(ceiling, 3),
            "utilization": round(achieved / ceiling, 4) if ceiling > 0
            else None,
        }
    coll_s = stages.get("collective", 0.0)
    coll_gbps = (counters.get("collective_bytes", 0.0) / coll_s / 1e9
                 if coll_s > 0 else 0.0)
    ici = float(ceilings.get("ici_gbps", 0.0) or 0.0)
    out["collective"] = {
        "achieved_gbps": round(coll_gbps, 4),
        "ceiling_gbps": round(ici, 3),
        "utilization": round(coll_gbps / ici, 4) if ici > 0 else None,
    }
    return out


def _finish(stages: Dict[str, float], counters: Dict[str, float],
            wall_s: float, ceilings: Optional[Dict] = None) -> Dict:
    """Shared verdict builder for :func:`attribute` and :func:`rolled`."""
    wall_s = max(float(wall_s), 1e-9)
    ceil = default_ceilings()
    if ceilings:
        ceil.update({k: v for k, v in ceilings.items() if v is not None})
    serial = (stages["parse"] + stages["h2d"] + stages["device_step"]
              + stages["collective"] + stages["checkpoint"]
              + stages["host_wait"])
    idle = max(0.0, wall_s - serial)
    budget = dict(stages, idle=idle)
    # binding: the stage whose time budget dominates the window. The
    # input-bound signature is host production time PLUS the consumer's
    # wait on it (overlapped pipelines starve via host_wait, serial
    # ones via host_batch) — both accrue to "parse".
    scores = {
        "parse": stages["parse"] + stages["host_wait"],
        "h2d": stages["h2d"],
        "device_step": stages["device_step"],
        "collective": stages["collective"],
        "checkpoint": stages["checkpoint"],
    }
    binding = max(scores, key=lambda k: scores[k])
    if scores[binding] <= 0.0 or idle > scores[binding]:
        binding = "idle"
    nbytes = counters.get("bytes", 0.0)
    rows = counters.get("rows", 0.0)
    # goodput = the fraction of wall the pipeline spent doing useful
    # device-side work (submission + step); badput = waiting + residual
    ratio = min(1.0, (stages["h2d"] + stages["device_step"]) / wall_s)
    roofline = _roofline(stages, counters, ceil)
    at_roof = False
    util = roofline.get(binding, {}).get("utilization")
    if util is not None and util >= 0.8:
        at_roof = True
    out = {
        "window_s": round(wall_s, 6),
        "budget_s": {k: round(v, 6) for k, v in budget.items()},
        "counters": {k: round(v, 3) for k, v in counters.items()},
        "goodput": {
            "rows_s": round(rows / wall_s, 3),
            "mbps": round(_rate_mbps(nbytes, wall_s), 3),
            "ratio": round(ratio, 4),
        },
        "roofline": roofline,
        "binding": binding,
        "at_roof": at_roof,
    }
    # model-based roofline: the window's XLA flop/byte estimate (steps ×
    # per-step compiled-program analytics, injected by attribute() or
    # summed across ranks by rolled()) against the peak knobs, with the
    # measured probes standing in for unset knobs. All three keys stay
    # absent when nothing is computable — conditional surfaces key off
    # their presence.
    xla_flops = counters.get("xla_flops", 0.0)
    if xla_flops > 0.0:
        peak = float(ceil.get("peak_flops", 0.0) or 0.0)
        if peak <= 0.0:
            from dmlc_tpu.obs import xla_cost

            peak = xla_cost.probed_peak_flops()
        if peak > 0.0:
            out["mfu"] = round(min(1.0, xla_flops / wall_s / peak), 4)
            out["compute"] = {
                "flops": round(xla_flops, 3),
                "peak_flops": round(peak, 3),
                "floor_s": round(xla_flops / peak, 6),
                "measured_s": round(stages["device_step"], 6),
            }
    xla_bytes = counters.get("xla_bytes", 0.0)
    if xla_bytes > 0.0:
        gbps = float(ceil.get("hbm_gbps", 0.0) or 0.0)
        if gbps <= 0.0:
            from dmlc_tpu.obs import xla_cost

            gbps = xla_cost.probed_hbm_gbps()
        if gbps > 0.0:
            out["hbm_fraction"] = round(
                min(1.0, xla_bytes / wall_s / (gbps * 1e9)), 4)
    return out


def attribute(delta: Dict[str, float], wall_s: float,
              ceilings: Optional[Dict] = None,
              current: Optional[Dict[str, float]] = None) -> Dict:
    """One window's attribution verdict from a flat-registry delta.

    ``delta`` is :func:`flat_delta` between two ``flat_values()``
    snapshots (or the totals themselves for a whole-run window);
    ``current`` optionally supplies the live snapshot for gauge reads
    (the straggler rank, the per-step XLA cost gauges — flat_delta
    clamps gauges, so they must come from a real snapshot)."""
    counters = progress_counters(delta)
    steps = counters.get("steps", 0.0)
    if steps > 0.0:
        from dmlc_tpu.obs import xla_cost

        costs = xla_cost.step_costs(current if current else delta)
        # only materialize the model-based counters when a compiled hot
        # step has actually been analyzed — their absence keeps the
        # mfu/compute keys (and every conditional surface) absent too
        if costs["flops"] > 0.0:
            counters["xla_flops"] = steps * costs["flops"]
        if costs["bytes"] > 0.0:
            counters["xla_bytes"] = steps * costs["bytes"]
    att = _finish(stage_seconds(delta), counters, wall_s, ceilings)
    if current:
        att["straggler_rank"] = int(_max_named(
            current, "dmlc_job_straggler_rank", default=-1.0))
    return att


def rolled(atts: Sequence[Dict]) -> Optional[Dict]:
    """Job-level roll-up of per-rank attributions: budgets and counters
    sum, the window is the widest rank's, and the verdict re-derives
    from the summed budgets via the same code path."""
    atts = [a for a in atts if isinstance(a, dict) and "budget_s" in a]
    if not atts:
        return None
    stages = {k: 0.0 for k in STAGES if k != "idle"}
    counters: Dict[str, float] = {}
    wall = 0.0
    straggler = -1
    for att in atts:
        for key, v in att.get("budget_s", {}).items():
            if key in stages:
                stages[key] += float(v)
        for key, v in att.get("counters", {}).items():
            counters[key] = counters.get(key, 0.0) + float(v)
        wall = max(wall, float(att.get("window_s", 0.0)))
        straggler = max(straggler, int(att.get("straggler_rank", -1)))
    out = _finish(stages, counters, wall)
    out["ranks"] = len(atts)
    out["straggler_rank"] = straggler
    return out


def format_attribution(att: Dict, label: str = "goodput") -> str:
    """The human table every surface prints (obs-report --attribution,
    the obs-top detail line, the watchdog log) — one verdict, one
    renderer."""
    g = att.get("goodput", {})
    lines = [
        "%s: binding=%s  ratio %.2f  %.1f MB/s  %.0f rows/s  "
        "window %.2fs%s" % (
            label, att.get("binding", "?"), g.get("ratio", 0.0),
            g.get("mbps", 0.0), g.get("rows_s", 0.0),
            att.get("window_s", 0.0),
            "  (at roof)" if att.get("at_roof") else ""),
        "%-12s %10s %6s %14s %14s %6s" % (
            "stage", "budget_s", "share", "achieved", "ceiling", "util"),
    ]
    wall = max(float(att.get("window_s", 0.0)), 1e-9)
    budget = att.get("budget_s", {})
    roofline = att.get("roofline", {})
    for stage in STAGES:
        sec = float(budget.get(stage, 0.0))
        roof = roofline.get(stage, {})
        achieved = roof.get("achieved_mbps", roof.get("achieved_gbps"))
        ceiling = roof.get("ceiling_mbps", roof.get("ceiling_gbps"))
        util = roof.get("utilization")
        mark = " <- binding" if stage == att.get("binding") else ""
        lines.append("%-12s %10.3f %5.0f%% %14s %14s %6s%s" % (
            stage, sec, 100.0 * sec / wall,
            "-" if achieved is None else "%.1f" % achieved,
            "-" if not ceiling else "%.1f" % ceiling,
            "-" if util is None else "%.0f%%" % (100.0 * util),
            mark))
    comp = att.get("compute")
    if comp:
        # the model-based floor under device_step: what the window's
        # XLA flop estimate predicts at peak vs what was measured
        mfu = att.get("mfu")
        lines.append(
            "compute      %10.3f floor vs %.3f measured  "
            "(%.3g FLOPs @ %.3g FLOP/s%s)" % (
                comp.get("floor_s", 0.0), comp.get("measured_s", 0.0),
                comp.get("flops", 0.0), comp.get("peak_flops", 0.0),
                "" if mfu is None else ", mfu %.0f%%" % (100.0 * mfu)))
    return "\n".join(lines)


class GoodputLedger:
    """Per-step runtime ledger: cheap progress notes on the hot path,
    window attribution at ``tick()`` boundaries.

    Construct via :func:`ledger` so ``DMLC_TPU_METRICS=0`` collapses to
    the shared no-op child."""

    def __init__(self, reg: Optional[Registry] = None,
                 ceilings: Optional[Dict] = None,
                 history: int = DEFAULT_HISTORY):
        self._reg = reg if reg is not None else registry()
        self._ceilings = dict(ceilings or {})
        self._g_ratio = self._reg.gauge(
            "dmlc_goodput_ratio_value",
            "useful-work fraction of the last ledger window")
        self._g_mfu = self._reg.gauge(
            "dmlc_goodput_mfu_ratio",
            "model FLOP utilization of the last ledger window (window "
            "XLA flop estimate over the peak-FLOPs ceiling; stays 0 "
            "until a compiled hot step has been analyzed)")
        self.windows: Deque[Dict] = collections.deque(maxlen=history)
        self._steps = 0
        self._prev = self._reg.flat_values()
        self._t0 = time.monotonic_ns()

    def note_step(self, n: int = 1) -> None:
        """Hot-path progress marker — one integer add, no allocation."""
        self._steps += n

    def tick(self, wall_ns: Optional[int] = None) -> Dict:
        """Close the current window: snapshot the registry, attribute
        the delta since the last tick, and return the window verdict."""
        now = time.monotonic_ns()
        flat = self._reg.flat_values()
        wall_s = ((wall_ns if wall_ns is not None else now - self._t0)
                  / 1e9)
        delta = flat_delta(flat, self._prev)
        att = attribute(delta, wall_s, self._ceilings, current=flat)
        if self._steps and att["counters"].get("steps", 0.0) <= 0.0:
            # registry fit counters can lag a custom loop; the ledger's
            # own notes still count as progress (watchdog stall input)
            att["counters"]["steps"] = float(self._steps)
        self._steps = 0
        self._prev = flat
        self._t0 = now
        self._g_ratio.set(att["goodput"]["ratio"])
        if att.get("mfu") is not None:
            self._g_mfu.set(att["mfu"])
        self.windows.append(att)
        return att


def ledger(reg: Optional[Registry] = None,
           ceilings: Optional[Dict] = None):
    """A :class:`GoodputLedger`, or the shared no-op child when the
    metrics registry is disabled (``DMLC_TPU_METRICS=0``) — the
    fit-loop hot path then costs one empty method call per step."""
    if not metrics_enabled():
        return NOOP
    return GoodputLedger(reg, ceilings)
