"""Pluggable exporters over the obs registry.

Three sinks, all fed from :meth:`Registry.families` / ``snapshot()``:

- **JSONL** (:func:`export_jsonl`) — appends one ``{"ts_unix": ...,
  "metrics": {...}}`` line per export; the machine-readable epoch trail.
- **Prometheus textfile** (:func:`export_prometheus`) — the node-exporter
  textfile-collector format.
- **Log sink** (:func:`summary_line`) — one compact ``k=v`` line through
  ``utils.logging`` for epoch-boundary fit-loop logs; histograms render
  as ``p50~<quantile>/<count>`` via :meth:`Histogram.quantile`.

Both file sinks write atomically (tmp file + ``os.replace``) so a
scraper — or the tracker status server's ``/metrics`` handler — never
reads a torn file; JSONL preserves append semantics by rewriting the
file with the new line attached.

:func:`export_epoch` is the fit loops' single call: it honors the
``DMLC_TPU_METRICS_EXPORT`` knob (``*.prom`` → Prometheus, else JSONL),
flushes any active trace, publishes an obs heartbeat to the tracker
(when the worker runs under one — see obs/plane.py), and returns the
summary line for the caller to log. With the knobs unset and no
metrics, it is a cheap no-op.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

from dmlc_tpu.obs import trace
from dmlc_tpu.obs.metrics import (
    Registry,
    escape_label_value,
    format_name,
    registry,
)
from dmlc_tpu.params.knobs import metrics_export_path


def _atomic_write(path: str, text: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)


def export_jsonl(path: str, reg: Optional[Registry] = None) -> None:
    """Append one snapshot line, atomically: the previous content plus
    the new line land via tmp + ``os.replace``, so a concurrent reader
    sees either the old file or the new one — never a torn tail."""
    reg = reg or registry()
    line = json.dumps({"ts_unix": time.time(), "metrics": reg.snapshot()})
    prev = ""
    try:
        with open(path) as fh:
            prev = fh.read()
    except FileNotFoundError:
        pass
    if prev and not prev.endswith("\n"):
        prev += "\n"
    _atomic_write(path, prev + line + "\n")


def _prom_labels(labelkey) -> str:
    if not labelkey:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, escape_label_value(v)) for k, v in labelkey
    )


def prometheus_lines(reg: Optional[Registry] = None) -> List[str]:
    """The registry rendered as Prometheus exposition lines (cumulative
    ``le`` buckets for histograms) — shared by the textfile exporter and
    the tracker status server's merged ``/metrics`` handler."""
    reg = reg or registry()
    lines: List[str] = []
    for name, (kind, help_, children) in sorted(reg.families().items()):
        if help_:
            lines.append("# HELP %s %s" % (name, help_))
        lines.append("# TYPE %s %s" % (name, kind))
        for key, child in sorted(children.items()):
            if kind == "histogram":
                for le, acc in child.cumulative():
                    lk = key + (("le", le),)
                    lines.append("%s_bucket%s %d"
                                 % (name, _prom_labels(lk), acc))
                lines.append("%s_sum%s %s"
                             % (name, _prom_labels(key), child.sum))
                lines.append("%s_count%s %d"
                             % (name, _prom_labels(key), child.count))
            else:
                lines.append("%s%s %s"
                             % (name, _prom_labels(key), child.value))
    return lines


def export_prometheus(path: str, reg: Optional[Registry] = None) -> None:
    """Write the whole registry in Prometheus textfile format, atomically."""
    _atomic_write(path, "\n".join(prometheus_lines(reg)) + "\n")


def summary_line(prefix: Optional[str] = None,
                 reg: Optional[Registry] = None) -> str:
    """Compact one-line ``name=value`` summary (histograms as
    ``p50~<median>/<count>`` — a typical value beats a raw sum for
    eyeballing a log line), optionally filtered to names starting with
    ``prefix`` — the log-sink form for epoch boundaries."""
    reg = reg or registry()
    parts = []
    for name, (kind, _help, children) in sorted(reg.families().items()):
        if prefix and not name.startswith(prefix):
            continue
        for key, child in sorted(children.items()):
            flat = format_name(name, key)
            if kind == "histogram":
                parts.append("%s=p50~%g/%d"
                             % (flat, child.quantile(0.5), child.count))
            else:
                v = child.value
                parts.append("%s=%g" % (flat, v))
    return " ".join(parts)


def export_epoch(reg: Optional[Registry] = None,
                 log_prefix: Optional[str] = None) -> str:
    """Epoch-boundary export: write the ``DMLC_TPU_METRICS_EXPORT`` file
    (if configured), flush the active trace (if any), publish an obs
    heartbeat to the tracker (if running under one), and return the
    log-sink summary line (callers decide whether/at what level to log
    it). Export failures degrade to a summary-only return — telemetry
    must never fail a fit loop."""
    reg = reg or registry()
    path = metrics_export_path()
    if path:
        try:
            if path.endswith(".prom"):
                export_prometheus(path, reg)
            else:
                export_jsonl(path, reg)
        except OSError:
            pass
    try:
        trace.flush()
    except OSError:
        pass
    # the worker side of the job observability plane: piggyback metrics +
    # spans onto a tracker heartbeat. Cheap no-op outside a tracker job.
    from dmlc_tpu.obs import flight, plane

    plane.publish_epoch()
    flight.recorder().note_metrics(reg)
    return summary_line(prefix=log_prefix, reg=reg)
