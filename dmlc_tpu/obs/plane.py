"""Job-wide observability plane: heartbeat piggyback + tracker status server.

PR 2 gave every *process* a metrics registry and a span tracer; this
module moves telemetry across the host boundary. The tf.data-service
lesson (arXiv:2210.14826) is that a disaggregated input pipeline needs a
central control plane that can see per-worker lag, and the MLPerf pod
studies attribute most multi-host debugging to correlating per-host
timelines — so:

- **Worker side** — :class:`ObsPublisher` piggybacks a compact JSON
  payload (metric snapshot + span batch + clock probe) onto the existing
  tracker ``heartbeat`` command. Payloads are capped at
  ``DMLC_TPU_OBS_PAYLOAD_MAX`` bytes: oldest spans are dropped first and
  counted in ``dmlc_obs_spans_dropped_total``. Publishing is opt-in via
  ``DMLC_TPU_OBS_PUBLISH`` — the tracker advertises it to workers only
  when its status plane is armed, so a worker never sends payloads a
  reference tracker would choke on.
- **Tracker side** — :class:`StatusPlane` accumulates per-rank state and
  :class:`StatusServer` (stdlib ``http.server``, opt-in via
  ``DMLC_TPU_STATUS_PORT``) serves it: ``/healthz``, ``/workers``
  (membership ``world_version`` + event log + rank →
  last-seen/lag/straggler), ``/metrics`` (Prometheus text merged
  across ranks), ``/trace`` (job-wide Chrome-trace JSON), ``/data``
  (the data dispatcher's worker/lease/requeue view, when one is
  attached — see data/dispatcher.py), ``/goodput`` (per-rank +
  job-rolled goodput attribution from consecutive metric snapshots —
  obs/goodput.py), and ``/xla`` (per-rank compiled-program cost tables
  parsed from the heartbeat metric snapshots plus the local record
  cache — obs/xla_cost.py).
- **Clock skew** — each payload carries the worker's send wall-time and
  its last measured heartbeat RTT; the tracker estimates per-rank offset
  as ``recv − sent − rtt/2`` (the NTP/obs-aggregate midpoint idea) and
  rebases every worker's span timestamps onto its own clock, so the
  merged trace is monotonically consistent per rank and aligned across
  ranks.
- **Critical path** — :meth:`StatusPlane.stage_slack` aggregates span
  time per (stage, rank) and emits ``dmlc_job_stage_slack_ns{stage=}``
  plus ``dmlc_job_straggler_rank`` (heartbeat-lag stragglers win over
  span-slack ones; −1 = none).
- **On-demand profiling** — ``GET /profile?seconds=N`` arms a job-wide
  capture request on the plane; the tracker piggybacks the encoded
  request on every heartbeat ack (a second int frame — the same passive
  pattern as the elastic generation protocol) and each publishing
  worker runs ``jax.profiler`` for the window, dropping the artifact
  beside its flight-recorder dump (obs/device_telemetry.py).

With ``DMLC_TPU_STATUS_PORT`` unset the tracker binds no socket, starts
no thread, and holds the shared :data:`NOOP_PLANE`; with
``DMLC_TPU_OBS_PUBLISH`` unset :func:`publish_epoch` is one early
return — the ``DMLC_TPU_METRICS=0`` zero-overhead convention.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Deque, Dict, List, Optional, Tuple

from dmlc_tpu.obs import audit, goodput, trace, xla_cost
from dmlc_tpu.obs.exporters import prometheus_lines
from dmlc_tpu.obs.metrics import Registry, registry
from dmlc_tpu.params.knobs import obs_payload_max, obs_publish_enabled

logger = logging.getLogger("dmlc_tpu.obs.plane")

PAYLOAD_MARK = "\nOBS1 "  # heartbeat-line suffix carrying the JSON payload

# /profile request encoding for the heartbeat-ack side channel: one i32,
# (req_id << PROFILE_SHIFT) | seconds. 0 = never requested. Workers act
# when the req_id part advances past the last one they served.
PROFILE_SHIFT = 12
PROFILE_MAX_S = (1 << PROFILE_SHIFT) - 1


def encode_profile_word(req_id: int, seconds: int) -> int:
    return (int(req_id) << PROFILE_SHIFT) | max(
        0, min(int(seconds), PROFILE_MAX_S))


def decode_profile_word(word: int) -> Tuple[int, int]:
    """→ ``(req_id, seconds)``; any non-positive word decodes to (0, 0)."""
    word = int(word)
    if word <= 0:
        return 0, 0
    return word >> PROFILE_SHIFT, word & PROFILE_MAX_S


# ---------------------------------------------------------------------------
# Worker side: payload building + publisher
# ---------------------------------------------------------------------------


def build_payload(
    rank: int,
    epoch: int = -1,
    spans: Optional[List[Dict]] = None,
    reg: Optional[Registry] = None,
    max_bytes: Optional[int] = None,
    rtt_ns: int = 0,
) -> Tuple[str, int]:
    """Serialize one obs heartbeat payload, honoring the size cap.

    Returns ``(json_blob, spans_dropped)``. Oldest spans are shed first
    (halving until the blob fits); if metrics alone still exceed the cap
    they are dropped too — liveness plus the clock probe always fit.
    """
    reg = reg or registry()
    cap = max_bytes if max_bytes is not None else obs_payload_max()
    spans = list(spans or ())
    dropped = 0
    obj = {
        "v": 1,
        "rank": int(rank),
        "epoch": int(epoch),
        "sent_unix_ns": time.time_ns(),
        "rtt_ns": int(rtt_ns),
        "anchor_unix_ns": trace.anchor_unix_ns(),
        "metrics": reg.flat_values(),
        "spans": spans,
        "spans_dropped": 0,
    }
    # determinism-audit chains ride the same payload (obs/audit.py);
    # the key is omitted entirely when audit is off or nothing was
    # digested, so pre-audit payloads stay byte-stable
    audit_obj = audit.auditor().export()
    if audit_obj:
        obj["audit"] = audit_obj
    blob = json.dumps(obj, separators=(",", ":"))
    while len(blob) > cap and obj["spans"]:
        shed = max(1, len(obj["spans"]) // 2)
        dropped += shed
        obj["spans"] = obj["spans"][shed:]
        obj["spans_dropped"] = dropped
        blob = json.dumps(obj, separators=(",", ":"))
    if len(blob) > cap and obj.get("audit"):
        # shed the chain windows before the metrics: heads + totals
        # still let the tracker spot length drift
        for chain in obj["audit"]["chains"].values():
            chain["d"] = []
        blob = json.dumps(obj, separators=(",", ":"))
    if len(blob) > cap and obj["metrics"]:
        obj["metrics"] = {}
        blob = json.dumps(obj, separators=(",", ":"))
    if dropped:
        registry().counter(
            "dmlc_obs_spans_dropped_total",
            "spans shed by the heartbeat payload size cap").inc(dropped)
    return blob, dropped


class ObsPublisher:
    """Worker-side publisher: batches spans via a trace listener and
    ships them (plus a metric snapshot) on tracker heartbeats.

    Publishing is best-effort: a failed heartbeat drops that batch —
    telemetry must never wedge a training loop. The measured
    send→ack RTT rides in the *next* payload as the tracker's skew
    probe."""

    def __init__(
        self,
        tracker_uri: str,
        tracker_port: int,
        rank: int,
        reg: Optional[Registry] = None,
        max_spans: int = 4096,
        job: Optional[str] = None,
    ):
        self.tracker_uri = tracker_uri
        self.tracker_port = int(tracker_port)
        self.rank = int(rank)
        # multi-tenant fleets: the data-service job this rank consumes;
        # rides every heartbeat as a "job=<name>" token so obs-top /
        # obs-report group per-rank tables by tenant
        self.job = str(job) if job else None
        self._reg = reg
        self._spans: Deque[Dict] = collections.deque(maxlen=max_spans)
        self._rtt_ns = 0
        self._m_publishes = registry().counter(
            "dmlc_obs_publishes_total",
            "obs heartbeat payloads published to the tracker")
        self._profile_seen = 0
        trace.add_listener(self._on_span)

    def _on_span(self, event: Dict) -> None:
        self._spans.append(event)

    def publish(self, epoch: int = -1, timeout: float = 10.0) -> bool:
        from dmlc_tpu.obs import device_telemetry
        from dmlc_tpu.tracker.rendezvous import send_heartbeat

        # refresh HBM / live-buffer gauges so every payload carries the
        # current device picture (no-op when telemetry is off)
        try:
            device_telemetry.sample(self._reg)
        except Exception:  # noqa: BLE001 - telemetry must not block publish
            pass
        spans: List[Dict] = []
        while True:
            try:
                spans.append(self._spans.popleft())
            except IndexError:
                break
        blob, _ = build_payload(
            rank=self.rank, epoch=epoch, spans=spans, reg=self._reg,
            rtt_ns=self._rtt_ns,
        )
        t0 = time.monotonic_ns()
        try:
            _, profile_word = send_heartbeat(
                self.tracker_uri, self.tracker_port, self.rank, epoch=epoch,
                metrics=(f"job={self.job}" if self.job else ""),
                obs_json=blob, timeout=timeout, want_profile=True,
            )
        except (OSError, ValueError) as err:
            logger.debug("obs publish failed: %s", err)
            return False
        self._rtt_ns = time.monotonic_ns() - t0
        self._m_publishes.inc()
        self._maybe_capture(profile_word)
        return True

    def _maybe_capture(self, profile_word: int) -> None:
        """Serve a ``/profile`` request seen in the heartbeat ack: a req_id
        past the last one served (with seconds > 0) starts one background
        ``jax.profiler`` capture."""
        req_id, seconds = decode_profile_word(profile_word)
        if req_id <= self._profile_seen:
            return
        self._profile_seen = req_id
        if seconds <= 0:
            return
        from dmlc_tpu.obs import device_telemetry

        logger.info(
            "profile request %d: capturing %ds (rank %d)",
            req_id, seconds, self.rank)
        device_telemetry.capture_profile(seconds, req_id=req_id)

    def close(self) -> None:
        trace.remove_listener(self._on_span)


_DEFAULT_LOCK = threading.Lock()
_DEFAULT_PUBLISHER: Optional[ObsPublisher] = None
_DEFAULT_INIT = False
_EPOCH_SEQ = 0


def default_publisher() -> Optional[ObsPublisher]:
    """The env-configured publisher for this worker process, or None.

    Built once from ``DMLC_TRACKER_URI``/``PORT`` + ``DMLC_TASK_ID`` when
    the tracker advertised ``DMLC_TPU_OBS_PUBLISH`` (status plane armed);
    None everywhere else — the disabled path is one cached check."""
    global _DEFAULT_PUBLISHER, _DEFAULT_INIT
    if _DEFAULT_INIT:
        return _DEFAULT_PUBLISHER
    with _DEFAULT_LOCK:
        if _DEFAULT_INIT:
            return _DEFAULT_PUBLISHER
        uri = os.environ.get("DMLC_TRACKER_URI")
        if uri and obs_publish_enabled():
            try:
                _DEFAULT_PUBLISHER = ObsPublisher(
                    uri,
                    int(os.environ.get("DMLC_TRACKER_PORT", "0") or 0),
                    int(os.environ.get("DMLC_TASK_ID", "0") or 0),
                )
            except ValueError:
                _DEFAULT_PUBLISHER = None
        _DEFAULT_INIT = True
        return _DEFAULT_PUBLISHER


def publish_epoch() -> bool:
    """Epoch-boundary publish through the default publisher (the hook
    ``obs.export_epoch`` calls). No-op outside an armed tracker job."""
    global _EPOCH_SEQ
    pub = default_publisher()
    if pub is None:
        return False
    with _DEFAULT_LOCK:
        _EPOCH_SEQ += 1
        epoch = _EPOCH_SEQ
    return pub.publish(epoch=epoch)


def reset_default_publisher() -> None:
    """Forget the cached env publisher (tests; env changed)."""
    global _DEFAULT_PUBLISHER, _DEFAULT_INIT, _EPOCH_SEQ
    with _DEFAULT_LOCK:
        if _DEFAULT_PUBLISHER is not None:
            _DEFAULT_PUBLISHER.close()
        _DEFAULT_PUBLISHER = None
        _DEFAULT_INIT = False
        _EPOCH_SEQ = 0


# ---------------------------------------------------------------------------
# Tracker side: per-rank state, skew rebase, analysis
# ---------------------------------------------------------------------------


class _WorkerView:
    __slots__ = ("rank", "last_seen_unix", "info", "epoch", "anchor_unix_ns",
                 "offset_ns", "rtt_ns", "metrics", "spans", "spans_dropped",
                 "payloads", "metrics_recv_unix_ns", "goodput")

    def __init__(self, rank: int, max_spans: int):
        self.rank = rank
        self.last_seen_unix = 0.0
        self.info = ""
        self.epoch = -1
        self.anchor_unix_ns: Optional[int] = None
        self.offset_ns = 0
        self.rtt_ns = 0
        self.metrics: Dict[str, float] = {}
        self.spans: Deque[Dict] = collections.deque(maxlen=max_spans)
        self.spans_dropped = 0
        self.payloads = 0
        # goodput attribution between consecutive metric snapshots
        # (obs/goodput.py — the same code path every surface renders)
        self.metrics_recv_unix_ns = 0
        self.goodput: Optional[Dict] = None


def _split_flat(flat: str) -> Tuple[str, str]:
    """``name{a="b"}`` → ``("name", 'a="b"')``; histogram ``:sum`` /
    ``:count`` scalars become Prometheus-legal ``_sum``/``_count``.
    The suffix sits at the very end of the flat key — after the ``}`` of
    a labeled family (``name{a="b"}:sum``), directly on the name of an
    unlabeled one (``name:sum``) — so strip it first, then split."""
    suffix = ""
    for s in (":sum", ":count"):
        if flat.endswith(s):
            flat = flat[: -len(s)]
            suffix = "_" + s[1:]
            break
    name, _, rest = flat.partition("{")
    labels = rest[:-1] if rest.endswith("}") else ""
    return name + suffix, labels


class StatusPlane:
    """Tracker-side accumulator behind the status server endpoints."""

    def __init__(self, num_workers: int = 0, heartbeat_gap: float = 60.0,
                 max_spans_per_rank: int = 20000):
        self.num_workers = int(num_workers)
        self.heartbeat_gap = float(heartbeat_gap)
        self._max_spans = int(max_spans_per_rank)
        self._lock = threading.Lock()
        self._views: Dict[int, _WorkerView] = {}
        self._start_unix = time.time()
        self._g_straggler = registry().gauge(
            "dmlc_job_straggler_rank",
            "rank currently flagged as the job straggler (-1 = none)")
        self._g_straggler.set(-1)
        # elastic membership (PR 6): generation counter + transition log
        self.world_version = 0
        self._events: Deque[Dict] = collections.deque(maxlen=512)
        # on-demand profiling: /profile?seconds=N bumps the request id;
        # the encoded word rides every heartbeat ack until superseded
        self._profile_req = 0
        self._profile_seconds = 0
        self._g_world = registry().gauge(
            "dmlc_tracker_world_version",
            "current membership generation committed by the tracker")
        self._g_world.set(0)
        # fault-tolerant data service (data/dispatcher.py): a snapshot
        # provider installed by DataDispatcher.attach_plane backs /data
        self._data_provider = None
        # determinism audit: cross-rank chain comparison behind /audit
        # (obs/audit.py; idle until a payload carries an "audit" key)
        self.audit = audit.AuditPlane()

    def _view(self, rank: int) -> _WorkerView:
        view = self._views.get(rank)
        if view is None:
            view = self._views[rank] = _WorkerView(rank, self._max_spans)
        return view

    # ---- ingestion (called by the tracker's heartbeat path) ------------
    def note_live(self, rank: int, when_unix: float, info: str) -> None:
        with self._lock:
            view = self._view(rank)
            view.last_seen_unix = when_unix
            view.info = info

    def note_payload(self, rank: int, obj: Dict, recv_unix_ns: int) -> None:
        if not isinstance(obj, dict):
            return
        with self._lock:
            view = self._view(rank)
            view.payloads += 1
            view.epoch = int(obj.get("epoch", view.epoch) or -1)
            anchor = obj.get("anchor_unix_ns")
            if anchor is not None:
                view.anchor_unix_ns = int(anchor)
            rtt = int(obj.get("rtt_ns", 0) or 0)
            if rtt > 0:
                view.rtt_ns = rtt
            sent = obj.get("sent_unix_ns")
            if sent:
                # RTT-midpoint skew estimate: worker clock + offset ≈ ours
                view.offset_ns = recv_unix_ns - int(sent) - view.rtt_ns // 2
            metrics = obj.get("metrics")
            if isinstance(metrics, dict) and metrics:
                # attribute the delta between consecutive snapshots —
                # the per-rank half of the /goodput endpoint (the job
                # roll-up re-derives from these via goodput.rolled)
                prev = view.metrics
                prev_ns = view.metrics_recv_unix_ns
                if prev and prev_ns and recv_unix_ns > prev_ns:
                    view.goodput = goodput.attribute(
                        goodput.flat_delta(metrics, prev),
                        (recv_unix_ns - prev_ns) / 1e9,
                        current=metrics)
                view.metrics = dict(metrics)
                view.metrics_recv_unix_ns = recv_unix_ns
            spans = obj.get("spans")
            if isinstance(spans, list):
                view.spans.extend(
                    e for e in spans if isinstance(e, dict) and "ts" in e)
            view.spans_dropped += int(obj.get("spans_dropped", 0) or 0)
        audit_obj = obj.get("audit")
        if audit_obj:
            self.audit.note_audit(rank, audit_obj)
        self.stage_slack()  # refresh straggler/slack gauges as data lands

    def note_membership(self, kind: str, **fields) -> None:
        """Record one membership transition (``join`` / ``evict`` /
        ``rebuild``) for the ``/workers`` event log; a ``world_version``
        field also advances the generation gauge."""
        event = dict(fields, kind=kind, unix=round(time.time(), 3))
        with self._lock:
            self._events.append(event)
            if "world_version" in fields:
                self.world_version = int(fields["world_version"])
        if "world_version" in fields:
            self._g_world.set(int(fields["world_version"]))

    def request_profile(self, seconds: int) -> Dict:
        """Arm a job-wide profiler capture request (the ``/profile``
        endpoint). Every worker that heartbeats with ``want_profile``
        sees the new request id in its ack and captures once."""
        seconds = max(1, min(int(seconds), PROFILE_MAX_S))
        with self._lock:
            self._profile_req += 1
            self._profile_seconds = seconds
            req = self._profile_req
        logger.info("profile capture requested: %ds (req %d)", seconds, req)
        return {"profile_req": req, "seconds": seconds}

    def profile_word(self) -> int:
        """The current request encoded for the heartbeat-ack side channel
        (0 = never requested)."""
        with self._lock:
            if not self._profile_req:
                return 0
            return encode_profile_word(self._profile_req,
                                       self._profile_seconds)

    def set_data_provider(self, fn) -> None:
        """Install the data-dispatcher snapshot callable behind ``/data``
        (``DataDispatcher.attach_plane``). Latest wins — one dispatcher
        per epoch, same lifecycle as the service."""
        self._data_provider = fn

    def data_view(self) -> Dict:
        """The ``/data`` body: live worker/lease/requeue view from the
        attached dispatcher, or ``{"attached": false}`` when no data
        service is running behind this tracker."""
        fn = self._data_provider
        if fn is None:
            return {"attached": False}
        try:
            return dict(fn(), attached=True)
        except Exception as err:  # noqa: BLE001 — a dying dispatcher must
            # not take the status server down with it
            return {"attached": True, "error": str(err)}

    def audit_view(self) -> Dict:
        """The ``/audit`` body: per-rank chain summaries and the
        cross-rank fork table (obs/audit.py AuditPlane.view)."""
        return self.audit.view()

    def goodput_view(self) -> Dict:
        """The ``/goodput`` body: per-rank attribution windows plus the
        job roll-up, all produced by obs/goodput.py's one code path
        (``attribute`` per rank in :meth:`note_payload`, ``rolled``
        across ranks here). Ranks appear once two metric snapshots have
        landed (a window needs a delta)."""
        with self._lock:
            per_rank = {
                str(rank): v.goodput
                for rank, v in sorted(self._views.items())
                if v.goodput is not None
            }
        return {
            "ranks": per_rank,
            "job": goodput.rolled(list(per_rank.values())),
        }

    def xla_view(self) -> Dict:
        """The ``/xla`` body: per-rank compiled-program cost tables plus
        this process's own record cache.

        ``ranks`` is parsed back out of each worker's latest flat metric
        snapshot (the ``dmlc_xla_*{fn=}`` gauges ride the heartbeat like
        every other metric — no new wire field), keyed rank → jit site →
        {flops, bytes_accessed, peak_bytes, collective_bytes};
        ``local`` is obs/xla_cost.py's in-process view (per-site latest
        records with bucket counts, plus the extraction count) for
        single-process runs and the tracker's own jits."""
        with self._lock:
            per_rank = {
                str(rank): sites
                for rank, v in sorted(self._views.items())
                for sites in (xla_cost.sites_from_flat(v.metrics),)
                if sites
            }
        return {"ranks": per_rank, "local": xla_cost.detail_section()}

    def membership(self) -> Dict:
        """``{"world_version": N, "events": [...]}`` — the elastic half of
        the ``/workers`` response."""
        with self._lock:
            return {
                "world_version": self.world_version,
                "events": list(self._events),
            }

    # ---- read side (HTTP handlers, obs-report) -------------------------
    def health(self) -> Dict:
        with self._lock:
            seen = len(self._views)
        return {
            "status": "ok",
            "workers_seen": seen,
            "workers_expected": self.num_workers,
            "uptime_s": round(time.time() - self._start_unix, 3),
        }

    def workers(self) -> Dict[str, Dict]:
        now = time.time()
        with self._lock:
            out = {}
            for rank, v in sorted(self._views.items()):
                lag = now - v.last_seen_unix if v.last_seen_unix else None
                out[str(rank)] = {
                    "last_seen_unix": v.last_seen_unix,
                    "lag_s": round(lag, 3) if lag is not None else None,
                    "straggler": bool(
                        lag is not None and lag > self.heartbeat_gap),
                    "epoch": v.epoch,
                    "info": v.info,
                    "clock_offset_ns": v.offset_ns,
                    "rtt_ns": v.rtt_ns,
                    "spans": len(v.spans),
                    "spans_dropped": v.spans_dropped,
                    "payloads": v.payloads,
                }
        return out

    def merged_trace(self) -> Dict:
        """Job-wide Chrome trace: every rank's spans rebased onto the
        tracker clock (anchor + ts, minus the skew offset estimate) and
        merged; ``pid`` is the rank, so Perfetto shows one process row
        per worker."""
        with self._lock:
            per_rank = [
                (rank, v.anchor_unix_ns, v.offset_ns, list(v.spans))
                for rank, v in sorted(self._views.items())
            ]
        stamped: List[Tuple[int, Dict, int]] = []
        offsets: Dict[str, int] = {}
        for rank, anchor, offset, spans in per_rank:
            if anchor is None:
                continue
            offsets[str(rank)] = offset
            for e in spans:
                abs_ns = anchor + int(e["ts"] * 1e3) + offset
                stamped.append((abs_ns, e, rank))
        stamped.sort(key=lambda item: item[0])
        base_ns = stamped[0][0] if stamped else 0
        events = []
        for abs_ns, e, rank in stamped:
            out = dict(e)
            out["ts"] = (abs_ns - base_ns) / 1e3
            out["pid"] = rank
            events.append(out)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "merged": True,
                "base_unix_ns": base_ns,
                "clock": "tracker",
                "offsets_ns": offsets,
            },
        }

    def stage_slack(self) -> Dict[str, Dict]:
        """Per-stage cross-rank slack from the merged spans.

        For each span name, sums duration per rank; slack is the
        max−min spread (the straggler's surplus on that stage). Updates
        ``dmlc_job_stage_slack_ns{stage=}`` and
        ``dmlc_job_straggler_rank`` (a heartbeat-lag straggler, if any,
        wins over the span-slack candidate)."""
        now = time.time()
        with self._lock:
            per_stage: Dict[str, Dict[int, float]] = {}
            lag_straggler = -1
            worst_lag = self.heartbeat_gap
            for rank, v in self._views.items():
                if v.last_seen_unix and now - v.last_seen_unix > worst_lag:
                    worst_lag = now - v.last_seen_unix
                    lag_straggler = rank
                for e in v.spans:
                    if e.get("ph") not in (None, "X"):
                        continue  # flow points carry no duration
                    per_stage.setdefault(e.get("name", "?"), {}).setdefault(
                        rank, 0.0)
                    per_stage[e.get("name", "?")][rank] += float(
                        e.get("dur", 0.0))
        out: Dict[str, Dict] = {}
        slack_straggler, widest = -1, 0.0
        reg = registry()
        for name, per_rank in sorted(per_stage.items()):
            mx_rank = max(per_rank, key=lambda r: per_rank[r])
            slack_us = per_rank[mx_rank] - min(per_rank.values())
            out[name] = {
                "slack_us": slack_us,
                "max_rank": mx_rank,
                "per_rank_us": {str(r): v for r, v in sorted(
                    per_rank.items())},
            }
            reg.gauge(
                "dmlc_job_stage_slack_ns",
                "cross-rank span-time spread per stage (max-min)",
                stage=name).set(slack_us * 1e3)
            if len(per_rank) > 1 and slack_us > widest:
                widest, slack_straggler = slack_us, mx_rank
        straggler = lag_straggler if lag_straggler >= 0 else slack_straggler
        self._g_straggler.set(straggler)
        return out

    def merged_metrics_text(self, reg: Optional[Registry] = None) -> str:
        """Prometheus exposition: the tracker's own registry (via the
        existing exporter) plus every rank's flat metrics re-labeled with
        ``rank=`` (worker values export as-is; their kind lives in the
        worker process)."""
        lines = prometheus_lines(reg)
        with self._lock:
            per_rank = [
                (rank, dict(v.metrics))
                for rank, v in sorted(self._views.items()) if v.metrics
            ]
        if per_rank:
            lines.append("# worker metrics merged from heartbeat payloads")
        for rank, metrics in per_rank:
            for flat, value in sorted(metrics.items()):
                name, labels = _split_flat(flat)
                labels = (labels + "," if labels else "") + 'rank="%d"' % rank
                lines.append("%s{%s} %g" % (name, labels, value))
        return "\n".join(lines) + "\n"


class _NoopPlane:
    """Shared disabled plane (``DMLC_TPU_STATUS_PORT`` unset): ingestion
    is two empty method calls, mirroring the no-op metrics child."""

    __slots__ = ()

    def note_live(self, rank, when_unix, info):
        pass

    def note_payload(self, rank, obj, recv_unix_ns):
        pass

    def note_membership(self, kind, **fields):
        pass

    def profile_word(self):
        return 0

    def set_data_provider(self, fn):
        pass


NOOP_PLANE = _NoopPlane()


# ---------------------------------------------------------------------------
# HTTP status server (stdlib only)
# ---------------------------------------------------------------------------


class _StatusHandler(BaseHTTPRequestHandler):
    server_version = "dmlc-tpu-status/1"

    def do_GET(self):  # noqa: N802 (http.server API)
        plane: StatusPlane = self.server.plane  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/healthz":
                body = json.dumps(plane.health()).encode()
                ctype = "application/json"
            elif path == "/workers":
                body = json.dumps(
                    dict(plane.membership(), workers=plane.workers())
                ).encode()
                ctype = "application/json"
            elif path == "/metrics":
                body = plane.merged_metrics_text().encode()
                ctype = "text/plain; version=0.0.4"
            elif path == "/trace":
                body = json.dumps(plane.merged_trace()).encode()
                ctype = "application/json"
            elif path == "/data":
                body = json.dumps(plane.data_view()).encode()
                ctype = "application/json"
            elif path == "/goodput":
                body = json.dumps(plane.goodput_view()).encode()
                ctype = "application/json"
            elif path == "/audit":
                body = json.dumps(plane.audit_view()).encode()
                ctype = "application/json"
            elif path == "/xla":
                body = json.dumps(plane.xla_view()).encode()
                ctype = "application/json"
            elif path == "/profile":
                from urllib.parse import parse_qs

                query = parse_qs(self.path.partition("?")[2])
                try:
                    seconds = int(query.get("seconds", ["5"])[0])
                except ValueError:
                    self.send_error(400, "seconds must be an integer")
                    return
                if seconds <= 0:
                    self.send_error(400, "seconds must be > 0")
                    return
                body = json.dumps(plane.request_profile(seconds)).encode()
                ctype = "application/json"
            else:
                self.send_error(404, "unknown endpoint %r" % path)
                return
        except Exception as err:  # a broken handler must not kill the plane
            self.send_error(500, "status handler failed: %s" % err)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):
        logger.debug("status http: " + fmt, *args)


class StatusServer:
    """The opt-in tracker HTTP endpoint (``DMLC_TPU_STATUS_PORT``).

    ``port=0`` binds an ephemeral port (tests); the bound port is
    exposed as :attr:`port` and advertised to workers via
    ``DMLC_TPU_STATUS_URI``."""

    def __init__(self, plane: StatusPlane, port: int, host: str = ""):
        self._httpd = ThreadingHTTPServer((host, int(port)), _StatusHandler)
        self._httpd.plane = plane  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="dmlc-status-http",
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
