"""Cross-host metric aggregation over ``collective.DeviceEngine``.

A TPU pod's ingest skew is invisible in per-host logs (the MLPerf pod
studies, arXiv:1909.09756: one slow host gates every step). This module
turns the local registry into a fixed-order float vector and exchanges it
through the engine's allreduce so EVERY rank — and rank 0 in particular —
can report per-host min/median/max for each metric.

The exchange is one sum-allreduce of a ``[world, n]`` matrix where each
rank fills only its own row: the reduced matrix IS the per-host table, so
exact medians (not just allreduce-expressible min/mean/max) come out of a
single collective. n is the metric count — these are counters, not
gradients; the O(world·n) payload is trivial next to one data batch.

Vector order must agree across hosts (SPMD processes registering the same
metrics in the same order do); a crc of the name list rides in front of
the values and any mismatch raises instead of silently mis-pairing
counters. On a 1-host engine allreduce degenerates to identity and the
snapshot is exact trivially.
"""

from __future__ import annotations

import zlib
from typing import Dict, Optional

import numpy as np

from dmlc_tpu.obs.metrics import Registry, registry
from dmlc_tpu.utils.logging import check, log_info


def cross_host_snapshot(engine, reg: Optional[Registry] = None,
                        prefix: Optional[str] = None) -> Dict:
    """Allreduce the registry's counter/gauge vector across hosts.

    Returns ``{"world": W, "rank": r, "metrics": {name: {"min", "median",
    "max", "sum", "mean"}}}`` on every rank (the collective is symmetric).
    ``prefix`` filters metric names before the exchange — all ranks must
    pass the same value. Histograms contribute their ``:sum``/``:count``
    scalars (see :meth:`Registry.flat_values`)."""
    reg = reg or registry()
    values = reg.flat_values()
    if prefix:
        values = {k: v for k, v in values.items() if k.startswith(prefix)}
    names = sorted(values)
    world = int(getattr(engine, "world_size", 1))
    rank = int(getattr(engine, "rank", 0))
    crc = float(zlib.crc32("\n".join(names).encode()))
    mat = np.zeros((world, len(names) + 1), dtype=np.float64)
    mat[rank, 0] = crc
    mat[rank, 1:] = [values[n] for n in names]
    table = np.asarray(engine.allreduce(mat, op="sum"))
    check(
        bool(np.all(table[:, 0] == crc)),
        "cross-host metric snapshot: hosts registered different metric "
        "sets (name-list crc mismatch) — pass a common prefix or align "
        "registrations",
    )
    per_host = table[:, 1:]
    out: Dict[str, Dict[str, float]] = {}
    for i, name in enumerate(names):
        col = per_host[:, i]
        out[name] = {
            "min": float(col.min()),
            "median": float(np.median(col)),
            "max": float(col.max()),
            "sum": float(col.sum()),
            "mean": float(col.mean()),
        }
    return {"world": world, "rank": rank, "metrics": out}


def report_skew(engine, reg: Optional[Registry] = None,
                prefix: Optional[str] = None, top: int = 5) -> Dict:
    """Take a cross-host snapshot and, on rank 0, log the ``top`` metrics
    with the widest per-host spread (max/min ratio; max-min for metrics
    whose min is 0). Returns the snapshot on every rank."""
    snap = cross_host_snapshot(engine, reg=reg, prefix=prefix)
    if snap["rank"] != 0:
        return snap

    def spread(stats: Dict[str, float]) -> float:
        if stats["min"] > 0:
            return stats["max"] / stats["min"]
        return stats["max"] - stats["min"]

    ranked = sorted(
        ((spread(s), name, s) for name, s in snap["metrics"].items()),
        reverse=True,
    )
    for _sp, name, s in ranked[:top]:
        log_info(
            "host skew %s: min %g / median %g / max %g over %d host(s)",
            name, s["min"], s["median"], s["max"], snap["world"],
        )
    return snap
