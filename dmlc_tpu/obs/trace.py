"""Span tracer: Chrome trace-event JSON (Perfetto-loadable) pipeline spans.

``span("parse", chunk=i)`` is a context manager that records one complete
("ph": "X") trace event — name, start, duration, thread — into an
in-process buffer; ``flush()`` (and an atexit hook) writes the buffer to
the path named by ``DMLC_TPU_TRACE`` as ``{"traceEvents": [...]}``, the
format both chrome://tracing and https://ui.perfetto.dev open directly.

Tracing is OFF unless ``DMLC_TPU_TRACE`` is set: ``span()`` then returns a
shared no-op context manager (two empty method calls per span). The env
var is re-read per ``span()`` call — one dict lookup — so tests and
long-lived processes can turn tracing on/off without re-imports.

Timestamps are anchored to ``time.monotonic_ns()`` with a one-shot
wall-clock anchor (``anchor_unix_ns``) captured at module import and
recorded in the trace ``metadata`` block: span ``ts`` values are µs since
the monotonic epoch, so an NTP step mid-job cannot fold or reorder the
timeline, and consumers that need absolute time (the tracker's merged
job trace, obs/plane.py) recover it as ``anchor_unix_ns + ts·1000``.
The emitted JSON stays Perfetto-compatible — extra top-level keys next
to ``traceEvents`` are part of the Chrome trace object format.

Span durations are measured by :class:`dmlc_tpu.utils.timer.Timer` (the
repo's one stopwatch — obs reuses it rather than growing a second one).

Listeners: :func:`add_listener` registers a callback invoked with each
completed span event. While any listener is registered, spans are
recorded even without ``DMLC_TPU_TRACE`` (the listener IS the consumer —
the flight recorder and the heartbeat span publisher both attach this
way), but the in-process buffer only grows when a trace *file* is
configured, so a listener alone cannot leak memory.

Optional jax bridging: with ``DMLC_TPU_TRACE_JAX=1`` each span also enters
a ``jax.profiler.TraceAnnotation`` (and ``step_span`` a
``StepTraceAnnotation``) when the running jax exposes them, so the same
span names show up inside an XLA profiler capture next to the device
timeline. Absent jax or the API, the bridge silently stays off.

Flow events: ``new_flow()`` allocates a job-unique flow id and
``flow_start/flow_step/flow_end`` emit Chrome-trace flow events
(``"ph": "s"/"t"/"f"``) that Perfetto renders as arrows connecting the
enclosing duration slices — across threads, and (because the id embeds
the rank) across ranks once obs/plane.py merges per-worker traces. A
flow point binds to the ``"ph": "X"`` slice open on the same pid/tid at
its timestamp, so always emit flow points *inside* the span for the
stage they mark. When tracing is off, ``new_flow()`` returns 0 and every
flow call is an early-returning no-op — zero allocations on the hot
path (the disabled contract tests/test_obs.py pins).
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from dmlc_tpu.utils.timer import Timer

_lock = threading.Lock()
_events: List[Dict] = []
_listeners: List[Callable[[Dict], None]] = []
_atexit_registered = False
# one-shot anchor pair: span ts are µs since _EPOCH_MONO_NS (NTP-immune);
# _ANCHOR_UNIX_NS is the wall clock at that same instant, published in the
# trace metadata so merged/absolute timelines can be reconstructed
_EPOCH_MONO_NS = time.monotonic_ns()
_ANCHOR_UNIX_NS = time.time_ns()

_PID = os.getpid()


def _now_us() -> float:
    return (time.monotonic_ns() - _EPOCH_MONO_NS) / 1e3


def anchor_unix_ns() -> int:
    """Wall-clock ns at the trace epoch (span ``ts`` zero point)."""
    return _ANCHOR_UNIX_NS


def _jax_annotation_cls(step: bool = False):
    if os.environ.get("DMLC_TPU_TRACE_JAX") != "1":
        return None
    try:
        import jax.profiler as _jp
    except Exception:
        return None
    return getattr(
        _jp, "StepTraceAnnotation" if step else "TraceAnnotation", None
    )


class _NoopSpan:
    """Shared disabled span: stateless, safe to reuse concurrently."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("name", "args", "_timer", "_ts", "_annot")

    def __init__(self, name: str, args: Dict, annot=None):
        self.name = name
        self.args = args
        self._timer = Timer()
        self._ts = 0.0
        self._annot = annot

    def __enter__(self):
        if self._annot is not None:
            self._annot.__enter__()
        self._ts = _now_us()
        self._timer.__enter__()
        return self

    def __exit__(self, *exc):
        self._timer.__exit__(*exc)
        if self._annot is not None:
            self._annot.__exit__(*exc)
        event = {
            "name": self.name,
            "ph": "X",
            "ts": self._ts,
            "dur": self._timer.elapsed * 1e6,
            "pid": _PID,
            "tid": threading.get_ident(),
        }
        if self.args:
            event["args"] = self.args
        # the buffer backs the DMLC_TPU_TRACE file; listeners keep their
        # own (bounded) state, so listener-only tracing cannot leak
        if _active_path() is not None:
            with _lock:
                _events.append(event)
        for fn in list(_listeners):
            try:
                fn(event)
            except Exception:
                pass  # telemetry consumers must never break the traced code
        return False


def _active_path() -> Optional[str]:
    # raw os.environ read: this sits on the per-batch path and must not
    # pay the typed-parse layer for the common "unset" case
    return os.environ.get("DMLC_TPU_TRACE") or None


def _ensure_atexit() -> None:
    global _atexit_registered
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(flush)


def add_listener(fn: Callable[[Dict], None]) -> None:
    """Register ``fn(event)`` to be called with every completed span.

    Registering a listener also arms span recording (``span()`` returns a
    live span while any listener exists, trace file or not)."""
    with _lock:
        if fn not in _listeners:
            _listeners.append(fn)


def remove_listener(fn: Callable[[Dict], None]) -> None:
    with _lock:
        try:
            _listeners.remove(fn)
        except ValueError:
            pass


def span(name: str, **args):
    """Context manager timing one pipeline stage as a named trace span.

    No-op (a shared inert object) unless ``DMLC_TPU_TRACE`` names an
    output file or a listener is registered. Keyword args become the
    event's ``args`` payload — keep them small and JSON-serializable
    (chunk/batch indices)."""
    if _active_path() is None and not _listeners:
        return NOOP_SPAN
    _ensure_atexit()
    cls = _jax_annotation_cls()
    annot = cls(name) if cls is not None else None
    return _Span(name, args, annot)


def step_span(step_num: int, name: str = "step", **args):
    """Like :func:`span` but bridges to ``jax.profiler.StepTraceAnnotation``
    (the profiler's step marker) when available — for fit-loop epochs."""
    if _active_path() is None and not _listeners:
        return NOOP_SPAN
    _ensure_atexit()
    cls = _jax_annotation_cls(step=True)
    annot = cls(name, step_num=step_num) if cls is not None else None
    return _Span(name, dict(args, step=step_num), annot)


# ---- Flow events (causal dataflow arrows) -------------------------------
# Chrome trace flow events match globally on (cat, id): a chunk's id must
# be unique across every process whose trace lands in the merged /trace.
# Layout: (rank+1) in the high bits | pid salt | per-process counter. The
# pid salt keeps colocated rank-0 processes (tests, local launcher) from
# colliding; the counter wraps at 2^24 flows, far past any one job.
_FLOW_CAT = "dataflow"
_FLOW_IDS = itertools.count(1)
_FLOW_BASE: Optional[int] = None
_FLOW_TLS = threading.local()


def _flow_base() -> int:
    global _FLOW_BASE
    if _FLOW_BASE is None:
        try:
            rank = int(os.environ.get("DMLC_TASK_ID") or 0)
        except ValueError:
            rank = 0
        _FLOW_BASE = (((rank & 0x3FFFFF) + 1) << 40) | (
            (_PID & 0xFFFF) << 24
        )
    return _FLOW_BASE


def new_flow() -> int:
    """Allocate a flow id, or 0 when tracing is disarmed.

    0 is the "no flow" sentinel every flow call early-returns on, so the
    disabled path allocates nothing — callers can thread the result
    unconditionally."""
    if _active_path() is None and not _listeners:
        return 0
    return _flow_base() | (next(_FLOW_IDS) & 0xFFFFFF)


def _flow_event(fid: int, ph: str, name: str) -> None:
    event = {
        "name": name,
        "cat": _FLOW_CAT,
        "ph": ph,
        "id": fid,
        "ts": _now_us(),
        "pid": _PID,
        "tid": threading.get_ident(),
    }
    if ph == "f":
        # bind the arrow head to the enclosing slice rather than the
        # next slice on the thread ("binding point: enclosing")
        event["bp"] = "e"
    if _active_path() is not None:
        _ensure_atexit()
        with _lock:
            _events.append(event)
    for fn in list(_listeners):
        try:
            fn(event)
        except Exception:
            pass  # telemetry consumers must never break the traced code


def flow_start(fid: int, name: str = "flow") -> None:
    """Emit the ``"s"`` (start) point of flow ``fid``. No-op when ``fid``
    is 0 or tracing is disarmed. Call inside the span of the producing
    stage so the arrow tail attaches to that slice."""
    if not fid or (_active_path() is None and not _listeners):
        return
    _flow_event(fid, "s", name)


def flow_step(fid: int, name: str = "flow") -> None:
    """Emit a ``"t"`` (step) point: the flow passed through the enclosing
    stage. No-op when ``fid`` is 0 or tracing is disarmed."""
    if not fid or (_active_path() is None and not _listeners):
        return
    _flow_event(fid, "t", name)


def flow_end(fid: int, name: str = "flow") -> None:
    """Emit the ``"f"`` (finish) point terminating flow ``fid`` (with
    ``"bp": "e"`` so the head binds to the enclosing slice)."""
    if not fid or (_active_path() is None and not _listeners):
        return
    _flow_event(fid, "f", name)


def set_current_flow(fid: int) -> None:
    """Stash ``fid`` as this thread's ambient flow. DeviceFeed sets it
    around the consume yield so fit-loop code (collective op spans,
    train_step) can mark the in-flight chunk without plumbing ids."""
    _FLOW_TLS.fid = fid


def current_flow() -> int:
    """This thread's ambient flow id (0 when none is set)."""
    return getattr(_FLOW_TLS, "fid", 0)


def events() -> List[Dict]:
    """Copy of the buffered trace events (ordered by span *completion*)."""
    with _lock:
        return list(_events)


def events_after(cursor: int) -> Tuple[List[Dict], int]:
    """Buffered events past ``cursor`` plus the new cursor — the
    incremental read the heartbeat span publisher batches from."""
    with _lock:
        return list(_events[cursor:]), len(_events)


def clear() -> None:
    with _lock:
        _events.clear()


def metadata() -> Dict:
    """The trace-file metadata block (clock anchor + process identity)."""
    return {
        "clock": "monotonic_ns",
        "anchor_unix_ns": _ANCHOR_UNIX_NS,
        "pid": _PID,
    }


def flush(path: Optional[str] = None) -> Optional[str]:
    """Write all buffered events to ``path`` (default: ``DMLC_TPU_TRACE``)
    as a Chrome trace JSON object. Returns the path written, or None when
    there is no destination. The buffer is kept: repeated flushes rewrite
    the file with the complete history (the file is always loadable)."""
    path = path or _active_path()
    if path is None:
        return None
    with _lock:
        payload = {
            "traceEvents": list(_events),
            "displayTimeUnit": "ms",
            "metadata": metadata(),
        }
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)
    return path
