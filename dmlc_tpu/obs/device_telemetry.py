"""Device/XLA telemetry: recompile sentinel, HBM accounting, profiler capture.

The obs stack up to PR 7 watches the host side (stages, flows, ranks); this
module lights up the device side on the same registry:

- ``instrumented_jit(fn, name=...)`` — a drop-in ``jax.jit`` wrapper that
  counts compiles per function (``dmlc_xla_compiles_total{fn=}``), histograms
  the wall time of each compiling call (``dmlc_xla_compile_ns{fn=}``), and
  after a warmup window treats any further compile as an anomaly: log
  warning + ``xla.recompile`` flight event + ``dmlc_xla_recompiles_total``.
  The trick is that jit traces the wrapped Python body exactly once per
  cache miss, so a counter bump inside the body IS a compile counter — no
  private jax APIs. This turns FixedShapePool's one-trace-per-bucket design
  claim into a live invariant. Each compiling call also hands the executable
  to obs/xla_cost.py (``note_compile``) which caches the compiled program's
  cost/memory analytics per (fn, bucket shape) — compile-time only, never
  per step, and ``jitted.lower`` reuses the cached trace so the recompile
  sentinel itself is not perturbed.
- ``sample()`` — per-device HBM gauges from ``device.memory_stats()``
  (``dmlc_device_hbm_bytes{device=}``; graceful no-op on CPU backends where
  the runtime reports nothing) plus a live-buffer census over
  ``jax.live_arrays()`` (``dmlc_device_live_bytes{device=}``) which works on
  every backend. Sampled at payload-publish time, by bench, and optionally
  by a background poller (``maybe_start_hbm_poller``).
- ``h2d_meter()`` — byte/bandwidth accounting for the feed's ``device_put``
  dispatch path (``dmlc_feed_h2d_bytes_total``, ``dmlc_feed_h2d_mbps``).
- ``capture_profile(seconds)`` — run ``jax.profiler`` for a window in a
  background thread and drop the artifact beside the flight-recorder dump;
  triggered job-wide by the tracker's ``/profile?seconds=N`` endpoint via
  the heartbeat-ack side channel (see obs/plane.py).

Knobs: ``DMLC_TPU_DEVICE_TELEMETRY`` (default 1; 0 makes ``instrumented_jit``
return the plain ``jax.jit`` callable — the disabled dispatch path is
byte-for-byte the uninstrumented one) and ``DMLC_TPU_HBM_POLL_S`` (default 0;
>0 starts a daemon thread sampling HBM every that many seconds).
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from typing import Any, Callable, Dict, Optional

from dmlc_tpu.obs import flight
from dmlc_tpu.obs.metrics import Registry, registry
from dmlc_tpu.params.knobs import device_telemetry_enabled, hbm_poll_s

logger = logging.getLogger("dmlc_tpu.obs.device")

__all__ = [
    "DEFAULT_WARMUP_CALLS",
    "InstrumentedJit",
    "instrumented_jit",
    "compile_counts",
    "H2DMeter",
    "h2d_meter",
    "sample",
    "peak_hbm_bytes",
    "maybe_start_hbm_poller",
    "capture_profile",
    "detail_section",
    "reset",
]

#: Calls before a fresh trace stops being "expected warmup" and becomes an
#: anomaly. Shape buckets all show up in the first few batches of a fit; a
#: compile after this many dispatches means an unbucketed shape leaked in.
DEFAULT_WARMUP_CALLS = 32


class InstrumentedJit:
    """``jax.jit`` with a compile counter and a post-warmup recompile alarm.

    The jitted callable wraps a shim whose Python body runs once per trace
    (jit cache miss): the shim bumps ``self.compiles``. Dispatch-side we
    compare the count before/after the call — a change means this call
    compiled, so its wall time (trace+compile+first run, documented caveat)
    goes to the compile-time histogram, and past ``warmup_calls`` dispatches
    it also fires the anomaly path.
    """

    __slots__ = (
        "fn_name",
        "warmup_calls",
        "compiles",
        "calls",
        "_jitted",
        "_reg",
        "_m_compiles",
        "_m_recompiles",
        "_h_compile_ns",
    )

    def __init__(
        self,
        fn: Callable,
        name: str,
        warmup_calls: int = DEFAULT_WARMUP_CALLS,
        reg: Optional[Registry] = None,
        **jit_kwargs: Any,
    ):
        import jax

        reg = reg if reg is not None else registry()
        self._reg = reg
        self.fn_name = name
        self.warmup_calls = int(warmup_calls)
        self.compiles = 0
        self.calls = 0
        self._m_compiles = reg.counter(
            "dmlc_xla_compiles_total",
            "XLA traces (jit cache misses) per instrumented function",
            fn=name,
        )
        self._m_recompiles = reg.counter(
            "dmlc_xla_recompiles_total",
            "post-warmup recompile anomalies per instrumented function",
            fn=name,
        )
        self._h_compile_ns = reg.histogram(
            "dmlc_xla_compile_ns",
            "wall time of calls that compiled (trace+compile+first run)",
            fn=name,
        )

        def _counting(*args, **kwargs):
            # Body executes once per jit cache miss — this IS the compile
            # counter. Runs under tracing, so only host-side effects here.
            self.compiles += 1
            self._m_compiles.inc()
            return fn(*args, **kwargs)

        try:
            _counting.__name__ = getattr(fn, "__name__", name)
        except (AttributeError, TypeError):
            pass
        self._jitted = jax.jit(_counting, **jit_kwargs)

    def __call__(self, *args, **kwargs):
        before = self.compiles
        t0 = time.monotonic_ns()
        out = self._jitted(*args, **kwargs)
        self.calls += 1
        if self.compiles != before:
            self._h_compile_ns.observe(time.monotonic_ns() - t0)
            try:
                from dmlc_tpu.obs import xla_cost

                xla_cost.note_compile(
                    self.fn_name, self._jitted, args, kwargs, reg=self._reg)
            except Exception:  # noqa: BLE001 - analytics never kill a step
                logger.debug(
                    "xla cost extraction failed for %s",
                    self.fn_name,
                    exc_info=True,
                )
            if self.calls > self.warmup_calls:
                self._m_recompiles.inc()
                flight.record_event(
                    "xla.recompile",
                    fn=self.fn_name,
                    compiles=self.compiles,
                    calls=self.calls,
                )
                logger.warning(
                    "xla recompile anomaly: %s traced signature #%d at call "
                    "%d (warmup window %d) — an unbucketed shape or dtype "
                    "reached the jitted step",
                    self.fn_name,
                    self.compiles,
                    self.calls,
                    self.warmup_calls,
                )
        return out

    # Pass through the bits of the jit surface used in-tree.
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def __repr__(self) -> str:
        return "InstrumentedJit(%s, compiles=%d, calls=%d)" % (
            self.fn_name,
            self.compiles,
            self.calls,
        )


def instrumented_jit(
    fn: Callable,
    name: str,
    warmup_calls: int = DEFAULT_WARMUP_CALLS,
    **jit_kwargs: Any,
):
    """``jax.jit`` with the recompile sentinel attached.

    With ``DMLC_TPU_DEVICE_TELEMETRY=0`` this returns the plain
    ``jax.jit(fn, **jit_kwargs)`` callable — no wrapper object, no counter,
    no per-dispatch branch: the disabled hot path is exactly the
    uninstrumented one (allocation-free, pinned by test like the PR 7
    flow-id discipline). The knob is read once, here, at build time.
    """
    if not device_telemetry_enabled():
        import jax

        return jax.jit(fn, **jit_kwargs)
    return InstrumentedJit(fn, name, warmup_calls=warmup_calls, **jit_kwargs)


_FLAT_FN_RE = re.compile(r'^dmlc_xla_compiles_total\{.*?fn="((?:[^"\\]|\\.)*)"')


def compile_counts(reg: Optional[Registry] = None) -> Dict[str, int]:
    """Per-function compile totals read back from the registry.

    Keys are the ``fn=`` label values; feeds the bench detail section and
    the one-trace-per-bucket test.
    """
    reg = reg if reg is not None else registry()
    out: Dict[str, int] = {}
    for flat, value in reg.flat_values().items():
        m = _FLAT_FN_RE.match(flat)
        if m:
            out[m.group(1).replace('\\"', '"').replace("\\\\", "\\")] = int(value)
    return out


class H2DMeter:
    """Byte/bandwidth accounting for one feed's host→device dispatch path."""

    __slots__ = ("_m_bytes", "_h_mbps")

    def __init__(self, reg: Optional[Registry] = None, **labels: str):
        reg = reg if reg is not None else registry()
        self._m_bytes = reg.counter(
            "dmlc_feed_h2d_bytes_total",
            "host->device payload bytes submitted through device_put",
            **labels,
        )
        self._h_mbps = reg.histogram(
            "dmlc_feed_h2d_mbps",
            "per-put H2D submission bandwidth, MB/s (bytes over the wall "
            "time of the dispatch call; async backends overstate sustained "
            "bandwidth — read it as submission rate)",
            **labels,
        )

    def note(self, nbytes: int, elapsed_ns: int) -> None:
        if nbytes <= 0:
            return
        self._m_bytes.inc(nbytes)
        if elapsed_ns > 0:
            # bytes/ns → MB/s: x * 1e9 / 1e6 = x * 1e3
            self._h_mbps.observe(nbytes * 1e3 / elapsed_ns)


def h2d_meter(reg: Optional[Registry] = None, **labels: str) -> Optional[H2DMeter]:
    """An :class:`H2DMeter`, or ``None`` when device telemetry is off.

    Callers keep the ``None`` and skip metering entirely — the disabled
    dispatch path has no timing calls and no byte walk.
    """
    if not device_telemetry_enabled():
        return None
    return H2DMeter(reg, **labels)


_state_lock = threading.Lock()
_peak_hbm = 0
_poller_started = False


def sample(reg: Optional[Registry] = None) -> Dict[str, Dict[str, int]]:
    """Refresh per-device memory gauges; returns ``{"hbm": {...}, "live": {...}}``.

    ``hbm`` comes from ``device.memory_stats()`` (``bytes_in_use`` →
    ``dmlc_device_hbm_bytes{device=}``, ``bytes_limit`` →
    ``dmlc_device_hbm_limit_bytes{device=}``); CPU backends report no stats
    and contribute nothing — graceful no-op, never an error. ``live`` is a
    census over ``jax.live_arrays()`` nbytes attributed evenly across each
    array's device set (``dmlc_device_live_bytes{device=}``), which works on
    every backend including CPU.
    """
    if not device_telemetry_enabled():
        return {"hbm": {}, "live": {}}
    try:
        import jax
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        return {"hbm": {}, "live": {}}
    reg = reg if reg is not None else registry()

    hbm: Dict[str, int] = {}
    try:
        devices = jax.local_devices()
    except Exception:
        devices = []
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        label = "%s:%d" % (getattr(dev, "platform", "dev"), getattr(dev, "id", 0))
        used = stats.get("bytes_in_use")
        if used is not None:
            reg.gauge(
                "dmlc_device_hbm_bytes",
                "device memory in use per device (memory_stats bytes_in_use)",
                device=label,
            ).set(int(used))
            hbm[label] = int(used)
        limit = stats.get("bytes_limit")
        if limit:
            reg.gauge(
                "dmlc_device_hbm_limit_bytes",
                "device memory capacity per device (memory_stats bytes_limit)",
                device=label,
            ).set(int(limit))

    live: Dict[str, float] = {}
    try:
        arrays = jax.live_arrays()
    except Exception:
        arrays = []
    for arr in arrays:
        try:
            devs = list(arr.devices())
            nbytes = int(arr.nbytes)
        except Exception:
            continue
        if not devs:
            continue
        share = nbytes / len(devs)
        for dev in devs:
            label = "%s:%d" % (getattr(dev, "platform", "dev"), getattr(dev, "id", 0))
            live[label] = live.get(label, 0.0) + share
    live_int = {k: int(v) for k, v in live.items()}
    for label, nbytes in live_int.items():
        reg.gauge(
            "dmlc_device_live_bytes",
            "live jax array bytes per device (live_arrays census; the "
            "backend-independent HBM proxy)",
            device=label,
        ).set(nbytes)

    global _peak_hbm
    peak_now = max(hbm.values(), default=0)
    if not peak_now:
        peak_now = max(live_int.values(), default=0)
    with _state_lock:
        if peak_now > _peak_hbm:
            _peak_hbm = peak_now
    return {"hbm": hbm, "live": live_int}


def peak_hbm_bytes() -> int:
    """High-water mark across every ``sample()`` so far (this process).

    Prefers ``memory_stats`` bytes; falls back to the live-buffer census on
    backends without stats so bench can still gate a peak on CPU.
    """
    with _state_lock:
        return _peak_hbm


def maybe_start_hbm_poller() -> bool:
    """Start the background HBM sampler once, if ``DMLC_TPU_HBM_POLL_S`` > 0.

    Returns True when a poller is (already) running. Default 0 means no
    thread at all — the periodic path costs nothing unless asked for.
    """
    period = hbm_poll_s()
    if period <= 0 or not device_telemetry_enabled():
        return False
    global _poller_started
    with _state_lock:
        if _poller_started:
            return True
        _poller_started = True

    def _loop():
        while True:
            time.sleep(period)
            try:
                sample()
            except Exception:  # noqa: BLE001 - telemetry must never kill the job
                logger.debug("hbm poll failed", exc_info=True)

    threading.Thread(target=_loop, daemon=True, name="dmlc-hbm-poll").start()
    logger.info("hbm poller started (every %.1fs)", period)
    return True


_capture_lock = threading.Lock()
_capturing = False


def _artifact_dir() -> str:
    """Where capture artifacts land: beside the flight-recorder dump when
    the recorder is armed, else the working directory."""
    rec = flight.recorder()
    path = rec.path() if hasattr(rec, "path") else None
    if path:
        return os.path.dirname(path) or "."
    return "."


def capture_profile(
    seconds: float,
    out_dir: Optional[str] = None,
    req_id: int = 0,
    block: bool = False,
) -> Optional[threading.Thread]:
    """Run ``jax.profiler`` for ``seconds`` in a background thread.

    The artifact directory is ``profile-rank<k>-req<n>/`` beside the
    flight-recorder dump. One capture at a time: overlapping requests are
    dropped (returns None) rather than corrupting the active trace. Always
    records a ``profile.capture`` flight event on completion. ``block=True``
    joins the thread (tests).
    """
    global _capturing
    with _capture_lock:
        if _capturing:
            logger.warning("profile capture already running; dropping req %d", req_id)
            return None
        _capturing = True

    rank = 0
    try:
        rank = int(os.environ.get("DMLC_TASK_ID", "0") or 0)
    except ValueError:
        pass
    base = out_dir if out_dir is not None else _artifact_dir()
    target = os.path.join(base, "profile-rank%d-req%d" % (rank, req_id))
    seconds = max(0.0, float(seconds))

    def _run():
        global _capturing
        ok = False
        try:
            import jax

            os.makedirs(target, exist_ok=True)
            jax.profiler.start_trace(target)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            ok = True
        except Exception as err:  # noqa: BLE001 - capture is best-effort
            logger.warning("profile capture failed: %s", err)
        finally:
            with _capture_lock:
                _capturing = False
            flight.record_event(
                "profile.capture",
                seconds=seconds,
                dir=target,
                req=req_id,
                ok=ok,
            )
            registry().counter(
                "dmlc_device_profile_captures_total",
                "on-demand profiler capture attempts (see ok field of the "
                "profile.capture flight event for failures)",
            ).inc()
            if ok:
                logger.info(
                    "profile capture done: %.1fs -> %s (req %d)",
                    seconds,
                    target,
                    req_id,
                )

    th = threading.Thread(target=_run, daemon=True, name="dmlc-profile-capture")
    th.start()
    if block:
        th.join()
    return th


def detail_section(reg: Optional[Registry] = None) -> Dict[str, Any]:
    """The ``device_telemetry`` block for bench's detail artifact.

    Compile counts per fn, the process-lifetime peak HBM, and the mean H2D
    submission bandwidth — the keys obs/sentry.py knows how to gate
    (``compiles.<fn>`` and ``hbm.peak_bytes`` lower-better, ``h2d_mbps``
    higher-better).
    """
    reg = reg if reg is not None else registry()
    sample(reg)
    out: Dict[str, Any] = {"compiles": compile_counts(reg)}
    peak = peak_hbm_bytes()
    if peak > 0:
        out["peak_hbm_bytes"] = peak
    h2d_sum = 0.0
    h2d_count = 0.0
    for flat, value in reg.flat_values().items():
        if flat.startswith("dmlc_feed_h2d_mbps"):
            if flat.endswith(":sum"):
                h2d_sum += value
            elif flat.endswith(":count"):
                h2d_count += value
    if h2d_count > 0:
        out["h2d_mbps"] = round(h2d_sum / h2d_count, 1)
    return out


def reset() -> None:
    """Forget process-level state (tests): peak HBM, poller/capture flags,
    and the xla cost-record cache (stale records would otherwise pin their
    gauges to a previous test's registry)."""
    global _peak_hbm, _poller_started, _capturing
    with _state_lock:
        _peak_hbm = 0
        _poller_started = False
    with _capture_lock:
        _capturing = False
    from dmlc_tpu.obs import xla_cost

    xla_cost.reset()
