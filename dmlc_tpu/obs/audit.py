"""Cross-rank determinism audit plane: streaming stage digests.

Every load-bearing parity claim in this repo — SPMD-vs-socket (PR 13),
resident-vs-legacy bit-identity (PR 16), chaos-kill bit-identity — is
verified offline by tests; in a live job a silent divergence (parser
backend drift, a double-counted requeued chunk, a psum reordering)
surfaces as a wrong model hours later with no trail. dmlc-core's own
posture is that integrity is an *in-band* property of the stream
(RecordIO magic/CRC framing); this module applies the same idea one
level up: content digests at every pipeline stage, threaded along the
existing flow ids and compared continuously.

- **Worker side** — :class:`Auditor` (via :func:`auditor`) keeps one
  seq-keyed digest chain per stage: ``io_read`` (raw chunk bytes, keyed
  by chunk seq), ``parse`` (the canonical columnar digest of the parsed
  RowBlockContainer — ``RowBlock.audit_arrays``, backend-independent by
  construction), ``batch`` (the same digest at pool emit, keyed by batch
  index; the device-resident feed hashes its pending container, the
  legacy feed the sliced block — byte-identical streams), and ``model``
  (a rolling hash over the epoch loss + a strided parameter sample,
  fetched at log cadence — no per-step D2H). The same fetch powers the
  numeric-health sentinel: non-finite counts on loss and the sampled
  params ride the goodput window into the watchdog's ``numeric`` alert.
- **Self-check** — :meth:`Auditor.roll_epoch` compares each data-stage
  chain against the previous epoch's over the same shard: the same
  bytes must parse and batch identically epoch over epoch, so the first
  mismatching seq *localizes* a nondeterminism without any tracker.
- **Cross-rank** — :meth:`Auditor.export` piggybacks the chains on the
  OBS1 heartbeat payload (obs/plane.py); the tracker-side
  :class:`AuditPlane` merges chains from every (rank, epoch) into one
  reference per (stage, shard) and flags the first forking seq.
- **On divergence** — both sides raise a typed ``audit.divergence``
  flight event, bump ``dmlc_audit_divergences_total{stage=}``, and write
  a minimal replay bundle ``audit-rank<k>.json`` beside the flightrec
  dump (shard window, knob snapshot, the offending seq, both chains);
  ``python -m dmlc_tpu.tools audit-report`` renders the fork.

Gating follows the metrics/goodput convention: ``DMLC_TPU_AUDIT`` off
(the default) hands every call site the shared :data:`NOOP_AUDITOR` —
one attribute load and an empty method call, allocation-free (pinned by
tests/test_audit.py); ``sample`` mode digests every
``DMLC_TPU_AUDIT_SAMPLE_N``-th seq for bounded overhead.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import os
import struct
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from dmlc_tpu.obs.flight import record_event
from dmlc_tpu.obs.metrics import Registry, registry
from dmlc_tpu.params import knobs

logger = logging.getLogger("dmlc_tpu.obs.audit")

#: digest width in bytes — 64-bit hex chains keep heartbeat payloads and
#: replay bundles small while collisions stay negligible at chunk counts
DIGEST_SIZE = 8

#: stages a worker chains, in pipeline order ("model" compares by
#: step/epoch index across ranks; the rest by chunk/batch seq)
STAGES = ("io_read", "parse", "batch", "model")

#: data stages are reset + self-compared at epoch boundaries; the model
#: chain spans the whole fit (loss changes every epoch by design)
DATA_STAGES = ("io_read", "parse", "batch")

#: entries shipped per stage on one heartbeat payload (newest seqs win;
#: ``n``/``head`` still summarize the full chain)
EXPORT_CAP = 512

#: in-memory entries kept per stage chain (oldest seqs evicted)
CHAIN_CAP = 4096


def _hasher() -> "hashlib._Hash":
    return hashlib.blake2b(digest_size=DIGEST_SIZE)


def digest_bytes(data) -> str:
    """Hex digest of one raw chunk (bytes-like or str)."""
    h = _hasher()
    if isinstance(data, str):
        data = data.encode()
    h.update(data)
    return h.hexdigest()


def rows_digest(obj) -> str:
    """Hex digest of a RowBlock / RowBlockContainer's canonical columnar
    stream (``audit_arrays`` — data/row_block.py). Field-major with
    per-row lengths and neutral defaults materialized, so the digest is
    invariant to chunking, slicing, and parse backend: equal rows ⇒
    equal digest."""
    h = _hasher()
    for tag, parts in obj.audit_arrays():
        h.update(b"\x1f")
        h.update(tag)
        h.update(b"\x1e")
        for a in parts:
            a = np.ascontiguousarray(a)
            h.update(a.data)
    return h.hexdigest()


def digest_arrays(fields: Dict[str, np.ndarray]) -> str:
    """Hex digest of a named array dict (the data-service wire payload) —
    the redelivery equality check hashes the delivered fields directly,
    before any RowBlock is built."""
    h = _hasher()
    for name in sorted(fields):
        arr = fields[name]
        h.update(b"\x1f")
        h.update(name.encode())
        h.update(b"\x1e")
        if arr is not None:
            a = np.ascontiguousarray(arr)
            h.update(a.data)
    return h.hexdigest()


def _param_sample(arr, k: int = 64) -> np.ndarray:
    """A strided sample of up to ``k`` elements of one parameter array —
    small enough that the epoch-cadence fetch is negligible, strided so
    a corrupted span anywhere in the array is likely sampled."""
    flat = arr.reshape(-1)
    size = int(flat.shape[0])
    if size == 0:
        return np.empty(0, dtype=np.float32)
    stride = max(1, size // k)
    return np.asarray(flat[::stride][:k])


class _NoopAuditor:
    """Shared disabled auditor (``DMLC_TPU_AUDIT`` off): every note is an
    empty method call, mirroring the no-op metrics child. Allocation-free
    on the hot path — pinned by tests/test_audit.py."""

    __slots__ = ()
    enabled = False
    every = 0
    shard = ""
    divergences = ()

    def set_shard(self, uri, part=0, nparts=1):
        pass

    def note_chunk(self, seq, data):
        pass

    def note_parse(self, seq, obj):
        pass

    def note_batch(self, idx, obj):
        pass

    def note_model(self, idx, loss, params=None):
        return 0

    def check_redelivery(self, seq, first_hex, redelivered_hex):
        return True

    def roll_epoch(self, epoch):
        return ()

    def export(self):
        return {}

    def snapshot(self):
        return {}

    def export_state(self):
        return {}

    def restore_state(self, st):
        return False


NOOP_AUDITOR = _NoopAuditor()


class Auditor:
    """Worker-side streaming digest ledger (construct via
    :func:`auditor`). Thread-safe: parse digests land from the
    pipeline's worker threads out of order — entries are keyed by seq,
    not append order, so a missing-vs-present seq is itself a signal."""

    enabled = True

    def __init__(self, reg: Optional[Registry] = None,
                 mode: Optional[str] = None,
                 sample_n: Optional[int] = None,
                 rank: Optional[int] = None):
        self._reg = reg if reg is not None else registry()
        mode = knobs.audit_mode() if mode is None else mode
        n = knobs.audit_sample_n() if sample_n is None else int(sample_n)
        self.every = n if mode == "sample" else 1
        self.rank = (int(os.environ.get("DMLC_TASK_ID", "0") or 0)
                     if rank is None else int(rank))
        self.epoch = 0
        self.shard = ""
        self._shard_info: Dict = {}
        self._lock = threading.Lock()
        self._chains: Dict[str, Dict[int, str]] = {}
        self._heads: Dict[str, str] = {}
        self._prev: Dict[str, Dict[int, str]] = {}
        self._prev_epoch = -1
        self._prev_shard = ""
        self.divergences: List[Dict] = []
        self._m_digests = {
            stage: self._reg.counter(
                "dmlc_audit_digests_total",
                "stage digests recorded by the audit ledger", stage=stage)
            for stage in STAGES
        }

    # ---- shard identity -------------------------------------------------
    def set_shard(self, uri, part: int = 0, nparts: int = 1) -> None:
        """Declare the data shard this worker's chains are computed over
        (``uri`` + part window). Chains only compare — across epochs,
        restarts, and ranks — within one shard signature; replicas
        reading the same window compare cross-rank, partitioned readers
        only against themselves."""
        sig = "%s|%d/%d" % (uri, int(part), int(nparts))
        with self._lock:
            if sig == self.shard:
                return
            self.shard = sig
            self._shard_info = {
                "uri": str(uri), "part": int(part), "nparts": int(nparts)}
            # a new shard invalidates every data chain comparison
            for stage in DATA_STAGES:
                self._chains.pop(stage, None)
                self._heads.pop(stage, None)
            self._prev = {}
            self._prev_epoch = -1
            self._prev_shard = sig

    # ---- digest points --------------------------------------------------
    def _record(self, stage: str, seq: int, hexd: str) -> None:
        seq = int(seq)
        with self._lock:
            chain = self._chains.setdefault(stage, {})
            chain[seq] = hexd
            self._heads[stage] = hashlib.blake2b(
                (self._heads.get(stage, "") + hexd).encode(),
                digest_size=DIGEST_SIZE).hexdigest()
            if len(chain) > CHAIN_CAP:
                del chain[min(chain)]
        self._m_digests[stage].inc()

    def note_chunk(self, seq: int, data) -> None:
        """Chunk-bytes digest at io_read, keyed by chunk seq."""
        if seq % self.every:
            return
        try:
            self._record("io_read", seq, digest_bytes(data))
        except TypeError:
            pass  # non-bytes chunk payloads (pre-parsed iterators) skip

    def note_parse(self, seq: int, obj) -> None:
        """Post-parse RowBlock(Container) digest, keyed by chunk seq."""
        if seq % self.every:
            return
        self._record("parse", seq, rows_digest(obj))

    def note_batch(self, idx: int, obj) -> None:
        """Batch digest at pool emit, keyed by batch index within the
        epoch."""
        if idx % self.every:
            return
        self._record("batch", idx, rows_digest(obj))

    def note_model(self, idx: int, loss, params=None) -> int:
        """Model digest-chain update at log cadence: loss bits + a
        strided sample of every parameter array. Returns the number of
        non-finite values seen in loss + samples — the numeric-health
        sentinel the fit loop feeds to the watchdog (one small fetch,
        shared with the digest)."""
        h = _hasher()
        nonfinite = 0
        if loss is not None:
            loss = float(loss)
            h.update(struct.pack("<d", loss))
            if not math.isfinite(loss):
                nonfinite += 1
        if params:
            for name in sorted(params):
                sample = _param_sample(params[name])
                h.update(name.encode())
                a = np.ascontiguousarray(sample)
                h.update(a.data)
                if np.issubdtype(a.dtype, np.floating):
                    nonfinite += int(a.size - np.isfinite(a).sum())
        self._record("model", idx, h.hexdigest())
        return nonfinite

    def check_redelivery(self, seq, first_hex: str,
                         redelivered_hex: str) -> bool:
        """Compare a requeued chunk redelivery's content digest against
        its first delivery's (data/service.py drops the duplicate either
        way). A mismatch means the requeue path rewrote content — a
        ``redelivery``-stage divergence. Returns True when equal."""
        if first_hex == redelivered_hex:
            return True
        self._divergence(stage="redelivery", seq=int(seq),
                         scope="redelivery", ours=redelivered_hex,
                         theirs=first_hex)
        return False

    # ---- epoch roll + self-check ---------------------------------------
    def roll_epoch(self, epoch: int) -> List[Dict]:
        """Close the epoch's data chains: compare them against the
        previous epoch's over the same shard (the same bytes must parse
        and batch identically), archive, and reset for the next epoch.
        Returns the divergences found (usually empty). Call *after* the
        epoch's payload publish so the full chains ride the heartbeat."""
        with self._lock:
            cur = {stage: dict(self._chains.get(stage, ()))
                   for stage in DATA_STAGES}
            prev = self._prev
            comparable = (self._prev_epoch >= 0
                          and self._prev_shard == self.shard)
            self._prev = cur
            self._prev_epoch = int(epoch)
            self._prev_shard = self.shard
            # exports during epoch N carry epoch=N (publish runs before
            # the roll), so the tracker can tell a rank's own chains
            # apart across epochs
            self.epoch = int(epoch) + 1
            for stage in DATA_STAGES:
                self._chains.pop(stage, None)
                self._heads.pop(stage, None)
        found: List[Dict] = []
        if not comparable:
            return found
        for stage in DATA_STAGES:
            ours, theirs = cur.get(stage, {}), prev.get(stage, {})
            for seq in sorted(set(ours) & set(theirs)):
                if ours[seq] != theirs[seq]:
                    found.append(self._divergence(
                        stage=stage, seq=seq, epoch=int(epoch),
                        ours=ours[seq], theirs=theirs[seq],
                        scope="epoch", against_epoch=self._prev_epoch - 1,
                        chains={"current": _chain_list(ours),
                                "previous": _chain_list(theirs)},
                    ))
                    break  # first divergence localizes; the rest cascade
        return found

    def _divergence(self, chains=None, **fields) -> Dict:
        div = dict(fields, rank=self.rank, shard=self.shard)
        emit_divergence(self._reg, div)
        self.divergences.append(div)
        write_bundle(self.rank, div, chains=chains,
                     shard_info=self._shard_info)
        return div

    # ---- export / introspection ----------------------------------------
    def export(self) -> Dict:
        """The ``audit`` key of one OBS1 heartbeat payload: per-stage
        chain windows (newest :data:`EXPORT_CAP` seqs), rolling heads,
        and totals. Empty dict when nothing was digested yet (the key is
        then omitted — payloads stay byte-stable with audit off)."""
        with self._lock:
            if not self._chains:
                return {}
            chains = {}
            for stage, chain in self._chains.items():
                seqs = sorted(chain)[-EXPORT_CAP:]
                chains[stage] = {
                    "n": len(chain),
                    "head": self._heads.get(stage, ""),
                    "d": [[seq, chain[seq]] for seq in seqs],
                }
            return {
                "shard": self.shard,
                "epoch": self.epoch,
                "every": self.every,
                "chains": chains,
                "divergences": len(self.divergences),
            }

    def export_state(self) -> Dict:
        """Resumable chain state for a job snapshot (JobSnapshot part).

        Full-fidelity where :meth:`export` windows: the model digest
        chain + rolling head (so a resumed run's final model head equals
        an uninterrupted run's) and the archived previous-epoch data
        chains (so the first resumed ``roll_epoch`` still self-checks
        against the interrupted run when the shard signature matches).
        Empty dict when nothing was digested yet.
        """
        with self._lock:
            model = self._chains.get("model")
            if model is None and not self._prev and self._prev_epoch < 0:
                return {}
            return {
                "epoch": self.epoch,
                "every": self.every,
                "model": {
                    "chain": dict(model or {}),
                    "head": self._heads.get("model", ""),
                },
                "prev": {s: dict(c) for s, c in self._prev.items()},
                "prev_epoch": self._prev_epoch,
                "prev_shard": self._prev_shard,
            }

    def restore_state(self, st: Dict) -> bool:
        """Re-inject chain state exported by :meth:`export_state`.

        Call *after* the data parser stamped :meth:`set_shard` for the
        resumed epoch — restore only refills chain/archive state, it
        never rewrites the live shard signature. Returns True when
        state was applied.
        """
        if not st:
            return False
        with self._lock:
            model = st.get("model") or {}
            chain = {int(k): v for k, v in (model.get("chain") or {}).items()}
            if chain:
                self._chains["model"] = chain
            if model.get("head"):
                self._heads["model"] = model["head"]
            self._prev = {
                stage: {int(k): v for k, v in c.items()}
                for stage, c in (st.get("prev") or {}).items()
            }
            self._prev_epoch = int(st.get("prev_epoch", -1))
            self._prev_shard = str(st.get("prev_shard", ""))
            self.epoch = int(st.get("epoch", self.epoch))
        return True

    def snapshot(self) -> Dict:
        """Local view for logs/tests: chain lengths + divergence list."""
        with self._lock:
            lengths = {s: len(c) for s, c in self._chains.items()}
        return {
            "rank": self.rank,
            "shard": self.shard,
            "every": self.every,
            "chains": lengths,
            "divergences": list(self.divergences),
        }


def _chain_list(chain: Dict[int, str], cap: int = EXPORT_CAP) -> List:
    return [[seq, chain[seq]] for seq in sorted(chain)[-cap:]]


def emit_divergence(reg: Optional[Registry], div: Dict) -> None:
    """The one divergence chokepoint both sides share: typed flight
    event + ``dmlc_audit_divergences_total{stage=}`` + a warning log."""
    record_event("audit.divergence", **div)
    (reg if reg is not None else registry()).counter(
        "dmlc_audit_divergences_total",
        "digest-chain forks detected by the audit plane",
        stage=str(div.get("stage", "?"))).inc()
    logger.warning("audit divergence: %s", div)


def bundle_path(rank: int, out_dir: Optional[str] = None) -> str:
    """Where rank ``k``'s replay bundle lands: ``audit-rank<k>.json``
    beside the flight-recorder dump (cwd when the recorder is off)."""
    base = out_dir if out_dir else (knobs.flightrec_dir() or ".")
    return os.path.join(base, "audit-rank%d.json" % int(rank))


def write_bundle(rank: int, div: Dict, chains: Optional[Dict] = None,
                 shard_info: Optional[Dict] = None,
                 out_dir: Optional[str] = None) -> Optional[str]:
    """Atomically write the minimal-repro bundle for one divergence:
    the fork coordinates, the shard window, a ``DMLC_TPU_*`` knob
    snapshot (seeds and backends ride here), and both chains. First
    divergence wins — the root cause; later ones cascade from it."""
    path = bundle_path(rank, out_dir)
    if os.path.exists(path):
        return None
    obj = {
        "v": 1,
        "rank": int(rank),
        "unix": round(time.time(), 3),
        "divergence": div,
        "shard": dict(shard_info or {}),
        "knobs": {k: os.environ[k] for k in knobs.KNOWN_KNOBS
                  if k in os.environ},
        "chains": chains or {},
    }
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as fh:
            json.dump(obj, fh, indent=1)
        os.replace(tmp, path)
    except OSError as err:  # a full disk must not take training down
        logger.warning("audit bundle write failed (%s): %s", path, err)
        return None
    return path


# ---------------------------------------------------------------------------
# process-wide auditor (the goodput.ledger / metrics.registry convention)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_AUDITOR = NOOP_AUDITOR
_INIT = False


def auditor():
    """The process auditor: a live :class:`Auditor` when
    ``DMLC_TPU_AUDIT`` is on, else the shared :data:`NOOP_AUDITOR`.
    Resolved once; call sites bind the result at construction so the
    disabled hot path is one empty method call."""
    global _AUDITOR, _INIT
    if _INIT:
        return _AUDITOR
    with _LOCK:
        if not _INIT:
            if knobs.audit_mode() != "off":
                _AUDITOR = Auditor()
            _INIT = True
    return _AUDITOR


def reset_auditor() -> None:
    """Forget the cached auditor (tests; env changed)."""
    global _AUDITOR, _INIT
    with _LOCK:
        _AUDITOR = NOOP_AUDITOR
        _INIT = False


# ---------------------------------------------------------------------------
# Tracker side: cross-rank / cross-epoch chain comparison
# ---------------------------------------------------------------------------


class AuditPlane:
    """Merges every rank's exported chains into one reference per
    (stage, shard) and localizes the first fork.

    The reference is built incrementally: the first digest seen for a
    (stage, shard, epoch-kind, seq) coordinate becomes the truth, every
    later arrival — from any rank, epoch, or restart — must match it.
    Data stages compare by chunk/batch seq (equal bytes ⇒ equal digests,
    whatever the arrival order); the model stage compares by step/epoch
    index (SPMD replicas must hold identical params). One divergence is
    flagged per (stage, rank) — the first fork localizes, the rest
    cascade."""

    def __init__(self, reg: Optional[Registry] = None,
                 out_dir: Optional[str] = None):
        self._reg = reg if reg is not None else registry()
        self._out_dir = out_dir
        self._lock = threading.Lock()
        # (stage, shard) -> seq -> (digest, rank, epoch)
        self._ref: Dict = {}
        self._flagged = set()
        self._divergences: List[Dict] = []
        self._ranks: Dict[int, Dict] = {}

    def note_audit(self, rank: int, obj: Dict) -> List[Dict]:
        """Ingest one payload's ``audit`` key; returns new divergences."""
        if not isinstance(obj, dict):
            return []
        rank = int(rank)
        shard = str(obj.get("shard", ""))
        epoch = int(obj.get("epoch", -1) or 0)
        chains = obj.get("chains")
        if not isinstance(chains, dict):
            return []
        found: List[Dict] = []
        with self._lock:
            view = self._ranks.setdefault(rank, {})
            view["shard"] = shard
            view["epoch"] = epoch
            view["worker_divergences"] = int(obj.get("divergences", 0) or 0)
            view.setdefault("chains", {})
            for stage, chain in chains.items():
                if not isinstance(chain, dict):
                    continue
                entries = chain.get("d") or []
                view["chains"][stage] = {
                    "n": int(chain.get("n", len(entries)) or 0),
                    "head": chain.get("head", ""),
                }
                # model chains are shard-independent (replicas must
                # agree); data chains compare within one shard window
                key = (stage, "" if stage == "model" else shard)
                ref = self._ref.setdefault(key, {})
                if (stage, rank) in self._flagged:
                    continue
                for seq_hex in entries:
                    try:
                        seq, hexd = int(seq_hex[0]), str(seq_hex[1])
                    except (TypeError, ValueError, IndexError):
                        continue
                    known = ref.get(seq)
                    if known is None:
                        ref[seq] = (hexd, rank, epoch)
                    elif known[0] != hexd and known[1:] != (rank, epoch):
                        self._flagged.add((stage, rank))
                        div = {
                            "stage": stage, "seq": seq, "rank": rank,
                            "epoch": epoch, "shard": shard,
                            "ours": hexd, "theirs": known[0],
                            "against_rank": known[1],
                            "against_epoch": known[2],
                            "scope": "cross-rank",
                        }
                        found.append(div)
                        break
        for div in found:
            emit_divergence(self._reg, div)
            with self._lock:
                self._divergences.append(div)
            write_bundle(div["rank"], div, out_dir=self._out_dir,
                         chains={"observed": [[div["seq"], div["ours"]]],
                                 "reference": [[div["seq"], div["theirs"]]]},
                         shard_info={"sig": div["shard"]})
        return found

    def view(self) -> Dict:
        """The ``/audit`` body: per-rank chain summaries + the fork
        table."""
        with self._lock:
            ranks = {
                str(rank): {
                    "shard": v.get("shard", ""),
                    "epoch": v.get("epoch", -1),
                    "chains": dict(v.get("chains", {})),
                    "worker_divergences": v.get("worker_divergences", 0),
                    "diverged": any(r == rank for _s, r in self._flagged),
                }
                for rank, v in sorted(self._ranks.items())
            }
            return {
                "enabled": bool(self._ranks),
                "ranks": ranks,
                "divergences": list(self._divergences),
            }
