"""In-run SLO watchdog over goodput ledger windows.

The tf.data fleet paper's core observation is that jobs silently run
input-bound; the at-scale MLPerf runs live or die by catching
input/step imbalance *while the job is running*. This module is the
live tripwire: the fit loops (models/fitloop.py) feed it one goodput
window per epoch (obs/goodput.py), and it fires on four shapes:

- ``collapse``        — window throughput falls below the rolling
  median − max(rel_tol·|median|, mad_mult·MAD) band over recent healthy
  windows (the same robust gate math as obs/sentry.py, applied in-run);
- ``recompile_storm`` — ``dmlc_xla_recompiles_total`` moved by at least
  ``recompile_limit`` within one window (a shape leak re-tracing the
  step, obs/device_telemetry.py);
- ``stall``           — no ledger progress (steps/batches/bytes all
  flat) for ``DMLC_TPU_WATCHDOG_STALL_S`` cumulative seconds
  (0 disables);
- ``straggler``       — the status plane flagged a straggler rank
  (``dmlc_job_straggler_rank`` ≥ 0);
- ``numeric``         — the determinism auditor's numeric-health
  sentinel saw non-finite values in the epoch loss or the strided
  parameter sample it already fetches for the model digest chain
  (obs/audit.py; the fit loop stamps the count onto the window as
  ``nonfinite``).

Each kind fires **once** per excursion: on firing it emits one
``watchdog.alert`` flight-recorder event, bumps
``dmlc_watchdog_alerts_total{kind=}``, logs a warning, and optionally
triggers the on-demand device profiler for the regression window
(``DMLC_TPU_WATCHDOG_PROFILE=1`` → device_telemetry.capture_profile).
The kind then stays disarmed until its condition clears — the same
re-arm hysteresis as the plane's straggler flag, so a sustained
collapse produces one alert, not an alert storm. Collapsed windows are
kept out of the rolling baseline so the band cannot erode into
accepting the regression.

Under ``DMLC_TPU_METRICS=0`` :func:`make_watchdog` returns the shared
no-op child (metrics.NOOP) — ``observe()`` is one empty method call.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from dmlc_tpu.obs import sentry
from dmlc_tpu.obs.flight import record_event
from dmlc_tpu.obs.metrics import NOOP, Registry, metrics_enabled, registry
from dmlc_tpu.params import knobs

logger = logging.getLogger("dmlc_tpu.obs.watchdog")

#: alert kinds, in evaluation order
KINDS = ("collapse", "recompile_storm", "stall", "straggler", "numeric")

#: collapse gate defaults: the sentry window/MAD machinery, with a wider
#: relative band — epoch windows are noisier than bench rounds
DEFAULT_REL_TOL = 0.25
DEFAULT_RECOMPILE_LIMIT = 3


class Watchdog:
    """Rolling median±MAD SLO gate over ledger windows (construct via
    :func:`make_watchdog`)."""

    def __init__(self, reg: Optional[Registry] = None,
                 window: int = sentry.DEFAULT_WINDOW,
                 rel_tol: float = DEFAULT_REL_TOL,
                 mad_mult: float = sentry.DEFAULT_MAD_MULT,
                 min_samples: int = sentry.DEFAULT_MIN_SAMPLES,
                 stall_s: Optional[float] = None,
                 recompile_limit: int = DEFAULT_RECOMPILE_LIMIT,
                 profile: Optional[bool] = None,
                 profile_seconds: float = 3.0):
        self._reg = reg if reg is not None else registry()
        self._window = int(window)
        self._rel_tol = float(rel_tol)
        self._mad_mult = float(mad_mult)
        self._min_samples = int(min_samples)
        self._stall_s = (knobs.watchdog_stall_s() if stall_s is None
                         else float(stall_s))
        self._recompile_limit = int(recompile_limit)
        self._profile = (knobs.watchdog_profile() if profile is None
                         else bool(profile))
        self._profile_seconds = float(profile_seconds)
        self._armed = {kind: True for kind in KINDS}
        self._signal_hist: List[float] = []
        self._stalled_s = 0.0
        self.alerts: List[Dict] = []

    # ---- firing / re-arm ----------------------------------------------
    def _fire(self, kind: str, **fields) -> Optional[Dict]:
        if not self._armed[kind]:
            return None
        self._armed[kind] = False
        alert = dict(fields, kind=kind)
        # the flight event's own kind is "watchdog.alert"; the alert
        # kind rides as the ``alert`` field
        record_event("watchdog.alert", **dict(fields, alert=kind))
        self._reg.counter(
            "dmlc_watchdog_alerts_total", "SLO watchdog alerts fired",
            kind=kind).inc()
        logger.warning("watchdog: %s alert %s", kind, fields)
        if self._profile:
            from dmlc_tpu.obs import device_telemetry

            device_telemetry.capture_profile(self._profile_seconds)
        self.alerts.append(alert)
        return alert

    def _clear(self, kind: str) -> None:
        self._armed[kind] = True

    # ---- window evaluation --------------------------------------------
    @staticmethod
    def _signal(win: Dict) -> float:
        g = win.get("goodput", {})
        rows_s = float(g.get("rows_s", 0.0))
        return rows_s if rows_s > 0.0 else float(g.get("mbps", 0.0))

    def observe(self, win: Dict) -> List[Dict]:
        """Evaluate one ledger window; returns the alerts fired by it
        (usually empty)."""
        fired: List[Dict] = []

        def note(alert):
            if alert is not None:
                fired.append(alert)

        # collapse: fresh signal vs rolling baseline over healthy windows
        signal = self._signal(win)
        hist = self._signal_hist[-self._window:]
        collapsed = False
        if len(hist) >= self._min_samples:
            med = sentry._median(hist)
            tol = max(self._rel_tol * abs(med),
                      self._mad_mult * sentry._mad(hist, med))
            if signal < med - tol:
                collapsed = True
                note(self._fire(
                    "collapse", signal=round(signal, 3),
                    baseline=round(med, 3), tolerance=round(tol, 3),
                    binding=win.get("binding")))
        if not collapsed:
            # collapsed windows stay out of their own baseline, so a
            # sustained regression cannot erode the band and re-fire
            self._clear("collapse")
            self._signal_hist.append(signal)
            del self._signal_hist[:-max(self._window * 4, 16)]

        counters = win.get("counters", {})
        # recompile storm
        recompiles = float(counters.get("recompiles", 0.0))
        if recompiles >= self._recompile_limit:
            note(self._fire("recompile_storm", recompiles=int(recompiles)))
        else:
            self._clear("recompile_storm")

        # stall: no forward progress across windows spanning stall_s
        progress = (float(counters.get("steps", 0.0))
                    + float(counters.get("batches", 0.0))
                    + float(counters.get("bytes", 0.0)))
        if progress <= 0.0:
            self._stalled_s += float(win.get("window_s", 0.0))
            if self._stall_s > 0.0 and self._stalled_s >= self._stall_s:
                note(self._fire(
                    "stall", stalled_s=round(self._stalled_s, 3)))
        else:
            self._stalled_s = 0.0
            self._clear("stall")

        # straggler rank flagged by the status plane
        rank = int(win.get("straggler_rank", -1))
        if rank >= 0:
            note(self._fire("straggler", rank=rank))
        else:
            self._clear("straggler")

        # numeric health: non-finite loss/param-sample values stamped
        # onto the window by the fit loop's audit hook
        nonfinite = int(win.get("nonfinite", 0) or 0)
        if nonfinite > 0:
            note(self._fire("numeric", nonfinite=nonfinite))
        else:
            self._clear("numeric")
        return fired


def make_watchdog(reg: Optional[Registry] = None, **kwargs):
    """A :class:`Watchdog`, or the shared no-op child when the metrics
    registry is disabled (``DMLC_TPU_METRICS=0``)."""
    if not metrics_enabled():
        return NOOP
    return Watchdog(reg, **kwargs)
