"""Thread-safe, label-aware metrics registry: Counter / Gauge / Histogram.

The ingest→TPU stack previously hand-rolled its telemetry per module
(`DeviceFeed._host_ns`, `FixedShapePool.allocated`, ...): string-formatted,
single-host, invisible to machines. This registry is the uniform layer the
tf.data input-pipeline work (arXiv:2101.12127 §5) argues for — every stage
counter becomes a named metric that exporters, the bench detail JSON, and
cross-host aggregation (obs/aggregate.py) can all read.

Design points:

- **Naming.** ``dmlc_<area>_<name>_<unit>`` with the unit last
  (``_total`` for counters, ``_ns``/``_bytes``/... for measures) —
  enforced repo-wide by ``scripts/check_metric_names.py``.
- **Labels.** ``registry().counter("dmlc_feed_batches_total", feed="f0")``
  returns the child for that label set; same (name, labels) → same child,
  so per-instance handles are cheap to re-obtain. Metric *names* must be
  string literals at the call site (the lint walks the source).
- **Cheap by default, free when off.** The default-on hot path is one
  lock-and-add. With ``DMLC_TPU_METRICS=0`` registration returns a shared
  no-op child whose methods are empty — near-zero cost, no branches on
  the caller side. The flag is read at *registration* time (instance
  construction), never per increment.
- **Histograms** use fixed log-scale buckets (powers of 4 by default:
  1 ns .. ~18 min for the ns timings this stack records). ``sum`` and
  ``count`` make a histogram a strict superset of a counter, so stage
  timings register one histogram, not a histogram + counter pair.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, Optional, Tuple

from dmlc_tpu.params.knobs import metrics_enabled
from dmlc_tpu.utils.logging import check

# log-scale bucket bounds: 4^0 .. 4^20 (≈1.1e12); values above the last
# bound land in the implicit +inf overflow bucket
DEFAULT_BUCKETS: Tuple[int, ...] = tuple(4 ** k for k in range(21))

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def escape_label_value(value) -> str:
    """Prometheus text-format label-value escaping: ``\\`` → ``\\\\``,
    ``"`` → ``\\"``, newline → ``\\n`` (exposition format spec). Backslash
    first, or the escapes it introduces would be re-escaped."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def format_name(name: str, labels: LabelKey) -> str:
    """``name{k="v",...}`` — the Prometheus-style flat identity used by
    snapshots, exporters, and the cross-host vector ordering. Label
    values are escaped per the Prometheus text format, so the flat name
    stays parseable (and servable by the plane's merged ``/metrics``)
    whatever the value contains."""
    if not labels:
        return name
    inner = ",".join(
        '%s="%s"' % (k, escape_label_value(v)) for k, v in labels
    )
    return "%s{%s}" % (name, inner)


class Counter:
    """Monotonic counter. ``inc``/``add`` are the same lock-and-add."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, v: int = 1) -> None:
        with self._lock:
            self._value += v

    add = inc

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (last write wins; ``inc``/``dec`` for levels)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._value += v

    def dec(self, v: float = 1.0) -> None:
        with self._lock:
            self._value -= v

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bound log-scale histogram with ``sum`` and ``count``.

    ``observe(v)`` counts v in the first bucket whose bound is >= v
    (Prometheus ``le`` semantics); values past the last bound go to the
    overflow bucket. Bounds are fixed at registration so merging across
    hosts/instances is element-wise.
    """

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count")

    def __init__(self, buckets: Optional[Iterable[float]] = None):
        self.bounds = tuple(buckets) if buckets else DEFAULT_BUCKETS
        check(list(self.bounds) == sorted(self.bounds),
              "histogram buckets must be sorted")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)  # +1 = overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        idx = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    # counter-compatible accumulation: stage code that measured a delta
    # can hist.add(dt) like it used to counter.add(dt)
    add = observe

    @property
    def sum(self):
        with self._lock:
            return self._sum

    @property
    def count(self):
        with self._lock:
            return self._count

    def buckets(self) -> Dict[str, int]:
        """Non-cumulative per-bucket counts, only non-empty buckets
        (``"+Inf"`` = overflow) — the compact JSON form."""
        with self._lock:
            counts = list(self._counts)
        out = {}
        for bound, n in zip(self.bounds, counts):
            if n:
                out[repr(int(bound) if float(bound).is_integer() else bound)] = n
        if counts[-1]:
            out["+Inf"] = counts[-1]
        return out

    def cumulative(self) -> Iterable[Tuple[str, int]]:
        """(le, cumulative count) pairs over ALL bounds plus +Inf — the
        Prometheus textfile form."""
        with self._lock:
            counts = list(self._counts)
        acc = 0
        out = []
        for bound, n in zip(self.bounds, counts):
            acc += n
            out.append((repr(int(bound) if float(bound).is_integer()
                             else bound), acc))
        out.append(("+Inf", acc + counts[-1]))
        return out

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (Prometheus
        ``histogram_quantile`` semantics).

        Linear interpolation inside the bucket holding the q-th
        observation; the first bucket interpolates from 0, and anything
        in the overflow bucket reports the last finite bound (the
        distribution above it is unknown). Empty histogram → 0.0;
        q clamps to [0, 1]."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return 0.0
        q = min(1.0, max(0.0, float(q)))
        target = q * total
        acc = 0
        for i, n in enumerate(counts[:-1]):
            if n == 0:
                continue
            if acc + n >= target:
                lo = float(self.bounds[i - 1]) if i > 0 else 0.0
                hi = float(self.bounds[i])
                frac = (target - acc) / n
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
            acc += n
        return float(self.bounds[-1])  # overflow bucket


class _Noop:
    """Shared do-nothing child handed out when DMLC_TPU_METRICS=0. Every
    mutator is an empty method (the disabled-path cost IS one no-op call);
    reads report zero so formatters stay total."""

    __slots__ = ()
    bounds: Tuple[float, ...] = ()
    value = 0
    sum = 0.0
    count = 0

    def inc(self, v=1):
        pass

    def add(self, v=1):
        pass

    def set(self, v):
        pass

    def dec(self, v=1.0):
        pass

    def observe(self, v):
        pass

    # goodput-ledger / watchdog surface (obs/goodput.py, obs/watchdog.py
    # factories hand this same child out under DMLC_TPU_METRICS=0, so
    # the fit-loop hot path stays one empty call, zero allocations)
    windows = ()
    alerts = ()

    def note_step(self, n=1):
        pass

    def tick(self, *args, **kwargs):
        return None

    def buckets(self):
        return {}

    def cumulative(self):
        return []

    def quantile(self, q):
        return 0.0


NOOP = _Noop()

_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    __slots__ = ("kind", "help", "children")

    def __init__(self, kind: str, help: str):
        self.kind = kind
        self.help = help
        self.children: Dict[LabelKey, object] = {}


class Registry:
    """Process-wide metric store. All methods are thread-safe; the
    per-increment path is on the child (one fine-grained lock), not here."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get(self, kind: str, name: str, help: str, labels: Dict,
             buckets=None):
        if not metrics_enabled():
            return NOOP
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(kind, help)
            else:
                check(fam.kind == kind,
                      "metric %s already registered as a %s (asked for %s)",
                      name, fam.kind, kind)
            child = fam.children.get(key)
            if child is None:
                if kind == "histogram":
                    child = Histogram(buckets)
                else:
                    child = _KINDS[kind]()
                fam.children[key] = child
            return child

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "", buckets=None,
                  **labels) -> Histogram:
        return self._get("histogram", name, help, labels, buckets=buckets)

    # ---- read side ------------------------------------------------------

    def families(self) -> Dict[str, Tuple[str, str, Dict[LabelKey, object]]]:
        """{name: (kind, help, {labelkey: child})} — a consistent shallow
        copy for exporters (children are live; their reads take the
        per-child lock)."""
        with self._lock:
            return {
                name: (fam.kind, fam.help, dict(fam.children))
                for name, fam in self._families.items()
            }

    def snapshot(self) -> Dict[str, object]:
        """Flat JSON-ready view: counters/gauges → number, histograms →
        {"count", "sum", "buckets"} with only non-empty buckets."""
        out: Dict[str, object] = {}
        for name, (kind, _help, children) in sorted(self.families().items()):
            for key, child in sorted(children.items()):
                flat = format_name(name, key)
                if kind == "histogram":
                    out[flat] = {
                        "count": child.count,
                        "sum": child.sum,
                        "buckets": child.buckets(),
                    }
                else:
                    out[flat] = child.value
        return out

    def flat_values(self) -> Dict[str, float]:
        """Numeric-only flat view for cross-host allreduce: counters and
        gauges by flat name; each histogram contributes ``_sum`` and
        ``_count`` entries (its distribution stays host-local)."""
        out: Dict[str, float] = {}
        for name, (kind, _help, children) in sorted(self.families().items()):
            for key, child in sorted(children.items()):
                flat = format_name(name, key)
                if kind == "histogram":
                    out[flat + ":sum"] = float(child.sum)
                    out[flat + ":count"] = float(child.count)
                else:
                    out[flat] = float(child.value)
        return out

    def reset(self) -> None:
        """Drop every family (tests; a fresh process state)."""
        with self._lock:
            self._families.clear()


_REGISTRY = Registry()


def registry() -> Registry:
    """The process-wide default registry."""
    return _REGISTRY
