"""Crash flight recorder: a bounded ring of recent telemetry, dumped on death.

Post-mortem debugging of a distributed job usually starts from a stack
trace and a prayer; the information that actually explains the crash —
what the process was *doing* in the seconds before — lived in metrics
and spans that died with it. This module keeps that tail alive: a
fixed-size ring of recent span completions, metric deltas, and
resilience events, written to ``<dir>/flightrec-rank<k>.json`` when the
process dies badly.

Armed by ``DMLC_TPU_FLIGHTREC=<dir>`` (empty = off, the default —
:func:`recorder` then returns the shared :data:`NOOP_RECORDER`, so every
hook below is one empty method call, the ``DMLC_TPU_METRICS=0``
convention). Ring capacity comes from ``DMLC_TPU_FLIGHTREC_CAP``
(default 256 records).

Sources feeding the ring:

- **spans** — via an ``obs.trace`` listener (installed by
  :meth:`FlightRecorder.install`), so recording works without a
  ``DMLC_TPU_TRACE`` file;
- **metric deltas** — :meth:`note_metrics` records which flat metrics
  moved since the last call (``export_epoch``'s publish path feeds it);
- **resilience events** — :func:`record_event` calls planted at the
  fault-injection fire path, retry give-up, collective recovery, and
  checkpoint fallback (kinds cataloged in docs/observability.md and
  linted by scripts/check_faultpoints.py).

Dump triggers: an uncaught exception (chained ``sys.excepthook``),
SIGTERM (handler installed only in the main thread; the previous
disposition is re-raised after the dump so kill semantics survive), and
explicitly from the retry layer on an ``InjectedFault`` give-up
(:func:`dump_if_injected`). Dumps are atomic (tmp + ``os.replace``) and
deliberately tiny — the ring, not a core file.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import signal
import sys
import threading
import time
from typing import Deque, Dict, Optional

from dmlc_tpu.obs import trace
from dmlc_tpu.obs.metrics import Registry, registry
from dmlc_tpu.params.knobs import flightrec_capacity, flightrec_dir

logger = logging.getLogger("dmlc_tpu.obs.flight")


class FlightRecorder:
    """Bounded in-memory ring of recent telemetry with an atomic dump."""

    def __init__(self, out_dir: str, capacity: Optional[int] = None,
                 rank: Optional[int] = None):
        self.out_dir = out_dir
        self.capacity = capacity if capacity else flightrec_capacity()
        if rank is None:
            rank = int(os.environ.get("DMLC_TASK_ID", "0") or 0)
        self.rank = rank
        self._lock = threading.Lock()
        self._ring: Deque[Dict] = collections.deque(maxlen=self.capacity)
        self._last_flat: Dict[str, float] = {}
        self._installed = False
        self._prev_excepthook = None
        self._prev_sigterm = None
        self._dumped_reason: Optional[str] = None

    # ---- feeds ---------------------------------------------------------
    def note(self, kind: str, **fields) -> None:
        entry = {"t_unix_ns": time.time_ns(), "kind": kind}
        entry.update(fields)
        with self._lock:
            self._ring.append(entry)

    def note_span(self, event: Dict) -> None:
        if event.get("ph") not in (None, "X"):
            return  # flow markers ride the trace, not the crash ring
        fields = dict(name=event.get("name"), ts=event.get("ts"),
                      dur=event.get("dur"), tid=event.get("tid"))
        args = event.get("args")
        if isinstance(args, dict) and args.get("flow"):
            # which chunk was in flight when the process died
            fields["flow"] = args["flow"]
        self.note("span", **fields)

    def note_metrics(self, reg: Optional[Registry] = None) -> None:
        """Record which flat metrics moved since the last call (deltas
        only — the ring is too small for full snapshots)."""
        flat = (reg or registry()).flat_values()
        with self._lock:
            delta = {
                k: v - self._last_flat.get(k, 0.0)
                for k, v in flat.items() if v != self._last_flat.get(k, 0.0)
            }
            self._last_flat = flat
        if delta:
            self.note("metrics", delta=delta)

    # ---- dump ----------------------------------------------------------
    def path(self) -> str:
        return os.path.join(self.out_dir, "flightrec-rank%d.json" % self.rank)

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Write the ring to ``flightrec-rank<k>.json`` atomically.

        Re-entrant-safe and duplicate-tolerant (an excepthook firing
        during SIGTERM teardown must not clobber the first dump with a
        shorter one): only the first reason wins."""
        with self._lock:
            if self._dumped_reason is not None:
                return self.path()
            self._dumped_reason = reason
            records = list(self._ring)
        payload = {
            "rank": self.rank,
            "reason": reason,
            "dumped_unix_ns": time.time_ns(),
            "anchor_unix_ns": trace.anchor_unix_ns(),
            "capacity": self.capacity,
            "records": records,
        }
        path = self.path()
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(payload, fh)
            os.replace(tmp, path)
        except OSError as err:
            logger.warning("flight-recorder dump to %s failed: %s", path, err)
            return None
        logger.warning("flight recorder dumped %d records to %s (%s)",
                       len(records), path, reason)
        return path

    # ---- trigger installation -----------------------------------------
    def _on_sigterm(self, signum, frame):
        self.dump("sigterm")
        # restore whatever was there and re-deliver, preserving the
        # process's kill semantics (exit status, parent's waitpid view)
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def _on_uncaught(self, exc_type, exc, tb):
        self.note("uncaught", error=exc_type.__name__, message=str(exc))
        self.dump("uncaught:%s" % exc_type.__name__)
        hook = self._prev_excepthook or sys.__excepthook__
        hook(exc_type, exc, tb)

    def install(self) -> None:
        """Attach the span listener, excepthook chain, and (from the main
        thread only) the SIGTERM handler. Idempotent."""
        if self._installed:
            return
        self._installed = True
        trace.add_listener(self.note_span)
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._on_uncaught
        try:
            self._prev_sigterm = signal.signal(
                signal.SIGTERM, self._on_sigterm)
        except ValueError:
            self._prev_sigterm = None  # not the main thread; skip SIGTERM

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        trace.remove_listener(self.note_span)
        # bound-method equality, not identity: each attribute access
        # builds a fresh method object
        if sys.excepthook == self._on_uncaught:
            sys.excepthook = self._prev_excepthook or sys.__excepthook__
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass

    def records(self):
        with self._lock:
            return list(self._ring)


class _NoopRecorder:
    """Shared disabled recorder (``DMLC_TPU_FLIGHTREC`` unset): every
    hook in the tree lands here as one empty method call."""

    __slots__ = ()

    def note(self, kind, **fields):
        pass

    def note_span(self, event):
        pass

    def note_metrics(self, reg=None):
        pass

    def dump(self, reason="manual"):
        return None

    def install(self):
        pass

    def uninstall(self):
        pass

    def records(self):
        return []


NOOP_RECORDER = _NoopRecorder()

_LOCK = threading.Lock()
_RECORDER = NOOP_RECORDER
_INIT = False


def recorder():
    """The process recorder: a live :class:`FlightRecorder` when
    ``DMLC_TPU_FLIGHTREC`` names a directory, else :data:`NOOP_RECORDER`.
    Resolved once; :func:`reset` re-reads the env (tests)."""
    global _RECORDER, _INIT
    if _INIT:
        return _RECORDER
    with _LOCK:
        if not _INIT:
            out_dir = flightrec_dir()
            if out_dir:
                _RECORDER = FlightRecorder(out_dir)
            _INIT = True
    return _RECORDER


def install_if_armed() -> bool:
    """Resolve the recorder and install its triggers when armed — the
    one call planted at process entry points (``collective.init``)."""
    rec = recorder()
    rec.install()
    return rec is not NOOP_RECORDER


def record_event(kind: str, **fields) -> None:
    """Append one resilience event to the ring (no-op when disarmed).

    ``kind`` must be a dotted literal at the call site — the faultpoint
    lint collects and cross-checks them against docs/observability.md."""
    recorder().note(kind, **fields)


def dump_if_injected(err: BaseException) -> Optional[str]:
    """Dump the ring when a give-up was caused by an injected fault —
    the chaos suite's hook for "did the flight recorder capture it"."""
    from dmlc_tpu.resilience.faults import InjectedFault

    cause = err
    while cause is not None:
        if isinstance(cause, InjectedFault):
            return recorder().dump("injected_giveup")
        cause = cause.__cause__
    return None


def configure(out_dir: str, capacity: Optional[int] = None,
              rank: Optional[int] = None,
              install: bool = True) -> FlightRecorder:
    """Explicitly (re)build the process recorder — tests and embedders
    that cannot use the env knob."""
    global _RECORDER, _INIT
    with _LOCK:
        if isinstance(_RECORDER, FlightRecorder):
            _RECORDER.uninstall()
        rec = FlightRecorder(out_dir, capacity=capacity, rank=rank)
        _RECORDER = rec
        _INIT = True
    if install:
        rec.install()
    return rec


def reset() -> None:
    """Tear down the process recorder and forget the cached env read."""
    global _RECORDER, _INIT
    with _LOCK:
        if isinstance(_RECORDER, FlightRecorder):
            _RECORDER.uninstall()
        _RECORDER = NOOP_RECORDER
        _INIT = False
