"""Compiled-program cost analytics: per-jit-site FLOP/byte/memory records.

PR 13 moved the training hot path inside the compiled graph (in-graph
``psum`` SPMD steps) and PR 16 made it device-resident, which left the
obs plane blind past the jit boundary: FLOPs executed, HBM bytes moved,
and ICI collective traffic all happen inside one opaque dispatch. This
module restores that visibility *at compile time, never per step*: when
an :class:`~dmlc_tpu.obs.device_telemetry.InstrumentedJit` site compiles
a new (fn, bucket-shape) signature, :func:`note_compile` re-lowers the
same arguments (``jitted.lower(...)`` reads cached jaxprs and argument
avals only — it does not re-trace the Python body, so the recompile
sentinel is untouched; verified against donated/deleted buffers) and
reads the compiled executable's analytics:

- ``compiled.cost_analysis()`` → per-call ``flops`` and ``bytes
  accessed`` (``dmlc_xla_flops{fn=}``,
  ``dmlc_xla_bytes_accessed{fn=}``);
- ``compiled.memory_analysis()`` → peak program bytes: argument +
  output + temp + generated code, minus donation aliasing
  (``dmlc_xla_peak_bytes{fn=}``);
- the optimized HLO text → bytes moved by in-graph collectives
  (all-reduce / all-gather / reduce-scatter / collective-permute /
  all-to-all result shapes summed; ``dmlc_xla_collective_bytes{fn=}``)
  — the allreduce traffic ``dmlc_collective_*`` stopped seeing when the
  psum moved in-graph.

Records are cached per (fn, bucket signature): a bucket that has been
analyzed once is never re-extracted (pinned by test), so steady-state
training pays nothing. Every probe is wrapped in try/except — a backend
without ``cost_analysis`` (or an opaque analysis shape) degrades to
absent gauges, never a crash. Under ``DMLC_TPU_METRICS=0`` the hook
returns immediately.

The same records feed the model-based roofline: obs/goodput.py turns
steps × per-step flops into an MFU verdict against
``DMLC_TPU_PEAK_FLOPS`` / ``DMLC_TPU_PEAK_HBM_GBPS`` (or the measured
:func:`probed_peak_flops` / :func:`probed_hbm_gbps` defaults), the
``/xla`` status endpoint and ``obs-report --xla`` render the per-site
tables, and bench's detail artifact carries the ``xla`` section plus
``sgd_mfu`` (sentry-gated higher-is-better).
"""

from __future__ import annotations

import logging
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from dmlc_tpu.obs.metrics import Registry, metrics_enabled, registry

logger = logging.getLogger("dmlc_tpu.obs.xla_cost")

__all__ = [
    "bucket_signature",
    "collective_bytes_from_hlo",
    "note_compile",
    "extraction_count",
    "records",
    "per_fn",
    "sites_from_flat",
    "step_costs",
    "detail_section",
    "probed_peak_flops",
    "probed_hbm_gbps",
    "reset",
]

_lock = threading.Lock()
# (fn, bucket signature) -> record; insertion-ordered, so per_fn() keeps
# the LATEST bucket per site while counting all of them
_records: Dict[Tuple[str, str], Dict[str, Any]] = {}
_extractions = 0

#: the gauge fields every record carries (and the flat-metric parser reads)
FIELDS = ("flops", "bytes_accessed", "peak_bytes", "collective_bytes")


def bucket_signature(args: tuple, kwargs: Optional[dict] = None) -> str:
    """Shape/dtype signature of one call's argument tree — the cache key
    half that distinguishes FixedShapePool buckets. Non-array leaves
    contribute their type name only (their values do not retrace)."""
    import jax

    parts: List[str] = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs or {})):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            parts.append(type(leaf).__name__)
        else:
            parts.append(
                "%s[%s]" % (dtype, ",".join(str(d) for d in shape)))
    return ";".join(parts)


# one collective *call site* per match: the op name must be applied
# (trailing "(" ), so parameter/operand shape mentions don't count, and
# async pairs count once — "-start" matches, "-done" cannot (the hyphen
# is outside [\w.]).
_COLL_CALL_RE = re.compile(
    r"=\s*([^=]*?)\s*"
    r"(?:all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?[\w.]*\(")
_SHAPE_RE = re.compile(r"\b(pred|[a-z]+[0-9]+[a-z0-9]*)\[([0-9,]*)\]")


def _dtype_bytes(token: str) -> int:
    if token == "pred":
        return 1
    m = re.search(r"(\d+)", token)
    bits = int(m.group(1)) if m else 8
    return max(1, bits // 8)


def collective_bytes_from_hlo(hlo_text: str) -> float:
    """Bytes produced by in-graph collective ops, summed over the result
    shapes in one optimized-HLO module text. XLA's CPU ``cost_analysis``
    carries no collective byte keys, so this is derived from the program
    itself — the per-call ICI payload of an SPMD psum step."""
    total = 0.0
    for m in _COLL_CALL_RE.finditer(hlo_text):
        for token, dims in _SHAPE_RE.findall(m.group(1)):
            count = 1
            for dim in dims.split(","):
                if dim:
                    count *= int(dim)
            total += count * _dtype_bytes(token)
    return total


def _extract(jitted, args: tuple, kwargs: dict) -> Dict[str, float]:
    """One executable's analytics, each probe independently best-effort."""
    compiled = jitted.lower(*args, **kwargs).compile()
    out = {field: 0.0 for field in FIELDS}
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            # older jax returns one dict per partition; they agree for
            # SPMD programs, so the first speaks for the site
            analysis = analysis[0] if analysis else {}
        if isinstance(analysis, dict):
            out["flops"] = max(0.0, float(analysis.get("flops", 0.0) or 0.0))
            out["bytes_accessed"] = max(
                0.0, float(analysis.get("bytes accessed", 0.0) or 0.0))
    except Exception:
        logger.debug("cost_analysis unavailable", exc_info=True)
    try:
        mem = compiled.memory_analysis()
        peak = 0.0
        for field in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "generated_code_size_in_bytes"):
            peak += float(getattr(mem, field, 0) or 0)
        # donated buffers alias an argument onto an output: counted once
        peak -= float(getattr(mem, "alias_size_in_bytes", 0) or 0)
        out["peak_bytes"] = max(0.0, peak)
    except Exception:
        logger.debug("memory_analysis unavailable", exc_info=True)
    try:
        out["collective_bytes"] = collective_bytes_from_hlo(
            compiled.as_text())
    except Exception:
        logger.debug("hlo text unavailable", exc_info=True)
    return out


def _set_gauges(fn_name: str, rec: Dict[str, Any],
                reg: Optional[Registry] = None) -> None:
    reg = reg if reg is not None else registry()
    reg.gauge(
        "dmlc_xla_flops",
        "per-call FLOPs of the latest compiled bucket per jit site "
        "(XLA cost_analysis)", fn=fn_name,
    ).set(float(rec.get("flops", 0.0)))
    reg.gauge(
        "dmlc_xla_bytes_accessed",
        "per-call memory traffic of the latest compiled bucket per jit "
        "site (XLA cost_analysis 'bytes accessed')", fn=fn_name,
    ).set(float(rec.get("bytes_accessed", 0.0)))
    reg.gauge(
        "dmlc_xla_peak_bytes",
        "compiled-program peak bytes per jit site (memory_analysis: "
        "argument+output+temp+code, donation aliases counted once)",
        fn=fn_name,
    ).set(float(rec.get("peak_bytes", 0.0)))
    reg.gauge(
        "dmlc_xla_collective_bytes",
        "per-call bytes produced by in-graph collectives per jit site "
        "(summed from the optimized HLO's all-reduce/all-gather/"
        "reduce-scatter/collective-permute/all-to-all result shapes)",
        fn=fn_name,
    ).set(float(rec.get("collective_bytes", 0.0)))


def note_compile(fn_name: str, jitted, args: tuple,
                 kwargs: Optional[dict] = None,
                 reg: Optional[Registry] = None) -> Optional[Dict[str, Any]]:
    """Record one jit site's compiled-program analytics; the
    InstrumentedJit compile-branch hook.

    Runs only when a call actually compiled, and extracts at most once
    per (fn, bucket signature) — a signature already analyzed returns
    its cached record with no lowering, no compile, no gauge write.
    Returns the record, or None when metrics are off or every probe
    failed (absent gauges, never a crash)."""
    if not metrics_enabled():
        return None
    kwargs = kwargs or {}
    try:
        key = (fn_name, bucket_signature(args, kwargs))
    except Exception:
        logger.debug("bucket signature failed for %s", fn_name,
                     exc_info=True)
        return None
    with _lock:
        rec = _records.get(key)
    if rec is not None:
        return rec
    t0 = time.monotonic_ns()
    try:
        costs = _extract(jitted, args, kwargs)
    except Exception as err:
        logger.debug("xla cost extraction failed for %s: %s", fn_name, err)
        return None
    rec = dict(costs, fn=fn_name, bucket=key[1],
               extract_ms=round((time.monotonic_ns() - t0) / 1e6, 3))
    global _extractions
    with _lock:
        if key in _records:  # lost a race: first extraction already won
            return _records[key]
        _records[key] = rec
        _extractions += 1
    _set_gauges(fn_name, rec, reg)
    return rec


def extraction_count() -> int:
    """Extractions actually performed this process (cache misses only) —
    what the no-re-extract pin asserts against."""
    with _lock:
        return _extractions


def records() -> List[Dict[str, Any]]:
    """Every cached record, extraction order (one per fn × bucket)."""
    with _lock:
        return [dict(rec) for rec in _records.values()]


def per_fn() -> Dict[str, Dict[str, Any]]:
    """Latest record per jit site plus its bucket count — the ``/xla``
    local view and bench's ``xla`` detail section rows."""
    out: Dict[str, Dict[str, Any]] = {}
    with _lock:
        items = list(_records.items())
    for (fn, _bucket), rec in items:
        row = dict(rec)
        row["buckets"] = out[fn]["buckets"] + 1 if fn in out else 1
        out[fn] = row
    return out


_FLAT_XLA_RE = re.compile(
    r'^(dmlc_xla_(?:flops|bytes_accessed|peak_bytes|collective_bytes))'
    r'\{[^}]*?fn="((?:[^"\\]|\\.)*)"')


def sites_from_flat(flat: Dict[str, float]) -> Dict[str, Dict[str, float]]:
    """Per-site cost rows parsed back out of a flat registry snapshot —
    how the tracker reads a *worker's* records off its heartbeat payload
    (the gauges ride ``flat_values()`` like every other metric)."""
    out: Dict[str, Dict[str, float]] = {}
    for key, value in flat.items():
        m = _FLAT_XLA_RE.match(key)
        if not m:
            continue
        name, fn = m.groups()
        fn = fn.replace('\\"', '"').replace("\\\\", "\\")
        out.setdefault(fn, {})[name[len("dmlc_xla_"):]] = float(value)
    return out


def step_costs(flat: Dict[str, float]) -> Dict[str, float]:
    """The model train step's per-call flops/bytes from a flat snapshot:
    the max across ``*.step`` / ``*.step_mp`` sites (the dominant bucket
    of the hot step). Feeds goodput's window flop estimate
    (steps × per-step flops) and the MFU verdict."""
    out = {"flops": 0.0, "bytes": 0.0}
    for fn, rec in sites_from_flat(flat).items():
        if fn.rsplit(".", 1)[-1] not in ("step", "step_mp"):
            continue
        out["flops"] = max(out["flops"], rec.get("flops", 0.0))
        out["bytes"] = max(out["bytes"], rec.get("bytes_accessed", 0.0))
    return out


def detail_section() -> Dict[str, Any]:
    """The ``xla`` block for bench's detail artifact and the ``/xla``
    endpoint's local half: per-site latest records + extraction count."""
    return {"sites": per_fn(), "extractions": extraction_count()}


# ---------------------------------------------------------------------------
# measured peaks: the auto-probed defaults behind DMLC_TPU_PEAK_FLOPS /
# DMLC_TPU_PEAK_HBM_GBPS (knob > 0 wins; these run once per process,
# lazily, only when a model-based verdict is actually requested)
# ---------------------------------------------------------------------------

_probe_lock = threading.Lock()
_peak_flops_probe: Optional[float] = None
_hbm_gbps_probe: Optional[float] = None


def _best_seconds(fn, arg, repeats: int = 3) -> float:
    import jax

    jax.block_until_ready(fn(arg))  # compile + warm outside the timing
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(arg))
        best = min(best, time.perf_counter() - t0)
    return best


def probed_peak_flops() -> float:
    """Measured matmul FLOP rate (FLOP/s), probed once per process: a
    256×256 f32 matmul timed best-of-3. A *measured* ceiling, so MFU
    against it reads as "fraction of what this backend demonstrably
    sustains"; 0.0 when the probe fails (MFU then stays absent)."""
    global _peak_flops_probe
    with _probe_lock:
        if _peak_flops_probe is not None:
            return _peak_flops_probe
    val = 0.0
    try:
        import jax
        import jax.numpy as jnp

        n = 256
        a = jnp.ones((n, n), jnp.float32)
        best = _best_seconds(jax.jit(lambda x: x @ x), a)
        if best > 0:
            val = 2.0 * n ** 3 / best
    except Exception:
        logger.debug("peak-flops probe failed", exc_info=True)
    with _probe_lock:
        if _peak_flops_probe is None:
            _peak_flops_probe = val
        return _peak_flops_probe


def probed_hbm_gbps() -> float:
    """Measured device memory bandwidth (GB/s), probed once per process:
    a 32 MiB f32 element-wise pass (read + write) timed best-of-3; 0.0
    when the probe fails (the HBM fraction then stays absent)."""
    global _hbm_gbps_probe
    with _probe_lock:
        if _hbm_gbps_probe is not None:
            return _hbm_gbps_probe
    val = 0.0
    try:
        import jax
        import jax.numpy as jnp

        x = jnp.ones(8 * 1024 * 1024, jnp.float32)  # 32 MiB
        best = _best_seconds(jax.jit(lambda v: v * 1.0000001), x)
        if best > 0:
            val = 2.0 * x.size * 4 / best / 1e9
    except Exception:
        logger.debug("hbm-bandwidth probe failed", exc_info=True)
    with _probe_lock:
        if _hbm_gbps_probe is None:
            _hbm_gbps_probe = val
        return _hbm_gbps_probe


def reset() -> None:
    """Forget process-level state (tests): records, the extraction
    counter, and both measured-peak probes."""
    global _extractions, _peak_flops_probe, _hbm_gbps_probe
    with _lock:
        _records.clear()
        _extractions = 0
    with _probe_lock:
        _peak_flops_probe = None
        _hbm_gbps_probe = None
