"""Perf sentry: turn bench history into a noise-aware regression gate.

The bench driver leaves two artifact shapes behind (bench.py):

- ``BENCH_r*.json`` — one per round, ``{"n", "cmd", "rc", "tail",
  "parsed"}`` where ``parsed`` is the compact summary line (may be null
  when a round produced no summary);
- ``bench_detail.json`` — the full unshed record for the latest run
  (``DMLC_TPU_BENCH_DETAIL``), one JSON object per line.

Both reduce to the same record: ``{"metric", "value", "unit", "extra"}``.
:func:`gate` compares a fresh record against the history series per
metric:

- the headline metric (``value``, e.g. ``higgs_libsvm_ingest`` MB/s) and
  every ``extra`` key ending ``_mbps``/``_gbps``/``_mrows_s`` are
  higher-is-better throughputs, as is the suffix-less
  ``cache_cross_job_hit_ratio`` (multijob bench tier — a drop below its
  1.0 history means a second tenant started re-parsing shared chunks);
- ``extra["pipelined_stall_stages"]`` keys ending ``_s`` are gated
  lower-is-better as ``stall.<key>`` (a stall stage growing is exactly
  the regression shape flow tracing exists to localize);
- ``extra["device_telemetry"]`` (obs/device_telemetry.py) contributes
  ``compiles.<fn>`` (per-function XLA compile counts) and
  ``hbm.peak_bytes`` lower-is-better — a compile-count increase is a
  recompile regression, an HBM peak increase is memory pressure — plus
  ``h2d_mbps`` (mean H2D submission bandwidth) higher-is-better.

Bench numbers are noisy (the recorded higgs history spans 468–678 MB/s
across environments), so the baseline is robust: per metric, take the
``window`` most recent history values, baseline = median, spread = MAD
(median absolute deviation), and tolerance = ``max(rel_tol·|median|,
mad_mult·MAD)`` — a metric whose history is jumpy earns a wide band, a
stable one a tight band. Metrics with fewer than ``min_samples`` history
points are skipped (no noise estimate to gate against). Regressions are
ranked by how far past the tolerance band they land, in tolerance units.

CLI: ``python -m dmlc_tpu.tools bench-gate`` (tools/bench_gate.py); the
``--smoke`` self-check runs the gate over the canned pair below and is
wired into scripts/ci_checks.sh. Each reported regression is also
recorded as a ``sentry.regression`` flight-recorder event
(docs/observability.md event catalog).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from dmlc_tpu.obs.flight import record_event

# baseline window / tolerances, tuned against the real BENCH_r01..r05
# history: the r05 record passes, a 20% headline degradation fails
# (pinned by tests/test_sentry.py and the --smoke self-check)
DEFAULT_WINDOW = 3
DEFAULT_REL_TOL = 0.10
DEFAULT_MAD_MULT = 2.0
DEFAULT_MIN_SAMPLES = 2

_HIGHER_SUFFIXES = ("_mbps", "_gbps", "_mrows_s")
# higher-is-better extras that carry no unit suffix: the cross-job
# source-cache hit ratio from the multijob bench tier (1.0 = the second
# tenant parsed nothing), and the SPMD in-graph step's ICI utilization
# (achieved/peak on the gradient psum — the ≥90% ROADMAP target).
# spmd_psum_step_gbps and the baked-shard tier keys (shard_ingest_gbps /
# sgd_e2e_shard_mbps / bake_mbps, the ISSUE's acceptance trio) are listed
# too for explicitness, though the suffix rule already gates them.
_HIGHER_KEYS = (
    "cache_cross_job_hit_ratio",
    "ici_utilization",
    "spmd_psum_step_gbps",
    "shard_ingest_gbps",
    "sgd_e2e_shard_mbps",
    "bake_mbps",
)
_STALL_PREFIX = "stall."
# lower-is-better key families: stall stages, XLA compile counts, and
# peak HBM (device_telemetry section)
_LOWER_PREFIXES = (_STALL_PREFIX, "compiles.", "hbm.")

# canned record pair for the --smoke self-check: a miniature history in
# the real artifact shape (values loosely after BENCH_r01..r05) plus a
# degraded twin of the last round. Canned rather than read from disk so
# the self-check runs anywhere (CI checkout, installed package).
SMOKE_HISTORY: List[Dict] = [
    {
        "metric": "higgs_libsvm_ingest", "value": 560.1, "unit": "MB/s",
        "extra": {
            "recordio_ingest_mbps": 2250.0,
            "pipelined_stall_stages": {
                "host_batch_s": 2.10, "dispatch_s": 0.40,
                "host_wait_s": 0.55, "consume_s": 1.95,
            },
        },
    },
    {
        "metric": "higgs_libsvm_ingest", "value": 612.4, "unit": "MB/s",
        "extra": {
            "recordio_ingest_mbps": 2310.0,
            "pipelined_stall_stages": {
                "host_batch_s": 2.02, "dispatch_s": 0.38,
                "host_wait_s": 0.49, "consume_s": 1.90,
            },
        },
    },
    {
        "metric": "higgs_libsvm_ingest", "value": 646.3, "unit": "MB/s",
        "extra": {
            "recordio_ingest_mbps": 2341.3,
            "pipelined_stall_stages": {
                "host_batch_s": 1.98, "dispatch_s": 0.41,
                "host_wait_s": 0.52, "consume_s": 1.88,
            },
        },
    },
    {
        "metric": "higgs_libsvm_ingest", "value": 678.0, "unit": "MB/s",
        "extra": {
            "recordio_ingest_mbps": 2338.0,
            "pipelined_stall_stages": {
                "host_batch_s": 1.95, "dispatch_s": 0.39,
                "host_wait_s": 0.50, "consume_s": 1.85,
            },
        },
    },
]


def smoke_degraded() -> Dict:
    """The canned fresh record with a 20% headline regression and a
    doubled host_wait stall — the shapes the gate must catch."""
    rec = json.loads(json.dumps(SMOKE_HISTORY[-1]))  # deep copy
    rec["value"] = round(rec["value"] * 0.8, 1)
    stalls = rec["extra"]["pipelined_stall_stages"]
    stalls["host_wait_s"] = round(stalls["host_wait_s"] * 2.0, 2)
    return rec


def _median(values: Sequence[float]) -> float:
    s = sorted(values)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _mad(values: Sequence[float], med: float) -> float:
    return _median([abs(v - med) for v in values])


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def load_record(path: str) -> List[Dict]:
    """Parse one bench artifact into normalized records.

    Accepts either driver shape (``{"parsed": {...}}`` — a null
    ``parsed`` yields no record, matching rounds that printed no
    summary) or a raw summary/detail object; files may hold one JSON
    object or one per line (bench_detail.json appends)."""
    text = open(path).read()
    try:
        objs = [json.loads(text)]
    except ValueError:
        objs = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                objs.append(json.loads(line))
            except ValueError:
                continue  # torn tail line: keep what parses
    out = []
    for obj in objs:
        if not isinstance(obj, dict):
            continue
        if "parsed" in obj:
            obj = obj["parsed"]
        if isinstance(obj, dict) and _is_number(obj.get("value")) \
                and obj.get("metric"):
            out.append(dict(obj, source=path))
    return out


def load_records(paths: Sequence[str]) -> List[Dict]:
    out: List[Dict] = []
    for path in paths:
        out.extend(load_record(path))
    return out


def record_values(rec: Dict) -> Dict[str, float]:
    """The gateable metric values of one record (see module docstring
    for the key→direction rules). Keys named in the record's optional
    ``directions`` map are gated too, whatever their suffix — the
    per-record direction registry that replaces growing
    ``_HIGHER_KEYS`` (``record_directions`` collects the map for
    :func:`gate`). That is how bench's ``sgd_goodput_ratio`` and
    ``sgd_mfu`` (model FLOP utilization, higher-is-better) gate with no
    sentry-side changes."""
    vals: Dict[str, float] = {}
    if _is_number(rec.get("value")) and rec.get("metric"):
        vals[str(rec["metric"])] = float(rec["value"])
    extra = rec.get("extra") or {}
    if not isinstance(extra, dict):
        return vals
    directions = rec.get("directions")
    if isinstance(directions, dict):
        for key in directions:
            v = extra.get(key)
            if _is_number(v):
                vals[str(key)] = float(v)
    for key, v in extra.items():
        if _is_number(v) and (key.endswith(_HIGHER_SUFFIXES)
                              or key in _HIGHER_KEYS):
            vals[key] = float(v)
    stalls = extra.get("pipelined_stall_stages")
    if isinstance(stalls, dict):
        for key, v in stalls.items():
            if _is_number(v) and key.endswith("_s"):
                vals[_STALL_PREFIX + key] = float(v)
    devtel = extra.get("device_telemetry")
    if isinstance(devtel, dict):
        compiles = devtel.get("compiles")
        if isinstance(compiles, dict):
            for fn, v in compiles.items():
                if _is_number(v):
                    vals["compiles." + str(fn)] = float(v)
        if _is_number(devtel.get("peak_hbm_bytes")):
            vals["hbm.peak_bytes"] = float(devtel["peak_hbm_bytes"])
        if _is_number(devtel.get("h2d_mbps")):
            vals["h2d_mbps"] = float(devtel["h2d_mbps"])
    return vals


def metric_series(records: Sequence[Dict]) -> Dict[str, List[float]]:
    """Per-metric history series, in record order (oldest first)."""
    series: Dict[str, List[float]] = {}
    for rec in records:
        for key, v in record_values(rec).items():
            series.setdefault(key, []).append(v)
    return series


def record_directions(records: Sequence[Dict]) -> Dict[str, str]:
    """Merge the per-record ``directions`` maps of a history series
    (latest record wins per key) — the second argument
    :func:`lower_is_better` consults before its prefix rules."""
    out: Dict[str, str] = {}
    for rec in records:
        d = rec.get("directions") if isinstance(rec, dict) else None
        if isinstance(d, dict):
            for key, v in d.items():
                if v in ("higher", "lower"):
                    out[str(key)] = v
    return out


def lower_is_better(key: str,
                    directions: Optional[Dict[str, str]] = None) -> bool:
    """Direction of one gated key: an explicit per-record ``directions``
    entry wins; otherwise the suffix/prefix rules in the module
    docstring decide (default: higher is better)."""
    if directions:
        d = directions.get(key)
        if d == "lower":
            return True
        if d == "higher":
            return False
    return key.startswith(_LOWER_PREFIXES)


def gate(
    fresh: Dict[str, float],
    series: Dict[str, List[float]],
    rel_tol: float = DEFAULT_REL_TOL,
    mad_mult: float = DEFAULT_MAD_MULT,
    window: int = DEFAULT_WINDOW,
    min_samples: int = DEFAULT_MIN_SAMPLES,
    directions: Optional[Dict[str, str]] = None,
) -> List[Dict]:
    """Compare fresh metric values against their history series.

    Returns the regressions ranked worst-first; each carries the fresh
    value, the baseline (median), the tolerance band, and ``severity``
    (how far past the band, in tolerance units). Also records each as a
    ``sentry.regression`` flight event (no-op unless the recorder is
    armed)."""
    regressions: List[Dict] = []
    for key in sorted(fresh):
        hist = series.get(key, [])[-window:]
        if len(hist) < min_samples:
            continue
        med = _median(hist)
        tol = max(rel_tol * abs(med), mad_mult * _mad(hist, med))
        value = fresh[key]
        if lower_is_better(key, directions):
            breach = value - (med + tol)
        else:
            breach = (med - tol) - value
        if breach <= 0:
            continue
        reg = {
            "metric": key,
            "value": value,
            "baseline": med,
            "tolerance": tol,
            "direction": "lower" if lower_is_better(key, directions)
            else "higher",
            "samples": len(hist),
            "severity": breach / tol if tol > 0 else float("inf"),
        }
        regressions.append(reg)
        record_event(
            "sentry.regression", metric=key, value=value,
            baseline=med, tolerance=tol,
        )
    regressions.sort(key=lambda r: -r["severity"])
    return regressions


def format_report(
    regressions: Sequence[Dict], fresh_source: Optional[str] = None
) -> str:
    """The ranked regression table bench-gate prints on failure."""
    lines = []
    head = "perf sentry: %d regression(s)" % len(regressions)
    if fresh_source:
        head += " in %s" % fresh_source
    lines.append(head)
    lines.append(
        "%-28s %12s %12s %12s %9s" % (
            "metric", "fresh", "baseline", "tolerance", "severity")
    )
    for r in regressions:
        lines.append(
            "%-28s %12.4g %12.4g %12.4g %8.1fx  (%s is better)" % (
                r["metric"], r["value"], r["baseline"], r["tolerance"],
                r["severity"], r["direction"])
        )
    return "\n".join(lines)
