"""Per-row objective math shared by the XLA and Pallas train steps.

One definition of (loss, dloss/dmargin) per objective so a numerics fix or
a new objective lands in both paths at once (models/linear.py consumes it
directly; ops/pallas_kernels.py traces it inside the fused kernel — it is
pure elementwise jnp, so it lowers in either context).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

OBJECTIVES = ("logistic", "squared", "hinge")


def margin_loss_grad(objective: str, margin, label):
    """(loss, dloss/dmargin) per row.

    logistic: labels in {0,1}, numerically stable softplus form.
    squared: plain least squares.
    hinge: labels in {0,1} mapped to {-1,+1}.
    """
    if objective == "logistic":
        loss = jnp.maximum(margin, 0.0) - margin * label + jnp.log1p(
            jnp.exp(-jnp.abs(margin))
        )
        grad = jax.nn.sigmoid(margin) - label
    elif objective == "squared":
        diff = margin - label
        loss = 0.5 * diff * diff
        grad = diff
    elif objective == "hinge":
        y = 2.0 * label - 1.0
        loss = jnp.maximum(0.0, 1.0 - y * margin)
        grad = jnp.where(y * margin < 1.0, -y, 0.0)
    else:
        raise ValueError(f"unknown objective {objective!r}")
    return loss, grad
