"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference predates pipelined model training (SURVEY §2.9 lists no PP);
this realizes the extension point the TPU-first way, completing the
parallelism matrix next to dp (allreduce), mp (feature-sharded), sp
(ring/ulysses attention) and ep (MoE dispatch):

- the model is N identical-structure STAGES whose parameters carry a
  leading stage dim sharded over the ``pp`` axis (each device materializes
  one stage — model memory scales out with depth);
- a batch is split into M microbatches; the schedule runs M + N - 1 ticks
  inside ONE ``lax.scan``: at tick t, device i computes its stage on
  microbatch t - i and hands the activation to device i+1 with a single
  ``ppermute`` hop (neighbor traffic only — ICI-friendly, no host);
- the classic GPipe bubble applies: N - 1 of the ticks are fill/drain, so
  efficiency is M / (M + N - 1) — raise M to amortize.

The schedule is exact: outputs equal folding the stages sequentially
(``pipeline_oracle``), including gradients through the scan + ppermute
(tests/test_pipeline_parallel.py, 8-stage virtual mesh).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_tpu.utils.jax_compat import axis_size, pcast, shard_map

from dmlc_tpu.utils.logging import check


def pipeline_oracle(stage_fn: Callable, params, x):
    """Sequential reference: fold every stage over x (stage s uses
    ``tree_map(lambda a: a[s], params)``)."""
    n_stages = jax.tree_util.tree_leaves(params)[0].shape[0]
    y = x
    for s in range(n_stages):
        p_s = jax.tree_util.tree_map(lambda a: a[s], params)
        y = stage_fn(p_s, y)
    return y


def make_pipeline(
    mesh: Mesh,
    stage_fn: Callable,
    num_microbatches: int,
    axis: str = "pp",
    batch_axis=None,
):
    """Jitted f(params, x[batch, ...]) -> y with GPipe microbatch schedule.

    ``stage_fn(stage_params, act) -> act`` is one stage (shapes preserved);
    ``params`` leaves have leading dim = axis size (one stage per device,
    sharded P(axis) by :func:`shard_pipeline_params`). ``x``'s batch dim
    must divide into ``num_microbatches``. With ``batch_axis=None`` x/y
    are replicated across the axis; with ``batch_axis="dp"`` (a second
    mesh axis) the batch dim shards over it — pass x placed P(batch_axis)
    — and each dp shard streams its own microbatches, so the PER-SHARD
    batch must divide ``num_microbatches``.
    """
    n_stages = mesh.shape[axis]
    m = num_microbatches

    def _local(params, x):
        idx = jax.lax.axis_index(axis)
        size = axis_size(axis)
        batch = x.shape[0]
        mb = batch // m
        micro = x.reshape(m, mb, *x.shape[1:])
        # pcast-to-varying: the scan outputs vary over the axis, so the
        # initial carries must too (same trick as the ring-attention scan)
        state = pcast(
            jnp.zeros_like(micro[0]), axis, to="varying"
        )  # activation arriving from my left
        outputs = pcast(jnp.zeros_like(micro), axis, to="varying")
        perm = [(i, i + 1) for i in range(size - 1)]  # forward handoff

        def tick(carry, t):
            state, outputs = carry
            # stage 0 injects microbatch t (clamped during drain ticks —
            # those results are never collected); others consume the
            # activation handed over last tick
            inp = jnp.where(
                idx == 0, micro[jnp.clip(t, 0, m - 1)], state
            )
            # device i participates only while t - i lands on a real
            # microbatch; on fill/drain ticks substitute a REAL microbatch
            # for the zero-initialized carry. The discarded results never
            # reach outputs or any valid tick downstream, but computing on
            # zeros would let stage fns with zero-singularities (norms,
            # divisions) produce NaN primals whose VJPs poison gradients
            # through 0*NaN even though the forward is masked.
            valid = (t >= idx) & (t - idx < m)
            inp = jnp.where(valid, inp, micro[0])
            out = stage_fn(jax.tree_util.tree_map(lambda a: a[0], params),
                           inp)
            # the LAST stage's output for microbatch t - (size - 1)
            done = t - (size - 1)
            collect = (idx == size - 1) & (done >= 0)
            outputs = outputs.at[jnp.clip(done, 0, m - 1)].set(
                jnp.where(collect, out, outputs[jnp.clip(done, 0, m - 1)])
            )
            state = jax.lax.ppermute(out, axis, perm)
            return (state, outputs), None

        (state, outputs), _ = jax.lax.scan(
            tick, (state, outputs), jnp.arange(m + size - 1)
        )
        # outputs live on the last stage only; psum replicates them (all
        # other shards contribute zeros)
        outputs = jax.lax.psum(
            jnp.where(idx == size - 1, outputs, jnp.zeros_like(outputs)),
            axis_name=axis,
        )
        return outputs.reshape(batch, *x.shape[1:])

    # batch_axis composes dp: each dp-shard streams its own microbatches
    # through the same per-device stages
    sharded = jax.jit(
        shard_map(
            _local,
            mesh=mesh,
            in_specs=(P(axis), P(batch_axis)),
            out_specs=P(batch_axis),
        )
    )

    def _wrapped(params, x):
        leading = jax.tree_util.tree_leaves(params)[0].shape[0]
        check(leading == n_stages,
              "params lead dim %d != pipeline stages %d", leading, n_stages)
        # the constraint is per batch shard: each dp shard streams its own
        # microbatches
        dp = mesh.shape[batch_axis] if batch_axis is not None else 1
        check(x.shape[0] % dp == 0,
              "batch %d must divide over %s size %d", x.shape[0],
              batch_axis, dp)
        local_batch = x.shape[0] // dp
        check(local_batch % m == 0 and local_batch >= m,
              "per-shard batch %d must divide into %d microbatches",
              local_batch, m)
        return sharded(params, x)

    return _wrapped


def shard_pipeline_params(params, mesh: Mesh, axis: str = "pp"):
    """Place stage-stacked params (leading dim = n_stages) one stage per
    device over ``axis``."""
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P(axis))), params
    )
