"""Pallas TPU kernels for the hot compute ops.

The flagship loop's compute core is ``margin = X @ w`` followed by an
elementwise loss/grad and ``gw = X^T (weight * dmargin)`` (models/linear.py).
XLA already fuses the elementwise work into the matmuls; the Pallas kernel
here goes one step further and keeps the whole step — both matmuls, the
loss, and the scalar reductions — resident in VMEM per batch tile, with the
gradient accumulated across grid steps. One HBM read of X per step, no
intermediate [B] arrays ever round-tripping through HBM.

The sparse (COO) path gets a kernel too, with a narrower scope. Per-entry
dynamic gather/scatter is exactly what the TPU's vector unit can't tile
(SURVEY §7 hard parts; ops/spmv.py design note), so the feature-id gather
(``vec[indices]`` — the segment keys span millions of features) stays on
XLA, where it fuses into the kernel's input. What Pallas CAN tile is the
row-direction reduce: ``coo_segment_sum`` turns the multi-op
scatter-segment-sum chain into a one-hot broadcast-compare + masked
VPU reduce per (row tile, entry tile) — the segment ids are batch row
ids, bounded by batch_size, so the one-hot tile is small and static. The
transpose direction (segment by FEATURE id, ops/spmv.spmv_transpose)
stays on XLA's scatter: a one-hot over millions of features would sweep
every entry tile per feature tile and serialize. Exact f32 by the same
argument as the dense kernel (VPU masked add, no MXU truncation), so
bit-parity with XLA holds on integer-valued data where sums are exactly
representable.

Tiling: batch rows are processed TILE_B at a time; the feature dim is padded
to a lane multiple (128) by the wrapper, and the row tile to a sublane
multiple. Padded rows carry weight 0, padded features carry x == w == 0, so
both are arithmetic no-ops (the same invariant as device/csr.py padding).

Opt-in: models/linear.py uses it when DMLC_TPU_PALLAS=1 (or use_pallas=True)
— measured on-par with XLA's fusion for small feature dims, it exists as the
template for wider fused steps (FM interactions, multi-tower).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from dmlc_tpu.ops.objectives import margin_loss_grad

try:  # pallas ships with jax; keep the module importable without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    available = True
except Exception:  # pragma: no cover
    pl = None
    pltpu = None
    available = False

_LANE = 128
_TILE_B = 512


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _fused_step_kernel(objective: str, x_ref, y_ref, wgt_ref, w_ref, b_ref,
                       gw_ref, gb_ref, loss_ref, wsum_ref):
    """One batch tile: margin → dloss → partial gw/gb/loss/wsum, accumulated
    across the (sequential) grid."""
    i = pl.program_id(0)

    x = x_ref[...]                       # [TILE_B, F]
    y = y_ref[...]                       # [TILE_B, 1]
    wgt = wgt_ref[...]                   # [TILE_B, 1]
    w = w_ref[...]                       # [1, F] — lane-major: an [F, 1]
    # layout would pad the unit lane dimension to 128 and cost 128x VMEM

    # A matvec is bandwidth-bound (2 flops/element): broadcast-multiply +
    # reduce on the VPU is both the natural lowering (Mosaic rejects the
    # [T,F]x[1,F] dot_general contraction) and exact f32 — the MXU's
    # single-pass bf16 truncation would cost ~1e-2 relative error here
    margin = jnp.sum(x * w, axis=1, keepdims=True) + b_ref[0, 0]  # [TILE_B, 1]
    loss, dmargin = margin_loss_grad(objective, margin, y)

    wg = wgt * dmargin                   # [TILE_B, 1]
    gw_part = jnp.sum(x * wg, axis=0, keepdims=True)  # [1, F]
    # (1,1)-shaped partials: Mosaic cannot store scalars to VMEM
    gb_part = jnp.sum(wg).reshape(1, 1)
    loss_part = jnp.sum(wgt * loss).reshape(1, 1)
    wsum_part = jnp.sum(wgt).reshape(1, 1)

    @pl.when(i == 0)
    def _():
        gw_ref[...] = gw_part
        gb_ref[...] = gb_part
        loss_ref[...] = loss_part
        wsum_ref[...] = wsum_part

    @pl.when(i > 0)
    def _():
        gw_ref[...] += gw_part
        gb_ref[...] += gb_part
        loss_ref[...] += loss_part
        wsum_ref[...] += wsum_part


@functools.partial(
    jax.jit, static_argnames=("objective", "tile_b", "interpret")
)
def fused_linear_grads(
    x, label, weight, w, b,
    objective: str = "logistic",
    tile_b: int = _TILE_B,
    interpret: bool = False,
):
    """(gw [F], gb, loss_sum, weight_sum) for a dense batch, one kernel.

    Same contract as the _local_grads dense path in models/linear.py.
    Shapes: x [B, F], label/weight [B], w [F], b scalar. B and F need not be
    tile-aligned — the wrapper zero-pads (padded rows get weight 0).
    """
    bsz, nfeat = x.shape
    fpad = _round_up(max(nfeat, _LANE), _LANE)
    # keep the x tile within a VMEM budget (~2 MiB leaves room for Mosaic's
    # double buffering inside the 16 MiB scoped limit); floor is the f32
    # sublane minimum so very wide feature dims shrink the row tile instead
    # of blowing VMEM
    vmem_rows = max(8, ((2 << 20) // (fpad * 4)) // 8 * 8)
    tile = min(tile_b, vmem_rows, _round_up(max(bsz, 8), 8))
    bpad = _round_up(max(bsz, tile), tile)
    if fpad != nfeat or bpad != bsz:
        x = jnp.pad(x, ((0, bpad - bsz), (0, fpad - nfeat)))
        label = jnp.pad(label, (0, bpad - bsz))
        weight = jnp.pad(weight, (0, bpad - bsz))
        w = jnp.pad(w, (0, fpad - nfeat))

    grid = bpad // tile
    kernel = functools.partial(_fused_step_kernel, objective)
    gw, gb, loss_sum, wsum = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((tile, fpad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, fpad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, fpad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, fpad), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * bpad * fpad,  # two matmuls over the batch
            bytes_accessed=bpad * fpad * 4 + fpad * 4 * 2 + bpad * 8,
            transcendentals=bpad,
        ),
        interpret=interpret,
    )(
        x.astype(jnp.float32),
        label.astype(jnp.float32).reshape(-1, 1),
        weight.astype(jnp.float32).reshape(-1, 1),
        w.astype(jnp.float32).reshape(1, -1),
        jnp.asarray(b, jnp.float32).reshape(1, 1),
    )
    return gw[0, :nfeat], gb[0, 0], loss_sum[0, 0], wsum[0, 0]


# ---------------------------------------------------------------------------
# COO row-direction segment-sum for the sparse SpMV path (ops/spmv.py)
# ---------------------------------------------------------------------------
#
# y[r] = sum_{e: row_ids[e]==r} contrib[e]. The grid walks (row tile,
# entry tile); each step compares the entry tile's row ids against the
# row tile's id range (2D broadcasted_iota — a 1D iota does not lower on
# TPU) and masked-adds the matching contributions on the VPU,
# accumulating across the sequential entry-tile sweep. Padded entries
# carry contrib 0 (the csr bucket invariant) and the wrapper's alignment
# pad carries row id -1, which matches no tile row.

_SEG_TILE_E = 512  # entries per grid step
_SEG_TILE_R = 256  # output rows per grid step (lane multiple)


def _seg_sum_kernel(rid_ref, contrib_ref, out_ref):
    j = pl.program_id(0)  # row tile (output block)
    k = pl.program_id(1)  # entry tile (sequential sweep, accumulates)
    rid = rid_ref[...]  # [TILE_E, 1] i32
    contrib = contrib_ref[...]  # [TILE_E, 1] f32
    rows = j * _SEG_TILE_R + jax.lax.broadcasted_iota(
        jnp.int32, (1, _SEG_TILE_R), 1
    )
    # one-hot membership of each entry in this row tile; masked add on
    # the VPU keeps f32 exact (MXU one-hot matmul would truncate to bf16
    # — the same exactness argument as the dense kernel's matvec)
    part = jnp.sum(
        jnp.where(rid == rows, contrib, 0.0), axis=0, keepdims=True
    )  # [1, TILE_R]

    @pl.when(k == 0)
    def _():
        out_ref[...] = part

    @pl.when(k > 0)
    def _():
        out_ref[...] += part


@functools.partial(jax.jit, static_argnames=("num_rows", "interpret"))
def coo_segment_sum(contrib, row_ids, num_rows: int, interpret: bool = False):
    """``jax.ops.segment_sum(contrib, row_ids, num_rows)`` as a Pallas
    reduce — the row-direction half of the SpMV chain (ops/spmv.spmv),
    with the feature gather left to XLA where it fuses into ``contrib``.
    contrib [E] f32, row_ids [E] i32 (entries beyond the valid nnz must
    carry contrib 0); returns [num_rows] f32."""
    e = contrib.shape[0]
    epad = _round_up(max(e, _SEG_TILE_E), _SEG_TILE_E)
    rpad = _round_up(max(num_rows, _SEG_TILE_R), _SEG_TILE_R)
    if epad != e:
        contrib = jnp.pad(contrib, (0, epad - e))
        row_ids = jnp.pad(row_ids, (0, epad - e), constant_values=-1)
    out = pl.pallas_call(
        _seg_sum_kernel,
        grid=(rpad // _SEG_TILE_R, epad // _SEG_TILE_E),
        in_specs=[
            pl.BlockSpec((_SEG_TILE_E, 1), lambda j, k: (k, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_SEG_TILE_E, 1), lambda j, k: (k, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, _SEG_TILE_R), lambda j, k: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (rpad // _SEG_TILE_R, _SEG_TILE_R), jnp.float32
        ),
        cost_estimate=pl.CostEstimate(
            # each (row tile, entry tile) pair compares + masked-adds
            flops=2 * (rpad // _SEG_TILE_R) * epad,
            bytes_accessed=(rpad // _SEG_TILE_R) * epad * 8 + rpad * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(
        row_ids.astype(jnp.int32).reshape(-1, 1),
        contrib.astype(jnp.float32).reshape(-1, 1),
    )
    return out.reshape(-1)[:num_rows]


# ---------------------------------------------------------------------------
# Byte tokenizer for the vectorized text-parse path (data/vparse.py)
# ---------------------------------------------------------------------------
#
# Token boundaries are a pure elementwise problem once the one-byte
# neighbor shifts are materialized: start = nonsep(cur) & sep(prev),
# end = nonsep(cur) & sep(next). The wrapper builds the three shifted
# views on the host (overlapping slices of one padded buffer — no extra
# copies) so the kernel is shift-free and tiles cleanly on the VPU; the
# 0x20 padding byte is a separator, so padded lanes produce no
# boundaries. Semantics are pinned to vparse.token_boundary_masks by the
# parity suite. Offset extraction (flatnonzero) stays on the host — it
# has no fixed-shape device analog.

_TOK_SEP = (0x20, 0x09, 0x3A, 0x0A, 0x0D)  # space tab colon \n \r
_TOK_ROWS = 256  # uint8 sublane tile is 32; 256x128 rows/step = 32 KiB


def _tokenize_kernel(cur_ref, prv_ref, nxt_ref, starts_ref, ends_ref):
    def sep(v):
        m = v == _TOK_SEP[0]
        for code in _TOK_SEP[1:]:
            m = m | (v == code)
        return m

    cur = cur_ref[...].astype(jnp.int32)
    nonsep = ~sep(cur)
    starts_ref[...] = (
        nonsep & sep(prv_ref[...].astype(jnp.int32))
    ).astype(jnp.uint8)
    ends_ref[...] = (
        nonsep & sep(nxt_ref[...].astype(jnp.int32))
    ).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _tokenize_call(cur, prv, nxt, interpret: bool = False):
    rows = cur.shape[0]
    spec = pl.BlockSpec((_TOK_ROWS, _LANE), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _tokenize_kernel,
        grid=(rows // _TOK_ROWS,),
        in_specs=[spec, spec, spec],
        out_specs=[spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANE), jnp.uint8),
            jax.ShapeDtypeStruct((rows, _LANE), jnp.uint8),
        ],
        interpret=interpret,
    )(cur, prv, nxt)


def tokenize_boundaries(a, interpret=None):
    """(starts_mask, ends_mask) bool arrays for libsvm tokens over the
    uint8 chunk ``a`` — the Pallas variant of
    ``vparse.token_boundary_masks``, used when ``DMLC_TPU_PALLAS`` is
    ``1``/``parse``. ``interpret=None`` auto-selects interpreter mode off
    TPU (Mosaic targets TPU only)."""
    import numpy as np

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = int(a.size)
    if n == 0:
        empty = np.zeros(0, dtype=bool)
        return empty, empty.copy()
    quantum = _TOK_ROWS * _LANE
    pad = -(-n // quantum) * quantum
    buf = np.full(pad + 2, 0x20, dtype=np.uint8)
    buf[1 : 1 + n] = a
    cur = buf[1 : 1 + pad].reshape(-1, _LANE)
    prv = buf[0:pad].reshape(-1, _LANE)
    nxt = buf[2 : 2 + pad].reshape(-1, _LANE)
    starts, ends = _tokenize_call(cur, prv, nxt, interpret=interpret)
    starts = np.asarray(starts).reshape(-1)[:n].astype(bool)
    ends = np.asarray(ends).reshape(-1)[:n].astype(bool)
    return starts, ends
