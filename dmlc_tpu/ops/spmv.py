"""Sparse matrix-vector products for device CSR batches.

Design note (why COO + segment_sum, not CSR offsets): per-row dynamic slicing
of a CSR ``offset`` array is serial, ragged control flow XLA cannot tile onto
the TPU's vector/matrix units. With a per-entry ``row_ids`` array the forward
SpMV is a gather + ``segment_sum`` — both static-shape, fully vectorized, and
fusable — and the gradient is the same primitive with feature ids as the
segment keys. Padded entries (value 0 at feature 0, row 0) are arithmetic
no-ops, so the static nnz bucket needs no masking.

Reference parity: this replaces `Row::SDot` (data.h:152-158), the only
compute kernel the reference ships.

On TPU the row-direction segment-sum can additionally route through the
fused Pallas kernel (:func:`spmv_pallas`, DMLC_TPU_PALLAS=1 with a csr
layout): same contract, the reduce tiled as a one-hot masked add instead
of XLA's scatter chain. The transpose (feature-direction) reduce stays
on XLA in every configuration — see the design note in
ops/pallas_kernels.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from dmlc_tpu.utils.jax_compat import shard_map


def expand_row_ids(offsets, nnz: int):
    """[rows + 1] CSR offsets → [nnz] COO row ids, on device.

    The feed ships the small offsets array across H2D (∝ rows) instead of
    per-entry row_ids (∝ nnz); this expansion — scatter-add a mark at every
    row boundary, then an inclusive cumsum — is O(nnz) vectorized work that
    XLA fuses into the consuming segment-sum's input. Entry e's row is
    #{r ≥ 1 : offsets[r] ≤ e}. Padding semantics: when the batch fills the
    bucket exactly (offsets[rows] == nnz) the tail boundary marks land past
    the end and ``mode="drop"`` discards them; when valid nnz < bucket, the
    padded rows' marks land in-bounds at the valid-nnz index, so the padded
    entries' cumsum overshoots and the clamp to the LAST row absorbs them
    (also saving ``jnp.take``'s out-of-bounds NaN fill) — harmless either
    way because padded values are 0 (arithmetic no-op in both segment-sum
    directions).

    ``nnz`` must be the static bucket size (values.shape[0] under jit).
    """
    marks = jnp.zeros(nnz, jnp.int32).at[offsets[1:]].add(1, mode="drop")
    return jnp.minimum(jnp.cumsum(marks), offsets.shape[0] - 2)


@partial(jax.jit, static_argnames=("num_rows",))
def spmv(values, indices, row_ids, weight_vec, num_rows: int):
    """y[r] = sum_{e: row_ids[e]==r} values[e] * weight_vec[indices[e]].

    values/indices/row_ids: [nnz] static-shape (padded) COO entries.
    weight_vec: [num_features]. Returns [num_rows].
    """
    contrib = values * jnp.take(weight_vec, indices, axis=0)
    return jax.ops.segment_sum(contrib, row_ids, num_segments=num_rows)


@partial(jax.jit, static_argnames=("num_rows", "interpret"))
def spmv_pallas(values, indices, row_ids, weight_vec, num_rows: int,
                interpret: bool = False):
    """:func:`spmv` with the row-direction reduce on the fused Pallas
    kernel (ops/pallas_kernels.coo_segment_sum) instead of XLA's
    scatter-based ``segment_sum`` lowering. The feature gather stays on
    XLA, where it fuses into the kernel's ``contrib`` input — per-entry
    dynamic gather is the part a TPU kernel cannot tile (module design
    note), the batch-row reduce is the part it can. Bit-parity with
    :func:`spmv` is pinned by the CI parity digest on integer-valued
    data (exact f32 sums ⇒ reduction order is unobservable)."""
    from dmlc_tpu.ops.pallas_kernels import coo_segment_sum

    contrib = values * jnp.take(weight_vec, indices, axis=0)
    return coo_segment_sum(contrib, row_ids, num_rows, interpret=interpret)


@partial(jax.jit, static_argnames=("num_features",))
def spmv_transpose(values, indices, row_ids, row_grads, num_features: int):
    """g[f] = sum_{e: indices[e]==f} values[e] * row_grads[row_ids[e]].

    The gradient of ``spmv`` w.r.t. ``weight_vec``: scatter-add of per-row
    grads back onto features. Returns [num_features].
    """
    contrib = values * jnp.take(row_grads, row_ids, axis=0)
    return jax.ops.segment_sum(contrib, indices, num_segments=num_features)


def make_sharded_spmv(mesh, num_rows: int, axis: str = "dp"):
    """SpMV with entries AND output rows sharded over ``axis``.

    Consumes the ShardedCSRBatch layout (device/csr.py): entry arrays are
    flat [num_shards * nnz_bucket] with per-shard sections and LOCAL row
    ids, so each device receives only its own entries (per-device H2D ∝
    global_nnz / world) and the segment-sum is purely local — no global
    mask, no replication. Returns f(values, indices, row_ids, weight_vec)
    -> [num_rows] sharded on the leading axis; weight_vec is replicated.
    """
    from jax.sharding import PartitionSpec as P

    n_shards = mesh.shape[axis]
    assert num_rows % n_shards == 0, "num_rows must divide over the mesh axis"
    rows_local = num_rows // n_shards

    def _local(values, indices, row_ids, weight_vec):
        contrib = values * jnp.take(weight_vec, indices, axis=0)
        return jax.ops.segment_sum(
            contrib, row_ids, num_segments=rows_local
        )

    return jax.jit(
        shard_map(
            _local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P()),
            out_specs=P(axis),
        )
    )
