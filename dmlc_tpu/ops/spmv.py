"""Sparse matrix-vector products for device CSR batches.

Design note (why COO + segment_sum, not CSR offsets): per-row dynamic slicing
of a CSR ``offset`` array is serial, ragged control flow XLA cannot tile onto
the TPU's vector/matrix units. With a per-entry ``row_ids`` array the forward
SpMV is a gather + ``segment_sum`` — both static-shape, fully vectorized, and
fusable — and the gradient is the same primitive with feature ids as the
segment keys. Padded entries (value 0 at feature 0, row 0) are arithmetic
no-ops, so the static nnz bucket needs no masking.

Reference parity: this replaces `Row::SDot` (data.h:152-158), the only
compute kernel the reference ships.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("num_rows",))
def spmv(values, indices, row_ids, weight_vec, num_rows: int):
    """y[r] = sum_{e: row_ids[e]==r} values[e] * weight_vec[indices[e]].

    values/indices/row_ids: [nnz] static-shape (padded) COO entries.
    weight_vec: [num_features]. Returns [num_rows].
    """
    contrib = values * jnp.take(weight_vec, indices, axis=0)
    return jax.ops.segment_sum(contrib, row_ids, num_segments=num_rows)


@partial(jax.jit, static_argnames=("num_features",))
def spmv_transpose(values, indices, row_ids, row_grads, num_features: int):
    """g[f] = sum_{e: indices[e]==f} values[e] * row_grads[row_ids[e]].

    The gradient of ``spmv`` w.r.t. ``weight_vec``: scatter-add of per-row
    grads back onto features. Returns [num_features].
    """
    contrib = values * jnp.take(row_grads, row_ids, axis=0)
    return jax.ops.segment_sum(contrib, indices, num_segments=num_features)


def make_sharded_spmv(mesh, num_rows: int, axis: str = "dp"):
    """SpMV with entries replicated and output rows sharded over ``axis``.

    Each shard computes the segment-sum for its row range only (row_ids are
    global; entries outside the shard's range contribute to masked-out
    segments). Returns f(values, indices, row_ids, weight_vec) -> [num_rows]
    sharded on the leading axis.
    """
    from jax.sharding import PartitionSpec as P

    n_shards = mesh.shape[axis]
    assert num_rows % n_shards == 0, "num_rows must divide over the mesh axis"
    rows_local = num_rows // n_shards

    def _local(values, indices, row_ids, weight_vec):
        shard = jax.lax.axis_index(axis)
        base = shard * rows_local
        local_ids = row_ids - base
        # entries outside this shard land in segment rows_local (dropped)
        oob = (local_ids < 0) | (local_ids >= rows_local)
        local_ids = jnp.where(oob, rows_local, local_ids)
        contrib = values * jnp.take(weight_vec, indices, axis=0)
        summed = jax.ops.segment_sum(
            contrib, local_ids, num_segments=rows_local + 1
        )
        return summed[:rows_local]

    return jax.jit(
        jax.shard_map(
            _local,
            mesh=mesh,
            in_specs=(P(), P(), P(), P()),
            out_specs=P(axis),
        )
    )
