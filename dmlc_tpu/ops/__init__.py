"""TPU compute ops over device-resident CSR batches and long sequences.

The reference stops at host CSR (`RowBlock`, data.h:170) and leaves compute to
downstream learners; here the framework supplies the TPU-shaped kernels those
learners need: COO/segment-sum SpMV (forward) and its transpose (gradient
scatter) plus mesh-sharded variants, and the sequence-parallel attention
schedules (ring / all-to-all) for long-context training — SURVEY §5.7's
extension point, realized.
"""

from dmlc_tpu.ops.spmv import (
    spmv,
    spmv_transpose,
    make_sharded_spmv,
)
from dmlc_tpu.ops.moe import (
    init_moe_params,
    make_moe_layer,
    moe_dense_oracle,
    shard_moe_params,
)
from dmlc_tpu.ops.pipeline_parallel import (
    make_pipeline,
    pipeline_oracle,
    shard_pipeline_params,
)
from dmlc_tpu.ops.sequence_parallel import (
    full_attention,
    make_pallas_flash_local,
    make_ring_attention,
    make_ulysses_attention,
    zigzag_shard,
    zigzag_unshard,
)

__all__ = [
    "spmv",
    "spmv_transpose",
    "make_sharded_spmv",
    "full_attention",
    "make_pallas_flash_local",
    "make_ring_attention",
    "make_ulysses_attention",
    "zigzag_shard",
    "zigzag_unshard",
    "init_moe_params",
    "make_moe_layer",
    "moe_dense_oracle",
    "shard_moe_params",
    "make_pipeline",
    "pipeline_oracle",
    "shard_pipeline_params",
]
