"""TPU compute ops over device-resident CSR batches.

The reference stops at host CSR (`RowBlock`, data.h:170) and leaves compute to
downstream learners; here the framework supplies the TPU-shaped kernels those
learners need: COO/segment-sum SpMV (forward) and its transpose (gradient
scatter), plus mesh-sharded variants.
"""

from dmlc_tpu.ops.spmv import (
    spmv,
    spmv_transpose,
    make_sharded_spmv,
)

__all__ = ["spmv", "spmv_transpose", "make_sharded_spmv"]
