"""Sequence/context parallelism: ring attention and all-to-all attention.

The reference predates long-context training and ships nothing here
(SURVEY §5.7: absent; the closest analog is record-boundary-preserving
chunked streaming). This module realizes the documented extension point
the TPU-first way — the sequence dimension is a mesh axis, and the two
standard schedules are provided:

- ``ring_attention``: K/V shards rotate around the mesh axis with
  ``ppermute`` while each device accumulates its queries' attention in
  the flash/online-softmax form (running max + denominator), so peak
  memory is O(T_local²) and the full T×T score matrix never exists.
  Communication rides the ICI ring; compute overlaps the rotation inside
  one jitted loop.
- ``ulysses_attention`` (all-to-all): ``all_to_all`` re-shards sequence →
  heads, every device runs FULL attention for its head group (exact
  softmax, any local kernel), and a second ``all_to_all`` restores the
  sequence sharding. Needs heads % axis_size == 0; two collectives total.

Shapes are [batch, seq, heads, head_dim] with ``seq`` sharded over the
axis. Both match full attention exactly (tests/test_sequence_parallel.py
asserts parity on an 8-device mesh), including causal masking via global
position indices.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from dmlc_tpu.utils.jax_compat import axis_size, pcast, shard_map

from dmlc_tpu.utils.logging import check

_NEG_INF = -1e30  # mask value: large-negative beats -inf (0*inf=nan in bwd)


def _group_ratio(q, k, v):
    """Q-heads per KV-head (grouped-query attention). 1 = classic MHA;
    H % H_kv must divide (llama-class GQA, MQA at H_kv = 1). K and V must
    agree — the grouped einsums would otherwise silently mis-pair heads
    (the classic MHA einsum made a mismatch a shape error; keep that)."""
    h, hk = q.shape[2], k.shape[2]
    check(k.shape[2] == v.shape[2],
          "k has %d heads but v has %d", k.shape[2], v.shape[2])
    check(h % hk == 0, "num_heads %d must divide by num_kv_heads %d", h, hk)
    return h // hk


def _grouped_scores(q, k, scale):
    """QKᵀ with KV-head grouping: q [B,Tq,H,D] x k [B,Tk,Hk,D] →
    [B,H,Tq,Tk] (the G = H/Hk query heads of a group share one KV head —
    no materialized KV repeat)."""
    b, t_q, h, d = q.shape
    hk = k.shape[2]
    qg = q.reshape(b, t_q, hk, h // hk, d)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k) * scale
    return s.reshape(b, h, t_q, k.shape[1])


def _grouped_pv(p, v):
    """probs [B,H,Tq,Tk] x v [B,Tk,Hk,D] → [B,Tq,H,D] under grouping."""
    b, h, t_q, t_k = p.shape
    hk = v.shape[2]
    pg = p.reshape(b, hk, h // hk, t_q, t_k)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", pg, v)
    return out.reshape(b, t_q, h, v.shape[-1])


def full_attention(q, k, v, causal: bool = False, window: int = 0):
    """Reference single-device attention: softmax(QKᵀ/√d)V.

    q [B, T, H, D]; k/v [B, T, H_kv, D] with H_kv | H (GQA/MQA — H_kv = H
    is classic MHA); out [B, T, H, D]. ``window > 0`` adds mistral-style
    sliding-window masking (query p attends keys in (p-window, p]; implies
    causal). The parity oracle for the sharded schedules."""
    check(window >= 0, "window must be >= 0, got %d", window)
    _group_ratio(q, k, v)
    causal = causal or window > 0
    d = q.shape[-1]
    scores = _grouped_scores(q, k, 1.0 / jnp.sqrt(float(d)))
    if causal:
        t_q, t_k = scores.shape[-2], scores.shape[-1]
        qp = jnp.arange(t_q)[:, None]
        kp = jnp.arange(t_k)[None, :]
        mask = qp >= kp
        if window > 0:
            mask &= (qp - kp) < window
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return _grouped_pv(probs, v)


def _block_accumulate(q, k_blk, v_blk, m, l, o, q_pos, k_pos, causal, scale,
                      window: int = 0):
    """One online-softmax block update (the flash-attention recurrence).

    q [B,Tq,H,D]; k_blk/v_blk [B,Tk,Hk,D] with Hk | H (GQA); m,l [B,H,Tq];
    o [B,Tq,H,D]. q_pos [Tq] / k_pos [Tk] are GLOBAL positions for causal
    and sliding-window masking. The accumulator stays per Q head — only the score/PV einsums
    group, so GQA costs nothing extra here (and the ring ships the SMALLER
    KV shards around the ICI ring: bandwidth ∝ Hk, not H).
    """
    s = _grouped_scores(q, k_blk, scale)
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
        if window > 0:
            mask &= (q_pos[:, None] - k_pos[None, :]) < window
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows: exp(-inf - -inf) must not produce nan
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * correction + p.sum(axis=-1)
    pv = _grouped_pv(p, v_blk)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new




def zigzag_indices(t: int, num_devices: int):
    """Permutation mapping natural order → zigzag device layout.

    The sequence splits into 2N equal chunks; device i holds chunks
    (i, 2N-1-i) — one early + one late — so under CAUSAL masking every
    device does the same total score work per ring hop. With the
    contiguous layout device 0's queries see almost nothing and device
    N-1's see everything: the ring runs in lockstep, so the most-loaded
    device sets every hop's wall time and half the fleet idles. Zigzag is
    the standard fix (llama-class context-parallel training).
    """
    check(t % (2 * num_devices) == 0,
          "seq len %d must divide by 2*num_devices (%d)", t, 2 * num_devices)
    c = t // (2 * num_devices)
    order = []
    for i in range(num_devices):
        order.extend(range(i * c, (i + 1) * c))
        j = 2 * num_devices - 1 - i
        order.extend(range(j * c, (j + 1) * c))
    return np.asarray(order, dtype=np.int32)


def zigzag_shard(x, num_devices: int):
    """Reorder [B, T, ...] from natural to zigzag layout (device i's
    contiguous shard then holds chunks i and 2N-1-i). Apply BEFORE
    sequence-sharding the array over the mesh axis; activations can stay
    in this layout across layers so the cost is paid once."""
    return jnp.take(x, jnp.asarray(zigzag_indices(x.shape[1], num_devices)),
                    axis=1)


def zigzag_unshard(x, num_devices: int):
    """Inverse of :func:`zigzag_shard`."""
    perm = zigzag_indices(x.shape[1], num_devices)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm), dtype=perm.dtype)
    return jnp.take(x, jnp.asarray(inv), axis=1)


def make_ring_attention(
    mesh: Mesh, axis: str = "sp", causal: bool = False, window: int = 0,
    layout: str = "contiguous", batch_axis=None, remat: bool = False,
):
    """Jitted f(q, k, v) -> out with the sequence dim sharded over ``axis``.

    Inside each step the local K/V shard is consumed and then rotated one
    hop around the ring (``ppermute``); after axis_size steps every query
    has seen every key. The accumulator is the online-softmax triple
    (m, l, o), so the result equals exact softmax attention — verified
    against ``full_attention`` — not an approximation.

    ``window > 0`` = mistral-style sliding window (implies causal). Blocks
    entirely outside every local query's window skip their compute exactly
    like fully-future causal blocks — at long T with a small window most
    hops are skips, so wall time approaches O(T·window) while the exact
    result is preserved.

    ``batch_axis`` (a second mesh axis) composes data parallelism: place
    q/k/v with P(batch_axis, axis) and each dp shard runs an independent
    ring over its own batch rows.

    ``remat=True`` wraps each ring hop in ``jax.checkpoint``: the backward
    pass recomputes the hop's scores instead of keeping every hop's
    intermediates alive — activation memory stops scaling with axis_size
    (the standard trade for long-context training; FLOPs roughly +1x fwd).
    """
    check(window >= 0, "window must be >= 0, got %d", window)
    check(layout in ("contiguous", "zigzag"),
          "layout must be 'contiguous' or 'zigzag', got %r", layout)
    causal = causal or window > 0
    zigzag = layout == "zigzag"

    def _local(q, k, v):
        size = axis_size(axis)
        idx = jax.lax.axis_index(axis)
        b, t_local, h, d = q.shape
        scale = 1.0 / jnp.sqrt(float(d))

        if zigzag:
            # device dev holds chunks (dev, 2N-1-dev) of 2N chunks: one
            # early + one late, so causal score work is equal on every
            # device (inputs must be pre-permuted with zigzag_shard)
            c = t_local // 2

            def dev_pos(dev):
                return jnp.concatenate([
                    dev * c + jnp.arange(c),
                    (2 * size - 1 - dev) * c + jnp.arange(c),
                ])
        else:

            def dev_pos(dev):
                return dev * t_local + jnp.arange(t_local)

        q_pos = dev_pos(idx)

        # pcast-to-varying: fresh constants enter the scan carry as
        # device-varying values (the step output varies over the axis)

        m = pcast(
            jnp.full((b, h, t_local), _NEG_INF, dtype=q.dtype),
            axis, to="varying",
        )
        l = pcast(
            jnp.zeros((b, h, t_local), dtype=q.dtype), axis, to="varying"
        )
        o = jnp.zeros_like(q)
        perm = [(i, (i + 1) % size) for i in range(size)]

        # block 0 (the local K/V shard) is consumed before any rotation,
        # and each scan step rotates THEN consumes — size-1 rotations
        # total, none discarded
        m, l, o = _block_accumulate(
            q, k, v, m, l, o, q_pos, dev_pos(idx), causal, scale, window,
        )

        def step(carry, step_idx):
            k_cur, v_cur, m, l, o = carry
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
            # after `step_idx` rotations this device holds the shard that
            # started at ring position (idx - step_idx) mod size
            src = (idx - step_idx) % size
            k_pos = dev_pos(src)
            if causal and not zigzag:
                # a block entirely in this device's future is fully masked,
                # and with a sliding window so is a block entirely OLDER
                # than every local query's window: skip the einsum/exp work
                # (the rotation still runs — the ring schedule needs every
                # hop). Divergent across devices by design; no collectives
                # inside the branches. Window overlap test: the youngest
                # key of block src is (src+1)*t_local - 1; the oldest local
                # query is idx*t_local; attendable iff their distance is
                # inside the window.
                needed = src <= idx
                if window > 0:
                    needed &= (
                        idx * t_local - ((src + 1) * t_local - 1)
                    ) < window
                m, l, o = jax.lax.cond(
                    needed,
                    lambda ops: _block_accumulate(
                        q, ops[0], ops[1], ops[2], ops[3], ops[4],
                        q_pos, k_pos, causal, scale, window,
                    ),
                    lambda ops: (ops[2], ops[3], ops[4]),
                    (k_cur, v_cur, m, l, o),
                )
            elif causal and window > 0:
                # zigzag + window: a hop IS fully masked when both of the
                # block's chunks fall outside every local query's window.
                # Per (q chunk, k chunk) pair the banded mask has a hit
                # iff q_hi >= k_lo (causal reach) and q_lo - k_hi < W
                # (window reach); the hop is needed if any of the 4 pairs
                # hits — keeps the documented O(T·W) walltime under zigzag
                def chunk_ranges(dev):
                    early = (dev * c, (dev + 1) * c - 1)
                    late = ((2 * size - 1 - dev) * c,
                            (2 * size - dev) * c - 1)
                    return (early, late)

                needed = False
                for qlo, qhi in chunk_ranges(idx):
                    for klo, khi in chunk_ranges(src):
                        needed |= (qhi >= klo) & ((qlo - khi) < window)
                m, l, o = jax.lax.cond(
                    needed,
                    lambda ops: _block_accumulate(
                        q, ops[0], ops[1], ops[2], ops[3], ops[4],
                        q_pos, k_pos, causal, scale, window,
                    ),
                    lambda ops: (ops[2], ops[3], ops[4]),
                    (k_cur, v_cur, m, l, o),
                )
            else:
                # zigzag pure-causal: no hop is ever fully masked (every
                # device holds an early chunk every other device's late
                # queries can see) — the BALANCE is the optimization;
                # positions make the masking exact
                m, l, o = _block_accumulate(
                    q, k_cur, v_cur, m, l, o, q_pos, k_pos, causal, scale,
                    window,
                )
            return (k_cur, v_cur, m, l, o), None

        # prevent_cse=False: inside lax.scan the problematic CSE cannot
        # happen (per the jax.checkpoint docs), so skip the optimization
        # barriers it would otherwise insert around every hop
        step_fn = (
            jax.checkpoint(step, prevent_cse=False) if remat else step
        )
        (k, v, m, l, o), _ = jax.lax.scan(
            step_fn, (k, v, m, l, o), jnp.arange(1, size)
        )
        denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return o / denom

    # batch_axis composes data parallelism on a multi-axis mesh: the
    # batch dim shards over it while seq shards over ``axis`` (each
    # dp-shard runs its own independent ring — no cross-talk)
    spec = P(batch_axis, axis)
    _sharded = jax.jit(
        shard_map(
            _local,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
    )

    def _wrapped(q, k, v):
        _group_ratio(q, k, v)  # validate heads before tracing
        if zigzag:
            n = mesh.shape[axis]
            check(q.shape[1] % (2 * n) == 0,
                  "zigzag needs seq len %% 2*axis_size == 0 (T=%d, n=%d)",
                  q.shape[1], n)
        return _sharded(q, k, v)

    return _wrapped


def make_ulysses_attention(
    mesh: Mesh, axis: str = "sp", causal: bool = False, window: int = 0,
    local_attention=None, batch_axis=None,
):
    """Jitted f(q, k, v) -> out: all-to-all sequence↔head re-sharding.

    Each device trades its sequence shard of every head for the FULL
    sequence of heads/axis_size heads, runs exact local attention (or a
    supplied ``local_attention(q, k, v)`` kernel — e.g. a Pallas flash
    kernel), and the second all-to-all restores [seq-sharded, all heads].

    A custom kernel owns its own masking, so combining ``causal=True``
    with ``local_attention`` is rejected rather than silently dropped.
    ``batch_axis`` composes data parallelism exactly as in
    :func:`make_ring_attention`.
    """
    check(window >= 0, "window must be >= 0, got %d", window)
    check(
        not ((causal or window > 0) and local_attention is not None),
        "pass causality/windowing inside your local_attention kernel; the "
        "flags only configure the built-in full_attention",
    )
    n_shards = mesh.shape[axis]

    def _local(q, k, v):
        # [B, T_local, H, D] -> [B, T, H/size, D]: gather seq, scatter heads
        def seq_to_heads(x):
            return jax.lax.all_to_all(
                x, axis, split_axis=2, concat_axis=1, tiled=True
            )

        def heads_to_seq(x):
            return jax.lax.all_to_all(
                x, axis, split_axis=1, concat_axis=2, tiled=True
            )

        qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
        fn = local_attention if local_attention is not None else partial(
            full_attention, causal=causal, window=window
        )
        out = fn(qh, kh, vh)
        return heads_to_seq(out)

    def _wrapped(q, k, v):
        check(
            q.shape[2] % n_shards == 0,
            "ulysses needs heads %% axis_size == 0 (got %d heads over %d)",
            q.shape[2], n_shards,
        )
        # GQA: KV heads re-shard over the same axis, so they must divide
        # too (each device then holds H/size query heads against Hk/size
        # KV heads — the group ratio is preserved locally)
        check(
            k.shape[2] % n_shards == 0,
            "ulysses needs kv_heads %% axis_size == 0 (got %d over %d)",
            k.shape[2], n_shards,
        )
        _group_ratio(q, k, v)
        return _sharded(q, k, v)

    u_spec = P(batch_axis, axis)
    _sharded = jax.jit(
        shard_map(
            _local,
            mesh=mesh,
            in_specs=(u_spec, u_spec, u_spec),
            out_specs=u_spec,
            # pallas_call out_shapes carry no varying-mesh-axes metadata,
            # so custom kernels cannot pass the vma check
            check_vma=local_attention is None,
        )
    )
    return _wrapped


def make_pallas_flash_local(causal: bool = False, block_sizes=None):
    """A ``local_attention`` kernel for ``make_ulysses_attention`` backed by
    the Pallas TPU flash-attention kernel (VMEM-resident blockwise softmax
    on the MXU — the hot-op kernel the all-to-all schedule is built to
    host). TPU-only (Mosaic lowering); adapts this module's [B, T, H, D]
    layout to the kernel's [B, H, T, D].

    Measured on v5e (BASELINE.md): crosses over XLA attention as T grows —
    the XLA path materializes T×T scores in HBM, flash never does.
    """
    import math

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    def _block(t: int, cap: int) -> int:
        """Largest divisor of t that is <= cap and a multiple of 128 (the
        Pallas kernel requires seq_len % block == 0; the MXU wants lane
        multiples). Falls back to t itself for short sequences."""
        for d in range(min(cap, t) // 128 * 128, 0, -128):
            if t % d == 0:
                return d
        return t

    def kernel(q, k, v):
        # the Pallas kernel wants matched head counts; GQA KV heads are
        # materialized to H here (local cost ∝ T·H·D — what MHA would pay)
        if k.shape[2] != q.shape[2]:
            rep = _group_ratio(q, k, v)
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        scale = 1.0 / math.sqrt(q.shape[-1])
        bs = block_sizes
        if bs is None:
            # measured on v5e at T=16k: the kernel's own defaults run 60x
            # slower than these (1178 ms vs 18 ms; XLA takes 54 ms) — big
            # q/k blocks keep the MXU fed and the grid small
            t = q.shape[1]
            bq = _block(t, 1024)
            bk = _block(t, 2048)
            bs = BlockSizes(
                block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
                block_q_major_dkv=bq, block_k_major_dkv=bk,
                block_q_dkv=bq, block_k_dkv=bk,
                block_q_dq=bq, block_k_dq=bk, block_k_major_dq=bk,
            )
        out = flash_attention(
            q.transpose(0, 2, 1, 3),
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
            causal=causal,
            sm_scale=scale,
            block_sizes=bs,
        )
        return out.transpose(0, 2, 1, 3)

    return kernel
