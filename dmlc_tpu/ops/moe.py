"""Expert parallelism: switch-style MoE dispatch over a mesh axis.

The reference predates mixture-of-experts training (SURVEY §2.9 lists no
EP); this realizes the documented extension point the TPU-first way, the
same stance as ``sequence_parallel``:

- experts are SHARDED over the ``ep`` mesh axis (each device owns
  ``num_experts / ep_size`` expert FFNs — model memory scales out);
- tokens stay sharded over the same axis (data-parallel token shards);
- routing is top-1 (switch) or renormalized top-k (GShard) softmax
  gating with a STATIC per-(device, expert)
  capacity (XLA needs static shapes — the standard switch-transformer
  bucketing; over-capacity tokens pass through the residual with zero
  expert output, never a recompile);
- dispatch/return ride ONE ``all_to_all`` each way over the axis
  ([E, C, D] grouped by owning device), the canonical TPU MoE exchange —
  ICI bandwidth, no host involvement.

Parity oracle: ``moe_dense_oracle`` applies every token's routed expert
directly (no capacity, one device); with capacity ≥ tokens the sharded
layer must match it exactly (tests/test_moe.py, 8-device mesh).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_tpu.utils.jax_compat import shard_map

from dmlc_tpu.utils.logging import check


def init_moe_params(
    num_experts: int, d_model: int, d_hidden: int, seed: int = 0
) -> Dict:
    """{"wg": [D, E], "w1": [E, D, H], "w2": [E, H, D]} — wg replicated,
    w1/w2 sharded over ep on the expert dim by the layer."""
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    s1 = 1.0 / np.sqrt(d_model)
    s2 = 1.0 / np.sqrt(d_hidden)
    return {
        "wg": jax.random.normal(k1, (d_model, num_experts)) * s1,
        "w1": jax.random.normal(k2, (num_experts, d_model, d_hidden)) * s1,
        "w2": jax.random.normal(k3, (num_experts, d_hidden, d_model)) * s2,
    }


def _route_topk(x, wg, num_experts: int, capacity: int, top_k: int):
    """Top-k routing with static capacity → (dispatch, combine, aux).

    x [T, D] (local tokens). dispatch [T, E, C] one-hot over every kept
    (token, choice); combine the same scaled by the RENORMALIZED gate
    probability of each choice (GShard: the k selected probs sum to 1 per
    token). Capacity fills first-choice tokens before second-choice —
    under pressure an expert drops k=2 overflow, not k=1 traffic. Tokens
    whose choice overflows get zero rows for that choice (the residual
    upstream handles them). aux is the switch/GShard load-balancing loss
    on FIRST choices: E * sum_e(frac_e * mean_prob_e)."""
    gates = jax.nn.softmax(x @ wg, axis=-1)  # [T, E]
    probs, ids = jax.lax.top_k(gates, top_k)  # [T, K]
    if top_k > 1:
        # GShard: the selected probs renormalize to a mixture. At k=1 the
        # RAW gate prob scales the output (switch semantics) — dividing
        # would make it exactly 1.0 and cut the router's gradient path
        # through the main output.
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    onehot = jax.nn.one_hot(ids, num_experts, dtype=x.dtype)  # [T, K, E]
    # bucket positions: choice-major order (all first choices claim slots
    # before any second choice) — flatten [K, T, E], exclusive-cumsum
    oh_km = onehot.transpose(1, 0, 2).reshape(top_k * onehot.shape[0],
                                              num_experts)
    pos_flat = jnp.cumsum(oh_km, axis=0) - oh_km
    pos = (
        jnp.sum(pos_flat.reshape(top_k, -1, num_experts)
                .transpose(1, 0, 2) * onehot, axis=-1)
    ).astype(jnp.int32)  # [T, K]
    keep = pos < capacity
    kept = (
        onehot[:, :, :, None]
        * jax.nn.one_hot(pos, capacity, dtype=x.dtype)[:, :, None, :]
        * keep[:, :, None, None]
    )  # [T, K, E, C]
    dispatch = jnp.sum(kept, axis=1)  # [T, E, C]
    combine = jnp.sum(kept * probs[:, :, None, None], axis=1)
    frac = jnp.mean(onehot[:, 0], axis=0)
    mean_prob = jnp.mean(gates, axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def moe_dense_oracle(params: Dict, x, top_k: int = 1):
    """Single-device reference: every token through its top-k experts
    (renormalized gate mixture), no capacity limit.
    [B, T, D] -> ([B, T, D], aux)."""
    b, t, d = x.shape
    xt = x.reshape(b * t, d)
    gates = jax.nn.softmax(xt @ params["wg"], axis=-1)
    probs, ids = jax.lax.top_k(gates, top_k)  # [T, K]
    if top_k > 1:
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    y = jnp.zeros_like(xt)
    for kk in range(top_k):
        w1 = params["w1"][ids[:, kk]]  # [T, D, H]
        w2 = params["w2"][ids[:, kk]]  # [T, H, D]
        h = jax.nn.gelu(jnp.einsum("td,tdh->th", xt, w1))
        y = y + jnp.einsum("th,thd->td", h, w2) * probs[:, kk:kk + 1]
    num_experts = params["wg"].shape[1]
    onehot = jax.nn.one_hot(ids[:, 0], num_experts, dtype=x.dtype)
    aux = num_experts * jnp.sum(
        jnp.mean(onehot, axis=0) * jnp.mean(gates, axis=0)
    )
    return y.reshape(b, t, d), aux


def make_moe_layer(
    mesh: Mesh,
    num_experts: int,
    capacity: int,
    axis: str = "ep",
    batch_axis=None,
    top_k: int = 1,
):
    """Jitted f(params, x[B, T, D]) -> (y[B, T, D], aux_loss).

    Tokens sharded over ``axis`` on T; expert weights sharded over the
    expert dim. ``capacity`` is PER (device, expert): each device may send
    at most ``capacity`` of its local tokens to any one expert (static
    shapes — raise it toward local_tokens for a no-drop guarantee).
    ``batch_axis`` (a second mesh axis) composes data parallelism: place x
    with P(batch_axis, axis) and each dp shard routes its own tokens
    independently (expert weights replicated across dp; aux averaged over
    both axes). ``top_k`` selects switch (1, default) or GShard-style
    top-2+ routing with renormalized gate mixtures; capacity admits first
    choices before second.
    """
    ep = mesh.shape[axis]
    check(num_experts % ep == 0,
          "num_experts %d must divide over axis size %d", num_experts, ep)
    check(1 <= top_k <= num_experts,
          "top_k %d must be in [1, %d]", top_k, num_experts)
    e_local = num_experts // ep

    def _local(params, x):
        b, t_local, d = x.shape
        xt = x.reshape(b * t_local, d)
        dispatch, combine, aux = _route_topk(
            xt, params["wg"], num_experts, capacity, top_k
        )
        # gather expert inputs: [E, C, D] with experts numbered
        # contiguously per owning device (expert e lives on device
        # e // e_local)
        xd = jnp.einsum("tec,td->ecd", dispatch, xt)
        # ONE all_to_all each way: trade "my tokens for every expert" for
        # "every device's tokens for my experts". split_axis=0 sends
        # slice [dst] to device dst; the received stack's leading axis
        # indexes the SOURCE device.
        xd = xd.reshape(ep, e_local, capacity, d)
        xd = jax.lax.all_to_all(xd, axis, split_axis=0, concat_axis=0)
        # [ep(source), e_local, C, D] -> [e_local, ep*C, D]: every
        # device's buckets for my experts, grouped per expert
        xd = xd.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, d)
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xd, params["w1"]))
        y = jnp.einsum("ech,ehd->ecd", h, params["w2"])
        # reverse exchange: slice [dst] = expert outputs for device dst's
        # tokens; received stack = my tokens' outputs by owner device,
        # which is exactly global expert order (contiguous per device)
        y = y.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
        y = jax.lax.all_to_all(y, axis, split_axis=0, concat_axis=0)
        y = y.reshape(num_experts, capacity, d)
        out = jnp.einsum("tec,ecd->td", combine, y)
        # aux is the mean of per-shard switch losses (each shard balances
        # its own routing mix — the standard distributed-MoE practice;
        # equals the global loss only when shards route identically).
        # Averaged over every token-sharding axis so it is replicated.
        aux = jax.lax.pmean(aux, axis_name=axis)
        if batch_axis is not None:
            aux = jax.lax.pmean(aux, axis_name=batch_axis)
        return out.reshape(b, t_local, d), aux

    # batch_axis composes dp on a multi-axis mesh (each dp-shard routes
    # its own tokens; expert weights stay replicated across dp)
    sharded = jax.jit(
        shard_map(
            _local,
            mesh=mesh,
            in_specs=(
                {"wg": P(), "w1": P(axis), "w2": P(axis)},
                P(batch_axis, axis),
            ),
            out_specs=(P(batch_axis, axis), P()),
        )
    )

    def _wrapped(params, x):
        check(x.shape[1] % ep == 0,
              "token dim %d must divide over axis size %d", x.shape[1], ep)
        return sharded(params, x)

    return _wrapped


def shard_moe_params(params: Dict, mesh: Mesh, axis: str = "ep") -> Dict:
    """Place params for :func:`make_moe_layer`: expert weights sharded on
    the expert dim, gate replicated — each device materializes only its
    own experts' FFNs."""
    return {
        "wg": jax.device_put(params["wg"], NamedSharding(mesh, P())),
        "w1": jax.device_put(params["w1"], NamedSharding(mesh, P(axis))),
        "w2": jax.device_put(params["w2"], NamedSharding(mesh, P(axis))),
    }
