"""URI "sugar" parsing: ``path?key=val&...#cachefile``.

Capability parity with ``dmlc::io::URISpec`` (src/io/uri_spec.h:43-76):
one optional ``#cachefile`` suffix (which gains ``.splitN.partK`` when
num_parts != 1), one optional ``?``-query of ``&``-separated ``key=value``
args (e.g. ``format=libsvm`` selecting the parser — src/data.cc:70-76).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from dmlc_tpu.utils.logging import check_eq


@dataclass
class URISpec:
    uri: str = ""
    args: Dict[str, str] = field(default_factory=dict)
    cache_file: str = ""

    def __init__(self, uri: str, part_index: int = 0, num_parts: int = 1):
        name_cache = uri.split("#")
        check_eq(
            len(name_cache) <= 2,
            True,
            "only one `#` is allowed in file path for cachefile specification",
        )
        if len(name_cache) == 2:
            cache = name_cache[1]
            if num_parts != 1:
                cache += f".split{num_parts}.part{part_index}"
            self.cache_file = cache
        else:
            self.cache_file = ""
        name_args = name_cache[0].split("?")
        check_eq(
            len(name_args) <= 2,
            True,
            "only one `?` is allowed in file path for argument specification",
        )
        self.args = {}
        if len(name_args) == 2:
            for i, item in enumerate(name_args[1].split("&")):
                if not item:
                    continue
                key, sep, value = item.partition("=")
                check_eq(sep, "=", f"Invalid uri argument format in arg {i + 1}")
                self.args[key] = value
        self.uri = name_args[0]
